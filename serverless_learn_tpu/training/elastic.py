"""Elastic training: membership epoch changes drive mesh re-formation.

This is the TPU-native realization of the reference's one genuinely novel
capability — "any worker can join anytime" (``src/master.cc:79-91``) — made
compatible with synchronous SPMD (SURVEY.md §7 "hard parts" (a), (d)):

    steady state: jitted step over a fixed Mesh, gradients psum'd on ICI
    epoch change (join/leave/eviction, from the native coordinator):
        drain  -> finish the in-flight step
        save   -> checkpoint to the shard server / local store
        remesh -> rebuild the Mesh & retrace the step for the new world size
        resume -> restore the checkpoint into the NEW shardings, continue

Gossip tolerated membership churn because every exchange was pairwise and
asynchronous; SPMD instead gets elasticity at checkpoint granularity — the
price of replacing O(N)-round gossip convergence with single-collective
exact synchronization.

Single-process realization: the world is a subset of local devices sized by
``device_policy(peers)`` (default: one device per chip registered by live
peers, capped at the local device count). On a real multi-host pod the same
epoch signal instead triggers a coordinated `jax.distributed` restart —
worker processes re-initialize with the new world size and restore from the
same checkpoint; the control-plane signals, drain/save/restore sequence, and
sharding-aware restore below are exactly what that path reuses.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import jax

from serverless_learn_tpu.config import (ExperimentConfig,
                                          UnsatisfiableMeshError, scale_mesh)
from serverless_learn_tpu.control.gossip import make_membership_agent
from serverless_learn_tpu.data.datasets import Prefetcher
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.telemetry import flight, get_registry, goodput
from serverless_learn_tpu.telemetry import tracing as ttrace
from serverless_learn_tpu.telemetry.dcn import instrument_store
from serverless_learn_tpu.training import wire_codec
from serverless_learn_tpu.training.checkpoint import Checkpointer
from serverless_learn_tpu.training.loop import make_source
from serverless_learn_tpu.training.replicate import (maybe_replicated,
                                                     serve_cache)
from serverless_learn_tpu.training.train_step import build_trainer
from serverless_learn_tpu.utils.metrics import log_json


def default_device_policy(peers, local_devices) -> List:
    """One device per registered chip across live peers, capped locally.
    With no peer info yet, use all local devices."""
    total = sum(p.n_chips for p in peers) if peers else len(local_devices)
    n = max(1, min(total, len(local_devices)))
    return list(local_devices)[:n]


@dataclass
class EpochTransition:
    epoch: int
    step: int
    n_devices: int
    stripe: tuple = (0, 1)  # (rank, size) in the live membership
    mesh: dict = field(default_factory=dict)  # non-unit axis sizes formed


class ElasticTrainer:
    """Owns the worker agent, the checkpointer and the (re)built trainer."""

    def __init__(
        self,
        config: ExperimentConfig,
        store,
        coordinator_addr: Optional[str] = None,
        advertise_addr: str = "local:0",
        name: str = "elastic",
        n_chips: Optional[int] = None,
        device_policy: Callable = default_device_policy,
        mesh_policy: Optional[Callable] = None,
        verbose: bool = False,
        name_wait_s: float = 15.0,
    ):
        self.config = config
        self.name = name
        # The worker's name is its checkpoint namespace: two live workers
        # sharing a name would silently clobber each other's state (guarded
        # at startup in run()). Sharded layout, same as the multi-host
        # path: saves write only replica-0 shards and restores ranged-fetch
        # exactly the target sharding's bytes — a single-host world change
        # (e.g. fsdp 2 -> 4) no longer round-trips the full state through
        # one blob (r2 weak item).
        # Round 15: the store is tiered per config.checkpoint — a
        # worker-local cache makes the remesh restore a local read, peer
        # replicas make a rejoin survive a slow or partitioned central
        # store, and restores verify checksums with corrupt steps
        # quarantined (falling back to the newest verified step).
        # Round 16: remesh state streaming (drain->save->restore through
        # the checkpoint store) is a DCN consumer — byte-counted under
        # consumer="remesh" (telemetry/dcn.py), so `slt top` can show what
        # an epoch transition actually shipped.
        store = maybe_replicated(instrument_store(store, "remesh"),
                                 config.checkpoint)
        self._cache_server = None
        if (config.checkpoint.serve_cache and config.checkpoint.cache_dir):
            self._cache_server = serve_cache(
                config.checkpoint.cache_dir,
                port=config.checkpoint.serve_cache_port)
        self.ckpt = Checkpointer(store, name=name, async_save=False,
                                 sharded=True, keep=config.checkpoint.keep,
                                 verify=config.checkpoint.verify)
        # Round 20: remesh state streaming can ride a blockwise int8/fp8
        # wire encoding (config elastic.remesh_wire_dtype) — the epoch
        # transition is a TRANSFER, not a durability point, so the
        # stream is a transient single-key blob beside (never through)
        # the CRC-verified checkpoint layout. Durable saves — final,
        # emergency, explicit — stay bit-exact through the Checkpointer;
        # the codec is structurally unreachable from that path.
        ecfg = getattr(config, "elastic", None)
        self._remesh_wire_dtype = wire_codec.require_supported(
            ecfg.remesh_wire_dtype if ecfg is not None else "float32")
        self._remesh_wire_block = int(
            ecfg.remesh_wire_block if ecfg is not None else 128)
        self.device_policy = device_policy
        # Default policy honors the CONFIGURED mesh: tp/pp/sp/ep stay fixed,
        # fsdp is a memory floor, dp stretches with the world (config.
        # scale_mesh). A trivial config mesh degenerates to dp-only, which
        # was the only behavior before round 3 (VERDICT r2 item 2: the
        # llama8b fsdp=4,tp=2 elastic config was silently discarded).
        self.mesh_policy = (mesh_policy
                            or (lambda n: scale_mesh(config.mesh, n)))
        self.verbose = verbose
        # How long to keep retrying an exclusive-name registration before
        # giving up — long enough to outlive a dead predecessor's lease
        # (default TTL 5 s) plus the eviction sweep, so a legitimate
        # restart under a stable name succeeds without racing the sweeper.
        self.name_wait_s = name_wait_s
        self.transitions: List[EpochTransition] = []
        self._remesh = threading.Event()
        self._stop = threading.Event()
        self._last_epoch_change_t = 0.0
        self._agent = None
        if coordinator_addr is not None:
            # Membership plane per config.membership.mode: the classic
            # master-heartbeat WorkerAgent, or the SWIM GossipAgent whose
            # epochs come from gossip state (round 11).
            self._agent = make_membership_agent(
                config, coordinator_addr, advertise_addr, name=name,
                n_chips=n_chips if n_chips is not None else len(jax.devices()),
                on_epoch_change=self._on_epoch_change,
                exclusive_name=True)

    # -- membership hook ---------------------------------------------------

    def _on_epoch_change(self, epoch: int, peers):
        self._last_epoch_change_t = time.time()
        self._remesh.set()

    def _remesh_due(self) -> bool:
        """Anti-flap hysteresis (membership.remesh_debounce_s): a pending
        epoch change only triggers the drain→save→remesh cycle once the
        view has held still for the debounce window. A member that bounces
        (lease blip: evict + instant re-register, or a suspicion that
        refutes) keeps pushing the window out and ends up causing ZERO
        remeshes when the final view equals the formed one."""
        if not self._remesh.is_set():
            return False
        debounce = self.config.membership.remesh_debounce_s
        if debounce <= 0:
            return True
        if time.time() - self._last_epoch_change_t < debounce:
            return False
        # Debounced long enough — but if the settled view is exactly the
        # world we already formed, skip the remesh entirely.
        epoch, devices = self._current_world()
        if (self.transitions
                and len(devices) == self.transitions[-1].n_devices
                and self._stripe() == self.transitions[-1].stripe):
            self._remesh.clear()
            self.transitions[-1].epoch = epoch
            return False
        return True

    def _safe_paused(self) -> bool:
        """Quorum-loss safe-pause (membership.safe_pause): when the live
        view drops below quorum, stop stepping instead of re-meshing down
        onto a minority island — a partitioned minority training on would
        fork the checkpoint namespace from the majority."""
        if not (self.config.membership.safe_pause
                and self._agent is not None
                and hasattr(self._agent, "quorum_lost")):
            return False
        return bool(self._agent.quorum_lost())

    def request_stop(self):
        """Graceful shutdown: finish the in-flight step, checkpoint, return."""
        self._stop.set()

    def _current_world(self):
        if self._agent is None:
            return 0, self.device_policy([], jax.devices())
        epoch, peers = self._agent.snapshot()
        return epoch, self.device_policy(peers, jax.devices())

    def _stripe(self):
        """(rank, size) in the live membership, ordered by worker id — the
        data stripe. Concurrent workers on one coordinator divide the
        dataset's shards instead of everyone reading everything (each
        trains its own full batch; striping governs which records feed
        it). Without a coordinator — or while the agent's own id is
        transiently absent mid re-registration — fall back to this
        process's slot in the fixed SPMD world, preserving make_source's
        default striping."""
        fallback = (jax.process_index(), jax.process_count())
        if self._agent is None:
            return fallback
        _, peers = self._agent.snapshot()
        ids = sorted(p.worker_id for p in peers)
        wid = self._agent.worker_id
        if wid not in ids:
            return fallback
        return ids.index(wid), len(ids)

    # -- quantized remesh streaming (round 20) ------------------------------

    def _stream_key(self) -> str:
        return f"{self.name}/remesh-stream"

    def _note_remesh_wire(self, direction: str, logical: int,
                          wire: int, step: int):
        from serverless_learn_tpu.telemetry import dcn

        try:
            dcn.record_logical("remesh", direction, logical)
        except Exception:
            pass
        ttrace.emit_event({
            "event": "dcn_wire", "consumer": "remesh",
            "direction": direction, "kind": "remesh_stream",
            "wire_dtype": self._remesh_wire_dtype,
            "logical_bytes": int(logical), "wire_bytes": int(wire),
            "step": int(step), "t_unix_s": round(time.time(), 3)})

    def _save_remesh_stream(self, state, step: int) -> bool:
        """Stream the drained state as ONE quantized blob (atomic store
        put) for the imminent restore. Returns False — caller falls back
        to the exact checkpoint save — when the state holds non-finite
        values (the codec's typed refusal) or the put fails."""
        try:
            blob = wire_codec.encode(
                state, self._remesh_wire_dtype, self._remesh_wire_block,
                meta={"step": int(step), "name": self.name})
        except wire_codec.NonFiniteError:
            return False
        try:
            with goodput.get_ledger().phase("checkpoint"):
                self.ckpt.store.put(self._stream_key(), blob)
        except (OSError, ConnectionError):
            return False  # store trouble: take the durable path instead
        self._note_remesh_wire("tx", wire_codec.logical_nbytes(state),
                               len(blob), step)
        return True

    def _load_remesh_stream(self, trainer):
        """-> (step, host_state) from the transient stream, or None —
        any decode/read trouble falls back to the verified checkpoint
        restore (the stream is a transfer encoding, not a source of
        truth)."""
        store = self.ckpt.store
        try:
            if not store.exists(self._stream_key()):
                return None
            blob = store.get(self._stream_key())
            import numpy as np

            template = jax.tree_util.tree_map(
                lambda x: np.zeros(x.shape, x.dtype),
                trainer.abstract_state())
            host, meta = wire_codec.decode(blob, template=template,
                                           with_meta=True)
        except Exception as e:
            ttrace.emit_event({"event": "remesh_stream_invalid",
                               "detail": f"{type(e).__name__}: {e}"})
            return None
        if meta.get("name") not in (None, self.name):
            return None  # another worker's stream: not ours to adopt
        step = int(meta.get("step", -1))
        self._note_remesh_wire("rx", wire_codec.logical_nbytes(host),
                               len(blob), step)
        return step, host

    def _start_agent(self):
        """Register under the exclusive name, retrying long enough for a
        dead predecessor's lease to be swept — the coordinator is the
        single authority on name ownership (no client-side polling race),
        so a refusal here means a LIVE worker holds the name."""
        assert self._agent is not None
        deadline = time.time() + self.name_wait_s
        while True:
            try:
                self._agent.start()
                return
            except RuntimeError as e:
                if "name" not in str(e) or time.time() > deadline:
                    raise
                time.sleep(0.3)

    # -- main loop ---------------------------------------------------------

    def run(self, num_steps: Optional[int] = None):
        """Train to ``num_steps`` (default from config), re-meshing on every
        membership epoch change. Returns (final_state, losses)."""
        num_steps = num_steps or self.config.train.num_steps
        if self._agent is not None:
            self._start_agent()
        reg = get_registry()
        m_steps = reg.counter("slt_train_steps_total", "optimizer steps run")
        m_loss = reg.gauge("slt_train_loss")
        m_members = reg.gauge("slt_membership_size",
                              "live workers in the stripe")
        m_epoch = reg.gauge("slt_membership_epoch")
        m_remesh = reg.counter("slt_remesh_total",
                               "mesh formations (first one included)")
        # Structural-health inputs (telemetry/health.py): remesh wall time
        # feeds the anomaly detector (an epoch transition suddenly 10x
        # slower is a sick store or coordinator), the last-step stamp
        # feeds the staleness watchdog / /healthz last-step age.
        m_remesh_t = reg.histogram(
            "slt_remesh_seconds",
            "drain -> save -> remesh -> restore wall time per epoch")
        m_last_step = reg.gauge("slt_train_last_step_unix_s",
                                "wall time of the latest optimizer step")
        m_safe_paused = reg.gauge(
            "slt_safe_paused",
            "1 while quorum-loss safe-pause is holding training")
        m_safe_pauses = reg.counter(
            "slt_safe_pause_ticks_total",
            "step-loop ticks skipped under quorum-loss safe-pause")
        losses: List[float] = []
        state = None
        source = None
        source_iter = None
        stripe = None
        # Emergency save on the death path (round 15): note_state keeps
        # a rate-limited HOST shadow of the newest state — the live
        # state's buffers are donated into the next jitted step, so the
        # dying handler can only serialize a host copy.
        if self.config.checkpoint.emergency_save:
            self.ckpt.arm_emergency(
                min_interval_s=self.config.checkpoint
                .emergency_min_interval_s)
        try:
            while True:
                self._remesh.clear()
                epoch, devices = self._current_world()
                # Each mesh formation is a span: `slt trace` shows how long
                # drain -> save -> remesh -> restore took per epoch, and
                # the flight ring keeps the transition in a crash dump.
                # The same window is "remesh" badput on the goodput ledger
                # (the nested checkpoint restore subtracts into its own
                # "checkpoint" phase — exclusive attribution).
                remesh_phase = goodput.get_ledger().phase("remesh")
                remesh_phase.__enter__()
                remesh_cm = ttrace.span("elastic/remesh", epoch=epoch)
                remesh_span = remesh_cm.__enter__()
                # Largest prefix of the world's devices the policy can host:
                # with model axes configured (tp=2, say) an odd device count
                # is unsatisfiable, and idling the remainder beats dying —
                # the spare picks up work at the next epoch change. A world
                # too small for even the memory floor IS fatal (raised).
                mesh_cfg = None
                for n in range(len(devices), 0, -1):
                    try:
                        mesh_cfg = self.mesh_policy(n)
                    except UnsatisfiableMeshError:
                        continue
                    devices = devices[:n]
                    break
                if mesh_cfg is None:
                    raise UnsatisfiableMeshError(
                        f"no subset of {len(devices)} local devices can "
                        f"host the configured mesh {self.config.mesh}")
                cfg = self.config.override(mesh=mesh_cfg)
                mesh = make_mesh(mesh_cfg, devices=devices)
                trainer = build_trainer(cfg, mesh=mesh)
                remesh_span.mark("trainer_built")
                rank, size = self._stripe()
                if source_iter is None or (rank, size) != stripe:
                    # Honor the configured data plane: a shard server means
                    # the worker streams the published dataset (the CLI's
                    # --shard-server/--dataset), not synthetic batches. The
                    # source is striped by this worker's rank in the LIVE
                    # membership — concurrent workers read disjoint shards —
                    # and rebuilt whenever the stripe changes (join/leave),
                    # not on every re-mesh.
                    if source is not None and hasattr(source, "close"):
                        source.close()
                    stripe = (rank, size)
                    source = make_source(cfg, trainer,
                                         dp_rank=rank, dp_size=size,
                                         start_step=self.ckpt.latest_step()
                                         or 0)
                    source_iter = iter(source)
                # restore (or cold-start) into the new world's shardings;
                # the restore template is abstract — no wasted init.
                # A quantized remesh stream (round 20) wins when it is at
                # least as new as the latest durable checkpoint — it IS
                # the drained state of the world we just tore down; the
                # CRC-verified restore stays the fallback for everything
                # else (cold rejoin, invalid stream, f32 config).
                stream = None
                if self._remesh_wire_dtype != "float32":
                    stream = self._load_remesh_stream(trainer)
                latest = self.ckpt.latest_step()
                if stream is not None and (latest is None
                                           or stream[0] >= latest):
                    with goodput.get_ledger().phase("checkpoint"):
                        state = jax.tree_util.tree_map(
                            lambda x, s: jax.device_put(x, s),
                            stream[1], trainer.state_shardings)
                elif latest is not None:
                    state = self.ckpt.restore(
                        trainer.abstract_state(),
                        shardings=trainer.state_shardings)
                elif state is None:
                    state = trainer.init()
                self.ckpt.note_state(state)
                remesh_span.mark("restored")
                # ZeRO (round 18): the trainer's shardings already carry
                # the new world's dp composition, so the restore above
                # re-partitioned dp-sharded optimizer state to the new
                # dp size (a replicated pre-ZeRO checkpoint restores
                # into the sharded layout the same way). Re-stamp the
                # per-chip byte gauge so the memory win tracks worlds.
                from serverless_learn_tpu.training.zero import (
                    publish_opt_state_gauge)

                publish_opt_state_gauge(state.opt_state)
                step = int(jax.device_get(state.step))
                if self.config.numerics.enabled:
                    # Round 17: fingerprint the restored params at every
                    # world formation — `slt numerics diff` can then
                    # prove a remesh/restore was value-preserving (or
                    # bisect which subtree a corrupt restore mangled)
                    # straight from two event trails.
                    from serverless_learn_tpu.telemetry import (
                        numerics as _numerics)

                    ncfg = self.config.numerics
                    fp = {k: {f: round(float(v), 9)
                              for f, v in d.items()}
                          for k, d in jax.device_get(_numerics.fingerprint(
                              state.params, depth=ncfg.depth,
                              chunks=ncfg.chunks)).items()}
                    ttrace.emit_event({"event": "numerics_fingerprint",
                                       "step": step, "epoch": epoch,
                                       "reason": "remesh_restore",
                                       "fp": fp})
                self.transitions.append(
                    EpochTransition(epoch=epoch, step=step,
                                    n_devices=len(devices),
                                    stripe=(rank, size),
                                    mesh=mesh_cfg.nontrivial_axes()))
                m_remesh.inc()
                m_epoch.set(epoch)
                m_members.set(size)
                remesh_span.meta.update(n_devices=len(devices), step=step)
                remesh_cm.__exit__(None, None, None)
                remesh_phase.__exit__(None, None, None)
                m_remesh_t.observe(remesh_span.duration_s)
                flight.record({"event": "mesh_formed", "epoch": epoch,
                               "n_devices": len(devices), "step": step,
                               "stripe": [rank, size]})
                if self.verbose:
                    log_json({"event": "mesh_formed", "epoch": epoch,
                              "n_devices": len(devices), "step": step,
                              "mesh": self.transitions[-1].mesh,
                              "stripe_rank": rank, "stripe_size": size})

                # Per-mesh prefetcher over the long-lived raw iterator:
                # overlaps host batch production with device steps, and its
                # queue depth is the flow signal heartbeats carry to the
                # coordinator (successor of the reference's reserved
                # FlowFeedback, proto :73-75). Rebuilt each epoch because
                # shard_batch's placement is mesh-specific.
                prefetch = Prefetcher(source_iter, trainer.shard_batch,
                                      depth=cfg.data.prefetch)
                # First step on a fresh mesh pays the XLA retrace/compile;
                # charge it to "compile", not "step", like the plain loop.
                first_step_on_mesh = True
                try:
                    while (step < num_steps and not self._remesh_due()
                           and not self._stop.is_set()):
                        if (self._agent is not None
                                and self._agent.fatal is not None):
                            # Our exclusive name was taken over during a
                            # lease lapse: the namespace belongs to a live
                            # successor now. Do NOT save — that would
                            # clobber its checkpoints.
                            raise RuntimeError(
                                f"worker fenced out: {self._agent.fatal}")
                        if self._safe_paused():
                            m_safe_paused.set(1)
                            m_safe_pauses.inc()
                            time.sleep(0.05)
                            continue
                        m_safe_paused.set(0)
                        batch = next(prefetch)
                        with goodput.get_ledger().phase(
                                "compile" if first_step_on_mesh
                                else "step"):
                            state, metrics = trainer.step(state, batch)
                            loss = float(jax.device_get(metrics["loss"]))
                        self.ckpt.note_state(state)
                        first_step_on_mesh = False
                        losses.append(loss)
                        step += 1
                        m_steps.inc()
                        m_last_step.set(time.time())
                        m_loss.set(loss)
                        if self._agent is not None:
                            self._agent.report(step, loss,
                                               flow=prefetch.depth())
                finally:
                    # Re-meshing forfeits batches already pulled off the
                    # source but not yet trained on (queue + in-flight) —
                    # accounted here, never silent.
                    dropped = prefetch.close()
                    if dropped and self.verbose:
                        log_json({"event": "remesh_dropped_batches",
                                  "n": dropped})
                    if not prefetch.stopped:
                        # Producer is stuck inside next(source_iter); the
                        # iterator is unsafe to share with a successor.
                        # Rebuild the source from scratch next epoch.
                        if hasattr(source, "close"):
                            source.close()
                        source = None
                        source_iter = None

                # drain is implicit (the step above completed); save before
                # tearing the mesh down. The fatal fence applies here too:
                # the loop can exit via its while-condition (remesh/stop/
                # step budget) without re-checking it, and a fenced-out
                # worker writing this save would clobber the live successor
                # that now owns the namespace.
                if self._agent is not None and self._agent.fatal is not None:
                    raise RuntimeError(
                        f"worker fenced out: {self._agent.fatal}")
                final = step >= num_steps or self._stop.is_set()
                streamed = False
                if not final and self._remesh_wire_dtype != "float32":
                    # Mid-run transition: stream the state quantized for
                    # the imminent restore instead of a full-precision
                    # checkpoint commit (~4x fewer DCN bytes per world
                    # change). Falls back to the exact save on refusal.
                    streamed = self._save_remesh_stream(state, step)
                if not streamed:
                    self.ckpt.save(state)
                    self.ckpt.wait()
                if final:
                    if streamed or self._remesh_wire_dtype != "float32":
                        try:  # the transient stream must not outlive the
                            self.ckpt.store.delete(self._stream_key())
                        except Exception:
                            pass  # run it belonged to (best-effort)
                    return state, losses
        finally:
            self.ckpt.close()  # disarms the emergency hook, drains uploads
            if hasattr(self.ckpt.store, "close"):
                self.ckpt.store.close()  # drain + stop the peer-push thread
            if self._cache_server is not None:
                try:
                    self._cache_server.stop()
                except Exception:
                    pass
            if source is not None and hasattr(source, "close"):
                source.close()
            if self._agent is not None:
                self._agent.stop()
