"""Multi-host elastic training: membership changes restart the JAX world.

The reference's headline capability is "any *process* can join anytime"
(``src/master.cc:79-91``, ``src/worker.cc:117-129``) — but its processes only
ever gossiped doubles pairwise. ``training/elastic.py`` realizes elasticity
for the devices of ONE process; this module is the multi-process realization
(VERDICT round 1 item 1): N independent worker processes, each owning its
local TPU chips, form and re-form a single SPMD world as membership changes.

Why checkpoint-restart with a *supervisor per host*, not an in-process
re-initialize: JAX's world is fixed at ``jax.distributed.initialize``
(SURVEY §7 hard part (a)), and — measured here, not assumed — when a member
dies mid-step the survivors either get hard-terminated by the distributed
runtime's error propagation (default) or, with ``jax_enable_recoverability``,
block forever inside the gloo/ICI collective with no catchable error. A
Python thread wedged in a collective cannot be recovered in-process. So each
host runs:

    supervisor (this module, pure Python, no JAX state)
        owns the WorkerAgent: registration under a run-scoped tag, lease
        heartbeats, membership snapshots from the native coordinator
    inner trainer (subprocess, one per *generation* of the world)
        jax.distributed world over the current member set; jitted step;
        sharded checkpoints on the shared data plane

Lifecycle per generation:

    form        supervisors wait for a *stable* view of tagged peers;
                ranks are ascending worker-id order
    rendezvous  rank 0's supervisor spawns its inner first; the inner binds
                a fresh coordination-service port and reports it; the
                supervisor publishes {generation, member ids, address} as
                one JSON value on the data plane (the same store that
                carries shards and checkpoints). Follower supervisors poll
                until the published ids match their own stable view —
                exact agreement, no port arithmetic, no split-brain joins.
    run         inner: initialize → Mesh over all global devices → step
                loop. Every step each inner all-gathers a tiny drain flag,
                so every process leaves the loop at the SAME step (a lone
                early exit would wedge the others' collectives). Periodic
                sharded checkpoints bound crash loss.
    drain       on a membership change that *grows* the set, supervisors
                send "drain" on the inner's stdin; inners agree via the
                flag allgather, finish the step, save a sharded checkpoint
                (process 0 commits), and exit cleanly.
    kill        on a membership change that *loses* a member, the world is
                already broken — no collective (not even the drain
                agreement or the checkpoint barrier) can complete. The
                supervisor grants a short grace, then SIGKILLs the wedged
                inner. Steps since the last committed checkpoint are lost:
                that is the fault-tolerance contract, and the COMMIT marker
                guarantees the loss is to a *consistent* step.
    resume      re-form with the new membership; the next inner restores
                the latest committed checkpoint into the new world's
                shardings (restore-time resharding moves only the byte
                ranges each host needs) and continues.

Joins and crashes are thus symmetric at the membership level — exactly the
reference's birth-registration elasticity — while the gradient path stays
synchronous SPMD with zero bytes on the control plane.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from serverless_learn_tpu.config import (ExperimentConfig,
                                          UnsatisfiableMeshError, scale_mesh)
from serverless_learn_tpu.control.gossip import make_membership_agent
from serverless_learn_tpu.training.checkpoint import (
    Checkpointer, LocalStore, ShardServerStore)
from serverless_learn_tpu.utils.metrics import log_json

# Registration-name tag for multi-host elastic participants. Distinct from
# multihost.MH_TAG (fixed-size bootstrap) so the two rendezvous protocols
# never rank each other's processes.
EMH_TAG = "emh!"


def store_spec(store) -> dict:
    """Serializable description of a checkpoint/rendezvous store, for
    handing to the inner subprocess."""
    if isinstance(store, ShardServerStore):
        return {"kind": "shard", "addr": store.addr}
    if isinstance(store, LocalStore):
        return {"kind": "local", "root": store.root}
    raise TypeError(f"unsupported store {type(store).__name__}")


def store_from_spec(spec: dict):
    if spec["kind"] == "shard":
        return ShardServerStore(spec["addr"])
    if spec["kind"] == "local":
        return LocalStore(spec["root"])
    raise ValueError(f"unknown store kind {spec['kind']!r}")


@dataclass
class Generation:
    """One formed world, as observed by this host's supervisor."""

    gen: int
    world: int
    rank: int
    start_step: int = -1
    end_step: int = -1
    status: str = "formed"  # formed | complete | remesh | killed | error
    mesh: Optional[dict] = None  # axis sizes the inner actually formed


# ---------------------------------------------------------------------------
# Supervisor (one per host)
# ---------------------------------------------------------------------------


class ElasticHostSupervisor:
    """Keeps one host participating in an elastic multi-host run."""

    def __init__(
        self,
        config: ExperimentConfig,
        store,
        coordinator_addr: str,
        run_name: str = "run",
        label: Optional[str] = None,
        advertise_host: str = "127.0.0.1",
        n_chips: Optional[int] = None,
        min_hosts: int = 1,
        form_timeout_s: float = 120.0,
        init_timeout_s: float = 30.0,
        drain_timeout_s: float = 120.0,
        kill_grace_s: float = 5.0,
        inner_env: Optional[dict] = None,
        verbose: bool = False,
    ):
        self.config = config
        self.store = store
        self.run_name = run_name
        self.min_hosts = min_hosts
        self.form_timeout_s = form_timeout_s
        self.init_timeout_s = init_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.kill_grace_s = kill_grace_s
        self.inner_env = inner_env
        self.verbose = verbose
        self.advertise_host = advertise_host
        self.generations: List[Generation] = []
        # step -> loss across all generations; a crash-restart re-records
        # the replayed steps (last write wins), so the series is the run's
        # actual training trajectory.
        self.step_losses: dict = {}
        self._membership_changed = threading.Event()
        label = label or f"{socket.gethostname()}-{os.getpid()}"
        self._tag = f"{EMH_TAG}{run_name}/"
        # Membership plane per config.membership.mode (SWIM gossip or the
        # classic master-heartbeat fallback) — round 11.
        self.agent = make_membership_agent(
            config, coordinator_addr, f"{advertise_host}:0",
            name=self._tag + label,
            n_chips=n_chips if n_chips is not None else 1,
            on_epoch_change=lambda e, p: self._membership_changed.set())
        self._last_gen = 0

    # -- membership --------------------------------------------------------

    def _tagged_ids(self, peers) -> List[int]:
        return sorted(p.worker_id for p in peers
                      if p.name.startswith(self._tag))

    def _current_ids(self) -> List[int]:
        return self._tagged_ids(self.agent.snapshot()[1])

    def _tagged_view(self) -> tuple:
        """(sorted ids, {id: chips}) for tagged peers — from ONE membership
        snapshot, so the pair is always internally consistent."""
        peers = [p for p in self.agent.snapshot()[1]
                 if p.name.startswith(self._tag)]
        return (sorted(p.worker_id for p in peers),
                {p.worker_id: max(1, p.n_chips) for p in peers})

    def _active_ids(self, ids: List[int],
                    chips: dict) -> Optional[List[int]]:
        """The subset of a stable membership that actually forms the world.

        The configured mesh makes some chip totals unusable (model axes
        need a divisible device total, fsdp has a memory floor — config.
        scale_mesh). Satisfiability depends only on the chip TOTAL, so this
        is a small subset-sum: every supervisor deterministically picks the
        member subset with the LARGEST satisfiable chip total (at least
        ``min_hosts`` members), which handles heterogeneous chip counts —
        e.g. hosts with [1, 2, 2] chips under tp=2 form the 4-chip world
        from the two 2-chip hosts, with the 1-chip host standing by (a
        plain id-prefix scan would find every prefix total odd and
        wrongly declare the membership unsatisfiable). Ties prefer
        lower-id members (join order). Spares re-join at the next
        membership change. Returns None when no subset works.

        ``chips`` MUST come from the same snapshot as ``ids`` (use
        ``_tagged_view``): mixing a stale id list with fresh chip counts
        would let two supervisors derive different active sets from "the
        same" view.
        """
        grand = sum(chips[i] for i in ids)
        need = max(self.min_hosts, 1)
        n = len(ids)
        # Layered reachability: reach[i][t] is a bitmask of member COUNTS
        # achievable with chip total t using only the first i members. The
        # layers are kept (not a rolling 1-D array with backpointers: a
        # single take[] table gets overwritten by later members and its
        # chains then mix DP generations — that produced duplicated
        # members / wrong totals) so the backtrack below is exact.
        reach = [[0] * (grand + 1) for _ in range(n + 1)]
        reach[0][0] = 1  # zero members, zero chips
        for i in range(n):
            c = chips[ids[i]]
            prev, cur = reach[i], reach[i + 1]
            for t in range(grand + 1):
                m = prev[t]
                if t >= c:
                    m |= prev[t - c] << 1
                cur[t] = m
        for total in range(grand, 0, -1):
            counts = reach[n][total] >> need
            if not counts:
                continue
            try:
                scale_mesh(self.config.mesh, total)
            except UnsatisfiableMeshError:
                continue
            # Largest achievable member count (use more of the fleet), then
            # backtrack preferring to EXCLUDE high-id members when both
            # choices remain feasible -> lower ids (join order) win ties.
            k = counts.bit_length() - 1 + need
            members, t = [], total
            for i in range(n, 0, -1):
                if (reach[i - 1][t] >> k) & 1:
                    continue  # droppable without losing feasibility
                members.append(ids[i - 1])
                t -= chips[ids[i - 1]]
                k -= 1
            assert t == 0 and k == 0, (ids, chips, total, members)
            return sorted(members)
        return None

    def _stable_view(self, deadline: float) -> tuple:
        """Wait until the set of tagged peers (incl. us) holds still for a
        stability window; returns (ids, {id: chips}) from the final
        snapshot. Untagged workers sharing the coordinator churn the epoch
        but not this view."""
        stability_s = max(2.0 * self.agent.interval, 0.3)
        view: Optional[List[int]] = None
        chips: dict = {}
        since = 0.0
        while True:
            ids, chips = self._tagged_view()
            me = self.agent.worker_id
            now = time.time()
            if me in ids and len(ids) >= self.min_hosts:
                if ids != view:
                    view, since = ids, now
                elif now - since >= stability_s:
                    return ids, chips
            else:
                view = None
            if now > deadline:
                raise TimeoutError(
                    f"no stable membership within {self.form_timeout_s}s "
                    f"(last view {view}, me {me})")
            time.sleep(0.05)

    # -- rendezvous over the data plane -------------------------------------

    def _form_key(self) -> str:
        return f"emh-{self.run_name}/FORM"

    def _read_form(self) -> Optional[dict]:
        try:
            return json.loads(self.store.get(self._form_key()))
        except (IOError, OSError, ValueError):
            return None

    def _committed_step(self) -> int:
        """Latest committed checkpoint step, observed via the data plane —
        how standby hosts (and the completion fast path) track a world they
        are not part of."""
        try:
            meta = json.loads(self.store.get(f"emh-{self.run_name}/LATEST"))
            return int(meta["step"])
        except (IOError, OSError, ValueError, KeyError):
            return -1

    def _standby(self, deadline: Optional[float], why: str) -> str:
        """Wait out a world this host is not part of.

        deadline=None: an active world is running without us (hot spare) —
        wait indefinitely for membership churn or run completion. With a
        deadline: NO satisfiable world exists; if membership still hasn't
        produced one by the deadline, raise (loudly — never fall back to a
        mesh the config doesn't describe).
        """
        if self.verbose:
            log_json({"event": "standby", "why": why,
                      "rank0_world": None if deadline is None else "none"})
        while True:
            if self._committed_step() >= self.config.train.num_steps:
                return "complete"
            # Event-wait gives instant membership wakeups while the LATEST
            # store read (a network RPC on ShardServerStore) stays at 1 Hz —
            # a spare can idle for hours without hammering the data plane.
            if self._membership_changed.wait(timeout=1.0):
                self._membership_changed.clear()
                return "standby"
            if deadline is not None and time.time() > deadline:
                raise UnsatisfiableMeshError(
                    f"no satisfiable world within {self.form_timeout_s}s: "
                    f"{why}")

    # -- inner process ------------------------------------------------------

    def _spawn_inner(self, gen: int, rank: int, world: int,
                     addr: Optional[str]) -> "_InnerHandle":
        args = [
            sys.executable, "-u", "-m",
            "serverless_learn_tpu.training.elastic_multihost",
            "--gen", str(gen), "--rank", str(rank), "--world", str(world),
            "--run-name", self.run_name,
            "--store", json.dumps(store_spec(self.store)),
            "--config", self.config.to_json(),
            "--advertise-host", self.advertise_host,
            "--init-timeout-s", str(self.init_timeout_s),
        ]
        if addr:
            args += ["--addr", addr]
        env = dict(os.environ)
        if self.inner_env:
            env.update(self.inner_env)
        proc = subprocess.Popen(args, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE, env=env, text=True)
        return _InnerHandle(proc, verbose=self.verbose, rank=rank)

    # -- main loop ----------------------------------------------------------

    def run(self, max_consecutive_failures: int = 8):
        """Participate until the run completes ``config.train.num_steps``
        (as observed via the shared checkpoint) or formation times out.

        ``max_consecutive_failures`` bounds deterministic-failure loops
        (bad config, broken store): generations that neither trained nor
        followed a real membership change count against it; any productive
        generation resets it.
        """
        self.agent.start()
        failures = 0
        try:
            while True:
                status = self._one_generation()
                if status == "complete":
                    return self.generations
                if status in ("remesh", "killed", "standby"):
                    failures = 0  # real membership churn / waiting, not a fault
                else:
                    failures += 1
                    if failures >= max_consecutive_failures:
                        raise RuntimeError(
                            f"{failures} consecutive failed world "
                            f"formations (last status {status!r}); giving "
                            "up — check the inner trainer's stderr")
                    time.sleep(min(0.5 * failures, 5.0))
        finally:
            self.agent.stop()

    def _one_generation(self) -> str:
        deadline = time.time() + self.form_timeout_s
        self._membership_changed.clear()
        if self._committed_step() >= self.config.train.num_steps:
            return "complete"  # run finished while we were between worlds
        ids, chips = self._stable_view(deadline)
        active = self._active_ids(ids, chips)
        if active is None:
            return self._standby(
                deadline, f"membership {ids} (chips {chips}) "
                          f"cannot host mesh {self.config.mesh}")
        if self.agent.worker_id not in active:
            return self._standby(None, f"hot spare behind active {active}")
        rank = active.index(self.agent.worker_id)
        world = len(active)

        inner: Optional[_InnerHandle] = None
        if rank == 0:
            prev = self._read_form()
            gen = max(prev["gen"] if prev else 0, self._last_gen) + 1
            inner = self._spawn_inner(gen, 0, world, addr=None)
            addr = inner.wait_event("service_addr",
                                    timeout=self.init_timeout_s)
            if addr is None:
                inner.kill()
                return "retry"
            self.store.put(self._form_key(), json.dumps(
                {"gen": gen, "ids": active, "addr": addr["addr"]}).encode())
        else:
            # Follower: wait for a FORM that matches our computed active set
            # (every supervisor derives the same one from the same stable
            # view + registered chip counts).
            form = None
            while time.time() < deadline:
                form = self._read_form()
                if (form and form["ids"] == active
                        and form["gen"] > self._last_gen):
                    break
                if self._current_ids() != ids:
                    return "retry"  # view moved; re-form
                time.sleep(0.05)
                form = None
            if form is None:
                return "retry"
            gen = form["gen"]
            inner = self._spawn_inner(gen, rank, world, addr=form["addr"])

        self._last_gen = gen
        g = Generation(gen=gen, world=world, rank=rank)
        self.generations.append(g)
        status = self._monitor(inner, g, ids, active)
        g.status = status
        if self.verbose:
            log_json({"event": "generation_done", "gen": gen, "rank": rank,
                      "world": world, "status": status,
                      "start_step": g.start_step, "end_step": g.end_step})
        return status

    def _monitor(self, inner: "_InnerHandle", g: Generation,
                 ids: List[int], active: List[int]) -> str:
        """Relay inner progress into heartbeats; react to membership
        changes; decide drain-vs-kill. Returns the generation's outcome.

        ``ids`` is the full stable view the world was formed from; ``active``
        is the subset actually IN the world. Only an active member's loss
        breaks collectives (-> kill); spare churn either offers growth
        (join -> drain) or is irrelevant (spare departure -> ignore).
        """
        drain_sent = False
        kill_at: Optional[float] = None
        while True:
            ev = inner.poll_event(timeout=0.1)
            if ev is not None:
                if ev["event"] == "inner_up":
                    g.start_step = ev["step"]
                    g.mesh = ev.get("mesh")
                    if self.verbose:
                        log_json({"event": "world_formed", "gen": g.gen,
                                  "world": g.world, "rank": g.rank,
                                  "step": ev["step"], "mesh": ev.get("mesh"),
                                  "devices": ev.get("devices")})
                elif ev["event"] == "step":
                    self.step_losses[ev["step"]] = ev.get("loss", 0.0)
                    self.agent.report(ev["step"], ev.get("loss", 0.0),
                                      flow=ev.get("flow", 0))
                elif ev["event"] == "inner_done":
                    g.end_step = ev["step"]
            if inner.exited():
                # Join the reader thread and drain the tail of the event
                # queue BEFORE judging the outcome: the process can exit
                # before its final stdout lines are parsed, and dropping
                # them would misread a clean drain as an error (and lose
                # the last step/loss records).
                inner.wait()
                while True:
                    tail = inner.poll_event()
                    if tail is None:
                        break
                    if tail["event"] == "inner_up":
                        g.start_step = tail["step"]
                        g.mesh = tail.get("mesh")
                    elif tail["event"] == "step":
                        self.step_losses[tail["step"]] = tail.get("loss", 0.0)
                rc = inner.returncode()
                done = inner.last_done()
                if done is not None:
                    g.end_step = done["step"]
                if rc == 0 and done is not None:
                    return done["status"]  # "complete" | "remesh"
                return "error"
            if self._membership_changed.is_set():
                self._membership_changed.clear()
                cur, cur_chips = self._tagged_view()
                if cur != ids:
                    lost_active = set(active) - set(cur)
                    would_be = self._active_ids(cur, cur_chips)
                    if lost_active:
                        # World broken: no collective (not even the drain
                        # agreement) can complete; the inner is wedged or
                        # about to be. Short grace, then kill — shortening
                        # any longer drain deadline a prior join set.
                        if not drain_sent:
                            inner.send_drain()
                            drain_sent = True
                        ka = time.time() + self.kill_grace_s
                        kill_at = ka if kill_at is None else min(kill_at, ka)
                    elif would_be is not None and would_be != active:
                        # Growth (or reshuffle) opportunity: the new
                        # membership forms a DIFFERENT active set. Drain
                        # cleanly and re-form to absorb it.
                        if not drain_sent:
                            inner.send_drain()
                            drain_sent = True
                        if kill_at is None:
                            kill_at = time.time() + self.drain_timeout_s
                    # Otherwise (spare-only churn, or a joiner that cannot
                    # change the active set — e.g. an odd chip that keeps
                    # the same satisfiable prefix): don't restart a healthy
                    # world for a membership change that alters nothing.
                    ids = cur
            if kill_at is not None and time.time() > kill_at:
                inner.kill()
                inner.wait()
                done = inner.last_done()
                if done is not None:
                    g.end_step = done["step"]
                return "killed"


class _InnerHandle:
    """Non-blocking line-event reader + control channel for one inner."""

    def __init__(self, proc: subprocess.Popen, verbose: bool, rank: int):
        self.proc = proc
        self.verbose = verbose
        self.rank = rank
        self._events: List[dict] = []
        self._done: Optional[dict] = None
        self._lock = threading.Lock()
        self._cursor = 0
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self):
        try:
            for line in self.proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # stray non-JSON output
                if not (isinstance(ev, dict) and "event" in ev):
                    # Native libraries under the inner occasionally write to
                    # fd 1; a bare JSON scalar ("1") parses fine and then
                    # crashed _monitor's ev["event"] (observed: supervisor
                    # death -> partner's formation timeout). Only dicts
                    # carrying an "event" tag are protocol messages.
                    continue
                with self._lock:
                    self._events.append(ev)
                    if ev.get("event") == "inner_done":
                        self._done = ev
        except (IOError, OSError, ValueError):
            pass

    def poll_event(self, timeout: float = 0.0) -> Optional[dict]:
        deadline = time.time() + timeout
        while True:
            with self._lock:
                if self._cursor < len(self._events):
                    ev = self._events[self._cursor]
                    self._cursor += 1
                    return ev
            if time.time() >= deadline:
                return None
            time.sleep(0.02)

    def wait_event(self, name: str, timeout: float) -> Optional[dict]:
        deadline = time.time() + timeout
        seen = 0
        while time.time() < deadline:
            with self._lock:
                while seen < len(self._events):
                    if self._events[seen].get("event") == name:
                        return self._events[seen]
                    seen += 1
            if self.proc.poll() is not None:
                return None
            time.sleep(0.02)
        return None

    def send_drain(self):
        try:
            self.proc.stdin.write("drain\n")
            self.proc.stdin.flush()
        except (IOError, OSError, ValueError):
            pass  # inner already gone

    def exited(self) -> bool:
        return self.proc.poll() is not None

    def returncode(self):
        return self.proc.returncode

    def wait(self, timeout: Optional[float] = None):
        self.proc.wait(timeout=timeout)
        self._reader.join(timeout=2.0)

    def kill(self):
        try:
            self.proc.kill()
        except OSError:
            pass

    def last_done(self) -> Optional[dict]:
        with self._lock:
            return self._done


# ---------------------------------------------------------------------------
# Inner trainer (one process per generation of the world)
# ---------------------------------------------------------------------------


def _emit(ev: dict):
    sys.stdout.write(json.dumps(ev) + "\n")
    sys.stdout.flush()


def inner_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--gen", type=int, required=True)
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--world", type=int, required=True)
    p.add_argument("--addr", default=None)
    p.add_argument("--run-name", required=True)
    p.add_argument("--store", required=True)
    p.add_argument("--config", required=True)
    p.add_argument("--advertise-host", default="127.0.0.1")
    p.add_argument("--init-timeout-s", type=float, default=30.0)
    args = p.parse_args(argv)

    # Honor an explicit platform request even though the image pre-imports
    # jax against the TPU tunnel (see tests/conftest.py for the same dance).
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    addr = args.addr
    if args.rank == 0 and addr is None:
        with socket.socket() as s:
            s.bind((args.advertise_host, 0))
            port = s.getsockname()[1]
        addr = f"{args.advertise_host}:{port}"
        _emit({"event": "service_addr", "addr": addr})

    jax.distributed.initialize(
        coordinator_address=addr, num_processes=args.world,
        process_id=args.rank,
        initialization_timeout=int(args.init_timeout_s),
        heartbeat_timeout_seconds=10)

    import numpy as np
    from jax.experimental import multihost_utils

    from serverless_learn_tpu.data.datasets import Prefetcher
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.training.loop import make_source
    from serverless_learn_tpu.training.train_step import build_trainer

    config = ExperimentConfig.from_json(args.config)
    store = store_from_spec(json.loads(args.store))
    ckpt = Checkpointer(store, name=f"emh-{args.run_name}",
                        async_save=False, sharded=True)

    # Honor the configured mesh at every world size: model axes fixed, fsdp
    # floor respected, dp stretched (config.scale_mesh). The supervisor only
    # forms worlds it believes satisfiable; this raise is the backstop for a
    # supervisor whose chip accounting was wrong (loud, not dp-fallback).
    mesh_cfg = scale_mesh(config.mesh, len(jax.devices()))
    cfg = config.override(mesh=mesh_cfg)
    mesh = make_mesh(mesh_cfg, devices=list(jax.devices()))
    trainer = build_trainer(cfg, mesh=mesh)
    if ckpt.latest_step() is not None:
        state = ckpt.restore(trainer.abstract_state(),
                             shardings=trainer.state_shardings)
    else:
        state = trainer.init()
    step = int(jax.device_get(state.step))
    _emit({"event": "inner_up", "gen": args.gen, "step": step,
           "rank": args.rank, "world": args.world,
           "devices": len(jax.devices()),
           "mesh": mesh_cfg.nontrivial_axes()})

    # Drain requests arrive on stdin from the supervisor.
    drain = threading.Event()

    def watch_stdin():
        for line in sys.stdin:
            if line.strip() == "drain":
                drain.set()

    threading.Thread(target=watch_stdin, daemon=True).start()

    num_steps = cfg.train.num_steps
    ckpt_every = cfg.train.checkpoint_every
    source = make_source(cfg, trainer, dp_rank=args.rank, dp_size=args.world,
                         start_step=step)
    prefetch = Prefetcher(iter(source), trainer.shard_batch,
                          depth=cfg.data.prefetch)
    status = "complete"
    # Test pacing knob: slows the step loop so process-level churn tests
    # can schedule joins/kills at meaningful points. Never set in production.
    step_delay = float(os.environ.get("SLT_STEP_DELAY_S", "0") or 0)
    try:
        while step < num_steps:
            # Every process must leave this loop at the same step: agree on
            # the drain flag with a tiny allgather before each step.
            flags = multihost_utils.process_allgather(
                np.array([1 if drain.is_set() else 0], np.int32))
            if int(np.max(flags)) > 0:
                status = "remesh"
                break
            batch = next(prefetch)
            state, metrics = trainer.step(state, batch)
            loss = float(jax.device_get(metrics["loss"]))
            step += 1
            _emit({"event": "step", "step": step, "loss": loss,
                   "flow": prefetch.depth()})
            if ckpt_every and step % ckpt_every == 0 and step < num_steps:
                ckpt.save_sharded(state)
            if step_delay:
                time.sleep(step_delay)
    finally:
        prefetch.close()
        if hasattr(source, "close"):
            source.close()
    ckpt.save_sharded(state)
    _emit({"event": "inner_done", "step": step, "status": status,
           "gen": args.gen})
    # Skip jax.distributed.shutdown(): with a clean exit the coordination
    # service notices the disconnect, and a wedged shutdown barrier (peer
    # already gone) would turn a clean drain into a supervisor kill.
    return 0


if __name__ == "__main__":
    sys.exit(inner_main())
