"""`slt herd`: a vmapped many-client DiLoCo harness on virtual time.

ROADMAP item "thousand-worker heterogeneous training scenarios via
vmapped clients" (DrJAX, arXiv:2403.07128). The chaos simulator
(``chaos/sim.py``) runs the REAL gossip membership protocol at hundreds
of nodes but modeled training as a scalar progress counter — none of the
straggler/churn/quorum claims had ever been validated with real model
updates in the loop. This module closes that gap:

* **N real clients, one process** — every simulated DiLoCo worker holds
  real (tiny-model) parameters and runs real inner SGD steps. All N
  workers live in ONE stacked pytree with a leading client axis and the
  whole inner phase is a single ``jax.vmap``-of-``lax.scan`` jit — the
  DrJAX trick that makes 256–1000 clients cost a few milliseconds per
  round on CPU instead of N processes.
* **non-IID shards** — worker ``i`` draws inputs from a shard-shifted
  distribution (``x ~ N(shift_i, 1)``, shift scale ``shard_skew``) while
  the label function (a fixed random projection) is SHARED, so the global
  task is learnable but per-worker gradients are genuinely heterogeneous
  (covariate + label skew).
* **speed skew + churn on the event heap** — compute is uniform inside
  the vmap; heterogeneity is temporal: worker ``i``'s delta *arrives* at
  ``round_start + inner_steps * step_time_i`` on the simulator's event
  heap, where ``step_time_i`` is seeded-lognormal. Kills, restarts,
  partitions and pauses come from the existing FaultPlan DSL and act on
  the same hosts that run the REAL SWIM gossip nodes — membership
  agreement is asserted with training in the loop.
* **participation policy** — the leader (min live id, exactly as
  ``diloco_dcn``) closes the round once ``quorum_fraction`` of its OWN
  gossip view has delivered, else at ``round_timeout_s``. Late deltas
  are dropped or staleness-discounted per ``late_policy`` — the same
  policy surface ``LocalSGDConfig`` exposes for real islands.
* **delta quarantine** — per-worker delta stats come from
  ``telemetry/numerics.tree_stats`` vmapped over the client axis:
  non-finite deltas are ALWAYS quarantined, norm outliers
  (median + ``outlier_factor`` × MAD over the round's finite deltas)
  are quarantined too, each emitting a ``diloco.delta_quarantined``
  alert event that ``slt doctor`` names per worker. A poisoned worker
  can therefore never fold NaNs into the anchor.

Everything is seeded and runs on virtual time: two runs with the same
(spec, plan, seed) produce byte-identical reports, which is what turns
"256 workers, kill 20% mid-round, quorum 0.8" into a cheap CI assertion
instead of a cluster rental.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from serverless_learn_tpu.chaos.plan import FaultPlan
from serverless_learn_tpu.chaos.sim import SIM_EPOCH, ChaosSim
from serverless_learn_tpu.control.gossip import GossipConfig

# How often an arrival blocked by a partition re-checks reachability.
_RETRY_S = 0.25


@dataclass(frozen=True)
class HerdSpec:
    """One herd scenario. Compute-shaping fields (model/optimizer/sizes)
    key the jit cache; schedule fields (quorum, timeouts, chaos knobs)
    are plain host logic and never recompile."""

    n_workers: int = 256
    rounds: int = 5
    inner_steps: int = 4
    batch_size: int = 8
    features: Tuple[int, ...] = (32,)
    num_classes: int = 10
    input_dim: int = 64
    inner_lr: float = 0.05
    inner_momentum: float = 0.9
    outer_lr: float = 1.0
    outer_momentum: float = 0.0
    # heterogeneity
    shard_skew: float = 1.0      # non-IID shard shift scale (0 = IID)
    speed_skew: float = 0.35     # lognormal sigma of per-worker step time
    base_step_s: float = 0.05    # median virtual seconds per inner step
    # participation policy (mirrors LocalSGDConfig round-19 fields)
    quorum_fraction: float = 1.0
    round_timeout_s: float = 2.0
    late_policy: str = "drop"    # "drop" | "discount"
    staleness_discount: float = 0.25
    # delta quarantine gate
    outlier_factor: float = 12.0
    gate_min_peers: int = 4
    # chaos knobs: scale worker poison_worker's round-poison_round delta
    # by NaN (the quarantine acceptance drill) or by scale_factor (the
    # norm-outlier drill). -1 = off.
    poison_worker: int = -1
    poison_round: int = -1
    scale_worker: int = -1
    scale_round: int = -1
    scale_factor: float = 1000.0
    # Wire codec (round 20, training/wire_codec.py): per-worker deltas
    # and the anchor broadcast ride a simulated blockwise-quantized wire
    # — the quantizer runs UNDER the client vmap, and error_feedback
    # carries each worker's residual into its next round's delta, the
    # property the int8-vs-f32 A/B (run_wire_ab) exists to prove.
    wire_dtype: str = "float32"  # float32 | int8 | fp8
    wire_block: int = 128
    error_feedback: bool = True
    bootstrap_s: float = 2.0     # gossip settle time before round 0
    # Start from an ESTABLISHED membership (every node knows every
    # node, the state of a fleet that has been up for a while) instead
    # of a cold-boot join storm. At 256+ nodes, cold-boot dissemination
    # alone takes ~130 protocol periods — far past the sim's post-fault
    # re-convergence bound — and it is not what herd scenarios test:
    # the interesting churn is kills/partitions DURING training, which
    # SWIM still detects and disseminates live. False = cold boot.
    established: bool = True

    def validate(self):
        if self.n_workers < 2:
            raise ValueError("herd needs >= 2 workers")
        if not 0.0 < self.quorum_fraction <= 1.0:
            raise ValueError("quorum_fraction must be in (0, 1]")
        if self.late_policy not in ("drop", "discount"):
            raise ValueError("late_policy must be 'drop' or 'discount'")
        if self.rounds < 1 or self.inner_steps < 1:
            raise ValueError("rounds and inner_steps must be >= 1")
        from serverless_learn_tpu.training import wire_codec

        wire_codec.normalize_dtype(self.wire_dtype)  # ValueError if bad
        if self.wire_block < 1:
            raise ValueError("wire_block must be >= 1")


# -- compiled kernels ---------------------------------------------------------
#
# Cached by compute shape only (not seed / schedule): a determinism pair
# or a quorum-A/B comparison reuses one compile. Seed-dependent values
# (base PRNG key, shard shifts, label projection) enter as ARGUMENTS.

_KERNEL_CACHE: Dict[tuple, dict] = {}


def _kernel_key(spec: HerdSpec) -> tuple:
    return (spec.n_workers, spec.inner_steps, spec.batch_size,
            tuple(spec.features), spec.num_classes, spec.input_dim,
            spec.inner_lr, spec.inner_momentum,
            spec.outer_lr, spec.outer_momentum,
            spec.wire_dtype, spec.wire_block, spec.error_feedback)


def _kernels(spec: HerdSpec) -> dict:
    key = _kernel_key(spec)
    hit = _KERNEL_CACHE.get(key)
    if hit is not None:
        return hit

    import jax
    import jax.numpy as jnp
    import optax

    from serverless_learn_tpu.models.registry import get_model
    from serverless_learn_tpu.telemetry.numerics import (global_norm,
                                                         tree_stats)
    from serverless_learn_tpu.training import wire_codec

    n, steps, batch = spec.n_workers, spec.inner_steps, spec.batch_size
    dim, classes = spec.input_dim, spec.num_classes
    wire = wire_codec.require_supported(spec.wire_dtype)
    quantized = wire != "float32"
    ef = spec.error_feedback

    def fq(tree):
        return wire_codec.tree_fake_quantize(tree, wire, spec.wire_block)
    bundle = get_model("mlp_mnist", features=tuple(spec.features),
                       num_classes=classes, image_shape=(dim, 1, 1))
    tx = optax.sgd(spec.inner_lr, momentum=spec.inner_momentum)
    olr, omu = spec.outer_lr, spec.outer_momentum
    tmap = jax.tree_util.tree_map

    def _bcast(mask, leaf):
        return mask.reshape(mask.shape + (1,) * (leaf.ndim - 1))

    def init(seed: int):
        kp = jax.random.PRNGKey(seed)
        params = bundle.module.init(kp, jnp.zeros((batch, dim)))["params"]
        params = tmap(lambda p: p.astype(jnp.float32), params)
        trace = tmap(jnp.zeros_like, params)
        opt = jax.vmap(tx.init)(
            tmap(lambda p: jnp.broadcast_to(p[None], (n,) + p.shape),
                 params))
        proj = jax.random.normal(jax.random.fold_in(kp, 7919),
                                 (dim, classes), jnp.float32)
        shifts = spec.shard_skew * jax.random.normal(
            jax.random.fold_in(kp, 104729), (n, dim), jnp.float32)
        return params, trace, opt, proj, shifts, kp

    @jax.jit
    def inner(anchor, opt_states, shifts, proj, base_key, delta_scale,
              alive, reset, round_idx, residual):
        """One round's inner phase for ALL workers: vmap over clients of
        a lax.scan over inner steps. Returns the stacked WIRE deltas —
        what the leader would dequantize, with the quantizer itself run
        under the client vmap — plus the per-worker gate stats (computed
        on the dequantized values, so a bad quantization block trips the
        same quarantine a sick worker would) and the updated per-worker
        error-feedback residual."""

        def per_worker(wid, opt, shift, rst):
            opt = tmap(lambda o: jnp.where(rst, jnp.zeros_like(o), o), opt)

            def body(carry, s):
                params, opt = carry
                kk = jax.random.fold_in(
                    jax.random.fold_in(
                        jax.random.fold_in(base_key, wid), round_idx), s)
                x = jax.random.normal(kk, (batch, dim), jnp.float32) + shift
                y = jnp.argmax(x @ proj, axis=-1).astype(jnp.int32)
                (loss, _), grads = jax.value_and_grad(
                    bundle.loss_fn, has_aux=True)(
                        params, {"image": x, "label": y})
                updates, opt = tx.update(grads, opt, params)
                params = tmap(lambda p, u: (p + u).astype(p.dtype),
                              params, updates)
                return (params, opt), loss

            (params, opt), losses = jax.lax.scan(
                body, (anchor, opt), jnp.arange(steps))
            delta = tmap(lambda a, p: (a - p).astype(jnp.float32),
                         anchor, params)
            return delta, opt, losses.mean()

        deltas, new_opts, mean_loss = jax.vmap(per_worker)(
            jnp.arange(n), opt_states, shifts, reset)
        # Chaos injection AFTER the real compute, BEFORE the wire: a NaN
        # (or huge) scale poisons the delta exactly as a sick worker
        # would, and the gate must catch it downstream.
        deltas = tmap(lambda l: l * _bcast(delta_scale, l), deltas)
        # Dead workers neither trained nor keep this round's opt state.
        new_opts = tmap(lambda nw, old: jnp.where(_bcast(alive, nw),
                                                  nw, old),
                        new_opts, opt_states)
        if quantized:
            # A restarted worker lost its residual carry with the rest
            # of its inner state.
            residual = tmap(lambda r: jnp.where(_bcast(reset, r), 0.0, r),
                            residual)
            send = (tmap(jnp.add, deltas, residual) if ef else deltas)
            wired = jax.vmap(fq)(send)
        else:
            send, wired = deltas, deltas
        stats = jax.vmap(lambda d: tree_stats(d, depth=1))(wired)
        nonfinite = sum(st["nonfinite"] for st in stats.values())
        l2 = jax.vmap(global_norm)(wired)
        if quantized and ef:
            # Absorb this round's quantization error — but never a NaN
            # (a poisoned delta must not poison every later round), and
            # never for a dead worker (it sent nothing).
            ok = alive & (nonfinite == 0)
            residual = tmap(lambda s, w, r: jnp.where(_bcast(ok, s),
                                                      s - w, r),
                            send, wired, residual)
        return wired, new_opts, mean_loss, l2, nonfinite, residual

    @jax.jit
    def outer(anchor, trace, deltas, weights):
        """Weighted-mean delta -> Nesterov outer step (the exact
        formulation diloco_dcn._nesterov_step uses)."""
        wsum = jnp.maximum(weights.sum(), 1e-9)
        # A quarantined NaN delta carries weight 0, but 0 * NaN = NaN —
        # non-finite entries must be zeroed BEFORE the weighted sum or
        # the quarantine is cosmetic.
        grad = tmap(lambda d: jnp.tensordot(
            weights, jnp.where(jnp.isfinite(d), d, 0.0), axes=1) / wsum,
            deltas)
        new_trace = tmap(lambda g, t: g + omu * t, grad, trace)
        new_anchor = tmap(
            lambda a, g, t: (a - olr * (g + omu * t)).astype(a.dtype),
            anchor, grad, new_trace)
        drift = global_norm(tmap(lambda x, y: x - y, new_anchor, anchor))
        return new_anchor, new_trace, drift

    @jax.jit
    def late_apply(anchor, deltas, idx, weight):
        """Stale straggler delta applied as plain discounted SGD on the
        current anchor (momentum deliberately untouched — a stale
        gradient must not steer the trace)."""
        d = tmap(lambda l: l[idx], deltas)
        return tmap(lambda a, x: (a - weight * x).astype(a.dtype),
                    anchor, d)

    @jax.jit
    def wire_anchor(anchor, resid):
        """The leader's anchor broadcast through the same wire: publish
        the quantized anchor (every worker — the leader included — adopts
        the DEQUANTIZED tree, so all islands hold bit-identical anchors),
        with a leader-side error-feedback carry."""
        if not quantized:
            return anchor, resid
        send = tmap(jnp.add, anchor, resid) if ef else anchor
        wired = fq(send)
        new_resid = tmap(jnp.subtract, send, wired) if ef else resid
        return wired, new_resid

    @jax.jit
    def eval_loss(anchor, shifts, proj, base_key):
        """Anchor loss on a fixed mixture batch drawn from EVERY shard —
        the global objective under non-IID data."""
        kk = jax.random.fold_in(base_key, 15485863)
        x = jax.random.normal(kk, (n, 2, dim), jnp.float32) \
            + shifts[:, None, :]
        x = x.reshape(2 * n, dim)
        y = jnp.argmax(x @ proj, axis=-1).astype(jnp.int32)
        loss, _ = bundle.loss_fn(anchor, {"image": x, "label": y})
        return loss

    kit = {"init": init, "inner": inner, "outer": outer,
           "late_apply": late_apply, "eval_loss": eval_loss,
           "wire_anchor": wire_anchor}
    _KERNEL_CACHE[key] = kit
    return kit


# -- the harness --------------------------------------------------------------


@dataclass
class _Round:
    idx: int
    t0: float
    leader: str
    view: Set[str]
    need: int
    closed: bool = False
    delivered: Dict[int, float] = field(default_factory=dict)
    accepted: List[int] = field(default_factory=list)
    quarantined: Dict[int, str] = field(default_factory=dict)
    deltas: object = None          # device [N, ...] tree, freed lazily
    l2: Optional[np.ndarray] = None
    nonfinite: Optional[np.ndarray] = None
    losses: Optional[np.ndarray] = None


class HerdSim(ChaosSim):
    """ChaosSim with the scalar training model replaced by the real
    vmapped DiLoCo herd. Membership, faults, telemetry and invariants
    are inherited — the herd only swaps what "training" means."""

    def __init__(self, spec: HerdSpec, seed: int = 0,
                 plan: Optional[FaultPlan] = None,
                 gossip: Optional[GossipConfig] = None,
                 events_log: Optional[str] = None):
        spec.validate()
        # ping_timeout = period/2 (not the CLI's 0.3x): the simulator
        # ticks every timeout/2, so a lazier direct-ack wait cuts the
        # dominant per-node event rate ~40% at herd scale; detection
        # stays bounded by the same suspicion math.
        super().__init__(
            spec.n_workers, seed=seed, plan=plan,
            gossip=gossip or GossipConfig(protocol_period_s=0.5,
                                          ping_timeout_s=0.25),
            events_log=events_log, round_s=spec.bootstrap_s,
            inner_steps=spec.inner_steps,
            quorum_fraction=spec.quorum_fraction)
        self.spec = spec
        if spec.established:
            from serverless_learn_tpu.control.gossip import ALIVE, Member

            for nid, host in self.hosts.items():
                for other in self.hosts:
                    if other == nid:
                        continue
                    host.node._members[other] = Member(
                        node_id=other, addr=f"sim://{other}",
                        incarnation=0, state=ALIVE, since=0.0,
                        meta={"worker_id": self._widx(other),
                              "n_chips": 1})
        self.k = _kernels(spec)
        (self.anchor, self.trace, self.opt_states, self._proj,
         self._shifts, self._base_key) = self.k["init"](seed)
        # Wire codec state + byte ledger (round 20): per-worker error-
        # feedback residuals ride the same stacked layout as the opt
        # states; the byte ledger prices each round the way the real
        # protocol pays it — one delta PUT per delivery, one anchor PUT
        # plus one GET per live worker.
        import jax
        import jax.numpy as jnp

        from serverless_learn_tpu.training import wire_codec

        self._wire = wire_codec.normalize_dtype(spec.wire_dtype)
        self.residual = jax.tree_util.tree_map(
            lambda p: jnp.zeros((self.n,) + p.shape, jnp.float32),
            self.anchor)
        self.anchor_resid = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), self.anchor)
        self._delta_logical = wire_codec.logical_nbytes(self.anchor)
        self._delta_wire = wire_codec.wire_nbytes(
            self.anchor, self._wire, spec.wire_block)
        # the anchor publish carries params + outer momentum trace
        self._anchor_logical = 2 * self._delta_logical
        self._anchor_wire = 2 * self._delta_wire
        self.wire_logical_bytes = 0
        self.wire_bytes = 0
        # Per-worker virtual step time: seeded lognormal speed skew.
        rng = np.random.default_rng([seed, 0x4E4D])
        self.step_times = spec.base_step_s * np.exp(
            spec.speed_skew * rng.standard_normal(spec.n_workers))
        self.round_idx = 0
        self._cur: Optional[_Round] = None
        self._prev: Optional[_Round] = None
        self._needs_reset: Set[int] = set()
        self._quarantine_firing: Set[int] = set()
        self._quarantine_log: Dict[int, dict] = {}
        self.participation: List[float] = []
        self.round_losses: List[float] = []
        self.round_waits: List[float] = []
        self.late_dropped = 0
        self.late_discounted = 0
        self.skipped_rounds = 0
        self._delivered_ever: Set[int] = set()
        self._init_eval = float(self.k["eval_loss"](
            self.anchor, self._shifts, self._proj, self._base_key))

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _widx(nid: str) -> int:
        return int(nid.split("-")[1])

    def _live_unpaused(self) -> Set[str]:
        return {nid for nid, h in self.hosts.items()
                if h.alive and h.paused_until <= self.now}

    def _leader_view(self) -> Tuple[Optional[str], Set[str]]:
        """Leader = min live id (diloco_dcn's rule); its quorum
        denominator is its OWN gossip view restricted to truly-live —
        the real membership protocol in the loop."""
        live = self._live_unpaused()
        if not live:
            return None, set()
        leader = min(live)
        view = set(self.hosts[leader].node.alive_ids()) & live
        view.add(leader)
        return leader, view

    def _join_initial(self, nid: str):
        if self.spec.established:
            return  # no join storm — membership is pre-seeded
        super()._join_initial(nid)

    def _restart(self, nid: str):
        super()._restart(nid)
        # A restarted worker lost its inner optimizer state; it adopts
        # the current anchor at its next round (params do automatically
        # — they start from the anchor every round).
        self._needs_reset.add(self._widx(nid))

    # -- the training model (replaces ChaosSim's scalar counter) -----------

    def _training_round(self):  # first scheduled by ChaosSim.run
        self._start_round()

    def _start_round(self):
        if self.round_idx >= self.spec.rounds:
            return
        leader, view = self._leader_view()
        if leader is None:
            self._push(self.now + self.spec.round_timeout_s,
                       self._start_round)
            return
        spec = self.spec
        r = self.round_idx
        alive = np.array([self.hosts[self._nid(i)].alive
                          for i in range(self.n)], np.bool_)
        reset = np.array([i in self._needs_reset and alive[i]
                          for i in range(self.n)], np.bool_)
        self._needs_reset -= {i for i in range(self.n) if reset[i]}
        scale = np.ones(self.n, np.float32)
        if spec.scale_worker >= 0 and r == spec.scale_round:
            scale[spec.scale_worker] = spec.scale_factor
        if spec.poison_worker >= 0 and r == spec.poison_round:
            scale[spec.poison_worker] = np.nan
        (deltas, self.opt_states, losses, l2, nonfinite,
         self.residual) = self.k["inner"](
            self.anchor, self.opt_states, self._shifts, self._proj,
            self._base_key, scale, alive, reset, r, self.residual)
        import jax

        losses, l2, nonfinite = (np.asarray(jax.device_get(losses)),
                                 np.asarray(jax.device_get(l2)),
                                 np.asarray(jax.device_get(nonfinite)))
        cur = _Round(idx=r, t0=self.now, leader=leader, view=view,
                     need=max(1, math.ceil(spec.quorum_fraction
                                           * len(view) - 1e-9)),
                     deltas=deltas, l2=l2, nonfinite=nonfinite,
                     losses=losses)
        self._cur = cur
        cohort = sorted(nid for nid, h in self.hosts.items() if h.alive)
        for nid in cohort:
            i = self._widx(nid)
            arrival = self.now + spec.inner_steps * float(
                self.step_times[i])
            self._push(arrival, self._delta_arrival, r, i)
        self._push(self.now + spec.round_timeout_s,
                   self._round_timeout, r)

    def _delta_arrival(self, r: int, i: int):
        cur = self._cur
        nid = self._nid(i)
        host = self.hosts[nid]
        if cur is None or cur.idx != r or cur.closed:
            self._late_delta(r, i)
            return
        if not host.alive:
            return  # crashed before posting — the churn case
        if host.paused_until > self.now:
            self._push(host.paused_until, self._delta_arrival, r, i)
            return
        leader, _ = self._leader_view()
        if leader is None or not self._reachable(nid, leader):
            # Partitioned away from the leader: retry until the round
            # closes (the timeout bounds these events).
            self._push(self.now + _RETRY_S, self._delta_arrival, r, i)
            return
        if i not in cur.delivered:
            cur.delivered[i] = round(self.now - cur.t0, 6)
            self._delivered_ever.add(i)
        if len(cur.delivered) >= cur.need:
            self._close_round(cur)

    def _round_timeout(self, r: int):
        cur = self._cur
        if cur is None or cur.idx != r or cur.closed:
            return
        if cur.delivered:
            self._close_round(cur)
            return
        # Nothing arrived at all (e.g. total partition): safe-pause the
        # round — anchor unchanged, no committed progress.
        cur.closed = True
        self.paused_rounds += 1
        self.skipped_rounds += 1
        self._emit({"event": "training_safe_pause", "leader": cur.leader,
                    "participants": 0, "needed": cur.need,
                    "round": cur.idx,
                    "t_unix_s": round(SIM_EPOCH + self.now, 3)})
        self._advance(cur)

    def _quarantine(self, cur: _Round, i: int, reason: str, value: float,
                    threshold: float):
        cur.quarantined[i] = reason
        log = self._quarantine_log.setdefault(
            i, {"rounds": [], "reason": reason})
        log["rounds"].append(cur.idx)
        self._quarantine_firing.add(i)
        self._alert(
            ("delta_quarantine", i), firing=True, severity="critical",
            alert="diloco.delta_quarantined", detector="diloco",
            node=self._nid(i), labels={"worker": str(i)},
            message=f"round {cur.idx}: delta from worker {i} quarantined "
                    f"({reason}) — excluded from the outer average",
            value=round(float(value), 6), threshold=round(threshold, 6))

    def _close_round(self, cur: _Round):
        cur.closed = True
        spec = self.spec
        # ---- delta quarantine gate ----------------------------------
        finite: List[int] = []
        for i in sorted(cur.delivered):
            if int(cur.nonfinite[i]) > 0:
                self._quarantine(cur, i, "nonfinite",
                                 float(cur.nonfinite[i]), 0.0)
            else:
                finite.append(i)
        if len(finite) >= spec.gate_min_peers:
            norms = np.array([cur.l2[i] for i in finite], np.float64)
            med = float(np.median(norms))
            mad = float(np.median(np.abs(norms - med)))
            # Spread floor 10% of the median: non-IID shards produce
            # legitimately unequal delta norms, and a tight MAD must
            # not quarantine a merely-heterogeneous worker.
            cut = med + spec.outlier_factor * max(mad, 0.1 * abs(med),
                                                  1e-9)
            kept = []
            for i, nrm in zip(finite, norms):
                if nrm > cut:
                    self._quarantine(cur, i, "norm_outlier", float(nrm),
                                     cut)
                else:
                    kept.append(i)
            finite = kept
        cur.accepted = finite
        if (spec.poison_worker >= 0 and cur.idx == spec.poison_round
                and spec.poison_worker in cur.delivered
                and spec.poison_worker not in cur.quarantined):
            self.violations.append(
                f"poisoned worker {spec.poison_worker} delivered in round "
                f"{cur.idx} but was never quarantined")
        for i in finite:
            if i in self._quarantine_firing:
                self._quarantine_firing.discard(i)
                self._alert(("delta_quarantine", i), firing=False,
                            severity="critical",
                            alert="diloco.delta_quarantined",
                            node=self._nid(i),
                            message=f"worker {i} posted a clean delta in "
                                    f"round {cur.idx}; readmitted")
        # ---- outer step ---------------------------------------------
        import jax
        import jax.numpy as jnp

        if finite:
            w = np.zeros(self.n, np.float32)
            w[finite] = 1.0
            self.anchor, self.trace, drift = self.k["outer"](
                self.anchor, self.trace, cur.deltas, jnp.asarray(w))
            drift = float(jax.device_get(drift))
            # The broadcast rides the same wire as the deltas: every
            # worker (the next leader included) adopts the DEQUANTIZED
            # anchor, with a leader-side error-feedback carry. A skipped
            # round republishes the previous round's bytes unchanged —
            # no re-quantization (matching diloco_dcn's packed-blob
            # reuse).
            self.anchor, self.anchor_resid = self.k["wire_anchor"](
                self.anchor, self.anchor_resid)
            self.committed_step += spec.inner_steps
            self.completed_rounds += 1
        else:
            drift = 0.0
            self.paused_rounds += 1
            self.skipped_rounds += 1
        # Byte ledger: one delta PUT per delivery, one anchor PUT plus
        # one anchor GET per live worker — the real protocol's shape.
        r_logical = (len(cur.delivered) * self._delta_logical
                     + (1 + len(cur.view)) * self._anchor_logical)
        r_wire = (len(cur.delivered) * self._delta_wire
                  + (1 + len(cur.view)) * self._anchor_wire)
        self.wire_logical_bytes += r_logical
        self.wire_bytes += r_wire
        self._emit({"event": "dcn_wire", "consumer": "diloco",
                    "direction": "tx", "kind": "herd_round",
                    "wire_dtype": self._wire,
                    "logical_bytes": int(r_logical),
                    "wire_bytes": int(r_wire), "round": cur.idx,
                    "t_unix_s": round(SIM_EPOCH + self.now, 3)})
        part = round(len(finite) / max(len(cur.view), 1), 4)
        self.participation.append(part)
        self.round_waits.append(round(self.now - cur.t0, 4))
        loss = float(np.mean([cur.losses[i] for i in sorted(cur.delivered)]
                             )) if cur.delivered else float("nan")
        self.round_losses.append(round(loss, 6))
        rec = {"event": "diloco_round", "run": "herd", "round": cur.idx,
               "leader": self._widx(cur.leader),
               "posted": sorted(cur.delivered),
               "live": sorted(self._widx(nid) for nid in cur.view),
               "arrivals_s": {str(i): cur.delivered[i]
                              for i in sorted(cur.delivered)},
               "participation": part,
               "quarantined": sorted(cur.quarantined),
               "delta_norms": {str(i): round(float(cur.l2[i]), 6)
                               for i in cur.accepted},
               "anchor_drift": round(drift, 6),
               "waited_s": round(self.now - cur.t0, 4),
               "t_unix_s": round(SIM_EPOCH + self.now, 3)}
        self._emit(rec)
        self._advance(cur)

    def _advance(self, cur: _Round):
        self._step_history.append((self.now, self.committed_step))
        if self._prev is not None:
            self._prev.deltas = None  # free the stale round's device tree
        self._prev = cur
        self.round_idx += 1
        self._start_round()

    def _late_delta(self, r: int, i: int):
        """A delta arriving after its round closed — the straggler path
        the participation policy exists for."""
        host = self.hosts[self._nid(i)]
        if not host.alive:
            return
        prev = self._prev
        record = {"event": "diloco_late_delta", "worker": i, "round": r,
                  "t_unix_s": round(SIM_EPOCH + self.now, 3)}
        if (self.spec.late_policy == "discount" and prev is not None
                and prev.idx == r and prev.deltas is not None
                and int(prev.nonfinite[i]) == 0):
            rounds_late = max(1, self.round_idx - r)
            weight = (self.spec.outer_lr
                      * self.spec.staleness_discount ** rounds_late)
            self.anchor = self.k["late_apply"](
                self.anchor, prev.deltas, i, weight)
            self.late_discounted += 1
            record["action"] = "discounted"
            record["weight"] = round(weight, 6)
        else:
            self.late_dropped += 1
            record["action"] = "dropped"
        self._emit(record)

    # -- run/report --------------------------------------------------------

    def run(self, duration_s: Optional[float] = None) -> dict:
        if duration_s is None:
            bound_s = (self.convergence_bound_periods()
                       * self.cfg.protocol_period_s)
            duration_s = (max(self.plan.end_time(),
                              self.round_s + self.spec.rounds
                              * self.spec.round_timeout_s)
                          + 2.0 * bound_s)
        return super().run(duration_s)

    def _report(self, converged_at, duration) -> dict:
        from serverless_learn_tpu.telemetry.numerics import tree_stats

        anchor_bad = int(sum(
            int(np.asarray(st["nonfinite"]))
            for st in tree_stats(self.anchor, depth=1).values()))
        if anchor_bad:
            self.violations.append(
                f"anchor contains {anchor_bad} non-finite value(s) — "
                f"a poisoned delta reached the outer step")
        spec = self.spec
        rep = super()._report(converged_at, duration)
        if not self.plan.faults:
            # The base convergence invariant measures RE-convergence
            # after the last fault; with no faults it degenerates to
            # "cold-boot dissemination finished", which at herd scale
            # (256+ simultaneous joins saturating the piggyback budget)
            # legitimately exceeds the post-fault O(log N) bound. Report
            # it, don't fail on it — quorum reads the leader's live
            # view, not global agreement.
            rep["violations"] = [v for v in rep["violations"]
                                 if "converge" not in v]
            rep["ok"] = not rep["violations"]
            rep["converged"] = True
        if self.round_idx >= spec.rounds:
            # The herd stops training when its schedule completes; the
            # base "no progress after the final fault" invariant only
            # applies while rounds remain.
            rep["violations"] = [v for v in rep["violations"]
                                 if "no progress after the final" not in v]
            rep["ok"] = not rep["violations"]
        final_eval = float(self.k["eval_loss"](
            self.anchor, self._shifts, self._proj, self._base_key))
        rep["herd"] = {
            "workers": self.n,
            "rounds_target": spec.rounds,
            "rounds_completed": self.completed_rounds,
            "rounds_skipped": self.skipped_rounds,
            "committed_step": self.committed_step,
            "quorum_fraction": spec.quorum_fraction,
            "participation": list(self.participation),
            "mean_participation": (round(float(np.mean(
                self.participation)), 4) if self.participation else None),
            "workers_delivered_ever": len(self._delivered_ever),
            "quarantined": {str(i): dict(v) for i, v in
                            sorted(self._quarantine_log.items())},
            "late_deltas": {"dropped": self.late_dropped,
                            "discounted": self.late_discounted},
            "round_losses": list(self.round_losses),
            "round_waits_s": list(self.round_waits),
            "init_eval_loss": round(self._init_eval, 6),
            "final_eval_loss": round(final_eval, 6),
            "anchor_finite": anchor_bad == 0,
            "wire": {
                "dtype": self._wire,
                "block": spec.wire_block,
                "error_feedback": bool(spec.error_feedback),
                "logical_bytes": int(self.wire_logical_bytes),
                "wire_bytes": int(self.wire_bytes),
                "compression_ratio": (
                    round(self.wire_logical_bytes / self.wire_bytes, 4)
                    if self.wire_bytes else None),
                "bytes_per_round": (
                    int(self.wire_bytes / max(len(self.participation), 1))
                    if self.participation else 0),
            },
        }
        return rep


def smoke_plan(spec: HerdSpec, kill_frac: float = 0.2) -> FaultPlan:
    """The CI smoke schedule: kill ``kill_frac`` of the herd mid-round
    (while deltas are in flight) and pause one straggler for a round."""
    mid = spec.bootstrap_s + 0.6 * spec.inner_steps * spec.base_step_s
    return FaultPlan.from_obj({"faults": [
        {"at": round(mid, 3), "op": "kill", "frac": kill_frac},
        {"at": round(mid + spec.round_timeout_s, 3), "op": "pause",
         "count": 1, "for": round(spec.round_timeout_s, 3)},
    ]})


def run_smoke(workers: int = 48, seed: int = 0,
              events_log: Optional[str] = None) -> dict:
    """Self-contained proof for `slt chaos herd --smoke`: small N, short
    virtual duration, a mid-round kill of 20% of the herd, one poisoned
    worker. Asserts (on top of the harness's own invariants) that two
    same-seed runs report byte-identically and that the poisoned worker
    was quarantined. Doctor attribution is asserted by the CLI."""
    spec = HerdSpec(n_workers=workers, rounds=3, inner_steps=2,
                    batch_size=4, features=(16,),
                    quorum_fraction=0.8, round_timeout_s=1.5,
                    poison_worker=workers - 3, poison_round=1)
    plan = smoke_plan(spec)

    def one(log):
        rep = HerdSim(spec, seed=seed, plan=plan, events_log=log).run()
        rep.pop("wall_time_s", None)
        return rep

    rep = one(events_log)
    rep2 = one(None)
    rep["deterministic"] = (json.dumps(rep, sort_keys=True)
                            == json.dumps(rep2, sort_keys=True))
    if not rep["deterministic"]:
        rep["ok"] = False
        rep["violations"].append("same-seed reports differ")
    if str(spec.poison_worker) not in rep["herd"]["quarantined"]:
        rep["ok"] = False
        rep["violations"].append(
            f"poisoned worker {spec.poison_worker} was not quarantined")
    return rep


def parity_specs(workers: int = 256, quorum: float = 0.8
                 ) -> Tuple[HerdSpec, HerdSpec]:
    """The partial-vs-full participation A/B pair (same compute key, so
    the second run reuses the first's compiles)."""
    base = HerdSpec(n_workers=workers, rounds=5, inner_steps=2,
                    batch_size=4, features=(16,), speed_skew=0.5,
                    round_timeout_s=1.0)
    return replace(base, quorum_fraction=quorum), \
        replace(base, quorum_fraction=1.0)


def wire_parity_specs(workers: int = 256, quorum: float = 0.8,
                      wire_dtype: str = "int8"
                      ) -> Tuple[HerdSpec, HerdSpec]:
    """The quantized-vs-f32 A/B pair (round 20): same seed ⇒ same init,
    shards, speed skew and fault schedule; ONLY the wire encoding
    differs, so a final-loss gap is attributable to the codec alone."""
    base = HerdSpec(n_workers=workers, rounds=5, inner_steps=2,
                    batch_size=4, features=(16,), speed_skew=0.5,
                    round_timeout_s=1.0, quorum_fraction=quorum)
    return replace(base, wire_dtype=wire_dtype), base


def run_wire_ab(workers: int = 48, seed: int = 0,
                wire_dtype: str = "int8", kill_frac: float = 0.2,
                events_log: Optional[str] = None) -> dict:
    """Int8(/fp8)-vs-f32 loss-parity proof under churn (quorum 0.8, a
    mid-round kill of ``kill_frac`` of the herd), with a no-error-
    feedback negative control. Checks, on one seed:

    * every leg's harness invariants hold;
    * the quantized-with-feedback leg's final eval loss lands within 5%
      of the f32 leg's, on the init-loss scale (the EQuARX claim);
    * wire bytes shrink >= 3.5x;
    * the negative control: either dropping error feedback measurably
      WORSENS parity (the feedback term matters — the typical small-herd
      outcome, e.g. the 24-worker CI smoke), or both gaps sit below a
      0.05%-of-init noise floor (documented equivalence: with hundreds of
      workers, per-round quantization noise already cancels in the
      cross-worker average, so the single-stream bias EF removes is
      invisible in one seed's final loss — the codec-level proof is
      tests/test_wire_codec.py::test_error_feedback_unbiases_the_stream).
      A feedback leg that is both worse than the control AND above the
      noise floor fails: the carry would be hurting, not helping.
    """
    quant_spec, f32_spec = wire_parity_specs(workers, 0.8, wire_dtype)
    noef_spec = replace(quant_spec, error_feedback=False)
    plan = smoke_plan(f32_spec, kill_frac)

    def leg(spec, log=None):
        rep = HerdSim(spec, seed=seed, plan=plan, events_log=log).run()
        rep.pop("wall_time_s", None)
        return rep

    rf = leg(f32_spec)
    rq = leg(quant_spec, events_log)
    rn = leg(noef_spec)
    init = rf["herd"]["init_eval_loss"]
    ef_gap = abs(rq["herd"]["final_eval_loss"]
                 - rf["herd"]["final_eval_loss"])
    noef_gap = abs(rn["herd"]["final_eval_loss"]
                   - rf["herd"]["final_eval_loss"])
    ratio = (rf["herd"]["wire"]["wire_bytes"]
             / max(rq["herd"]["wire"]["wire_bytes"], 1))
    violations = []
    for name, rep in (("f32", rf), ("quant", rq), ("quant-noef", rn)):
        if not rep["ok"]:
            violations.append(f"{name} leg: {rep['violations']}")
    if not ef_gap < 0.05 * init:
        violations.append(
            f"quantized leg diverged: |{rq['herd']['final_eval_loss']} "
            f"- {rf['herd']['final_eval_loss']}| = {ef_gap:.6f} >= 5% "
            f"of init {init}")
    if ratio < 3.5:
        violations.append(
            f"wire bytes shrank only {ratio:.2f}x (< 3.5x)")
    noise_floor = 0.0005 * init
    if ef_gap <= noef_gap + 1e-9:
        feedback_verdict = "matters" if noef_gap > noise_floor \
            else "equivalent_below_noise_floor"
    elif ef_gap <= noise_floor:
        feedback_verdict = "equivalent_below_noise_floor"
    else:
        feedback_verdict = "hurts"
        violations.append(
            f"error feedback HURT parity ({ef_gap:.6f} with vs "
            f"{noef_gap:.6f} without, noise floor {noise_floor:.6f}) — "
            f"the feedback term is broken")
    return {
        "ok": not violations, "violations": violations,
        "feedback_verdict": feedback_verdict,
        "workers": workers, "seed": seed, "wire_dtype": wire_dtype,
        "quorum_fraction": quant_spec.quorum_fraction,
        "killed_frac": kill_frac,
        "init_eval_loss": init,
        "final_eval_loss": {
            "f32": rf["herd"]["final_eval_loss"],
            "quant": rq["herd"]["final_eval_loss"],
            "quant_no_feedback": rn["herd"]["final_eval_loss"]},
        "parity_gap": {"with_feedback": round(ef_gap, 6),
                       "without_feedback": round(noef_gap, 6)},
        "bytes": {"f32": rf["herd"]["wire"]["wire_bytes"],
                  "quant": rq["herd"]["wire"]["wire_bytes"],
                  "ratio": round(ratio, 3)},
        "bytes_per_round": {
            "f32": rf["herd"]["wire"]["bytes_per_round"],
            "quant": rq["herd"]["wire"]["bytes_per_round"]},
        "mean_round_wait_s": {
            "f32": _mean_wait(rf), "quant": _mean_wait(rq)},
    }


def _mean_wait(rep: dict) -> Optional[float]:
    waits = rep.get("herd", {}).get("round_waits_s") or []
    return round(float(np.mean(waits)), 4) if waits else None
