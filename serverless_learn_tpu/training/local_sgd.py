"""Local SGD with gossip or DiLoCo-style outer synchronization.

The reference's headline model-sync mechanism is asynchronous *gossip*: each
node trains locally and, on a timer, exchanges model deltas with ONE random
peer, applying the remote delta at ``LEARN_RATE = 0.5``
(``src/worker.cc:194-219``, ``src/master.cc:58-60,95-114``). The framework's
default trainer replaces that with exact per-step all-reduce (zero gossip
rounds); this module is the *faithful* TPU-native descendant for workloads
that want gossip's communication pattern — infrequent, pairwise, inexact
model mixing — but on ICI instead of gRPC:

* Each ``dp``-axis replica trains **independently** for ``inner_steps``
  batches: parameters carry a leading replica dimension sharded over ``dp``,
  and the vmapped inner step compiles to purely replica-local compute — no
  collectives at all between syncs (the analogue of the reference's nodes
  training between gossip timers).
* Every ``inner_steps``, one **outer sync** runs:
  - ``outer="gossip"`` — one hypercube round: replica ``i`` mixes with
    partner ``i XOR 2^(round mod log2 R)`` via ``lax.ppermute``, applying
    ``p += mix_rate * (partner - p)`` — the reference's delta-apply rule
    (rate 0.5 default), but deterministic, deadlock-free, and in one ICI hop
    instead of a gRPC round-trip. With ``mix_rate=0.5``, ``log2 R``
    consecutive rounds reproduce the exact global average.
  - ``outer="average"`` — DiLoCo-style: the replica-mean delta from the last
    anchor is fed to an outer SGD-with-Nesterov-momentum step on the anchor
    parameters, and all replicas restart from the new anchor.

Elasticity note: because replicas only meet at outer syncs, membership
changes (the elastic controller re-meshing, ``training/elastic.py``) only
need to land on outer-sync boundaries — the same property the reference's
gossip bought with its tolerance of stale peers.

Degradation note (round 19): inside ONE SPMD world every replica steps in
the same jit, so "participation" is all-or-nothing here. The cross-process
descendant (``training/diloco_dcn.py``) is where the round-19
``LocalSGDConfig`` policy fields (``participation``/``quorum_fraction``/
``late_policy``/``delta_gate``) take effect — quorum round closes, late-
delta handling and the leader-side delta quarantine gate; and
``training/herd.py`` validates those policies at 256+ vmapped workers
under churn.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from serverless_learn_tpu.config import ExperimentConfig
from serverless_learn_tpu.models.registry import get_model
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.training.optimizer import make_optimizer

from serverless_learn_tpu.parallel.compat import shard_map as _shard_map

import flax.struct


@flax.struct.dataclass
class LocalSGDState:
    step: Any  # scalar int32 — global inner-step counter
    params: Any  # leaves [R, ...] — per-replica parameters
    opt_state: Any  # leaves [R, ...] — per-replica inner optimizer state
    anchor: Any  # leaves [...] — outer anchor params ("average" mode)
    outer_opt_state: Any  # outer optimizer state ("average" mode)
    model_state: Any = flax.struct.field(default_factory=dict)
    # ^ leaves [R, ...] — per-replica mutable collections (BatchNorm
    # running stats etc.); round 4 — r3 refused stateful models outright.


def _mean_float_leaves(tree):
    """Replica-mean of float leaves (BatchNorm stats at a sync), tiled back
    to the stacked [R, ...] shape; non-float leaves (counters) pass through
    untouched — averaging an int step counter would be meaningless."""
    def mix(l):
        if not jnp.issubdtype(l.dtype, jnp.floating):
            return l
        return jnp.broadcast_to(l.mean(0, keepdims=True), l.shape
                                ).astype(l.dtype)
    return jax.tree_util.tree_map(mix, tree)


# Round 17: one implementation for every cross-replica divergence
# consumer — this gauge, the numerics fingerprint path, and `slt
# numerics`'s live compares all share telemetry/numerics.py.
from serverless_learn_tpu.telemetry.numerics import (  # noqa: E402
    replica_divergence)


class LocalSGDTrainer:
    """Gossip / DiLoCo trainer over the mesh's ``dp`` axis.

    The replica axis is ``dp``; each replica may additionally be SHARDED
    over ``fsdp``/``tp`` (round 3 — r2 capped replicas at a single chip):
    the stacked ``[R, ...]`` state leaves carry the rule-table shardings on
    their inner dims (``P("dp", <rule spec>)``), so within each dp slice
    GSPMD scopes the usual fsdp all-gathers / tp all-reduces to that
    replica's devices, and between syncs there is STILL zero cross-replica
    traffic. ``ep``/``sp``/``pp`` remain out of scope here.
    """

    def __init__(
        self,
        config: ExperimentConfig,
        mesh: Optional[Mesh] = None,
        inner_steps: int = 8,
        outer: str = "gossip",  # "gossip" | "average"
        mix_rate: float = 0.5,  # reference LEARN_RATE (src/master.cc:60)
        outer_lr: float = 0.7,
        outer_momentum: float = 0.9,
    ):
        if mesh is None:
            mesh = make_mesh(config.mesh)
        for ax in ("ep", "sp", "pp"):
            if mesh.shape[ax] != 1:
                raise ValueError(f"local SGD replicas shard over fsdp/tp "
                                 f"only; {ax}={mesh.shape[ax]}")
        if outer not in ("gossip", "average"):
            raise ValueError(f"outer must be 'gossip' or 'average', "
                             f"got {outer!r}")
        self.R = mesh.shape["dp"]
        if outer == "gossip" and (self.R & (self.R - 1)):
            raise ValueError(f"gossip needs a power-of-two replica count, "
                             f"got {self.R}")
        if config.train.batch_size % self.R:
            raise ValueError(f"batch {config.train.batch_size} not divisible "
                             f"by {self.R} replicas")
        self.config = config
        self.mesh = mesh
        self.inner_steps = inner_steps
        self.outer = outer
        self.mix_rate = mix_rate
        self.bundle = get_model(config.model, **config.model_overrides)
        # NOTE: freezes via the optimizer-mask path (multi_transform +
        # set_to_zero), NOT train_step.py's gradient partitioning — fine at
        # the scales Local SGD runs at today, but it pays the full-model
        # backward for frozen bases and cannot take an int8 base; migrate
        # to training/partition.py when a frozen-base model needs DiLoCo.
        self.tx = make_optimizer(config.optimizer, self.bundle.trainable_mask)
        self.outer_tx = optax.sgd(outer_lr, momentum=outer_momentum,
                                  nesterov=True)
        self._round = 0  # host-side outer-round counter (gossip schedule)
        self._gossip_jits: Dict[int, Callable] = {}
        self._build()

    # -- construction ------------------------------------------------------

    def _build(self):
        cfg, mesh, R = self.config, self.mesh, self.R
        bundle, tx = self.bundle, self.tx
        per_replica = cfg.train.batch_size // R
        spec = bundle.input_spec(cfg.data, per_replica)

        # Stateful models (BatchNorm running stats etc.): every non-param
        # collection is stacked per replica and vmapped through the inner
        # step alongside the params — each replica owns its own statistics
        # between syncs, exactly as each reference worker owned its own
        # model vector between gossip exchanges (src/worker.cc:221-231).
        first_spec = (next(iter(spec.values()))
                      if isinstance(spec, dict) else spec)

        # Per-replica batch rows additionally split over fsdp (standard
        # ZeRO data parallelism WITHIN the replica); tp replicates data.
        fsdp_live = mesh.shape["fsdp"] > 1
        if fsdp_live and per_replica % mesh.shape["fsdp"]:
            raise ValueError(
                f"per-replica batch {per_replica} not divisible by "
                f"fsdp={mesh.shape['fsdp']}")
        self.batch_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(
                mesh, P("dp", "fsdp") if fsdp_live else P("dp")), spec)

        average_mode = self.outer == "average"

        def init_raw(seed):
            rng = jax.random.PRNGKey(seed)
            first = jnp.zeros(first_spec.shape, first_spec.dtype)
            variables = bundle.module.init(rng, first)
            params = variables["params"]
            mstate = {k: v for k, v in variables.items()
                      if k not in ("params", "losses")}
            tile = lambda p: jnp.broadcast_to(p[None], (R,) + p.shape)
            params_r = jax.tree_util.tree_map(tile, params)
            opt_r = jax.vmap(tx.init)(params_r)
            return LocalSGDState(
                step=jnp.zeros((), jnp.int32),
                params=params_r,
                opt_state=opt_r,
                # anchor + outer momentum exist only in DiLoCo mode — in
                # gossip mode they'd be a dead 2x-params HBM cost.
                anchor=params if average_mode else {},
                outer_opt_state=(self.outer_tx.init(params)
                                 if average_mode else {}),
                model_state=jax.tree_util.tree_map(tile, mstate),
            )

        abstract = jax.eval_shape(init_raw, 0)
        # Inner-dim shardings come from the same rule table the exact
        # trainer uses, computed on the UNSTACKED (single-replica) shapes,
        # then shifted one dim right under the leading replica axis. On a
        # dp-only mesh every rule spec prunes to P() and this degenerates
        # to the original P("dp") layout.
        from serverless_learn_tpu.parallel.sharding import specs_for_tree

        def un_abstract(tree):
            return jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape[1:], l.dtype), tree)

        # divisible_only (opt trees only): optimizer leaves match param
        # PATHS but not necessarily param shapes (adafactor's factored
        # stats) — see parallel/sharding._drop_indivisible. Params stay
        # strict, matching train_step.
        def stacked_shardings(tree, lenient=False):
            inner = specs_for_tree(un_abstract(tree), mesh,
                                   divisible_only=lenient)
            return jax.tree_util.tree_map(
                lambda sp: NamedSharding(mesh, P("dp", *tuple(sp))), inner,
                is_leaf=lambda x: isinstance(x, P))

        def inner_shardings(tree, lenient=False):
            inner = specs_for_tree(tree, mesh, divisible_only=lenient)
            return jax.tree_util.tree_map(
                lambda sp: NamedSharding(mesh, sp), inner,
                is_leaf=lambda x: isinstance(x, P))

        self.state_shardings = LocalSGDState(
            step=NamedSharding(mesh, P()),
            params=stacked_shardings(abstract.params),
            opt_state=stacked_shardings(abstract.opt_state, lenient=True),
            anchor=inner_shardings(abstract.anchor),
            outer_opt_state=inner_shardings(abstract.outer_opt_state,
                                            lenient=True),
            model_state=stacked_shardings(abstract.model_state,
                                          lenient=True),
        )
        # Two-stage init (round 17 un-xfail): under this image's jax
        # (threefry_partitionable=False), jitting the random init with
        # fsdp/tp-sharded out_shardings lets XLA's SPMD partitioner
        # lower the threefry counters shard-locally — each shard draws
        # DIFFERENT random bits, so the initial parameters depended on
        # the mesh layout. That (not training drift) is what failed
        # test_sharded_replicas_match_single_chip[fsdp-*]: the sharded
        # and single-chip runs started from different models. Compute
        # the init once without sharded out_shardings (sharding-
        # invariant bits), then reshard device-to-device.
        init_unsharded = jax.jit(init_raw, static_argnums=(0,))
        st_shardings = self.state_shardings

        def init_sharded(seed):
            state = init_unsharded(seed)
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), state, st_shardings)

        self.init_fn = init_sharded

        def one_replica(params, mstate, opt_state, batch, rng):
            def loss_fn(p):
                loss, aux = bundle.loss_fn(p, batch, rngs=rng,
                                           model_state=mstate)
                return loss, aux
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates)
            return new_params, (aux["model_state"] or mstate), new_opt, loss

        st_sh = self.state_shardings

        @partial(jax.jit, donate_argnums=(0,),
                 in_shardings=(st_sh, self.batch_shardings),
                 out_shardings=(st_sh, NamedSharding(mesh, P("dp"))))
        def inner_step(state: LocalSGDState, batch):
            rngs = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(cfg.train.seed), i),
                    state.step))(jnp.arange(R))
            new_params, new_mstate, new_opt, losses = jax.vmap(one_replica)(
                state.params, state.model_state, state.opt_state, batch, rngs)
            return state.replace(step=state.step + 1, params=new_params,
                                 opt_state=new_opt,
                                 model_state=new_mstate), losses

        self.inner_step = inner_step

        if not average_mode:
            self.average_sync = None
            return

        @partial(jax.jit, donate_argnums=(0,),
                 in_shardings=(st_sh,), out_shardings=st_sh)
        def average_sync(state: LocalSGDState):
            # DiLoCo outer step: outer grad = anchor - mean(replicas).
            mean_params = jax.tree_util.tree_map(
                lambda p: p.mean(0).astype(p.dtype), state.params)
            outer_grad = jax.tree_util.tree_map(
                lambda a, m: (a - m).astype(jnp.float32),
                state.anchor, mean_params)
            updates, new_outer = self.outer_tx.update(
                outer_grad, state.outer_opt_state, state.anchor)
            new_anchor = jax.tree_util.tree_map(
                lambda a, u: a + u.astype(a.dtype), state.anchor, updates)
            tile = lambda p: jnp.broadcast_to(
                p[None], (R,) + p.shape).astype(p.dtype)
            return state.replace(
                params=jax.tree_util.tree_map(tile, new_anchor),
                anchor=new_anchor,
                outer_opt_state=new_outer,
                model_state=_mean_float_leaves(state.model_state))

        self.average_sync = average_sync

    def _gossip_sync_for_bit(self, bit: int) -> Callable:
        """Jitted one-hypercube-round gossip mix (partner = i XOR 2^bit)."""
        if bit in self._gossip_jits:
            return self._gossip_jits[bit]
        mesh, R, rate = self.mesh, self.R, self.mix_rate
        perm = [(j, j ^ (1 << bit)) for j in range(R)]

        def mix_leaf(p):  # inside shard_map: leading dim 1 (this replica)
            if not jnp.issubdtype(p.dtype, jnp.floating):
                return p  # int state (counters) doesn't gossip
            partner = jax.lax.ppermute(p, "dp", perm)
            # The reference's delta-apply (src/worker.cc:91-94): mix toward
            # the partner's model at the gossip learn rate.
            return p + rate * (partner - p).astype(p.dtype)

        # Per-leaf specs (not a blanket P("dp")): sharded-replica leaves
        # carry fsdp/tp on their inner dims, and shard_map must keep those
        # dims device-local — the ppermute then exchanges each replica
        # SHARD with the same-positioned shard of the partner replica.
        as_specs = lambda tree: jax.tree_util.tree_map(
            lambda s: s.spec, tree,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        param_specs = as_specs(self.state_shardings.params)
        mstate_specs = as_specs(self.state_shardings.model_state)

        @partial(jax.jit, donate_argnums=(0,),
                 in_shardings=(self.state_shardings,),
                 out_shardings=self.state_shardings)
        def gossip_sync(state: LocalSGDState):
            # model_state gossips with the params: BatchNorm statistics ARE
            # part of the model the reference's workers exchanged (its
            # whole vector went over the wire, src/worker.cc:205-208).
            mixed, mixed_state = _shard_map(
                lambda params, ms: (
                    jax.tree_util.tree_map(mix_leaf, params),
                    jax.tree_util.tree_map(mix_leaf, ms)),
                mesh=mesh,
                in_specs=(param_specs, mstate_specs),
                out_specs=(param_specs, mstate_specs),
            )(state.params, state.model_state)
            return state.replace(params=mixed, model_state=mixed_state)

        self._gossip_jits[bit] = gossip_sync
        return gossip_sync

    # -- public API --------------------------------------------------------

    def init(self, seed: Optional[int] = None) -> LocalSGDState:
        return self.init_fn(seed if seed is not None
                            else self.config.train.seed)

    def shard_batch(self, host_batch):
        """host batch [global_B, ...] -> [R, B/R, ...] placed on the mesh."""
        R = self.R

        def place(x, s):
            x = np.asarray(x).reshape((R, x.shape[0] // R) + x.shape[1:])
            return jax.device_put(x, s)

        return jax.tree_util.tree_map(place, host_batch,
                                      self.batch_shardings)

    def outer_sync(self, state: LocalSGDState) -> LocalSGDState:
        if self.outer == "average":
            state = self.average_sync(state)
        elif self.R > 1:  # gossip with one replica has no partner: no-op
            bit = self._round % int(math.log2(self.R))
            state = self._gossip_sync_for_bit(bit)(state)
        self._round += 1
        return state

    def run(self, source_iter, num_steps: Optional[int] = None
            ) -> Tuple[LocalSGDState, list]:
        """Train ``num_steps`` inner steps, syncing every ``inner_steps``.
        Returns (state, per-step mean losses)."""
        num_steps = num_steps or self.config.train.num_steps
        state = self.init()
        losses = []
        for t in range(num_steps):
            state, step_losses = self.inner_step(
                state, self.shard_batch(next(source_iter)))
            losses.append(float(jax.device_get(step_losses.mean())))
            if (t + 1) % self.inner_steps == 0:
                state = self.outer_sync(state)
        return state, losses


def run_local_sgd(config: ExperimentConfig, checkpointer=None,
                  verbose: bool = False) -> Tuple[LocalSGDState, Any]:
    """CLI-grade Local SGD run: data plane, metrics, checkpointing.

    The full-program twin of ``training/loop.run_training`` for the gossip/
    DiLoCo trainer — sources batches via ``make_source`` (shard server or
    synthetic, same config surface), reports JSON-line step metrics with a
    replica-divergence gauge (the quantity gossip trades away vs exact
    all-reduce), and saves through any ``Checkpointer`` (``LocalSGDState``
    serializes like a ``TrainState``). Round-1 verdict: Local SGD was "a
    demonstration, not an integrated capability" — this is the integration.
    """
    from serverless_learn_tpu.data.datasets import Prefetcher
    from serverless_learn_tpu.training.loop import make_source
    from serverless_learn_tpu.utils.metrics import ThroughputMeter, log_json

    lcfg = config.local_sgd
    trainer = LocalSGDTrainer(
        config, inner_steps=lcfg.inner_steps, outer=lcfg.outer,
        mix_rate=lcfg.mix_rate, outer_lr=lcfg.outer_lr,
        outer_momentum=lcfg.outer_momentum)
    start = 0
    if checkpointer is not None and checkpointer.latest_step() is not None:
        # Restore into an abstract template — a full init here would
        # compile and materialize R-replicated state only to discard it.
        state = checkpointer.restore(jax.eval_shape(lambda: trainer.init()),
                                     shardings=trainer.state_shardings)
        start = int(jax.device_get(state.step))
        trainer._round = start // max(trainer.inner_steps, 1)
    else:
        state = trainer.init()
    source = make_source(config, trainer, start_step=start)
    prefetch = Prefetcher(iter(source), trainer.shard_batch,
                          depth=config.data.prefetch)
    meter = ThroughputMeter(batch_size=config.train.batch_size,
                            n_chips=trainer.mesh.size)
    meter.start()
    last_saved = None
    from serverless_learn_tpu.telemetry import get_registry
    from serverless_learn_tpu.telemetry import numerics as _numerics

    # Round 17: the divergence gauge rides the numerics catalog — one
    # name, one implementation, whether the producer is gossip, DiLoCo
    # or the exact trainer's parity harness.
    m_div = get_registry().gauge(
        "slt_numerics_replica_divergence",
        "max |p_r - mean_r p| across dp replicas, sampled at log_every")
    try:
        for t in range(start, config.train.num_steps):
            state, step_losses = trainer.inner_step(state, next(prefetch))
            loss = float(jax.device_get(step_losses.mean()))
            stats = meter.record(t + 1, {"loss": loss})
            synced = (t + 1) % trainer.inner_steps == 0
            if synced:
                state = trainer.outer_sync(state)
            if (t + 1) % config.train.log_every == 0:
                div = float(jax.device_get(
                    replica_divergence(state.params)))
                m_div.set(div)
                _numerics.note_step({"step": t + 1, "loss": loss,
                                     "replica_divergence": round(div, 9),
                                     "nonfinite": 0 if np.isfinite(loss)
                                     else 1})
                if verbose:
                    log_json({"step": t + 1, "loss": round(loss, 5),
                              "samples_per_sec":
                              round(stats.samples_per_sec, 1),
                              "outer_synced": synced,
                              "replica_divergence": round(div, 6)})
            if (checkpointer is not None and config.train.checkpoint_every
                    and (t + 1) % config.train.checkpoint_every == 0):
                checkpointer.save(state, step=t + 1)
                last_saved = t + 1
    finally:
        prefetch.close()
        if hasattr(source, "close"):
            source.close()
    if checkpointer is not None and last_saved != config.train.num_steps:
        checkpointer.save(state, step=config.train.num_steps)
    if checkpointer is not None:
        checkpointer.wait()
    return state, meter
