"""Training loop: wire a Trainer, a data source and metrics together.

Functional successor of the reference worker's thread soup (service thread +
gossip thread + simulated-training thread, ``src/worker.cc:233-258``): one
loop, with data prefetch on a background thread and all synchronization
inside the jitted step.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import numpy as np

from serverless_learn_tpu.config import ExperimentConfig
from serverless_learn_tpu.data.datasets import Prefetcher, SyntheticSource
from serverless_learn_tpu.telemetry import flight, get_registry, goodput
from serverless_learn_tpu.telemetry import tracing as ttrace
from serverless_learn_tpu.training.train_step import Trainer, build_trainer
from serverless_learn_tpu.utils.metrics import ThroughputMeter, log_json
from serverless_learn_tpu.utils.tracing import get_tracer, step_annotation


def make_source(config: ExperimentConfig, trainer: Trainer,
                dataset: Optional[str] = None, seed: Optional[int] = None,
                dp_rank: Optional[int] = None, dp_size: Optional[int] = None,
                start_step: int = 0, train: bool = True):
    """Pick a host batch source for a config.

    ``data.shard_server_addr`` set => stream the named dataset from the
    native shard server (pull-based data plane); otherwise synthesize
    batches locally from the model bundle. ``dataset``/``seed`` override
    the config's training split — the eval path uses them.

    ``dp_rank``/``dp_size`` override the data stripe. Default is this
    process's slot in the fixed SPMD world (``jax.process_index``); the
    elastic controller instead passes its rank in the *live membership*, so
    concurrent workers on one coordinator read disjoint shards
    (VERDICT round 1 item 7) instead of everyone streaming everything.

    ``start_step`` is folded into the stream seed: a source (re)built at a
    resume/re-mesh boundary must NOT replay the batches the restored model
    already trained on — the replayed, partially-memorized data would show
    up as a bogus loss cliff (observed, not hypothetical: the elastic
    multi-host bring-up dropped from 2.4 to 0.97 at a re-mesh before this).
    """
    # Each process handles only its 1/process_count slice of the global
    # batch; Trainer.shard_batch assembles the global array from the
    # process-local data. The stripe rank is a separate concept: it selects
    # WHICH shards this consumer reads, not how big its batch is.
    n_proc = jax.process_count()
    if config.train.batch_size % n_proc:
        raise ValueError(
            f"batch_size {config.train.batch_size} not divisible by "
            f"process count {n_proc}")
    seed = config.train.seed if seed is None else seed
    seed = seed + 100_003 * start_step  # fresh stream per resume point
    if dp_rank is None:
        dp_rank = jax.process_index()
    if dp_size is None:
        dp_size = n_proc
    if config.data.shard_server_addr:
        from serverless_learn_tpu.data.shard_client import ShardStreamSource
        from serverless_learn_tpu.data.transforms import (
            TransformedSource, auto_transform)

        # Stream the named dataset from the worker's own stripe of shards.
        source = ShardStreamSource(
            config.data.shard_server_addr,
            dataset or config.data.dataset,
            config.train.batch_size // n_proc,
            seed=seed,
            dp_rank=dp_rank,
            dp_size=dp_size,
        )
        # Bridge storage schema -> model inputs (uint8 decode + augment for
        # images, dynamic MLM masking / field rename for token corpora).
        # ``train=False`` (eval sources) converts dtypes but never augments.
        bundle = trainer.bundle
        model_cfg = getattr(bundle.module, "cfg", None)
        fn = auto_transform(
            source.meta.fields,
            bundle.input_spec(config.data, config.train.batch_size // n_proc),
            task=bundle.task, train=train, seed=seed + dp_rank,
            augment=config.data.augment, mask_rate=config.data.mask_rate,
            vocab_size=getattr(model_cfg, "vocab_size", None))
        return TransformedSource(source, fn) if fn is not None else source
    # Synthetic: each stripe rank generates its own slice (distinct seed so
    # consumers don't all produce identical data).
    return SyntheticSource(trainer.bundle.make_batch, config.data,
                           config.train.batch_size // n_proc,
                           seed=seed + dp_rank)


def eval_uses_train_data(config: ExperimentConfig) -> bool:
    """True when eval batches come from the *training* split (shard server
    configured but no ``data.eval_dataset`` published) — the single predicate
    both the in-loop and standalone eval paths tag their metrics with."""
    return bool(config.data.shard_server_addr) and not config.data.eval_dataset


def make_eval_source(config: ExperimentConfig, trainer: Trainer):
    """Held-out source for eval passes: ``data.eval_dataset`` from the shard
    server if published, else the training source re-seeded disjointly.
    Eval sources convert dtypes but never augment."""
    return make_source(config, trainer,
                       dataset=config.data.eval_dataset or None,
                       seed=config.train.seed + 995_801, train=False)


def run_eval(
    config: ExperimentConfig,
    trainer: Trainer,
    state,
    source=None,
    num_batches: Optional[int] = None,
) -> dict:
    """Forward-only pass over ``num_batches`` eval batches; returns mean metrics.

    The eval step runs in inference mode (e.g. BatchNorm running statistics)
    and never mutates ``state``. Default source follows the training data
    config: with a shard server it streams ``data.eval_dataset`` (or, if
    unset, the training dataset re-shuffled with a disjoint seed — flagged
    in the metrics as ``eval_on_train_data``); otherwise a held-out
    synthetic stream (seed offset from training so the data is disjoint).
    """
    num_batches = num_batches or config.train.eval_steps
    created = source is None
    eval_on_train = created and eval_uses_train_data(config)
    if source is None:
        source = make_eval_source(config, trainer)
    sums: dict = {}
    n = 0
    try:
        it = iter(source)
        for _ in range(num_batches):
            batch = trainer.shard_batch(next(it))
            metrics = jax.device_get(trainer.eval_step(state, batch))
            for k, v in metrics.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            n += 1
    finally:
        if created and hasattr(source, "close"):
            source.close()
    out = {f"eval_{k}": v / max(n, 1) for k, v in sums.items()}
    if "eval_perplexity" in out:
        # Derive from the mean loss; a mean of per-batch exp(loss) would be
        # Jensen-biased and incomparable across eval_steps settings.
        out["eval_perplexity"] = float(np.exp(out["eval_loss"]))
    if eval_on_train:
        out["eval_on_train_data"] = 1.0
    return out


def run_training(
    config: ExperimentConfig,
    trainer: Optional[Trainer] = None,
    state=None,
    source=None,
    step_callback: Optional[Callable] = None,
    verbose: bool = False,
    auditor=None,
):
    """Run ``config.train.num_steps`` steps; returns (state, meter).

    ``step_callback(step, state, stats)`` runs after each step — the hook used
    by checkpointing and the elastic controller. ``auditor`` (or config
    ``numerics.enabled``) attaches the numerics auditor: the jitted step
    emits in-graph tensor stats and the auditor fetches/emits them at the
    configured cadence (``training/audit.py``).
    """
    trainer = trainer or build_trainer(config)
    if auditor is None and config.numerics.enabled:
        from serverless_learn_tpu.training.audit import NumericsAuditor

        auditor = NumericsAuditor(config, bundle=trainer.bundle)
    if state is None:
        state = trainer.init()
    created_source = source is None
    if source is None:
        source = make_source(config, trainer)
    prefetch = Prefetcher(iter(source), trainer.shard_batch,
                          depth=config.data.prefetch)
    meter = ThroughputMeter(batch_size=config.train.batch_size,
                            n_chips=trainer.mesh.size)
    meter.start()
    start_step = int(jax.device_get(state.step))
    tracer = get_tracer()
    # Cluster telemetry (scraped by /metrics + `slt top`): per-step
    # counters/gauges are a handful of float ops per step — noise next to
    # a device step — and give the serving/elastic planes' dashboards the
    # same substrate the inference engines publish into.
    reg = get_registry()
    m_steps = reg.counter("slt_train_steps_total", "optimizer steps run")
    m_step_t = reg.histogram("slt_train_step_seconds", "wall time per step")
    m_sps = reg.gauge("slt_train_samples_per_sec")
    m_sps_chip = reg.gauge("slt_train_samples_per_sec_per_chip")
    m_loss = reg.gauge("slt_train_loss")
    # Wall time of the latest optimizer step: the health engine's
    # staleness watchdog and /healthz "last-step age" read this — a loop
    # wedged inside one step (device hang, stuck host callback) stops
    # advancing it even though the process stays alive.
    m_last_step = reg.gauge("slt_train_last_step_unix_s",
                            "wall time of the latest optimizer step")
    reg.gauge("slt_train_grad_accum",
              "microbatches per step").set(config.train.grad_accum)
    reg.gauge("slt_train_batch_size").set(config.train.batch_size)
    reg.gauge("slt_train_n_chips").set(trainer.mesh.size)
    reg.gauge("slt_train_zero_stage").set(config.train.zero_stage)
    # Per-chip resident opt-state bytes: the ZeRO memory claim as a
    # scraped number (shrinks ~1/dp at zero_stage >= 1), not a doc claim.
    from serverless_learn_tpu.training.zero import publish_opt_state_gauge

    publish_opt_state_gauge(state.opt_state, registry=reg)
    last_batch = None
    # Goodput accounting: the run ledger's t0 pins the total-time
    # denominator; every wait below lands in a named phase ("step" is the
    # only productive one — compile, data_wait, eval, checkpoint are
    # badput with a name, scraped at /goodput and by `slt goodput`).
    ledger = goodput.get_ledger()
    ledger.ensure_started()
    # One run-level trace span brackets the whole loop (children: every
    # RPC a shard-streaming source issues inherits it via the ambient
    # context) and per-step records feed the flight ring, so a dying
    # trainer's dump shows its last steps, not just its last metrics.
    run_span_cm = ttrace.span("train/run", steps=config.train.num_steps,
                              model=config.model)
    run_span = run_span_cm.__enter__()
    try:
        for i, batch in zip(range(start_step, config.train.num_steps), prefetch):
            last_batch = batch
            # The first step pays the XLA trace+compile; attributing it
            # to "step" would poison both the goodput number and the
            # step-time anomaly baseline's warmup.
            phase_name = "compile" if i == start_step else "step"
            with step_annotation(i + 1), tracer.span("train/step",
                                                     annotate_device=False), \
                    ledger.phase(phase_name):
                state, metrics = trainer.step(state, batch)
                # The numerics sub-tree is NOT part of the per-step
                # fetch: the auditor device_gets it only at its cadence
                # (charged to its own "numerics" ledger phase below).
                num_tree = (metrics.pop("numerics", None)
                            if isinstance(metrics, dict) else None)
                # Block on the metrics (small) so step timing is honest;
                # params stay on device.
                metrics = {k: float(v)
                           for k, v in jax.device_get(metrics).items()}
            if auditor is not None:
                auditor.on_step(i + 1, num_tree, metrics,
                                state=state, batch=batch,
                                final=i + 1 == config.train.num_steps)
            stats = meter.record(i + 1, metrics)
            flight.record({"event": "train_step", "step": i + 1,
                           "step_time_s": round(stats.step_time_s, 5),
                           **{k: round(v, 5) for k, v in metrics.items()}})
            m_steps.inc()
            m_step_t.observe(stats.step_time_s)
            m_last_step.set(time.time())
            m_sps.set(stats.samples_per_sec)
            m_sps_chip.set(stats.samples_per_sec / max(trainer.mesh.size, 1))
            if "loss" in metrics:
                m_loss.set(metrics["loss"])
            if verbose and (i + 1) % config.train.log_every == 0:
                log_json({"step": stats.step, "step_time_s": round(stats.step_time_s, 5),
                          "samples_per_sec": round(stats.samples_per_sec, 1),
                          **{k: round(v, 5) for k, v in metrics.items()}})
            if (config.train.eval_every > 0
                    and (i + 1) % config.train.eval_every == 0):
                # A fresh source per pass so every eval scores the SAME
                # seeded batch set — eval-loss deltas stay comparable across
                # the run (a reused source would advance between passes).
                # Cost: one connect per eval pass, amortized over
                # eval_every training steps.
                with ledger.phase("eval"):
                    eval_metrics = run_eval(config, trainer, state)
                if verbose:
                    log_json({"step": i + 1,
                              **{k: round(v, 5)
                                 for k, v in eval_metrics.items()}})
                # Eval wall time must not bleed into the next step's
                # throughput window.
                meter.start()
            if step_callback is not None:
                step_callback(i + 1, state, stats)
    finally:
        run_span.meta["last_step"] = int(jax.device_get(state.step))
        run_span_cm.__exit__(None, None, None)
        prefetch.close()
        if created_source and hasattr(source, "close"):
            source.close()
    if last_batch is not None:
        # Attach the compiled step's FLOPs so steady_state can report MFU.
        # lower() retraces but compile() hits the executable cache; cost is
        # one trace at end-of-run, not a second compilation.
        from serverless_learn_tpu.utils.flops import compiled_step_flops

        meter.flops_per_step = compiled_step_flops(
            trainer.step_fn, state, last_batch,
            n_devices=trainer.mesh.size)
        summary = meter.steady_state()
        if "mfu" in summary:
            reg.gauge("slt_train_mfu",
                      "model FLOPs utilization").set(summary["mfu"])
        if "tflops_per_sec_per_chip" in summary:
            reg.gauge("slt_train_tflops_per_sec_per_chip").set(
                summary["tflops_per_sec_per_chip"])
    return state, meter
