"""Optimizer construction from ``OptimizerConfig`` via optax."""

from __future__ import annotations

import jax
import optax

from serverless_learn_tpu.config import OptimizerConfig


def make_schedule(cfg: OptimizerConfig):
    if cfg.warmup_steps <= 0 and cfg.decay_steps <= 0:
        return cfg.learning_rate
    if cfg.decay_steps > 0:
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=max(cfg.warmup_steps, 1),
            decay_steps=max(cfg.decay_steps, cfg.warmup_steps + 1),
        )
    return optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)


def make_optimizer(cfg: OptimizerConfig, trainable_mask=None) -> optax.GradientTransformation:
    schedule = make_schedule(cfg)
    decay_mask = None
    if cfg.weight_decay > 0 and cfg.decay_exclude_1d:
        # Decay only matrices/embeddings; biases and norm scales (ndim <= 1)
        # are exempt, per the standard transformer recipe.
        decay_mask = lambda params: jax.tree_util.tree_map(
            lambda p: getattr(p, "ndim", 0) >= 2, params)
    if cfg.name == "adamw":
        core = optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2,
                           weight_decay=cfg.weight_decay, mask=decay_mask)
    elif cfg.name == "adam":
        core = optax.adam(schedule, b1=cfg.b1, b2=cfg.b2)
    elif cfg.name == "sgd":
        core = optax.sgd(schedule, momentum=cfg.momentum)
    elif cfg.name == "adafactor":
        core = optax.adafactor(schedule)
    elif cfg.name == "lion":
        core = optax.lion(schedule, b1=cfg.b1, b2=cfg.b2,
                          weight_decay=cfg.weight_decay, mask=decay_mask)
    elif cfg.name == "rmsprop":
        core = optax.rmsprop(schedule, momentum=cfg.momentum)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if cfg.weight_decay > 0 and cfg.name not in ("adamw", "lion"):
        # Only adamw/lion implement decoupled decay; silently dropping the
        # configured decay would quietly diverge from intent.
        raise ValueError(
            f"weight_decay={cfg.weight_decay} is ignored by optimizer "
            f"{cfg.name!r}; use 'adamw' or 'lion', or set weight_decay=0")
    parts = []
    if cfg.grad_clip_norm > 0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    parts.append(core)
    tx = optax.chain(*parts)
    if trainable_mask is not None:
        # Freeze non-trainable params (LoRA): zero their updates entirely.
        tx = optax.multi_transform(
            {"train": tx, "freeze": optax.set_to_zero()},
            lambda params: jax.tree_util.tree_map(
                lambda m: "train" if m else "freeze", trainable_mask(params)),
        )
    return tx
