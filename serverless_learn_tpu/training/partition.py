"""Trainable/frozen parameter partitioning for frozen-base fine-tuning.

LoRA-style training differentiates a FEW leaves of a LARGE pytree. The
round-1..4 trainer froze base params at the optimizer (``optax
.multi_transform`` + ``set_to_zero``), which still pays the full-model
weight-gradient backward and materializes a full-size gradient pytree —
fatal at 8B scale (an 8B f32 gradient tree is 32 GB; the chip has 16),
and impossible at all for an int8-quantized frozen base (JAX refuses to
differentiate with respect to integer leaves). This module partitions
instead: ``prune`` extracts the trainable SUBTREE (keeping its nested
names, so the sharding rule table still applies), the loss closes over
the frozen remainder, and ``jax.grad`` runs only over the subtree — the
backward never computes frozen weight gradients, and the optimizer state
covers only what trains.

The reference has no counterpart (its "training" bumps a double vector,
``/root/reference/src/worker.cc:221-231``); this is the TPU-native
machinery behind the ladder's QLoRA rung (int8 frozen 8B base + bf16
LoRA, ``benchmarks/ladder.py --rows llama8b_real``).
"""

from __future__ import annotations

from typing import Any


class _Empty:
    """Sentinel for a fully-frozen branch (``None`` is itself a pytree)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<all-frozen>"


EMPTY = _Empty()


def prune(tree: Any, mask: Any) -> Any:
    """Subtree of ``tree`` where ``mask``'s (same-structure) leaves are True.

    Nested-dict trees only (flax params). Fully-frozen branches disappear
    entirely — the result's leaf paths are a subset of the input's, so
    path-keyed sharding rules resolve identically. Raises if nothing is
    trainable (a silent no-op optimizer is never what the caller meant).
    """
    out = _prune(tree, mask)
    if out is EMPTY:
        raise ValueError("trainable mask selects no parameters")
    return out


def _prune(tree, mask):
    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            sub = _prune(v, mask[k])
            if sub is not EMPTY:
                out[k] = sub
        return out if out else EMPTY
    return tree if mask else EMPTY


def overlay(full: Any, sub: Any) -> Any:
    """``full`` with every leaf present in ``sub`` replaced by ``sub``'s.

    The merge direction matters for autodiff: gradients flow through the
    returned tree's ``sub`` leaves only — ``full`` contributes constants.
    """
    if isinstance(sub, dict):
        return {k: (overlay(v, sub[k]) if k in sub else v)
                for k, v in full.items()}
    return sub
