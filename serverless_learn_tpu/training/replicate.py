"""Peer state replication for fast rejoin (round 15).

Every elastic remesh used to pay a full central-store round trip to
restore the state it had JUST saved, and a rejoining worker always
pulled from the (possibly distant, possibly partitioned) shard server.
This module keeps the central store authoritative while adding two
cheaper replicas of every checkpoint file:

* a **worker-local cache** (a :class:`~serverless_learn_tpu.training.
  checkpoint.LocalStore` directory): every ``put`` lands here first, so
  the common remesh restore — "re-read the state I committed a moment
  ago" — is a local disk read, not N ranged RPCs. The cache survives a
  process crash (it's a directory), so a RESTARTED worker also rejoins
  from local disk;
* **peer replicas**: each commit is pushed, in commit order, to up to
  ``fanout`` peer caches over the existing shard-server wire protocol
  (each worker can serve its cache with :func:`serve_cache` — the
  pure-Python protocol twin on an ephemeral port). A rejoining or
  remeshing worker then restores from the nearest live peer's copy
  instead of the central store.

Reads stay verified: the Checkpointer consumes the replicas through
``restore_sources()`` (cache → primary → peers) and CRC-checks whichever
copy it loads, so a replica corrupted anywhere is healed by any intact
copy of the same step before step-level fallback gives up ground
(``training/checkpoint.py``).

Pushes are strictly best-effort and ASYNCHRONOUS: a single daemon push
thread drains a bounded FIFO queue (commit order preserved — a peer
never sees a manifest before its blob), failures are counted
(``slt_ckpt_replica_push_failures_total``), and a full queue drops the
oldest entry rather than stalling the training thread.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from serverless_learn_tpu.telemetry import get_registry
from serverless_learn_tpu.training.checkpoint import LocalStore


def _default_peer_factory(addr: str):
    from serverless_learn_tpu.training.checkpoint import ShardServerStore

    return ShardServerStore(addr)


class ReplicatedStore:
    """Checkpoint store tiering: local cache + authoritative primary +
    best-effort peer replicas, with the same put/get/list/delete surface
    as LocalStore/ShardServerStore.

    ``peers`` entries are either store objects (tests, in-process twins)
    or ``host:port`` strings dialed lazily via ``peer_factory`` (default:
    :class:`ShardServerStore`, i.e. a peer's :func:`serve_cache`
    endpoint). Only the first ``fanout`` peers receive pushes; ALL peers
    are candidates for restore reads.
    """

    _QUEUE_DEPTH = 256

    def __init__(self, primary, cache: Optional[LocalStore] = None,
                 peers: Sequence = (), fanout: int = 2,
                 peer_factory: Optional[Callable] = None):
        self.primary = primary
        self.cache = cache
        self._peer_specs = list(peers)
        self._peer_factory = peer_factory or _default_peer_factory
        self._peer_stores: List = [
            None if isinstance(p, str) else p for p in self._peer_specs]
        self.fanout = max(0, int(fanout))
        self._q: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._push_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = get_registry()
        self._m_pushes = reg.counter(
            "slt_ckpt_replica_pushes_total",
            "checkpoint files pushed to peer replicas")
        self._m_push_failures = reg.counter(
            "slt_ckpt_replica_push_failures_total",
            "peer pushes that failed or were dropped (best-effort)")

    # -- peers --------------------------------------------------------------

    def _peer(self, i: int):
        p = self._peer_stores[i]
        if p is None:
            try:
                p = self._peer_factory(self._peer_specs[i])
            except (ConnectionError, OSError):
                return None  # peer down; retried on the next use
            self._peer_stores[i] = p
        return p

    def restore_sources(self) -> List[Tuple[str, object]]:
        """(label, store) per replica, nearest first — the Checkpointer's
        per-step read order."""
        out: List[Tuple[str, object]] = []
        if self.cache is not None:
            out.append(("cache", self.cache))
        out.append(("primary", self.primary))
        for i, spec in enumerate(self._peer_specs):
            p = self._peer(i)
            if p is not None:
                label = spec if isinstance(spec, str) else f"peer-{i}"
                out.append((f"peer:{label}", p))
        return out

    # -- async peer push ----------------------------------------------------

    def _enqueue(self, op: str, key: str, data: Optional[bytes]):
        if self.fanout <= 0 or not self._peer_specs:
            return
        if self._push_thread is None:
            self._push_thread = threading.Thread(
                target=self._push_loop, daemon=True,
                name=f"ckpt-replica-push-{id(self):x}")
            self._push_thread.start()
        try:
            self._q.put_nowait((op, key, data))
        except queue.Full:
            # Never stall the training thread on a slow peer: drop the
            # OLDEST entry (its step will be superseded) and count it.
            self._m_push_failures.inc()
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            try:
                self._q.put_nowait((op, key, data))
            except queue.Full:
                pass

    def _push_loop(self):
        from serverless_learn_tpu.telemetry import dcn

        while True:
            item = self._q.get()
            if item is None:
                return
            op, key, data = item
            for i in range(min(self.fanout, len(self._peer_specs))):
                p = self._peer(i)
                if p is None:
                    self._m_push_failures.inc()
                    continue
                t0 = time.monotonic()
                try:
                    if op == "put":
                        p.put(key, data)
                    else:
                        p.delete(key)
                    self._m_pushes.inc()
                    if op == "put":
                        # Round 16: peer pushes are the third DCN
                        # consumer — byte-counted per transfer so the
                        # replication tier's network cost is visible
                        # next to diloco/remesh (telemetry/dcn.py).
                        dcn.record_transfer(
                            "replica_push", "tx", len(data or b""),
                            time.monotonic() - t0)
                except (ConnectionError, OSError):
                    self._m_push_failures.inc()

    def flush(self, timeout_s: float = 5.0):
        """Best-effort wait until the push queue drains (tests, drain-on
        -exit). Returns True when empty."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while not self._q.empty():
            if _time.monotonic() > deadline:
                return False
            _time.sleep(0.005)
        # queue empty != last item pushed; give the in-flight push a beat
        _time.sleep(0.01)
        return True

    def close(self):
        """Stop the push thread (pending pushes drain first). Leaves the
        primary/cache/peer stores themselves open — this wrapper does not
        own them."""
        if self._push_thread is not None:
            self._q.put(None)
            self._push_thread.join(timeout=5.0)
            self._push_thread = None

    # -- store surface ------------------------------------------------------

    def put(self, key: str, data: bytes):
        # Local first (cheap, crash-persistent), peers next (async), the
        # authoritative primary LAST — so when the primary is partitioned
        # the replicas still carry the newest state for a rejoin, and the
        # caller still sees the primary's failure.
        if self.cache is not None:
            self.cache.put(key, data)
        self._enqueue("put", key, data)
        self.primary.put(key, data)

    def _absent(self) -> tuple:
        from serverless_learn_tpu.control.client import KeyNotFound

        return (FileNotFoundError, KeyNotFound)

    def get(self, key: str) -> bytes:
        absent = self._absent()
        if key.endswith("/LATEST"):
            # LATEST is the one MUTABLE key: the primary is the truth.
            # Only when it is unreachable do the replicas vote — newest
            # step wins (a lagging peer must not roll the run back).
            try:
                return self.primary.get(key)
            except absent:
                raise
            except (ConnectionError, OSError) as e:
                best = None
                for _, src in self._replica_sources():
                    try:
                        data = src.get(key)
                        step = int(json.loads(data)["step"])
                    except Exception:
                        continue
                    if best is None or step > best[0]:
                        best = (step, data)
                if best is not None:
                    return best[1]
                raise e
        if self.cache is not None:
            try:
                return self.cache.get(key)
            except (FileNotFoundError, OSError):
                pass
        try:
            data = self.primary.get(key)
        except absent:
            raise
        except (ConnectionError, OSError) as e:
            for _, src in self._replica_sources(skip_cache=True):
                try:
                    return src.get(key)
                except Exception:
                    continue
            raise e
        if self.cache is not None:
            try:
                self.cache.put(key, data)
            except OSError:
                pass
        return data

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        if self.cache is not None and self.cache.exists(key):
            return self.cache.get_range(key, offset, length)
        try:
            return self.primary.get_range(key, offset, length)
        except self._absent():
            raise
        except (ConnectionError, OSError) as e:
            for _, src in self._replica_sources(skip_cache=True):
                try:
                    return src.get_range(key, offset, length)
                except Exception:
                    continue
            raise e

    def exists(self, key: str) -> bool:
        if self.cache is not None and self.cache.exists(key):
            return True
        try:
            return self.primary.exists(key)
        except (ConnectionError, OSError):
            for _, src in self._replica_sources(skip_cache=True):
                try:
                    if src.exists(key):
                        return True
                except Exception:
                    continue
            raise

    def list(self, prefix: str):
        try:
            return self.primary.list(prefix)
        except (ConnectionError, OSError):
            # Primary unreachable: the union of the replicas' listings is
            # the best available candidate set for a rejoin restore.
            seen = {}
            for _, src in self._replica_sources():
                try:
                    for k in src.list(prefix):
                        seen[k] = True
                except Exception:
                    continue
            return sorted(seen)

    def delete(self, key: str):
        if self.cache is not None:
            try:
                self.cache.delete(key)
            except OSError:
                pass
        self._enqueue("delete", key, None)
        self.primary.delete(key)

    def _replica_sources(self, skip_cache: bool = False):
        for label, src in self.restore_sources():
            if label == "primary" or (skip_cache and label == "cache"):
                continue
            yield label, src


def serve_cache(root: str, host: str = "127.0.0.1", port: int = 0):
    """Serve a worker's local checkpoint cache to its peers over the
    shard-server wire protocol (the in-process pure-Python twin). Returns
    the running server; ``.addr`` is what goes into peers' config."""
    from serverless_learn_tpu.control.py_daemons import PyShardServer

    srv = PyShardServer(host=host, port=port, root=root)
    srv.start()
    return srv


def maybe_replicated(store, cfg) -> object:
    """Wrap ``store`` per ``config.CheckpointConfig`` — identity when no
    cache and no peers are configured, so callers wire unconditionally."""
    if cfg is None:
        return store
    peers = [p.strip() for p in (cfg.peers or "").split(",") if p.strip()]
    if not cfg.cache_dir and not peers:
        return store
    cache = LocalStore(cfg.cache_dir) if cfg.cache_dir else None
    return ReplicatedStore(store, cache=cache, peers=peers,
                           fanout=cfg.replica_fanout)
