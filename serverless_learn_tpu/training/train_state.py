"""Training state pytree.

The reference's entire training state is two global vectors + a float
(``src/master.cc:58-60``), shared *by data race* between three threads
(SURVEY.md §2.8). Here state is an immutable pytree threaded functionally
through a jitted step — race-free by construction — and sharded across the
mesh per ``parallel/sharding.py``.
"""

from __future__ import annotations

from typing import Any

import flax.struct


@flax.struct.dataclass
class TrainState:
    step: Any  # scalar int32 array
    params: Any  # trainable parameter pytree
    opt_state: Any  # optax state
    model_state: Any  # non-trainable collections (e.g. batch_stats), {} if none
