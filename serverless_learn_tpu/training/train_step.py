"""Jitted, mesh-sharded train step — the heart of the framework.

The reference "trains" by bumping a vector on a timer (``src/worker.cc:221-231``)
and synchronizes models by gossiping deltas over gRPC every 5 s
(``src/worker.cc:194-219``, ``src/master.cc:268-293``). Here one ``jax.jit``
over a ``Mesh`` subsumes both: the forward/backward runs on the MXU in bf16,
and XLA inserts the gradient ``psum`` (and any FSDP all-gathers /
reduce-scatters, TP all-reduces) as ICI collectives derived from the sharding
annotations. Gradient traffic over gRPC: zero bytes, by construction —
BASELINE.md's north-star requirement.

Round 18: ``train.zero_stage`` shards the optimizer state and the weight
update over the ``dp`` axis (``training/zero.py`` — ZeRO-1/2 via the same
annotation-first machinery): reduce-scatter in, update on 1/dp slices,
one all-gather out, overlap left to XLA's latency-hiding scheduler.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from serverless_learn_tpu.analysis import jitcheck
from serverless_learn_tpu.config import ExperimentConfig
from serverless_learn_tpu.models.registry import ModelBundle, get_model
from serverless_learn_tpu.parallel.mesh import batch_sharding, make_mesh, replicated
from serverless_learn_tpu.parallel.sharding import ShardingRules, shardings_for_tree
from serverless_learn_tpu.training.optimizer import (
    make_optimizer, make_schedule)
from serverless_learn_tpu.training.train_state import TrainState


@dataclass
class Trainer:
    """Compiled artifacts for one (model, mesh, config) triple."""

    config: ExperimentConfig
    bundle: ModelBundle
    mesh: Mesh
    init_fn: Callable  # (seed:int) -> TrainState (sharded, on device)
    step_fn: Callable  # (TrainState, batch) -> (TrainState, metrics)
    eval_fn: Callable  # (TrainState, batch) -> metrics (no state update)
    state_shardings: Any
    batch_shardings: Any

    def init(self, seed: Optional[int] = None) -> TrainState:
        self._bind_mesh()
        return self.init_fn(seed if seed is not None else self.config.train.seed)

    def abstract_state(self) -> TrainState:
        """Shape/dtype skeleton of the TrainState — a restore template that
        costs nothing. Re-meshing used to pay a full random init (8B scale:
        tens of GB of HBM churn) just to have a structure to restore into."""
        self._bind_mesh()
        return jax.eval_shape(lambda: self.init_fn(self.config.train.seed))

    def step(self, state: TrainState, batch) -> tuple:
        # (Re)tracing can happen at any step call; bind this trainer's mesh
        # so mesh-dependent ops (ring attention's shard_map) trace against it
        # even if another trainer was built since.
        self._bind_mesh()
        return self.step_fn(state, batch)

    def eval_step(self, state: TrainState, batch):
        """Forward-only metrics on one batch (inference mode, no state update)."""
        self._bind_mesh()
        return self.eval_fn(state, batch)

    def _bind_mesh(self):
        from serverless_learn_tpu.parallel.ring_attention import set_active_mesh

        set_active_mesh(self.mesh)

    def shard_batch(self, host_batch) -> Any:
        """Place a host batch onto the mesh with the input shardings.

        Single-process: ``host_batch`` is the global batch and ``device_put``
        scatters it. Multi-host: ``host_batch`` is this process's slice of
        the global batch (``make_source`` yields per-process batches) and the
        global array is assembled from the process-local shards without any
        cross-host copy.
        """
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s),
                host_batch, self.batch_shardings)
        return jax.tree_util.tree_map(
            lambda x, s: jax.make_array_from_process_local_data(s, x),
            host_batch, self.batch_shardings)


# Compile-budget contract (SLT_JITCHECK=1, analysis/jitcheck.py): the
# three jits build_trainer creates — init, step, eval — each see ONE
# abstract signature per trainer (the loop feeds fixed-shape sharded
# batches), so each jit object compiles exactly once. A second compile
# on the same object is shape drift in the hot loop and fails the
# session with the stack that caused it.
jitcheck.declare_budget(
    "serverless_learn_tpu/training/train_step.py:build_trainer",
    max_compiles_per_jit=1)


def build_trainer(
    config: ExperimentConfig,
    mesh: Optional[Mesh] = None,
    rules: Optional[ShardingRules] = None,
) -> Trainer:
    # train.dtype / train.param_dtype are the config-level mixed-precision
    # policy; explicit model_overrides win.
    overrides = dict(config.model_overrides)
    overrides.setdefault("dtype", jnp.dtype(config.train.dtype))
    overrides.setdefault("param_dtype", jnp.dtype(config.train.param_dtype))
    if config.train.remat:
        # Only set when asked: model families without a remat knob (MLP,
        # ResNet) should fail loudly on the unknown kwarg, not silently
        # ignore the request.
        overrides.setdefault("remat", True)
    bundle = get_model(config.model, **overrides)
    if mesh is None:
        mesh = make_mesh(config.mesh)
    # With a trainable_mask the trainer PARTITIONS (training/partition.py):
    # grads and optimizer state cover only the trainable subtree, so the
    # optimizer needs no multi_transform freeze — and the backward never
    # computes frozen weight gradients at all. (An 8B frozen base would
    # otherwise materialize a 32 GB gradient pytree; an int8 frozen base
    # cannot be differentiated against, period.)
    tx = make_optimizer(config.optimizer)

    # Ring attention (sequence parallelism) shard_maps over this mesh.
    from serverless_learn_tpu.parallel.ring_attention import set_active_mesh

    set_active_mesh(mesh)

    batch_size = config.train.batch_size
    spec = bundle.input_spec(config.data, batch_size)
    # Sequence-model inputs [B, T] additionally shard T over sp (inert when
    # sp == 1); image batches stay batch-sharded only.
    sp_seq = bundle.task in ("lm", "mlm") and mesh.shape["sp"] > 1
    b_shardings = jax.tree_util.tree_map(
        lambda s: batch_sharding(mesh, sp_seq=sp_seq and len(s.shape) >= 2),
        spec)

    from serverless_learn_tpu.training.partition import overlay, prune

    def trainable_of(params):
        """Trainable subtree (the whole tree when no mask is set)."""
        if bundle.trainable_mask is None:
            return params
        return prune(params, bundle.trainable_mask(params))

    def init_raw(seed):
        rng = jax.random.PRNGKey(seed)
        dummy = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), spec)
        first = next(iter(dummy.values())) if isinstance(dummy, dict) else dummy
        variables = bundle.module.init(rng, first)
        params = variables["params"]
        # "losses" is an ephemeral sow target (MoE aux), not model state —
        # keeping it would freeze init-time scalars into checkpoints.
        model_state = {k: v for k, v in variables.items()
                       if k not in ("params", "losses")}
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(trainable_of(params)),
            model_state=model_state,
        )

    # ZeRO update sharding (round 18, training/zero.py): with
    # train.zero_stage >= 1 the optimizer state (and the update
    # computation) shards 1/dp per replica instead of replicating — the
    # per-chip memory win and the dp-collective restructuring
    # (reduce-scatter in, all-gather out) ride the SAME annotation-first
    # machinery as fsdp/tp; no step-code fork.
    from serverless_learn_tpu.training import zero as zero_mod

    zero_stage = zero_mod.validate_zero_stage(config.train.zero_stage)
    grad_reduce_dtype = zero_mod.normalize_grad_reduce_dtype(
        config.train.grad_reduce_dtype)
    zero_on = zero_stage >= 1 and mesh.shape[zero_mod.UPDATE_AXIS] > 1

    # Resolve state shardings from abstract shapes, then materialize the real
    # state directly into its sharded layout (no host round-trip).
    abstract = jax.eval_shape(init_raw, 0)
    state_shardings = TrainState(
        step=replicated(mesh),
        params=shardings_for_tree(abstract.params, mesh, rules),
        # divisible_only: optimizer leaves match param PATHS but not
        # necessarily param shapes (adafactor's factored stats, counts) —
        # non-dividing rule axes drop to replicated instead of crashing.
        # Under ZeRO the dp axis is additionally composed into every
        # leaf that divides; tx.init then materializes straight into the
        # dp-sharded layout through the jitted init's out_shardings.
        opt_state=(zero_mod.zero_shardings_for_tree(abstract.opt_state,
                                                    mesh, rules)
                   if zero_on else
                   shardings_for_tree(abstract.opt_state, mesh, rules,
                                      divisible_only=True)),
        model_state=shardings_for_tree(abstract.model_state, mesh, rules),
    )
    # dp-composed shardings for gradient/update leaves (trainable-tree
    # shaped): the update constraint (stage >= 1) makes GSPMD compute the
    # optimizer chain on 1/dp slices and all-gather the updated params;
    # the grads constraint (stage 2) turns the gradient psum into a
    # reduce-scatter into the owned slice.
    update_shardings = (zero_mod.zero_shardings_for_tree(
        jax.eval_shape(trainable_of, abstract.params), mesh, rules)
        if zero_on else None)
    init_jit = jax.jit(init_raw, static_argnums=(0,),
                       out_shardings=state_shardings)

    def loss_for_grad(t_params, full_params, model_state, batch, rng):
        # Gradients flow only through ``t_params``; with a trainable_mask
        # the frozen remainder of ``full_params`` enters as constants.
        params = (overlay(full_params, t_params)
                  if bundle.trainable_mask is not None else t_params)
        loss, aux = bundle.loss_fn(params, batch, rngs=rng,
                                   model_state=model_state)
        return loss, aux

    accum = config.train.grad_accum
    if accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {accum}")
    n_data = mesh.shape["dp"] * mesh.shape["fsdp"]
    if accum > 1 and batch_size % (accum * n_data):
        # Each microbatch must itself divide evenly over the data axes, or
        # the per-microbatch sharding is invalid / forces data movement.
        raise ValueError(
            f"batch_size {batch_size} not divisible by grad_accum {accum} "
            f"x dp*fsdp {n_data}")
    # Microbatches keep the per-sample sharding; the scan axis is unsharded.
    micro_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P(None, *tuple(s.spec))), b_shardings)

    def grads_and_aux(t_params, full_params, model_state, batch, rng):
        """(mean grads over the TRAINABLE tree, last model_state, mean loss,
        mean metrics).

        accum == 1: single whole-batch backward. accum > 1: ``lax.scan`` over
        microbatches — activations live only for one microbatch at a time,
        so live memory is ~1/accum of the whole-batch backward; BatchNorm-style
        state threads through the scan carry sequentially.
        """
        grad_fn = jax.value_and_grad(loss_for_grad, has_aux=True)
        if accum == 1:
            (loss, aux), grads = grad_fn(t_params, full_params, model_state,
                                         batch, rng)
            return grads, (aux["model_state"] or model_state), loss, aux["metrics"]

        def to_micro(x, s):
            b = x.shape[0]
            if n_data > 1:
                # Communication-free microbatching: each device's contiguous
                # batch block splits into `accum` sub-blocks and microbatch m
                # takes sub-block m from every device. A naive
                # reshape-to-(accum, b/accum) would need rows that live on
                # other devices (an all-to-all of the whole batch every
                # step); this is a pure sample permutation — harmless for
                # i.i.d. batches, gradient mean unchanged — that keeps every
                # row on the device that already holds it.
                local = b // (n_data * accum)
                mb = x.reshape((n_data, accum, local) + x.shape[1:])
                mb = jnp.moveaxis(mb, 1, 0)
                mb = mb.reshape((accum, n_data * local) + x.shape[1:])
            else:
                mb = x.reshape((accum, b // accum) + x.shape[1:])
            return jax.lax.with_sharding_constraint(mb, s)

        micro = jax.tree_util.tree_map(to_micro, batch, micro_shardings)

        def body(carry, xs):
            g_acc, w_acc, mstate = carry
            mb, idx = xs
            (loss, aux), g = grad_fn(t_params, full_params, mstate,
                                     mb, jax.random.fold_in(rng, idx))
            # Losses with data-dependent normalization (MLM divides by the
            # microbatch's masked-token count) report that denominator as
            # aux["loss_weight"]; weighting each microbatch's gradient by it
            # reproduces the whole-batch gradient exactly. Uniform losses
            # omit it (weight 1) and reduce to a plain mean.
            w = aux.get("loss_weight", jnp.float32(1.0))
            g_acc = jax.tree_util.tree_map(
                lambda a, gi: a + w * gi.astype(a.dtype), g_acc, g)
            return ((g_acc, w_acc + w, aux["model_state"] or mstate),
                    (loss * w, jax.tree_util.tree_map(lambda m: m * w,
                                                      aux["metrics"])))

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), t_params)
        (g_sum, w_sum, mstate), (losses, metrics) = jax.lax.scan(
            body, (zeros, jnp.float32(0.0), model_state),
            (micro, jnp.arange(accum)))
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / w_sum).astype(p.dtype), g_sum, t_params)
        metrics = jax.tree_util.tree_map(lambda m: m.sum() / w_sum, metrics)
        return grads, mstate, losses.sum() / w_sum, metrics

    donate = (0,) if config.train.donate_state else ()
    ncfg = config.numerics

    @partial(jax.jit, donate_argnums=donate,
             in_shardings=(state_shardings, b_shardings),
             out_shardings=(state_shardings, replicated(mesh)))
    def step_fn(state: TrainState, batch):
        from serverless_learn_tpu.telemetry import numerics as _numerics

        rng = jax.random.fold_in(jax.random.PRNGKey(config.train.seed),
                                 state.step)
        t_params = trainable_of(state.params)
        grads, new_model_state, loss, metrics = grads_and_aux(
            t_params, state.params, state.model_state, batch, rng)
        if ncfg.inject_nan_step:
            # Chaos knob (round 17): poison the named subtree's gradient
            # at exactly one step, so the NaN-provenance acceptance test
            # has a seeded, layer-attributable fault.
            from serverless_learn_tpu.training.audit import inject_nan

            grads = inject_nan(grads, state.step + 1, ncfg.inject_nan_step,
                               ncfg.inject_nan_subtree, ncfg.depth)
        if grad_reduce_dtype != "float32":
            # bf16 gradient exchange: round the reduced gradient to the
            # exchange dtype (halves the reduce-scatter bytes on the
            # wire; numerically this IS the precision the update sees,
            # so the bf16 loss-curve-parity test measures the real
            # cost). No error feedback by design — see TrainConfig.
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)
        if zero_on and zero_stage >= 2:
            # Stage 2: the gradient tree itself lives dp-sharded — the
            # dp psum becomes a reduce-scatter into the owned slice.
            # Applied HERE, after the grad-accum scan, never inside it:
            # microbatches accumulate locally and the step pays ONE
            # cross-replica reduce (pinned by test_grad_accum_eval's
            # jaxpr audit).
            grads = jax.lax.with_sharding_constraint(grads,
                                                     update_shardings)
        updates, new_opt = tx.update(grads, state.opt_state, t_params)
        if zero_on:
            # Stage 1+: the update computation runs on 1/dp slices; the
            # replicated new params below force the one all-gather.
            updates = jax.lax.with_sharding_constraint(updates,
                                                       update_shardings)
        new_t = jax.tree_util.tree_map(
            lambda p, u: (p + u.astype(p.dtype)), t_params, updates)
        new_params = (overlay(state.params, new_t)
                      if bundle.trainable_mask is not None else new_t)
        metrics = dict(metrics)
        schedule = make_schedule(config.optimizer)
        metrics["lr"] = (schedule(state.step) if callable(schedule)
                         else jnp.float32(schedule))
        if "perplexity" in metrics:
            # exp() is nonlinear: averaging per-microbatch perplexities
            # (Jensen) would make the metric depend on grad_accum. The
            # averaged loss is exact, so derive perplexity from it.
            metrics["perplexity"] = jnp.exp(loss)
        metrics["loss"] = loss
        metrics["grad_norm"] = _numerics.global_norm(grads)
        if ncfg.enabled:
            # In-graph numerics (round 17): per-subtree grad/param/update
            # norms, update-to-param ratios, non-finite flags and
            # parameter fingerprints as fused scalar reductions — the
            # loop pops this sub-dict BEFORE its per-step device_get and
            # hands it to the auditor, which fetches it only at the
            # configured cadence (zero extra per-step host syncs).
            metrics["numerics"] = _numerics.step_summary(
                new_t, grads, updates, loss=loss, depth=ncfg.depth,
                chunks=ncfg.chunks, with_fingerprint=ncfg.fingerprint)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt, model_state=new_model_state)
        return new_state, metrics

    eval_loss = bundle.eval_loss_fn or bundle.loss_fn

    @partial(jax.jit, in_shardings=(state_shardings, b_shardings),
             out_shardings=replicated(mesh))
    def eval_fn(state: TrainState, batch):
        loss, aux = eval_loss(state.params, batch, rngs=None,
                              model_state=state.model_state)
        metrics = dict(aux["metrics"])
        metrics["loss"] = loss
        return metrics

    return Trainer(config=config, bundle=bundle, mesh=mesh,
                   init_fn=init_jit, step_fn=step_fn, eval_fn=eval_fn,
                   state_shardings=state_shardings, batch_shardings=b_shardings)
