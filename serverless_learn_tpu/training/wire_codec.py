"""Blockwise int8/fp8 wire codec for pytrees crossing DCN (round 20).

EQuARX (arXiv:2506.17615) showed blockwise-quantized collectives buy a
~4x byte reduction at negligible quality cost. This module is that idea
for the repo's *store-mediated* DCN exchanges: DiLoCo outer-boundary
delta pushes and anchor broadcasts (``training/diloco_dcn.py``), elastic
remesh state streaming (``training/elastic.py``), and the vmapped herd's
simulated delta wire (``training/herd.py``). Checkpoint persistence and
peer replication stay **bit-exact** — their CRC machinery depends on
byte identity, so this codec is deliberately not reachable from
``training/checkpoint.py`` / ``training/replicate.py`` write paths.

Format
------
One value = one byte (int8 two's complement, or fp8-e4m3fn where the
runtime supports it) plus one float32 scale per block of ``block``
consecutive values of the flattened leaf:

    scale_b = max(|x_b|) / QMAX          (QMAX: 127 int8, 448 fp8)
    q_b     = round_half_even(x_b / scale_b)   clipped to [-QMAX, QMAX]
    x_b'    = q_b * scale_b

An all-zero block has scale 0 and dequantizes to exact zeros; ties round
half-to-even identically in the numpy and jax paths, so the codec is
deterministic and vmap-equals-loop (pinned by tests/test_wire_codec.py).
int8 host and in-graph paths agree bit-for-bit; fp8 host/graph may
differ by one fp8 step on borderline values (XLA's f32→f8 convert
double-rounds) — harmless, since no value stream crosses the two paths.
Only floating leaves are quantized — integer/bool leaves (optimizer step
counts, PRNG keys) ride the wire verbatim, because rounding a counter is
corruption, not compression.

Error feedback
--------------
Quantization noise per exchange is bounded (|x - x'| <= scale_b / 2) but
*biased* within a round. :class:`ErrorFeedback` carries each sender's
residual ``sent - dequantized`` into the next round's payload before
quantization, so the long-run average of what receivers see equals the
long-run average of what senders meant — the property DiLoCo's outer
Nesterov step needs (herd A/B: ``training/herd.py run_wire_ab``).

Non-finite values are **refused** with the typed :class:`NonFiniteError`
(a NaN has no finite scale, and silently flushing it to zero would make
the leader's delta-quarantine gate cosmetic). Callers that must deliver
a poisoned tree anyway — so the gate can see and quarantine it — fall
back to the uncompressed f32 encoding. The in-graph
:func:`fake_quantize` path can't raise; it propagates NaN through any
block containing one, which trips the same gate on dequantized values.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np
from flax import serialization

_MARKER = "__slt_wire__"
_VERSION = 1
BLOCK_DEFAULT = 128
QMAX = {"int8": 127.0, "fp8": 448.0}
_ALIASES = {
    "f32": "float32", "float32": "float32", "fp32": "float32",
    "int8": "int8", "i8": "int8",
    "fp8": "fp8", "fp8_e4m3": "fp8", "float8_e4m3fn": "fp8", "f8": "fp8",
}
# Non-numpy float dtypes (ml_dtypes) report kind 'V'; name-match them.
_FLOAT_NAMES = ("bfloat16", "float8_e4m3fn", "float8_e5m2",
                "float8_e4m3b11fnuz")


class WireCodecError(ValueError):
    """Malformed wire blob / unsupported dtype / bad parameters."""


class NonFiniteError(WireCodecError):
    """The tree holds NaN/Inf — refused so quarantine semantics hold."""

    def __init__(self, path: str, count: int):
        self.path, self.count = path, count
        super().__init__(
            f"non-finite value(s) refused by the wire codec: {count} "
            f"at {path!r} (send uncompressed so the gate can see them)")


def normalize_dtype(name: str) -> str:
    """Canonical wire dtype ("float32" | "int8" | "fp8") or ValueError."""
    out = _ALIASES.get(str(name).lower())
    if out is None:
        raise WireCodecError(
            f"unknown wire dtype {name!r} (want f32|int8|fp8)")
    return out


def fp8_dtype():
    """The fp8-e4m3 numpy dtype, or None where the runtime lacks it."""
    try:
        import ml_dtypes

        return np.dtype(ml_dtypes.float8_e4m3fn)
    except (ImportError, AttributeError):
        return None


def fp8_supported() -> bool:
    return fp8_dtype() is not None


def require_supported(dtype: str) -> str:
    """Normalize + assert the runtime can actually encode ``dtype``."""
    dtype = normalize_dtype(dtype)
    if dtype == "fp8" and not fp8_supported():
        raise WireCodecError(
            "wire dtype fp8 requested but ml_dtypes.float8_e4m3fn is "
            "unavailable in this runtime; use int8 or f32")
    return dtype


def _is_float(dt: np.dtype) -> bool:
    dt = np.dtype(dt)
    return dt.kind == "f" or dt.name in _FLOAT_NAMES


def _is_q_leaf(node) -> bool:
    return isinstance(node, dict) and node.get("__q__") == 1


def _walk(node, fn, path=""):
    """Depth-first map over a flax state dict (nested str-keyed dicts);
    encoded-leaf records (``{"__q__": 1, ...}``) are leaves, not nodes."""
    if isinstance(node, dict) and not _is_q_leaf(node):
        return {k: _walk(v, fn, f"{path}/{k}" if path else str(k))
                for k, v in node.items()}
    return fn(path, node)


# -- host (numpy) path --------------------------------------------------------


def _blocks(flat: np.ndarray, block: int) -> np.ndarray:
    pad = (-flat.shape[0]) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(-1, block)


def quantize_array(x, dtype: str, block: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """One leaf -> (q [nb*block] int8-or-fp8-as-uint8-bytes, scales [nb]).
    Caller has already verified finiteness."""
    flat = np.asarray(x, np.float32).reshape(-1)
    b = _blocks(flat, block)
    amax = np.max(np.abs(b), axis=1)
    scales = (amax / QMAX[dtype]).astype(np.float32)
    safe = np.where(scales > 0, scales, np.float32(1.0))
    y = b / safe[:, None]
    if dtype == "int8":
        q = np.clip(np.rint(y), -127, 127).astype(np.int8)
    else:
        q = y.astype(fp8_dtype())
    # uint8 view on the wire: flax msgpack round-trips fp8 in THIS image,
    # but a receiver without ml_dtypes must still be able to decode the
    # container and fail typed, not on an unknown-dtype ext code. The
    # block-padding tail is trimmed — it is all zeros by construction
    # and the decoder re-pads from the stamped shape.
    return q.reshape(-1)[:flat.shape[0]].view(np.uint8), scales


def dequantize_array(q: np.ndarray, scales: np.ndarray, dtype: str,
                     shape, out_dtype,
                     block: int = BLOCK_DEFAULT) -> np.ndarray:
    view = np.int8 if dtype == "int8" else fp8_dtype()
    if view is None:
        raise WireCodecError("fp8 wire blob but no fp8 runtime support")
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nb = max(int(scales.shape[0]), 1)
    vals = q.view(view).astype(np.float32)
    pad = nb * block - vals.shape[0]
    if pad < 0:
        raise WireCodecError(
            f"quantized leaf holds {vals.shape[0]} values but "
            f"{nb} block(s) of {block} imply at most {nb * block}")
    if pad:
        vals = np.concatenate([vals, np.zeros(pad, np.float32)])
    deq = (vals.reshape(nb, block)
           * scales[:, None].astype(np.float32)).reshape(-1)
    return deq[:n].reshape(shape).astype(out_dtype)


def _encode_leaf(path: str, leaf, dtype: str, block: int,
                 decoded: Dict[str, Any]):
    arr = np.asarray(leaf)
    if not _is_float(arr.dtype):
        decoded[path] = arr
        return arr
    bad = int(arr.size - np.isfinite(
        np.asarray(arr, np.float32)).sum())
    if bad:
        raise NonFiniteError(path, bad)
    q, scales = quantize_array(arr, dtype, block)
    decoded[path] = dequantize_array(q, scales, dtype, arr.shape,
                                     arr.dtype, block)
    return {"__q__": 1, "q": q, "s": scales,
            "shape": [int(n) for n in arr.shape],
            "dt": arr.dtype.name}


def encode(tree, dtype: str = "int8", block: int = BLOCK_DEFAULT,
           meta: Optional[dict] = None) -> bytes:
    blob, _ = encode_with_decoded(tree, dtype, block, meta)
    return blob


def encode_with_decoded(tree, dtype: str = "int8",
                        block: int = BLOCK_DEFAULT,
                        meta: Optional[dict] = None):
    """Encode ``tree`` and also return what the receiver will decode —
    the sender-side dequantized twin the error-feedback residual needs,
    produced without a serialize/parse round trip."""
    dtype = require_supported(dtype)
    if block < 1:
        raise WireCodecError(f"block must be >= 1, got {block}")
    state = serialization.to_state_dict(tree)
    decoded_flat: Dict[str, Any] = {}
    if dtype == "float32":
        enc = _walk(state, lambda p, l: np.asarray(l))
        payload = {_MARKER: _VERSION, "dtype": dtype, "block": int(block),
                   "meta": dict(meta or {}), "tree": enc}
        return serialization.msgpack_serialize(payload), \
            serialization.from_state_dict(tree, enc)
    enc = _walk(state,
                lambda p, l: _encode_leaf(p, l, dtype, block,
                                          decoded_flat))
    payload = {_MARKER: _VERSION, "dtype": dtype, "block": int(block),
               "meta": dict(meta or {}), "tree": enc}
    decoded = _walk(state, lambda p, l: decoded_flat[p])
    return serialization.msgpack_serialize(payload), \
        serialization.from_state_dict(tree, decoded)


def is_wire(obj) -> bool:
    return isinstance(obj, dict) and obj.get(_MARKER) == _VERSION


def _decode_leaf(path: str, leaf, dtype: str, block: int):
    if _is_q_leaf(leaf):
        return dequantize_array(
            np.asarray(leaf["q"]), np.asarray(leaf["s"]), dtype,
            tuple(int(n) for n in leaf["shape"]),
            np.dtype(str(leaf["dt"])), block)
    return leaf


def decode_payload(obj) -> Any:
    """Dequantize a parsed wire payload back into a host state dict."""
    if not is_wire(obj):
        raise WireCodecError("not a wire-codec payload")
    dtype = normalize_dtype(obj.get("dtype", "int8"))
    block = int(obj.get("block", BLOCK_DEFAULT))
    return _walk(obj["tree"],
                 lambda p, l: _decode_leaf(p, l, dtype, block))


def decode(blob: bytes, template=None, with_meta: bool = False):
    """Decode a wire blob — or a legacy bare flax state-dict blob, so
    mixed-dtype fleets interoperate (a rejoining island can adopt
    whatever encoding the current leader publishes). With ``template``
    the result is mapped through ``from_state_dict``."""
    try:
        obj = serialization.msgpack_restore(blob)
    except Exception as e:
        raise WireCodecError(f"undecodable wire blob: {e}")
    meta: dict = {}
    if is_wire(obj):
        meta = dict(obj.get("meta") or {})
        tree = decode_payload(obj)
    else:
        tree = obj  # legacy uncompressed state dict
    if template is not None:
        tree = serialization.from_state_dict(template, tree)
    return (tree, meta) if with_meta else tree


def blob_dtype(blob: bytes) -> str:
    """The wire dtype a blob was encoded with ("float32" for legacy)."""
    try:
        obj = serialization.msgpack_restore(blob)
    except Exception as e:
        raise WireCodecError(f"undecodable wire blob: {e}")
    return normalize_dtype(obj.get("dtype", "float32")) if is_wire(obj) \
        else "float32"


# -- byte accounting ----------------------------------------------------------


def logical_nbytes(tree) -> int:
    """Bytes the exchange would move at full precision: 4 per float
    value (the f32 wire the codec replaces), itemsize otherwise. Pure
    shape/dtype metadata — safe on device arrays and ShapeDtypeStructs."""
    total = 0
    for _, leaf in _iter_leaves(serialization.to_state_dict(tree)):
        size = int(np.prod(getattr(leaf, "shape", ()) or (1,),
                           dtype=np.int64))
        dt = np.dtype(getattr(leaf, "dtype", np.float32))
        total += size * (4 if _is_float(dt) else dt.itemsize)
    return total


def wire_nbytes(tree, dtype: str = "int8",
                block: int = BLOCK_DEFAULT) -> int:
    """Payload bytes of the quantized encoding (1 byte/value padded to
    the block + one f32 scale per block), excluding container framing —
    the estimator the vmapped herd uses where nothing is serialized."""
    dtype = normalize_dtype(dtype)
    if dtype == "float32":
        return logical_nbytes(tree)
    total = 0
    for _, leaf in _iter_leaves(serialization.to_state_dict(tree)):
        size = int(np.prod(getattr(leaf, "shape", ()) or (1,),
                           dtype=np.int64))
        dt = np.dtype(getattr(leaf, "dtype", np.float32))
        if _is_float(dt):
            total += size + 4 * math.ceil(size / block)
        else:
            total += size * dt.itemsize
    return total


def _iter_leaves(node, path=""):
    if isinstance(node, dict):
        for k, v in node.items():
            yield from _iter_leaves(v, f"{path}/{k}" if path else str(k))
    else:
        yield path, node


# -- error feedback -----------------------------------------------------------


def _tree_binop(a, b, op):
    if isinstance(a, dict):
        return {k: _tree_binop(a[k], b[k], op) for k in a}
    return op(np.asarray(a, np.float32), np.asarray(b, np.float32)) \
        if _is_float(np.asarray(a).dtype) else a


class ErrorFeedback:
    """Per-sender residual carry for a quantized exchange.

    ``encode(tree)`` quantizes ``tree + residual`` and retains the new
    residual ``(tree + residual) - dequantized`` for the next call —
    the receiver-visible stream is unbiased in the long run. A
    :class:`NonFiniteError` from the codec leaves the residual untouched
    (the caller ships the poisoned tree uncompressed instead; folding a
    NaN into the carry would poison every later round). ``reset()``
    drops the carry (e.g. after a rejoin adopted a fresh anchor)."""

    def __init__(self, dtype: str = "int8", block: int = BLOCK_DEFAULT,
                 enabled: bool = True):
        self.dtype = require_supported(dtype)
        self.block = int(block)
        self.enabled = bool(enabled)
        self.residual = None

    def reset(self):
        self.residual = None

    def encode(self, tree, meta: Optional[dict] = None) -> bytes:
        state = serialization.to_state_dict(tree)
        send = state if (self.residual is None or not self.enabled) \
            else _tree_binop(state, self.residual, np.add)
        blob, decoded = encode_with_decoded(send, self.dtype, self.block,
                                            meta)
        if self.enabled and self.dtype != "float32":
            self.residual = _tree_binop(
                send, serialization.to_state_dict(decoded), np.subtract)
        return blob


# -- in-graph (jit/vmap) path -------------------------------------------------


def fake_quantize(x, dtype: str = "int8", block: int = BLOCK_DEFAULT):
    """Quantize→dequantize one array inside a jitted/vmapped program —
    the herd's simulated wire. Identical math to the host path (same
    half-even rounding, same scale rule), but instead of raising on
    NaN/Inf it turns every value of an affected block into NaN, so the
    downstream quarantine gate (which reads DEQUANTIZED deltas) still
    sees and rejects the poisoned sender."""
    import jax.numpy as jnp

    dtype = require_supported(dtype)
    if dtype == "float32":
        return x
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    b = flat.reshape(-1, block)
    finite = jnp.isfinite(b)
    amax = jnp.max(jnp.abs(jnp.where(finite, b, 0.0)), axis=1,
                   keepdims=True)
    scale = amax / QMAX[dtype]
    safe = jnp.where(scale > 0, scale, 1.0)
    y = b / safe
    if dtype == "int8":
        deq = jnp.clip(jnp.round(y), -127, 127) * scale
    else:
        deq = y.astype(jnp.float8_e4m3fn).astype(jnp.float32) * scale
    deq = jnp.where(finite.all(axis=1, keepdims=True), deq, jnp.nan)
    return deq.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)


def tree_fake_quantize(tree, dtype: str = "int8",
                       block: int = BLOCK_DEFAULT):
    """:func:`fake_quantize` over every floating leaf of a pytree."""
    import jax

    dtype = require_supported(dtype)
    if dtype == "float32":
        return tree
    return jax.tree_util.tree_map(
        lambda l: fake_quantize(l, dtype, block)
        if _is_float(np.dtype(l.dtype)) else l, tree)
