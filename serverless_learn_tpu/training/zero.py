"""ZeRO-style update sharding over the dp axis (arXiv:2004.13336).

Data parallelism replicates the optimizer update: every dp replica holds
the full optimizer state and applies the identical update to the
identical parameters — at dp=8 that is 8x the opt-state HBM and 8x the
update FLOPs the math needs. This module shards both across the ``dp``
axis, sharding-annotation-first (the ``parallel/sharding.py`` idiom —
no manual collectives, no shard_map):

* **Optimizer state** (`zero_stage >= 1`): every opt-state leaf gets the
  ``dp`` axis composed into the first dimension it divides
  (:func:`serverless_learn_tpu.parallel.sharding.compose_axis`), on top
  of its rule-derived fsdp/tp spec — each replica owns a 1/dp slice and
  ``tx.init`` materializes directly into that layout via the jitted
  init's ``out_shardings``.
* **Update computation** (`zero_stage >= 1`): the ``tx.update`` output is
  constrained to the same dp-sharded layout, so GSPMD partitions the
  whole optimizer chain (moment updates, clip, decay) over dp — each
  replica computes only its slice — and inserts ONE all-gather where the
  updated slices meet the replicated params.
* **Gradients** (`zero_stage == 2`): the post-accumulation gradient tree
  is additionally constrained dp-sharded, which turns the gradient
  all-reduce into a reduce-scatter into the owned slice and keeps any
  full-gradient tree from materializing per replica.

Overlap is XLA's job, by design: annotation-first keeps the
reduce-scatter / all-gather inside the one jitted step program, where
the latency-hiding scheduler overlaps them with backward / next-step
compute (on TPU; XLA:CPU lowers the same program with unoverlapped
collectives, which is what the tests run on). ``slt xray`` measures the
result — ``exposed_collective_s`` per ``@dp`` key — instead of trusting
the schedule.

Numerics: reduce-scatter + all-gather re-associates the same summands
the all-reduce summed, so ``zero_stage=1`` matches ``zero_stage=0``
step-for-step (ulp-tight at f32 grad reduce — pinned by the
``ParityHarness`` tests). ``grad_reduce_dtype=bf16`` rounds the
exchanged gradient to bf16 (loss-curve parity within tolerance, not
ulp parity).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

from serverless_learn_tpu.parallel.sharding import (
    ShardingRules, compose_axis, specs_for_tree)

ZERO_STAGES = (0, 1, 2)
UPDATE_AXIS = "dp"

_GRAD_REDUCE_DTYPES = {
    "float32": "float32", "f32": "float32",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
}


def normalize_grad_reduce_dtype(name: str) -> str:
    """Canonical dtype name for ``train.grad_reduce_dtype`` ("float32" |
    "bfloat16"); raises on anything else — a typo'd dtype must not
    silently train in full precision."""
    key = str(name or "float32").lower()
    if key not in _GRAD_REDUCE_DTYPES:
        raise ValueError(
            f"train.grad_reduce_dtype must be one of "
            f"{sorted(set(_GRAD_REDUCE_DTYPES))}, got {name!r}")
    return _GRAD_REDUCE_DTYPES[key]


def validate_zero_stage(stage: int) -> int:
    if stage not in ZERO_STAGES:
        raise ValueError(
            f"train.zero_stage must be one of {ZERO_STAGES}, got {stage!r}")
    return int(stage)


def zero_specs_for_tree(tree: Any, mesh, rules: Optional[ShardingRules]
                        = None, axis: str = UPDATE_AXIS) -> Any:
    """Rule specs for ``tree`` with ``axis`` composed into every leaf
    that can host it (``divisible_only`` base — these are opt-state /
    gradient leaves, which share the params' PATHS, not their shapes)."""
    base = specs_for_tree(tree, mesh, rules, divisible_only=True)

    def one(leaf, spec):
        shape = tuple(getattr(leaf, "shape", ()))
        return compose_axis(spec, shape, mesh, axis)

    return jax.tree_util.tree_map(one, tree, base)


def zero_shardings_for_tree(tree: Any, mesh,
                            rules: Optional[ShardingRules] = None,
                            axis: str = UPDATE_AXIS) -> Any:
    from jax.sharding import NamedSharding

    specs = zero_specs_for_tree(tree, mesh, rules, axis)
    return jax.tree_util.tree_map(lambda _, s: NamedSharding(mesh, s),
                                  tree, specs)


# -- layout accounting --------------------------------------------------------


def bytes_per_chip(tree: Any) -> float:
    """Mean per-device bytes actually resident for a pytree of (possibly
    sharded) ``jax.Array``s — the number ``slt_opt_state_bytes`` reports.
    Replicated leaves cost their full size on every chip; a dp-sharded
    leaf costs 1/dp. Host/numpy leaves count at full size (they live on
    every host)."""
    per_device: dict = {}
    host_bytes = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            for sh in leaf.addressable_shards:
                key = getattr(sh.device, "id", sh.device)
                per_device[key] = (per_device.get(key, 0.0)
                                   + float(np.prod(sh.data.shape))
                                   * np.dtype(leaf.dtype).itemsize)
        else:
            arr = np.asarray(leaf)
            host_bytes += float(arr.nbytes)
    if not per_device:
        return host_bytes
    return host_bytes + sum(per_device.values()) / len(per_device)


def publish_opt_state_gauge(opt_state, registry=None) -> float:
    """Stamp ``slt_opt_state_bytes`` (per-chip resident optimizer-state
    bytes) from a live state; returns the value. Called by the training
    loop after init and by the elastic trainer after every remesh
    restore, so the gauge tracks re-partitioning across worlds."""
    from serverless_learn_tpu.telemetry.registry import get_registry

    reg = registry or get_registry()
    val = bytes_per_chip(opt_state)
    reg.gauge("slt_opt_state_bytes",
              "resident optimizer-state bytes per chip "
              "(shrinks ~1/dp under train.zero_stage >= 1)").set(val)
    return val


# -- xray-derived collective accounting ---------------------------------------


def grad_reduce_scatter_seconds(xray_summary: Optional[dict]) -> Optional[float]:
    """Seconds of dp-axis gradient-exchange collectives in an `slt xray`
    summary (``per_collective_s`` keys ``reduce-scatter@dp`` +
    ``all-reduce@dp`` — XLA emits either form for the same logical
    reduce depending on backend/fusion). None when the capture carries
    no per-collective table."""
    per = (xray_summary or {}).get("per_collective_s")
    if not isinstance(per, dict):
        return None
    total = 0.0
    for key, v in per.items():
        base = str(key).partition("@")[0]
        if (str(key).endswith(f"@{UPDATE_AXIS}")
                and base in ("reduce-scatter", "all-reduce")):
            total += float(v)
    return total


def publish_grad_reduce_gauge(xray_summary: Optional[dict],
                              registry=None) -> Optional[float]:
    """Stamp ``slt_grad_reduce_scatter_seconds`` from an xray capture
    summary; no-op (returns None) without one."""
    from serverless_learn_tpu.telemetry.registry import get_registry

    val = grad_reduce_scatter_seconds(xray_summary)
    if val is None:
        return None
    reg = registry or get_registry()
    reg.gauge("slt_grad_reduce_scatter_seconds",
              "dp-axis gradient-exchange collective seconds in the "
              "latest profiled window").set(val)
    return val
