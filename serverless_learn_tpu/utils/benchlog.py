"""Shared benchmark history + regression guard.

Round 2 guarded only the ResNet-18 headline; every other README number was
a hand-recorded one-off (two in-repo flash timings even disagreed, 14 vs
16 ms). Every benchmark row now funnels through :func:`record`, which
appends to one history file and flags any regression beyond a relative
threshold against the best comparable historical entry.

Comparability: an entry only competes with entries that match it on every
``key_fields`` value (metric name, device kind, and whatever shape knobs
the caller lists) — a batch-size sweep or a different chip must neither
flag nor mask a phantom regression.

Variance-awareness: noisy timings (the flash kernel's chip-load variance is
a few ms at ~15 ms) report a relative spread (``spread_rel``, e.g.
IQR/median over repeats); the effective threshold widens to
``max(rel_threshold, 2 * spread_rel)`` so day-to-day noise doesn't cry
wolf while real regressions still trip it.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional, Sequence


def load_history(path: str) -> list:
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            return json.load(f)
    except ValueError:
        # Never silently overwrite the regression baseline: preserve the
        # corrupt file and start a fresh history beside it.
        corrupt = path + ".corrupt"
        os.replace(path, corrupt)
        print(f"WARNING: {path} was unreadable; moved to {corrupt}",
              file=sys.stderr)
        return []
    except (IOError, OSError):
        return []


def best_comparable(
    history: list,
    entry: dict,
    key_fields: Sequence[str] = ("metric", "device_kind"),
    better: str = "max",
) -> Optional[float]:
    """The single definition of "comparable baseline": best numeric value
    among history entries matching ``entry`` on every key field."""
    vals = [h["value"] for h in history
            if all(h.get(k) == entry.get(k) for k in key_fields)
            and isinstance(h.get("value"), (int, float))]
    if not vals:
        return None
    return max(vals) if better == "max" else min(vals)


def record(
    entry: dict,
    history_path: str,
    *,
    better: str = "max",
    rel_threshold: float = 0.05,
    key_fields: Sequence[str] = ("metric", "device_kind"),
) -> dict:
    """Append ``entry`` to the history; mark ``entry["regression"]`` and
    warn on stderr if its ``value`` is worse than the best comparable
    entry by more than the (variance-widened) threshold. Returns the
    entry (mutated) either way — benches report honestly, never fail."""
    assert better in ("max", "min")
    history = load_history(history_path)
    best = best_comparable(history, entry, key_fields, better)
    gap = max(rel_threshold, 2.0 * float(entry.get("spread_rel", 0.0)))
    if best is not None:
        worse = (entry["value"] < best * (1 - gap) if better == "max"
                 else entry["value"] > best * (1 + gap))
        if worse:
            entry["regression"] = True
            entry["best"] = round(best, 2)
            print(
                f"WARNING: {entry.get('metric')} = {entry['value']} is a "
                f">{gap:.0%} regression vs best {best} "
                f"({os.path.basename(history_path)})", file=sys.stderr)
    history.append(dict(entry, time=time.strftime("%Y-%m-%dT%H:%M:%S")))
    try:
        with open(history_path, "w") as f:
            json.dump(history, f, indent=1)
    except (IOError, OSError):
        pass  # read-only checkout: still report
    return entry
