"""FLOPs and MFU accounting.

Round-1 verdict item 6: throughput was reported as samples/sec only, so
nobody could see that e.g. ResNet-50 at 1,786 samples/s/chip was ~10% MFU.
Per-step FLOPs come from XLA's own compiled cost model
(``lowered.compile().cost_analysis()["flops"]``) — exact for whatever was
actually compiled (fusion, remat recompute, padding included), with no
per-architecture hand formulas to rot. MFU divides by the chip's peak for
the compute dtype.

Peak numbers are per chip (not per core) from published TPU specs; bf16
matmuls on the MXU. MFU is always quoted AGAINST THE bf16 PEAK — the
framework's training dtype policy is bf16 compute on TPU, and fp32 MXU
peaks are not published per generation, so a quoted-vs-fp32 number would
be invented. A deliberately-fp32 run therefore reads as low MFU, which is
truthful about the hardware left on the table. Unknown device kinds yield
None and MFU is simply omitted — never guessed.
"""

from __future__ import annotations

from typing import Optional

# device_kind -> peak dense bf16 TFLOP/s per chip (published specs).
PEAK_TFLOPS_BF16 = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5": 459.0,       # v5p
    "TPU v6 lite": 918.0,  # v6e / Trillium
}

# device_kind -> peak HBM bandwidth, GB/s per chip (published specs; the
# 819 GB/s v5e figure is the one docs/MFU_ANALYSIS.md already reasons
# with). The roofline ridge point is peak_flops / peak_bw FLOPs/byte —
# ops below it are HBM-bound no matter how good the kernel is.
PEAK_HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,   # v5e
    "TPU v5": 2765.0,       # v5p
    "TPU v6 lite": 1640.0,  # v6e / Trillium
}


def _lookup_kind(table: dict, kind: str) -> Optional[float]:
    for name, v in table.items():
        if kind.startswith(name):
            return v
    return None


def peak_flops_for_kind(kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for a device_kind string (no jax import — the
    xray analyzer runs on deviceless nodes against recorded captures)."""
    tf = _lookup_kind(PEAK_TFLOPS_BF16, kind)
    return tf * 1e12 if tf else None


def peak_hbm_bytes_per_s_for_kind(kind: str) -> Optional[float]:
    """Peak HBM bytes/s for a device_kind string, or None if unknown."""
    gb = _lookup_kind(PEAK_HBM_GBPS, kind)
    return gb * 1e9 if gb else None


def peak_flops_per_chip(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s for one chip, or None if unknown."""
    import jax

    kind = (device or jax.devices()[0]).device_kind
    return peak_flops_for_kind(kind)


def peak_hbm_bytes_per_s(device=None) -> Optional[float]:
    """Peak HBM bytes/s for one chip, or None if unknown."""
    import jax

    kind = (device or jax.devices()[0]).device_kind
    return peak_hbm_bytes_per_s_for_kind(kind)


def compiled_step_cost(step_fn, *args, n_devices: int = 1
                       ) -> Optional[dict]:
    """XLA's own compiled cost model for one call of ``step_fn(*args)``:
    ``{"flops": F, "bytes_accessed": B}`` across the whole mesh (either
    value may be absent when the backend doesn't report it). None when no
    cost analysis is exposed at all.

    ``n_devices`` MUST be the mesh size the function is jitted over: under
    SPMD, ``cost_analysis()`` reports the per-shard partitioned module's
    work (verified on an 8-device mesh: exactly 1/8 of the analytic
    global FLOPs), so the global count is per-shard x devices."""
    import jax

    try:
        # Already-jitted callables expose .lower — reuse their cache instead
        # of wrapping in a second jit (which would recompile from scratch).
        if hasattr(step_fn, "lower"):
            lowered = step_fn.lower(*args)
        else:
            lowered = jax.jit(step_fn).lower(*args)
        analysis = lowered.compile().cost_analysis()
    except Exception:
        return None
    if not analysis:
        return None
    # jax used to return one dict; newer versions return a one-element
    # list of per-computation dicts. Accept both.
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out = {}
    flops = analysis.get("flops")
    if flops:
        out["flops"] = float(flops) * n_devices
    # The key XLA emits is literally "bytes accessed" (space included).
    nbytes = analysis.get("bytes accessed")
    if nbytes:
        out["bytes_accessed"] = float(nbytes) * n_devices
    return out or None


def compiled_step_flops(step_fn, *args, n_devices: int = 1
                        ) -> Optional[float]:
    """Total FLOPs of one compiled call of ``step_fn(*args)`` across the
    whole mesh. None when the backend doesn't expose a cost analysis."""
    cost = compiled_step_cost(step_fn, *args, n_devices=n_devices)
    return cost.get("flops") if cost else None


def mfu(flops_per_step: Optional[float], step_time_s: float,
        n_chips: int = 1, device=None) -> Optional[float]:
    """Model FLOPs utilization in [0, 1]; None when either side is unknown."""
    if not flops_per_step or step_time_s <= 0:
        return None
    peak = peak_flops_per_chip(device)
    if not peak:
        return None
    return flops_per_step / step_time_s / (peak * n_chips)
