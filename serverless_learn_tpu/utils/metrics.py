"""Structured metrics & throughput accounting.

Successor of the reference's observability story — unconditional ``std::cout``
narration on every RPC (SURVEY.md §5 "Metrics") — as step-timed counters with
JSON-line output. samples/sec/chip is BASELINE.json's primary metric.

This module stays the *local* accounting the training loop returns
(per-run history, steady-state aggregation); the cluster-facing,
scrapeable view of the same quantities is published into
``telemetry/`` (``slt_train_*`` series on the /metrics endpoint).
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class StepStats:
    step: int
    step_time_s: float
    samples_per_sec: float
    metrics: Dict[str, float]


@dataclass
class ThroughputMeter:
    batch_size: int
    n_chips: int = 1
    # Whole-mesh FLOPs of one compiled step (utils/flops.compiled_step_flops).
    # When set, steady_state reports MFU and achieved TFLOP/s.
    flops_per_step: Optional[float] = None
    history: List[StepStats] = field(default_factory=list)
    _t_last: Optional[float] = None

    def start(self):
        self._t_last = time.perf_counter()

    def record(self, step: int, metrics: Dict[str, float]) -> StepStats:
        now = time.perf_counter()
        dt = now - (self._t_last if self._t_last is not None else now)
        self._t_last = now
        sps = self.batch_size / dt if dt > 0 else float("inf")
        stats = StepStats(step=step, step_time_s=dt, samples_per_sec=sps,
                          metrics=metrics)
        self.history.append(stats)
        return stats

    def steady_state(self, skip: int = 2) -> Dict[str, float]:
        """Aggregate over history, skipping warmup/compile steps."""
        usable = self.history[skip:] if len(self.history) > skip else self.history
        if not usable:
            return {"samples_per_sec": 0.0, "step_time_s": 0.0}
        times = [s.step_time_s for s in usable]
        sps = self.batch_size * len(usable) / sum(times)
        out = {
            "samples_per_sec": sps,
            "samples_per_sec_per_chip": sps / max(self.n_chips, 1),
            "step_time_s": sum(times) / len(times),
        }
        if self.flops_per_step:
            from serverless_learn_tpu.utils.flops import mfu

            out["tflops_per_sec_per_chip"] = (
                self.flops_per_step / out["step_time_s"] / 1e12
                / max(self.n_chips, 1))
            u = mfu(self.flops_per_step, out["step_time_s"], self.n_chips)
            if u is not None:
                out["mfu"] = u
        return out


def log_json(record: dict, stream=None):
    (stream or sys.stderr).write(json.dumps(record) + "\n")
    (stream or sys.stderr).flush()
