"""Tracing & profiling.

The reference had no tracing at all — its observability was unconditional
``std::cout`` narration on every RPC and one in-source perf TODO
(reference ``src/master.cc:257``; SURVEY.md §5 "Tracing / profiling").
This module is the rebuild's tracing story, in three parts:

* **Host spans** — ``Tracer.span(name)`` times named host-side sections
  (data fetch, shard decode, step dispatch) into per-name aggregates that
  mirror the native daemons' ``RpcStat`` (count/total/max).
* **Device traces** — ``capture(logdir)`` wraps ``jax.profiler.trace`` so a
  training window can be captured for TensorBoard/Perfetto;
  ``annotate(name)`` / ``step_annotation(step)`` wrap
  ``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` so host spans
  show up aligned with device ops inside the captured trace. All wrappers
  degrade to no-ops when the profiler is unavailable.
* **Daemon scrape** — ``rpc_stats(client)`` turns a Coordinator/Shard
  ``StatsReply`` into the same dict shape as ``Tracer.summary()``, so one
  report covers Python hosts and C++ daemons.

Cluster-wide, scrapeable telemetry (counters/gauges/histograms, the
``/metrics`` endpoint, ``slt top``) lives in ``telemetry/``;
``telemetry.publish_rpc_stats`` lifts this module's scrape shape into
that registry.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

# Per-span narration — the descendant of the reference's unconditional
# ``std::cout`` line on every RPC (its in-source perf TODO,
# ``src/master.cc:257``). Narrating a tight loop costs real wall-clock:
# a flushed stdout write is tens of microseconds, which SKEWS the
# goodput ledger's phase timings and the step-time anomaly baseline for
# sub-millisecond spans. It is therefore OFF by default and gated twice:
# the ``SLT_TRACE_NARRATE`` env var (or ``Tracer(narrate=True)``) must
# opt in, and output goes to stderr, unbuffered by line — never stdout,
# which the CLI reserves for machine-readable JSON.
NARRATE_ENV = "SLT_TRACE_NARRATE"


def _narrate_enabled() -> bool:
    return os.environ.get(NARRATE_ENV, "").strip().lower() \
        not in ("", "0", "false", "no")


def narrate(message: str, force: bool = False):
    """Verbosity-gated debug narration; a no-op unless SLT_TRACE_NARRATE
    is set (or ``force``). Never raises (a closed stderr must not kill a
    span)."""
    if not (force or _narrate_enabled()):
        return
    try:
        sys.stderr.write(message + "\n")
    except (IOError, OSError, ValueError):
        pass

# framing.h MsgType tag -> human name, for daemon-scraped reports.
# Mirrors native/rpc_stats.h: kMaxMsgType (32) is the overflow slot where
# the daemons aggregate tags they don't know (a newer peer's message
# types) instead of dropping their count/max silently.
K_MAX_MSG_TYPE = 32
MSG_TYPE_NAMES = {
    1: "register", 3: "heartbeat", 5: "deregister", 6: "membership",
    20: "manifest", 22: "fetch", 24: "put", 25: "stats", 27: "delete",
    K_MAX_MSG_TYPE: "other",
}


@dataclass
class SpanStat:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def add(self, dt: float):
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


@dataclass
class Tracer:
    """Accumulates named host-side span timings; thread-safe.

    ``narrate=True`` (or the SLT_TRACE_NARRATE env var) prints one
    stderr line per finished span — debugging only; silent by default so
    per-RPC spans in tight loops cost aggregation, not I/O flushes."""

    stats: Dict[str, SpanStat] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    narrate: bool = False

    @contextlib.contextmanager
    def span(self, name: str, annotate_device: bool = True):
        """Time a section; optionally mirror it into the device trace."""
        ctx = annotate(name) if annotate_device else contextlib.nullcontext()
        t0 = time.perf_counter()
        with ctx:
            yield
        dt = time.perf_counter() - t0
        with self._lock:
            self.stats.setdefault(name, SpanStat()).add(dt)
        if self.narrate or _narrate_enabled():
            narrate(f"[span] {name} {dt * 1e3:.3f} ms", force=self.narrate)

    def record(self, name: str, dt: float):
        with self._lock:
            self.stats.setdefault(name, SpanStat()).add(dt)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {"count": s.count, "total_s": s.total_s,
                       "mean_s": s.mean_s, "max_s": s.max_s}
                for name, s in sorted(self.stats.items())
            }

    def reset(self):
        with self._lock:
            self.stats.clear()


_global_tracer: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _global_tracer
    if _global_tracer is None:
        _global_tracer = Tracer()
    return _global_tracer


def annotate(name: str):
    """Named device-trace annotation; no-op if the profiler is unavailable."""
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


def step_annotation(step: int):
    """Step marker for TensorBoard's step-time view."""
    try:
        import jax.profiler

        return jax.profiler.StepTraceAnnotation("train", step_num=step)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def capture(logdir: str):
    """Capture a jax.profiler trace (TensorBoard/Perfetto) over the block."""
    import jax.profiler

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def rpc_stats(client_or_reply) -> Dict[str, Dict[str, float]]:
    """Scrape a daemon's per-RPC latency table into summary() shape.

    Accepts a CoordinatorClient/ShardClient (issues the stats RPC) or an
    already-fetched StatsReply (no extra round trip).
    """
    rep = (client_or_reply if hasattr(client_or_reply, "rpc")
           else client_or_reply.stats())
    out: Dict[str, Dict[str, float]] = {}
    for s in rep.rpc:
        # Tag bounds: gaps inside [0, kMaxMsgType) (e.g. the reserved 9-19
        # range) render as msg_<N>; kMaxMsgType is the daemons' overflow
        # slot ("other"); anything past it (a reply from a daemon built
        # with a LARGER table) still lands as msg_<N> instead of being
        # dropped — per-type max latency must survive unknown tags.
        name = MSG_TYPE_NAMES.get(s.msg_type, f"msg_{s.msg_type}")
        out[f"rpc/{name}"] = {
            "count": s.count,
            "total_s": s.total_us / 1e6,
            "mean_s": (s.total_us / s.count / 1e6) if s.count else 0.0,
            "max_s": s.max_us / 1e6,
        }
    return out
