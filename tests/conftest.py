"""Test harness: 8 virtual CPU devices stand in for a TPU slice.

The reference's only "test rig" was three localhost processes simulating a
cluster (SURVEY.md §4). The JAX-idiomatic equivalent is
``--xla_force_host_platform_device_count``: one process, eight devices, real
Mesh/collective semantics. Must run before jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (the real-TPU tunnel), so the env var above can be too
# late; backends are lazy, so overriding the config before first device use
# still wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_active_mesh():
    """The active mesh is process-global (set by build_trainer); a test that
    builds a trainer must not leak it into the next test — a stale mesh
    silently reroutes the pallas ops' mesh-aware dispatch (e.g. flash
    falling back to dense for batch-indivisibility against a mesh the test
    never asked for)."""
    yield
    from serverless_learn_tpu.parallel.ring_attention import set_active_mesh

    set_active_mesh(None)
