"""Test harness: 8 virtual CPU devices stand in for a TPU slice.

The reference's only "test rig" was three localhost processes simulating a
cluster (SURVEY.md §4). The JAX-idiomatic equivalent is
``--xla_force_host_platform_device_count``: one process, eight devices, real
Mesh/collective semantics. Must run before jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (the real-TPU tunnel), so the env var above can be too
# late; backends are lazy, so overriding the config before first device use
# still wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# -- runtime lock-order detection (SLT_LOCKCHECK=1) --------------------------
#
# The dynamic half of `slt check`'s SLT001: instrument every lock the
# package creates, record real acquisition orderings across the whole
# suite, and fail the session on cycles (analysis/lockcheck.py). Installed
# HERE — before any serverless_learn_tpu module runs its module-level
# `threading.Lock()` — and scoped to locks created from this repo's files.

_LOCKCHECK = os.environ.get("SLT_LOCKCHECK", "") == "1"
if _LOCKCHECK:
    from serverless_learn_tpu.analysis import lockcheck as _lockcheck

    _lockcheck.install()

# -- runtime happens-before race detection (SLT_RACECHECK=1) ------------------
#
# The dynamic half of SLT007 (analysis/racecheck.py): vector clocks over
# lock acquire/release, Thread start/join and queue/Event handoffs, plus
# sampled attribute-write instrumentation on the fleet/gossip/kvcache/
# health classes. Unordered write/write (and, with SLT_RACECHECK_READS=1,
# read/write) pairs fail the session with both stacks.

_RACECHECK = os.environ.get("SLT_RACECHECK", "") == "1"
if _RACECHECK:
    from serverless_learn_tpu.analysis import racecheck as _racecheck

    _racecheck.install()

# -- runtime compile monitoring (SLT_JITCHECK=1) -------------------------------
#
# The dynamic half of SLT010-SLT013 (analysis/jitcheck.py): wrap every
# jax.jit the package creates, record real compilations (site, abstract
# shapes, donation mask, elapsed), enforce the per-site compile budgets
# declared next to the bucket functions, and detect donated-buffer reuse
# logically (the round-15 "Array has been deleted" class — caught on CPU
# where donation is otherwise a silent no-op). Installed HERE, before
# any `@jax.jit` decorator binds at package import. Budget/frozen/reuse
# violations fail the session below (exit 5; lockcheck=3, racecheck=4).

_JITCHECK = os.environ.get("SLT_JITCHECK", "") == "1"
if _JITCHECK:
    from serverless_learn_tpu.analysis import jitcheck as _jitcheck

    _jitcheck.install()


def pytest_sessionfinish(session, exitstatus):
    if _JITCHECK:
        jmon = _jitcheck.monitor()
        print(f"\n{jmon.report()}")
        jmon.close_log()
        if jmon.violations():
            pytest.exit("jitcheck: compile-budget/frozen-window/"
                        "donation violations observed (see report "
                        "above)", returncode=5)
    if _RACECHECK:
        rmon = _racecheck.monitor()
        print(f"\n{rmon.report()}")
        rmon.close_log()
        if rmon.races():
            pytest.exit("racecheck: unordered conflicting accesses "
                        "observed (see report above)", returncode=4)
    if not _LOCKCHECK:
        return
    mon = _lockcheck.monitor()
    rep = mon.report()
    print(f"\n{rep}")
    if mon.violations():
        # pytest.exit with a returncode is the one channel wrap_session
        # honors from inside this hook (assigning session.exitstatus here
        # is discarded).
        pytest.exit("lockcheck: lock-order cycle observed (see report "
                    "above)", returncode=3)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_active_mesh():
    """The active mesh is process-global (set by build_trainer); a test that
    builds a trainer must not leak it into the next test — a stale mesh
    silently reroutes the pallas ops' mesh-aware dispatch (e.g. flash
    falling back to dense for batch-indivisibility against a mesh the test
    never asked for)."""
    yield
    from serverless_learn_tpu.parallel.ring_attention import set_active_mesh

    set_active_mesh(None)


# -- fast/slow tiers ---------------------------------------------------------
#
# The full suite takes ~13 min on the 8-device CPU mesh (VERDICT round 1:
# "split the suite so rounds 2+ can actually run it"). Tests measured >=3 s
# are tier "slow"; `make test` runs the fast tier (<2 min), `make test-all`
# runs everything. Node ids, not file-level marks, so every subsystem keeps
# fast-tier coverage. Re-measure with `pytest --durations=0` when adding
# compile-heavy tests.

SLOW_TESTS = {
    "tests/test_checkpoint.py::test_checkpoint_via_shard_server",
    "tests/test_checkpoint_sharded.py::test_save_dp_restore_fsdp_tp_bit_exact",
    "tests/test_checkpoint_sharded.py::test_restore_fetches_ranges_not_blobs",
    "tests/test_checkpoint_sharded.py::test_bf16_leaves_roundtrip",
    "tests/test_checkpoint_sharded.py::test_latest_gc_and_layout_autodetect",
    "tests/test_checkpoint_sharded.py::test_sharded_checkpoint_via_shard_server",
    "tests/test_checkpoint.py::test_latest_and_gc",
    "tests/test_checkpoint.py::test_resume_is_exact",
    "tests/test_cli.py::test_publish_stats_and_train_from_shard_server",
    "tests/test_real_data.py::test_cifar_bytes_to_rising_accuracy",
    "tests/test_real_data.py::test_corpus_to_bert_mlm_training",
    "tests/test_cli.py::test_train_end_to_end",
    "tests/test_configs.py::test_small_rungs_build[cifar_resnet18_dp4.json]",
    "tests/test_configs.py::test_small_rungs_build[mnist_mlp.json]",
    "tests/test_elastic.py::test_join_grows_mesh_and_crash_shrinks_it",
    "tests/test_elastic.py::test_solo_run_without_coordinator",
    "tests/test_elastic.py::test_state_survives_remesh_exactly",
    "tests/test_elastic_shard_data.py::test_elastic_worker_streams_from_shard_server",
    "tests/test_flash_attention.py::test_flash_inside_pipeline_stage",
    "tests/test_flash_masks.py::test_bert_step_executes_flash_path",
    "tests/test_flash_attention.py::test_flash_sharded_train_step_matches_xla[mesh_kw0]",
    "tests/test_flash_attention.py::test_flash_sharded_train_step_matches_xla[mesh_kw1]",
    "tests/test_flash_attention.py::test_transformer_with_flash_impl",
    "tests/test_fused_ce.py::test_bf16_logits",
    "tests/test_fused_ce.py::test_fused_train_step_matches_unfused",
    "tests/test_fused_ce.py::test_matches_optax_forward_and_grad[shape0-512]",
    "tests/test_fused_ce.py::test_matches_optax_forward_and_grad[shape1-1024]",
    "tests/test_fused_ce.py::test_matches_optax_forward_and_grad[shape2-512]",
    "tests/test_generate.py::test_decode_matches_full_forward",
    "tests/test_generate.py::test_eos_is_sticky",
    "tests/test_generate.py::test_greedy_generation_matches_full_forward_argmax",
    "tests/test_grad_accum_eval.py::test_grad_accum_matches_whole_batch",
    "tests/test_grad_accum_eval.py::test_grad_accum_sharded_transformer_runs",
    "tests/test_grad_accum_eval.py::test_in_loop_eval_fires",
    "tests/test_grad_accum_eval.py::test_mlm_grad_accum_matches_whole_batch",
    "tests/test_grad_accum_eval.py::test_resnet_eval_uses_running_stats_and_keeps_state",
    "tests/test_grad_accum_eval.py::test_run_eval_mean_metrics",
    "tests/test_grad_accum_eval.py::test_run_eval_streams_from_shard_server",
    "tests/test_local_sgd.py::test_replicas_diverge_then_gossip_reconverges",
    "tests/test_local_sgd.py::test_run_local_sgd_integrated_with_checkpoint",
    "tests/test_moe.py::test_moe_aux_loss_reported",
    "tests/test_moe.py::test_moe_group_size_bounds_capacity_without_changing_math",
    "tests/test_moe.py::test_moe_init_state_has_no_losses_collection",
    "tests/test_moe.py::test_moe_layer_matches_manual_dense_top1",
    "tests/test_moe.py::test_moe_trains_ep_matches_dp[mesh_cfg0]",
    "tests/test_moe.py::test_moe_trains_ep_matches_dp[mesh_cfg1]",
    "tests/test_moe.py::test_n_experts_override_keeps_aux_loss",
    "tests/test_multihost.py::test_two_process_training",
    "tests/test_optimizers.py::test_lr_reported_in_metrics",
    "tests/test_optimizers.py::test_optimizer_reduces_loss_on_fixed_batch[adafactor]",
    "tests/test_optimizers.py::test_optimizer_reduces_loss_on_fixed_batch[adam]",
    "tests/test_optimizers.py::test_optimizer_reduces_loss_on_fixed_batch[adamw]",
    "tests/test_optimizers.py::test_optimizer_reduces_loss_on_fixed_batch[lion]",
    "tests/test_optimizers.py::test_optimizer_reduces_loss_on_fixed_batch[rmsprop]",
    "tests/test_optimizers.py::test_optimizer_reduces_loss_on_fixed_batch[sgd]",
    "tests/test_pipeline.py::test_gpipe_matches_sequential_forward",
    "tests/test_pipeline.py::test_pipelined_train_step_matches_dp",
    "tests/test_pipeline.py::test_pp_tp_train_step_matches_dp",
    "tests/test_pipeline.py::test_interleaved_schedule_matches_dp",
    "tests/test_pipeline.py::test_interleaved_toy_matches_permuted_sequential",
    "tests/test_ring_attention.py::test_llama_trains_with_sp_axis",
    "tests/test_ring_attention.py::test_ring_flash_hops_selected_and_match",
    "tests/test_ring_attention.py::test_ring_flash_hops_gqa_unexpanded",
    "tests/test_ring_attention.py::test_ring_flash_hops_noncausal_grad",
    "tests/test_ring_attention.py::test_ring_grad_matches_dense",
    "tests/test_ring_attention.py::test_ring_matches_dense_gqa",
    "tests/test_serve.py::test_serve_matches_direct_generate",
    "tests/test_serve.py::test_serve_survives_malformed_json_values",
    "tests/test_shard_datasets.py::test_publish_from_bundle_and_training",
    "tests/test_tracing.py::test_training_records_step_spans",
    "tests/test_train_step.py::test_bert_tiny_mlm_step",
    "tests/test_train_step.py::test_dp8_matches_single_device_exactly",
    "tests/test_train_step.py::test_dp_tp_matches_dp_only",
    "tests/test_train_step.py::test_llama_lora_freezes_base",
    "tests/test_train_step.py::test_llama_tiny_fsdp_tp",
    "tests/test_train_step.py::test_mlp_overfits_fixed_batch_single_device",
    "tests/test_train_step.py::test_remat_matches_no_remat",
    "tests/test_train_step.py::test_resnet18_step_runs_and_updates_batchstats",
    "tests/test_train_step.py::test_train_dtype_policy_reaches_model",
    # round 4
    "tests/test_pipeline.py::test_pp_sp_train_step_matches_dp",
    "tests/test_pipeline.py::test_pp_sp_suffix_lengths_match_dp",
    "tests/test_pipeline.py::test_pp_ep_train_step_matches_dp",
    "tests/test_pipeline.py::test_pp_tp_moe_train_step_matches_dp",
    "tests/test_pipeline.py::test_moe_pipeline_matches_dp",
    "tests/test_local_sgd.py::test_stateful_resnet_gossip_trains_and_stats_gossip",
    "tests/test_local_sgd.py::test_stateful_diloco_exact_parity_groupnorm",
    "tests/test_local_sgd.py::test_stateful_diloco_batchnorm_tolerance_documented",
    "tests/test_serve_batching.py::test_engine_coalesces_and_is_exact",
    "tests/test_serve_batching.py::test_engine_groups_by_sampling_params",
    "tests/test_serve_batching.py::test_engine_mixed_max_new_truncates_exactly",
    "tests/test_serve_batching.py::test_server_concurrent_clients_share_batches",
    "tests/test_serve_batching.py::test_padded_batch_generate_matches_solo",
    "tests/test_parallel_ingest.py::test_resnet50_device_augment_trains",
    "tests/test_tokenizer.py::test_packed_batches_train_llama_and_bert",
    "tests/test_flash_masks.py::test_dispatcher_honors_kv_lengths_alone",
    # round 5
    "tests/test_continuous.py::test_concurrent_greedy_exact",
    "tests/test_continuous.py::test_mid_stream_admission_exact",
    "tests/test_continuous.py::test_eos_retires_slot_early",
    "tests/test_continuous.py::test_more_requests_than_slots",
    "tests/test_continuous.py::test_mixed_sampling_in_one_batch_no_starvation",
    "tests/test_continuous.py::test_sampled_is_reproducible_and_batch_invariant",
    "tests/test_continuous.py::test_server_with_continuous_engine",
    "tests/test_moe_generate.py::test_moe_through_continuous_engine",
    "tests/test_moe_generate.py::test_moe_serves_over_the_wire",
    "tests/test_moe_generate.py::test_moe_batched_padded_prompts_match_solo",
    "tests/test_diloco_dcn.py::test_two_islands_converge_and_track_single_world",
    "tests/test_diloco_dcn.py::test_island_crash_does_not_wedge_survivors",
    "tests/test_diloco_dcn.py::test_leader_crash_hands_over",
    "tests/test_diloco_dcn.py::test_late_joiner_adopts_current_anchor",
    "tests/test_diloco_dcn.py::test_islands_are_sharded_worlds",
    # round 19 (real-daemon DiLoCo quorum integration; the jit-free gate
    # units and the vmapped herd acceptance stay fast)
    "tests/test_diloco_dcn.py::test_quorum_closes_round_without_straggler",
    "tests/test_speculative.py::test_cross_draft_is_exact",
    "tests/test_speculative.py::test_self_draft_is_exact_and_fully_accepted",
    "tests/test_speculative.py::test_unequal_prompts_exact",
    "tests/test_qlora.py::test_int8_frozen_base_trains_lora",
    "tests/test_qlora.py::test_qlora_lora_grads_track_bf16_base_grads",
    "tests/test_quantize.py::test_quant_moe_experts",
    # round 9 (goodput acceptance: a real train run through the ledger)
    "tests/test_goodput.py::test_train_run_records_goodput",
    # round 13 (paged KV: model-backed equivalence suite; the jax-free
    # allocator/trie/doctor units stay fast)
    "tests/test_kvcache.py::test_paged_generate_matches_monolithic",
    "tests/test_kvcache.py::test_paged_engine_greedy_exact_with_chunked_prefill",
    "tests/test_kvcache.py::test_paged_engine_seeded_sampling_matches_monolithic",
    "tests/test_kvcache.py::test_paged_engine_eos_retires_and_frees_blocks",
    "tests/test_kvcache.py::test_shared_prefix_reuse_hits_and_stays_exact",
    "tests/test_kvcache.py::test_exhaustion_backpressure_and_preemption_stay_exact",
    "tests/test_kvcache.py::test_decode_cost_tracks_live_slots",
    "tests/test_kvcache.py::test_static_engine_paged_matches_monolithic",
    "tests/test_kvcache.py::test_server_ping_reports_kv_and_prompt_histogram",
    # round 6 (telemetry integration; registry/endpoint/top units stay fast)
    "tests/test_telemetry.py::test_server_metrics_endpoint_scrape",
    "tests/test_telemetry.py::test_continuous_cancellation_retires_slot",
    "tests/test_telemetry.py::test_warm_compiles_admit_buckets_deterministically",
    "tests/test_telemetry.py::test_top_once_covers_trainer_and_inference",
    # round 17 (numerics: real-trainer fingerprint runs + the cadence/
    # overhead acceptance run; the stat/detector/provenance units stay
    # fast)
    "tests/test_numerics.py::test_fingerprint_bisection_finds_seeded_divergence",
    "tests/test_numerics.py::test_numerics_cadence_and_overhead_acceptance",
    # round 18 (ZeRO: the adafactor parity variant pays a second pair of
    # trainer compiles; the adamw variant and the mlp parity/layout/
    # checkpoint/elastic tests stay in the fast tier)
    "tests/test_optimizers.py::test_zero1_update_matches_replicated[adafactor]",
    # round 25 (jitcheck: the engine+trainer acceptance run pays real
    # compiles, and the no-baseline HEAD scan duplicates the full-repo
    # walk test_analysis already pays once; the rule fixtures, monitor
    # units and subprocess session-failure tests stay fast)
    "tests/test_jitcheck.py::"
    "test_warmed_engine_and_train_loop_have_no_unexpected_compiles",
    "tests/test_jitcheck.py::test_repo_at_head_is_clean_for_new_rules",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: compile-heavy test (excluded from `make test`)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        nodeid = item.nodeid.replace("\\", "/")
        # A bare (un-parametrized) entry in SLOW_TESTS marks every
        # parametrization of that test.
        if nodeid in SLOW_TESTS or nodeid.split("[")[0] in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
