"""One elastic multi-host participant, for process-level tests.

Launched N times (as separate processes) by tests/test_elastic_multihost.py;
each instance supervises its own chain of inner trainer subprocesses. On
completion prints one JSON line with the host's generation history and the
observed loss-by-step series, which the test asserts on.
"""

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from serverless_learn_tpu.config import (  # noqa: E402
    ControlConfig, DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
    TrainConfig)
from serverless_learn_tpu.training.checkpoint import LocalStore  # noqa: E402
from serverless_learn_tpu.training.elastic_multihost import (  # noqa: E402
    ElasticHostSupervisor)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--store-root", required=True)
    p.add_argument("--run-name", default="t")
    p.add_argument("--label", required=True)
    p.add_argument("--steps", type=int, default=36)
    p.add_argument("--batch", type=int, default=96)
    p.add_argument("--ckpt-every", type=int, default=4)
    p.add_argument("--min-hosts", type=int, default=1)
    p.add_argument("--step-delay", type=float, default=0.0)
    p.add_argument("--chips", type=int, default=1,
                   help="local device count to register (must match the "
                        "inner's XLA_FLAGS-forced device count for the "
                        "supervisor's satisfiability math to be truthful)")
    p.add_argument("--mesh", default=None,
                   help="JSON MeshConfig overrides, e.g. "
                        '\'{"fsdp": 2, "tp": 2}\' — the config mesh the '
                        "elastic world must honor at every generation")
    args = p.parse_args()

    mesh = (MeshConfig(**json.loads(args.mesh)) if args.mesh
            else MeshConfig())
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=mesh,
        # Hyperparameters chosen so the learnable synthetic task shows a
        # clear fresh-data loss decrease within the test's step budget
        # (1.5 -> ~0.66 in 60 steps measured on the CPU mesh).
        model_overrides={"features": [256], "num_classes": 4},
        optimizer=OptimizerConfig(name="adamw", learning_rate=5e-3),
        train=TrainConfig(batch_size=args.batch, num_steps=args.steps,
                          checkpoint_every=args.ckpt_every,
                          dtype="float32", param_dtype="float32"),
        data=DataConfig(learnable=True),
        control=ControlConfig(coordinator_addr=args.coordinator,
                              heartbeat_interval_ms=200),
    )
    sup = ElasticHostSupervisor(
        cfg, LocalStore(args.store_root), args.coordinator,
        run_name=args.run_name, label=args.label,
        n_chips=args.chips,
        min_hosts=args.min_hosts,
        form_timeout_s=90.0, init_timeout_s=30.0,
        drain_timeout_s=60.0, kill_grace_s=3.0,
        inner_env={"SLT_STEP_DELAY_S": str(args.step_delay)},
        verbose=True)
    gens = sup.run()
    print("RESULT " + json.dumps({
        "label": args.label,
        "generations": [{"gen": g.gen, "world": g.world, "rank": g.rank,
                         "start_step": g.start_step, "end_step": g.end_step,
                         "status": g.status, "mesh": g.mesh}
                        for g in gens],
        "losses": sorted(((int(s), l) for s, l in sup.step_losses.items())),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
