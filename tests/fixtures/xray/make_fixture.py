"""Regenerate the committed xray fixture capture.

Runs a real tiny-model training loop (mlp_mnist, dp=8 virtual CPU
devices) under ``telemetry/profiler.capture_session``, then *sanitizes*
the capture for committing:

* only trace metadata + device-op events are kept (host-side python
  spans carry machine paths and are not what xray reads);
* timestamps are rebased to t=0;
* ``all-reduce`` events gain the ``replica_groups`` arg a TPU trace
  carries (the real dp=8 group — the CPU runtime just doesn't stamp it),
  so the fixture exercises mesh-axis recovery;
* ``capture-meta.json`` keeps the real ledger snapshot, mesh axes and
  device kind from the generating run.

The matching ``expected_summary.json`` is the analyzer's output over the
sanitized capture — ``slt xray --self-check`` fails on any drift.

Usage (from the repo root):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tests/fixtures/xray/make_fixture.py
"""

import glob
import gzip
import json
import os
import shutil
import sys
import tempfile

FIXTURE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(os.path.dirname(FIXTURE)))
sys.path.insert(0, ROOT)

N_STEPS = 3
BATCH = 1024


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from serverless_learn_tpu.config import (DataConfig, ExperimentConfig,
                                             MeshConfig, OptimizerConfig,
                                             TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.telemetry import profiler, xray
    from serverless_learn_tpu.telemetry.goodput import PhaseLedger
    from serverless_learn_tpu.training.train_step import build_trainer

    n_dev = len(jax.devices())
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=n_dev),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=BATCH),
        data=DataConfig(),
    )
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data,
                               cfg.train.batch_size, seed=0))
    batch = trainer.shard_batch(next(src))
    ledger = PhaseLedger(emit=False)
    ledger.ensure_started()
    with ledger.phase("compile"):
        state, m = trainer.step(state, batch)
        float(jax.device_get(m["loss"]))
    raw = tempfile.mkdtemp(prefix="slt-xray-fixture-")
    with profiler.capture_session(raw):
        for _ in range(N_STEPS):
            with ledger.phase("step"):
                state, m = trainer.step(state, batch)
                float(jax.device_get(m["loss"]))
    ledger_report = ledger.report()

    src_trace = glob.glob(os.path.join(
        raw, "plugins", "profile", "*", "*.trace.json.gz"))[0]
    with gzip.open(src_trace) as f:
        trace = json.load(f)

    # -- sanitize ------------------------------------------------------------
    keep = []
    t0 = None
    group = "{" + ",".join(str(i) for i in range(n_dev)) + "}"
    for e in trace.get("traceEvents", []):
        args = e.get("args") or {}
        if e.get("ph") == "M":
            keep.append(e)
            continue
        if e.get("ph") != "X" or "hlo_op" not in args:
            continue
        if t0 is None or e["ts"] < t0:
            t0 = e["ts"]
        if str(e.get("name", "")).startswith("all-reduce"):
            e = dict(e, args=dict(
                args, long_name=f"replica_groups={{{group}}}"))
        keep.append(e)
    for e in keep:
        if "ts" in e and t0 is not None:
            e["ts"] = round(e["ts"] - t0, 3)

    out_dir = os.path.join(FIXTURE, "tiny-train")
    shutil.rmtree(out_dir, ignore_errors=True)
    run_dir = os.path.join(out_dir, "plugins", "profile", "fixture")
    os.makedirs(run_dir)
    with gzip.open(os.path.join(run_dir, "fixture.trace.json.gz"), "wt",
                   compresslevel=9) as f:
        json.dump({"displayTimeUnit": trace.get("displayTimeUnit", "ns"),
                   "traceEvents": keep}, f)
    mesh_axes = {a: int(s) for a, s in
                 zip(trainer.mesh.axis_names, trainer.mesh.devices.shape)}
    meta = {"event": "profile_capture", "reason": "fixture",
            "seconds": None,
            "device_kind": jax.devices()[0].device_kind,
            "mesh_axes": mesh_axes,
            "ledger_at_trigger": ledger_report,
            "n_steps": N_STEPS, "batch_size": BATCH}
    with open(os.path.join(out_dir, "capture-meta.json"), "w") as f:
        json.dump(meta, f, indent=1)

    summary = xray.analyze_dir(out_dir)
    with open(os.path.join(FIXTURE, "expected_summary.json"), "w") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
    shutil.rmtree(raw, ignore_errors=True)
    print(json.dumps({"events": len(keep),
                      "steps": summary["steps"]["n"],
                      "coverage": summary["coverage_frac"],
                      "verdict": summary["verdict"]}, indent=1))


if __name__ == "__main__":
    main()
