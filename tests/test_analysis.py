"""Tier-1 tests for `slt check` (serverless_learn_tpu/analysis/).

Per-rule fixture tests (known-bad code triggers the rule, known-good
passes), the baseline round-trip, the `--json` schema, the seeded-defect
acceptance tree, the repo-at-HEAD clean run, and the runtime lockcheck
detecting a deliberately inverted two-lock ordering.
"""

import json
import os
import textwrap
import threading

import pytest

from serverless_learn_tpu.analysis import lockcheck
from serverless_learn_tpu.analysis.engine import discover, run_check
from serverless_learn_tpu.analysis.rules import (RULES, slt001_lock_order,
                                                 slt002_metric_drift,
                                                 slt003_jit_purity,
                                                 slt004_thread_lifecycle,
                                                 slt005_proto_compat,
                                                 slt006_config_drift,
                                                 slt007_guarded_by,
                                                 slt008_resource_lifecycle,
                                                 slt009_atomicity)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def _run_rule(rule, root):
    return rule.run(discover(root))


# -- SLT001: lock order ------------------------------------------------------

def test_slt001_blocking_call_under_lock_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(1)
        """})
    fs = _run_rule(slt001_lock_order, root)
    assert any("sleep" in f.message and "L" in f.message for f in fs), fs


def test_slt001_interprocedural_blocking_chain(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def _dump(self):
                with open("/tmp/x", "w") as f:
                    pass

            def tick(self):
                with self._lock:
                    self._dump()
        """})
    fs = _run_rule(slt001_lock_order, root)
    assert any("file open" in f.message and "_dump" in f.message
               for f in fs), fs


def test_slt001_inverted_lock_pair_is_a_cycle(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass
        """})
    fs = _run_rule(slt001_lock_order, root)
    cyc = [f for f in fs if "cycle" in f.message]
    assert len(cyc) == 1 and "A" in cyc[0].message and "B" in cyc[0].message


def test_slt001_consistent_ordering_passes(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ab2():
            with A:
                with B:
                    x = 1 + 1
        """})
    assert _run_rule(slt001_lock_order, root) == []


# -- SLT002: metric drift ----------------------------------------------------

def test_slt002_consumed_but_never_emitted(tmp_path):
    root = _tree(tmp_path, {
        "serverless_learn_tpu/engine.py": """\
            def setup(reg):
                reg.counter("slt_requests_total", "help")
            """,
        "serverless_learn_tpu/top.py": """\
            WANT = ["slt_requests_total", "slt_reqeusts_total"]
            """,
    })
    fs = _run_rule(slt002_metric_drift, root)
    assert len(fs) == 1
    assert "slt_reqeusts_total" in fs[0].message
    assert fs[0].severity == "error"


def test_slt002_undocumented_emission_is_a_warning(tmp_path):
    root = _tree(tmp_path, {
        "serverless_learn_tpu/engine.py": """\
            def setup(reg):
                reg.gauge("slt_documented")
                reg.gauge("slt_undocumented")
            """,
        "docs/ARCHITECTURE.md": "`slt_documented` is the only metric.\n",
    })
    fs = _run_rule(slt002_metric_drift, root)
    assert [f.severity for f in fs] == ["warning"]
    assert "slt_undocumented" in fs[0].message


def test_slt002_doc_shorthand_expansion():
    names = slt002_metric_drift.doc_names(
        "`slt_train_samples_per_sec[_per_chip]` and "
        "`slt_rpc_{calls,time_seconds,max_seconds}`")
    assert "slt_train_samples_per_sec" in names
    assert "slt_train_samples_per_sec_per_chip" in names
    assert {"slt_rpc_calls", "slt_rpc_time_seconds",
            "slt_rpc_max_seconds"} <= names


# -- SLT003: jit purity ------------------------------------------------------

def test_slt003_clock_read_inside_jitted_fn(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import time

        import jax

        @jax.jit
        def step(x):
            t0 = time.time()
            return x + t0

        def pure(x):
            return x * 2

        pure_jit = jax.jit(pure)

        def outside(x):
            return time.time()  # not traced: fine
        """})
    fs = _run_rule(slt003_jit_purity, root)
    assert len(fs) == 1
    assert "time.time" in fs[0].message and "step" in fs[0].message


def test_slt003_partial_jit_and_metric_emission(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        from functools import partial

        import jax

        class T:
            @partial(jax.jit, static_argnums=(0,))
            def step(self, x):
                self.m.inc()
                return x
        """})
    fs = _run_rule(slt003_jit_purity, root)
    assert len(fs) == 1 and "trace time" in fs[0].message


# -- SLT004: thread lifecycle ------------------------------------------------

def test_slt004_joinless_nondaemon_thread(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        def fire_and_forget():
            t = threading.Thread(target=print)
            t.start()
        """})
    fs = _run_rule(slt004_thread_lifecycle, root)
    assert len(fs) == 1 and "neither daemonized nor joined" in fs[0].message


def test_slt004_daemon_or_joined_passes(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        class Owner:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()

            def stop(self):
                self._t.join()

        def scoped():
            t = threading.Thread(target=print, daemon=True)
            t.start()

        def fanout(n):
            ts = [threading.Thread(target=print) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        """})
    assert _run_rule(slt004_thread_lifecycle, root) == []


# -- SLT005: proto compat ----------------------------------------------------

_MINI_PROTO = """\
    syntax = "proto3";
    package t;

    message TraceContext {
      string trace_id = 1;
    }

    message FooRequest {
      string a = 1;
      TraceContext trace = 15;
    }
    """


def test_slt005_field_number_reuse(tmp_path):
    bad = _MINI_PROTO.replace("string a = 1;",
                              "string a = 1;\n      string b = 1;")
    root = _tree(tmp_path, {"native/proto/slt.proto": bad})
    fs = _run_rule(slt005_proto_compat, root)
    assert any("field number 1 reused" in f.message for f in fs), fs


def test_slt005_field_15_must_stay_trace(tmp_path):
    bad = _MINI_PROTO.replace("TraceContext trace = 15;",
                              "uint32 shiny = 15;")
    root = _tree(tmp_path, {"native/proto/slt.proto": bad})
    fs = _run_rule(slt005_proto_compat, root)
    msgs = [f.message for f in fs]
    assert any("reserved field 15" in m for m in msgs), msgs


def test_slt005_request_without_trace_carrier(tmp_path):
    bad = _MINI_PROTO.replace("      TraceContext trace = 15;\n", "")
    root = _tree(tmp_path, {"native/proto/slt.proto": bad})
    fs = _run_rule(slt005_proto_compat, root)
    assert any("lacks the optional" in f.message and f.severity == "warning"
               for f in fs), fs


def test_slt005_generated_code_drift(tmp_path):
    with open(os.path.join(REPO, "native/proto/slt.proto")) as f:
        proto = f.read()
    with open(os.path.join(REPO, "native/gen/slt_pb2.py")) as f:
        gen = f.read()
    # Renumber HeartbeatRequest.step without regenerating: wire break.
    drifted = proto.replace("uint64 step = 2;", "uint64 step = 9;")
    assert drifted != proto
    root = _tree(tmp_path, {"native/proto/slt.proto": drifted,
                            "native/gen/slt_pb2.py": gen})
    fs = _run_rule(slt005_proto_compat, root)
    assert any("regenerate native/gen" in f.message
               and "HeartbeatRequest.step" in f.message for f in fs), fs


def test_slt005_real_tree_parses_all_messages():
    proj = discover(REPO)
    msgs = slt005_proto_compat.parse_proto(
        proj.read(slt005_proto_compat.PROTO_PATH))
    gen = slt005_proto_compat.parse_gen(
        proj.read(slt005_proto_compat.GEN_PATH))
    assert "HeartbeatRequest" in msgs and len(msgs) == len(gen)
    assert gen["HeartbeatRequest"]["trace"] == 15


# -- SLT006: config drift ----------------------------------------------------

_MINI_CONFIG = """\
    from dataclasses import dataclass, field

    @dataclass
    class TrainConfig:
        num_steps: int = 1

    @dataclass
    class ExperimentConfig:
        train: TrainConfig = field(default_factory=TrainConfig)
    """


def test_slt006_unknown_field_read(tmp_path):
    root = _tree(tmp_path, {
        "serverless_learn_tpu/config.py": _MINI_CONFIG,
        "serverless_learn_tpu/loop.py": """\
            def run(cfg):
                good = cfg.train.num_steps
                return good + cfg.train.nmu_steps
            """,
    })
    fs = _run_rule(slt006_config_drift, root)
    assert len(fs) == 1 and "nmu_steps" in fs[0].message


def test_slt006_unknown_committed_config_key(tmp_path):
    root = _tree(tmp_path, {
        "serverless_learn_tpu/config.py": _MINI_CONFIG,
        "configs/bad.json": '{"train": {"nmu_steps": 5}, "trian": {}}',
    })
    fs = _run_rule(slt006_config_drift, root)
    msgs = " | ".join(f.message for f in fs)
    assert "nmu_steps" in msgs and "trian" in msgs and len(fs) == 2


# -- engine: baseline + CLI --------------------------------------------------

# -- SLT007: guarded-by inference --------------------------------------------

_GUARDED_BY_FIXTURE = """\
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                with self._lock:
                    self.count += 1

        def snapshot(self):
            with self._lock:
                return self.count

        def reset(self):
            self.count = 0
    """


def test_slt007_unguarded_write_to_disciplined_attr(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py":
                            _GUARDED_BY_FIXTURE})
    fs = _run_rule(slt007_guarded_by, root)
    assert len(fs) == 1, fs
    assert "Stats.count" in fs[0].message and "reset()" in fs[0].message
    assert "_lock" in fs[0].message


def test_slt007_locked_write_passes(tmp_path):
    fixed = _GUARDED_BY_FIXTURE.replace(
        "        def reset(self):\n"
        "            self.count = 0",
        "        def reset(self):\n"
        "            with self._lock:\n"
        "                self.count = 0")
    assert fixed != _GUARDED_BY_FIXTURE
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": fixed})
    assert _run_rule(slt007_guarded_by, root) == []


def test_slt007_init_and_single_thread_exempt(tmp_path):
    # No Thread in the module -> out of scope; __init__ writes never
    # count; a locally-constructed object's writes never count.
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        class Quiet:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0

            def bump(self):
                with self._lock:
                    self.x += 1

            def rebuild(self):
                q = Quiet()
                q.x = 9
                return q
        """})
    assert _run_rule(slt007_guarded_by, root) == []


def test_slt007_locked_suffix_convention_respected(tmp_path):
    fixed = _GUARDED_BY_FIXTURE.replace(
        "        def reset(self):", "        def reset_locked(self):")
    assert fixed != _GUARDED_BY_FIXTURE
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": fixed})
    assert _run_rule(slt007_guarded_by, root) == []


# -- SLT008: resource lifecycle ----------------------------------------------

def test_slt008_refcount_leak_by_construction(tmp_path):
    # BlockPool-like: a class that increfs and never releases.
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        class Registry:
            def __init__(self, pool):
                self.pool = pool
                self.held = []

            def register(self, bid):
                self.pool.incref(bid)
                self.held.append(bid)
        """})
    fs = _run_rule(slt008_resource_lifecycle, root)
    assert any("Registry" in f.message and "refcount leak" in f.message
               for f in fs), fs


def test_slt008_balanced_refcounts_pass(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        class Registry:
            def __init__(self, pool):
                self.pool = pool
                self.held = []

            def register(self, bid):
                self.pool.incref(bid)
                self.held.append(bid)

            def release(self, bid):
                self.held.remove(bid)
                self.pool.decref(bid)
        """})
    assert _run_rule(slt008_resource_lifecycle, root) == []


def test_slt008_exception_edge_leak(tmp_path):
    # incref'd refs unrecorded when a later alloc can raise = leak edge.
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        class Cache:
            def admit(self, ids):
                shared = self.trie.lookup(ids)
                self.pool.incref(shared)
                fresh = self.pool.alloc(4)
                self.pages = (shared, fresh)

            def evict(self):
                self.pool.decref(self.pages)
        """})
    fs = _run_rule(slt008_resource_lifecycle, root)
    assert any("exception edge" in f.message and "incref" in f.message
               for f in fs), fs


def test_slt008_guarded_exception_edge_passes(tmp_path):
    # try/except around the fallible window discharges the obligation:
    # the handler is where the incref'd refs get returned.
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        class Cache:
            def admit(self, ids):
                shared = self.trie.lookup(ids)
                self.pool.incref(shared)
                try:
                    fresh = self.pool.alloc(4)
                except Exception:
                    self.pool.decref(shared)
                    raise
                self.pages = (shared, fresh)

            def evict(self):
                self.pool.decref(self.pages)
        """})
    fs = _run_rule(slt008_resource_lifecycle, root)
    assert not any("exception edge" in f.message for f in fs), fs


def test_slt008_socket_never_closed(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import socket

        def probe(addr):
            s = socket.create_connection(addr)
            s.sendall(b"ping")
            return True
        """})
    fs = _run_rule(slt008_resource_lifecycle, root)
    assert any("never closed" in f.message for f in fs), fs


def test_slt008_closed_managed_or_escaping_sockets_pass(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import socket

        def probe(addr):
            s = socket.create_connection(addr)
            try:
                s.sendall(b"ping")
            finally:
                s.close()

        def managed(addr):
            with socket.create_connection(addr) as s:
                s.sendall(b"ping")

        def dialed(addr):
            return socket.create_connection(addr)

        class Holder:
            def connect(self, addr):
                self._sock = socket.create_connection(addr)

            def close(self):
                self._sock.close()
        """})
    assert _run_rule(slt008_resource_lifecycle, root) == []


def test_slt008_self_stored_socket_needs_teardown(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import socket

        class Holder:
            def connect(self, addr):
                self._sock = socket.create_connection(addr)
        """})
    fs = _run_rule(slt008_resource_lifecycle, root)
    assert any("never closes" in f.message and "_sock" in f.message
               for f in fs), fs


# -- SLT009: atomicity (check-then-act) --------------------------------------

_CHECK_THEN_ACT_FIXTURE = """\
    import threading

    class Cooldown:
        def __init__(self):
            self.last = -1.0
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            self.tick(0.0)

        def tick(self, now):
            if now - self.last > 5.0:
                self.last = now
    """


def test_slt009_check_then_act_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py":
                            _CHECK_THEN_ACT_FIXTURE})
    fs = _run_rule(slt009_atomicity, root)
    assert len(fs) == 1, fs
    assert "Cooldown.last" in fs[0].message
    assert "tick()" in fs[0].message


def test_slt009_locked_check_then_act_passes(tmp_path):
    fixed = _CHECK_THEN_ACT_FIXTURE.replace(
        "            self.last = -1.0",
        "            self.last = -1.0\n"
        "            self._lock = threading.Lock()").replace(
        "        def tick(self, now):\n"
        "            if now - self.last > 5.0:\n"
        "                self.last = now",
        "        def tick(self, now):\n"
        "            with self._lock:\n"
        "                if now - self.last > 5.0:\n"
        "                    self.last = now")
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": fixed})
    assert _run_rule(slt009_atomicity, root) == []


def test_slt009_double_checked_locking_not_flagged(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        class Lazy:
            def __init__(self):
                self._lock = threading.Lock()
                self.cache = None
                self._t = threading.Thread(target=self.get, daemon=True)
                self._t.start()

            def get(self):
                if self.cache is None:
                    with self._lock:
                        if self.cache is None:
                            self.cache = object()
                return self.cache
        """})
    assert _run_rule(slt009_atomicity, root) == []


def test_slt009_single_thread_class_not_flagged(tmp_path):
    # Same shape, but no thread entry points and no inferred guard:
    # no concurrency evidence, no finding.
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import threading

        def spawn():
            threading.Thread(target=print, daemon=True).start()

        class Local:
            def tick(self, now):
                if now - self.last > 5.0:
                    self.last = now
        """})
    assert _run_rule(slt009_atomicity, root) == []


_SEEDED = {
    # one seeded defect per acceptance bullet
    "serverless_learn_tpu/locks.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass
        """,
    "serverless_learn_tpu/top.py": """\
        WANT = "slt_never_emitted_total"
        """,
    "serverless_learn_tpu/step.py": """\
        import time

        import jax

        @jax.jit
        def step(x):
            return x + time.time()
        """,
    "native/proto/slt.proto": """\
        syntax = "proto3";
        message FooRequest {
          string a = 1;
          string b = 1;
        }
        """,
    "serverless_learn_tpu/guarded.py": _GUARDED_BY_FIXTURE,
    "serverless_learn_tpu/leak.py": """\
        class Registry:
            def register(self, bid):
                self.pool.incref(bid)
                self.held.append(bid)
        """,
    "serverless_learn_tpu/cooldown.py": _CHECK_THEN_ACT_FIXTURE,
}


def test_seeded_defects_fail_the_check(tmp_path):
    root = _tree(tmp_path, _SEEDED)
    rep = run_check(root, baseline_path="baseline.json")
    assert not rep["ok"]
    rules_hit = {f["rule"] for f in rep["findings"]}
    assert {"SLT001", "SLT002", "SLT003", "SLT005",
            "SLT007", "SLT008", "SLT009"} <= rules_hit


def test_baseline_roundtrip(tmp_path):
    root = _tree(tmp_path, _SEEDED)
    rep = run_check(root, baseline_path="baseline.json",
                    update_baseline=True)
    assert rep["ok"] and rep["counts"]["baselined"] > 0
    # Clean rerun: everything suppressed, nothing new.
    rep2 = run_check(root, baseline_path="baseline.json")
    assert rep2["ok"] and rep2["counts"]["new"] == 0
    # A NEW defect is never absorbed by the old baseline.
    (tmp_path / "serverless_learn_tpu" / "new.py").write_text(
        textwrap.dedent("""\
            import threading
            import time

            L = threading.Lock()

            def f():
                with L:
                    time.sleep(9)
            """))
    rep3 = run_check(root, baseline_path="baseline.json")
    assert not rep3["ok"]
    assert all(f["rule"] == "SLT001" for f in rep3["findings"])


def test_cli_check_json_schema(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    root = _tree(tmp_path, _SEEDED)
    rc = main(["check", "--root", root, "--json",
               "--baseline", "baseline.json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["ok"] is False
    assert set(out["rules"]) == set(RULES)
    for f in out["findings"]:
        assert {"rule", "path", "line", "severity", "message",
                "fingerprint"} <= set(f)
    assert out["counts"]["new"] == len(out["findings"]) > 0


def test_cli_check_single_rule(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    root = _tree(tmp_path, _SEEDED)
    rc = main(["check", "--root", root, "--json", "--rule", "SLT005",
               "--baseline", "baseline.json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in out["findings"]} == {"SLT005"}


def test_repo_at_head_is_clean():
    """The acceptance gate: `slt check` exits 0 on this checkout — every
    finding is fixed or baselined with a justification."""
    rep = run_check(REPO)
    assert rep["ok"], json.dumps(rep["findings"], indent=2)
    # And the committed baseline carries no stale or unjustified entries.
    from serverless_learn_tpu.analysis.engine import (DEFAULT_BASELINE,
                                                      load_baseline)

    baseline = load_baseline(os.path.join(REPO, DEFAULT_BASELINE))
    assert rep["counts"]["stale_baseline_entries"] == 0
    for entry in baseline.values():
        assert not entry["justification"].startswith("TODO"), entry


def test_update_baseline_prunes_fixed_defects(tmp_path):
    """Satellite: a removed defect's suppression must not outlive it."""
    root = _tree(tmp_path, _SEEDED)
    rep = run_check(root, baseline_path="baseline.json",
                    update_baseline=True)
    assert rep["ok"]
    from serverless_learn_tpu.analysis.engine import load_baseline

    before = load_baseline(str(tmp_path / "baseline.json"))
    lock_fps = {fp for fp, e in before.items() if e["rule"] == "SLT001"}
    assert lock_fps
    # Fix the lock-order defect, then update again: its entries vanish,
    # the others survive with their justifications intact.
    (tmp_path / "serverless_learn_tpu" / "locks.py").write_text(
        "X = 1\n")
    rep2 = run_check(root, baseline_path="baseline.json",
                     update_baseline=True)
    assert rep2["ok"]
    after = load_baseline(str(tmp_path / "baseline.json"))
    assert not (lock_fps & set(after)), "fixed defect's entry survived"
    assert any(e["rule"] == "SLT009" for e in after.values())


def test_update_baseline_preserves_unselected_rules(tmp_path):
    """--rule SLTxxx --update-baseline must not drop entries of rules
    that did not run (no evidence either way)."""
    root = _tree(tmp_path, _SEEDED)
    run_check(root, baseline_path="baseline.json", update_baseline=True)
    from serverless_learn_tpu.analysis.engine import load_baseline

    before = load_baseline(str(tmp_path / "baseline.json"))
    run_check(root, rule_ids=["SLT003"], baseline_path="baseline.json",
              update_baseline=True)
    after = load_baseline(str(tmp_path / "baseline.json"))
    assert set(after) == set(before)


def test_discovery_skips_pycache_and_gen_trees(tmp_path):
    root = _tree(tmp_path, {
        "serverless_learn_tpu/ok.py": "X = 1\n",
        "serverless_learn_tpu/__pycache__/junk.py": "import threading\n",
        "serverless_learn_tpu/gen/slt_pb2.py": "this is not python(\n",
    })
    proj = discover(root)
    assert [f.path for f in proj.files] == ["serverless_learn_tpu/ok.py"]


def _git(tmp_path, *args):
    import subprocess

    return subprocess.run(["git", "-C", str(tmp_path)] + list(args),
                          capture_output=True, text=True, check=True)


def test_changed_only_scopes_per_file_rules(tmp_path):
    """Satellite: --changed-only runs per-file rules on git-changed files
    only; project-scoped rules still see the full tree; --update-baseline
    refuses to run from a subset."""
    _tree(tmp_path, {"serverless_learn_tpu/clean.py": "X = 1\n"})
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "add", "-A")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "seed")
    # A new (untracked) defective file + an untouched committed file.
    _tree(tmp_path, {"serverless_learn_tpu/new.py": """\
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(9)
        """})
    rep = run_check(str(tmp_path), baseline_path="baseline.json",
                    changed_only=True)
    assert rep["changed_only"] is True
    assert rep["files_scanned"] == 1
    # Per-file findings come from the changed file only (project-scoped
    # rules — here SLT005's missing-proto warning — still run on the
    # full tree and are unaffected by the scoping).
    per_file = [f for f in rep["findings"] if f["rule"] == "SLT001"]
    assert per_file and {f["path"] for f in per_file} == \
        {"serverless_learn_tpu/new.py"}
    with pytest.raises(ValueError):
        run_check(str(tmp_path), baseline_path="baseline.json",
                  changed_only=True, update_baseline=True)
    # Nothing changed -> nothing scanned, no per-file findings.
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "add", "-A")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "new file")
    rep2 = run_check(str(tmp_path), baseline_path="baseline.json",
                     changed_only=True)
    assert rep2["files_scanned"] == 0
    assert not any(f["rule"] == "SLT001" for f in rep2["findings"])


def test_changed_only_without_git_falls_back_to_full_scan(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": "X = 1\n"})
    rep = run_check(root, baseline_path="baseline.json",
                    changed_only=True)
    assert rep["changed_only"] is False
    assert rep["files_scanned"] == 1


# -- runtime lockcheck -------------------------------------------------------

def test_lockcheck_detects_inverted_two_lock_ordering():
    mon = lockcheck.LockOrderMonitor("inversion-test")
    a = mon.wrap(site="fixture.py:1")
    b = mon.wrap(site="fixture.py:2")
    with a:
        with b:
            pass
    assert mon.violations() == []
    # The deliberate inversion: same pair, opposite order.
    with b:
        with a:
            pass
    vio = mon.violations()
    assert len(vio) == 1
    assert set(vio[0]["cycle"]) == {"fixture.py:1", "fixture.py:2"}
    with pytest.raises(lockcheck.LockOrderViolation):
        mon.assert_clean()
    assert "cycle" in mon.report()


def test_lockcheck_reentrant_rlock_and_same_site_are_clean():
    mon = lockcheck.LockOrderMonitor("reentrant-test")
    rl = mon.wrap(threading.RLock(), site="fixture.py:10")
    with rl:
        with rl:  # reentrant: no self-edge
            pass
    # Two locks from one creation site (per-instance class locks): held
    # together they model the same class-level node, never a cycle.
    c1 = mon.wrap(site="counter.py:5")
    c2 = mon.wrap(site="counter.py:5")
    with c1:
        with c2:
            pass
    assert mon.violations() == []
    mon.assert_clean()


def test_lockcheck_cross_thread_edges_merge():
    """Orderings recorded on DIFFERENT threads still conflict: thread 1
    takes A then B, thread 2 takes B then A — no run deadlocked, the
    graph still has the cycle."""
    mon = lockcheck.LockOrderMonitor("cross-thread")
    a = mon.wrap(site="x.py:1")
    b = mon.wrap(site="x.py:2")

    def order(first, second):
        with first:
            with second:
                pass

    t1 = threading.Thread(target=order, args=(a, b))
    t1.start()
    t1.join()
    t2 = threading.Thread(target=order, args=(b, a))
    t2.start()
    t2.join()
    assert len(mon.violations()) == 1


def test_lockcheck_wrapper_supports_condition_and_event():
    """Condition/Event built on instrumented locks must keep working —
    that is what makes suite-wide installation safe."""
    mon = lockcheck.LockOrderMonitor("condition-test")
    lk = mon.wrap(site="c.py:1")
    cond = threading.Condition(lk)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time as _time

    for _ in range(100):
        with cond:
            cond.notify_all()
        if hits:
            break
        _time.sleep(0.01)
    t.join(timeout=5)
    assert hits == [1]
    assert mon.violations() == []
