"""The shared bench-history regression guard (VERDICT r2 item 8)."""

import json

from serverless_learn_tpu.utils.benchlog import load_history, record


def _entry(**kw):
    base = {"metric": "m", "value": 100.0, "unit": "x/s",
            "device_kind": "TPU v5 lite"}
    base.update(kw)
    return base


def test_record_appends_and_flags_regression(tmp_path):
    path = str(tmp_path / "hist.json")
    first = record(_entry(value=100.0), path)
    assert "regression" not in first
    ok = record(_entry(value=97.0), path)  # within 5%
    assert "regression" not in ok
    bad = record(_entry(value=90.0), path)  # 10% below best
    assert bad["regression"] is True and bad["best"] == 100.0
    assert len(load_history(path)) == 3


def test_only_comparable_entries_compete(tmp_path):
    path = str(tmp_path / "hist.json")
    record(_entry(value=100.0, batch_per_chip=4096), path,
           key_fields=("metric", "device_kind", "batch_per_chip"))
    # different batch: not a baseline for this entry
    other = record(_entry(value=50.0, batch_per_chip=256), path,
                   key_fields=("metric", "device_kind", "batch_per_chip"))
    assert "regression" not in other
    # different chip: also no competition
    chip = record(_entry(value=50.0, batch_per_chip=4096,
                         device_kind="TPU v4"), path,
                  key_fields=("metric", "device_kind", "batch_per_chip"))
    assert "regression" not in chip


def test_min_better_direction(tmp_path):
    path = str(tmp_path / "hist.json")
    record(_entry(metric="t_ms", value=14.0), path, better="min")
    worse = record(_entry(metric="t_ms", value=16.0), path, better="min")
    assert worse["regression"] is True
    better = record(_entry(metric="t_ms", value=13.0), path, better="min")
    assert "regression" not in better


def test_variance_widens_threshold(tmp_path):
    """The r2 flash ambiguity (14 vs 16 ms one-offs): with a measured 15%
    spread the guard must NOT flag a 14 -> 16 ms move, but a clean 2x
    regression still trips it."""
    path = str(tmp_path / "hist.json")
    record(_entry(metric="t_ms", value=14.0, spread_rel=0.15), path,
           better="min")
    noisy = record(_entry(metric="t_ms", value=16.0, spread_rel=0.15), path,
                   better="min")
    assert "regression" not in noisy  # 14.3% worse < 2*15% widened gap
    real = record(_entry(metric="t_ms", value=30.0, spread_rel=0.15), path,
                  better="min")
    assert real["regression"] is True


def test_corrupt_history_preserved(tmp_path):
    path = str(tmp_path / "hist.json")
    with open(path, "w") as f:
        f.write("{not json")
    rec = record(_entry(), path)
    assert "regression" not in rec
    assert (tmp_path / "hist.json.corrupt").exists()
    assert len(json.load(open(path))) == 1
