"""Canary (round 23): weight-version identity end to end, golden-probe
quality SLIs, and the promote/hold/rollback verdict engine.

The contract under test: a weight version is ONE fingerprint everywhere
(numerics digest -> registration name -> ping -> route_decision ->
waterfall span), the router's version split is session-sticky and
deterministic, golden-probe traffic is shed-exempt and EXCLUDED from
user SLI aggregates while staying fully present in the ledgers, and
`slt canary` folds the version-tagged streams into a deterministic
verdict whose evidence names the exact trigger. The slow acceptance at
the bottom proves the whole loop on a live 2-version stub fleet with an
injected quality regression flipping the verdict to rollback.
"""

import json
import os
import threading
import time

import pytest

from serverless_learn_tpu.telemetry import canary
from serverless_learn_tpu.telemetry.registry import MetricsRegistry

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "canary",
                       "canary_fixture.jsonl")
BENCH_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                             "canary", "bench_history_canary.json")

V_BASE, V_CAND = canary.V_BASE, canary.V_CAND


# -- version identity --------------------------------------------------------


def test_probe_fingerprint_order_sensitive_and_deterministic():
    fp = canary.probe_fingerprint([1, 2, 3, 4])
    assert len(fp) == 12 and fp == canary.probe_fingerprint([1, 2, 3, 4])
    assert fp != canary.probe_fingerprint([4, 3, 2, 1])
    assert fp != canary.probe_fingerprint([1, 2, 3])


def test_weight_version_fingerprints_weights_not_metadata():
    """Same weights => same 12-hex tag; different weights => different
    tag; no weights => no tag (a replica without params registers
    version-less and parses exactly as before round 23)."""
    import jax.numpy as jnp

    from serverless_learn_tpu.telemetry.numerics import weight_version

    tree = {"dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros(4)}}
    v1 = weight_version(tree)
    assert v1 is not None and len(v1) == 12
    assert weight_version({"dense": {"kernel": jnp.ones((4, 4)),
                                     "bias": jnp.zeros(4)}}) == v1
    tree2 = {"dense": {"kernel": jnp.ones((4, 4)) * 2.0,
                       "bias": jnp.zeros(4)}}
    assert weight_version(tree2) != v1
    assert weight_version(None) is None


def test_replica_name_roundtrips_version():
    from serverless_learn_tpu.fleet.registration import (parse_replica,
                                                         replica_name)

    name = replica_name("serve", "10.0.0.1:9100", version="aaaa00001111")
    assert name.endswith(";v=aaaa00001111")
    info = parse_replica(name, "10.0.0.1:9000")
    assert info == {"service": "serve", "serve_addr": "10.0.0.1:9000",
                    "metrics_addr": "10.0.0.1:9100",
                    "version": "aaaa00001111"}
    # Pre-round-23 names (no ;v=) parse exactly as before.
    old = parse_replica(replica_name("serve", "m:1"), "a:2")
    assert old["version"] is None and old["metrics_addr"] == "m:1"
    with pytest.raises(ValueError):
        replica_name("serve", version="bad;stuff")
    with pytest.raises(ValueError):
        replica_name("se;rve")


# -- verdict engine ----------------------------------------------------------


def _mk_summary(cand_row, base_row, timeline=None):
    """Hand-built summarize() output: verdict() is a pure function of
    this shape, so units can poke single triggers."""
    return {"candidate": V_CAND, "baseline": V_BASE,
            "versions": {V_CAND: cand_row, V_BASE: base_row},
            "timelines": {V_CAND: timeline or []},
            "canary": {"active": True, "candidate_version": V_CAND,
                       "frac": 0.25}}


_HEALTHY_CAND = {"requests": 10, "probe_total": 4, "probe_match": 4,
                 "errors": 0, "ttft_p99_ms": 45.0}
_HEALTHY_BASE = {"requests": 20, "probe_total": 4, "probe_match": 4,
                 "errors": 0, "ttft_p99_ms": 45.0}


def test_verdict_promote_names_all_three_checks():
    vd = canary.verdict(_mk_summary(dict(_HEALTHY_CAND),
                                    dict(_HEALTHY_BASE)))
    assert vd["decision"] == "promote"
    assert vd["probe_match_frac"] == 1.0
    assert vd["p99_delta_frac"] == 0.0 and vd["delta_basis"] == "ttft_p99_ms"
    ev = " ".join(vd["evidence"])
    assert "golden probes 4/4" in ev and "burn-rate clean" in ev


def test_verdict_holds_without_two_versions():
    vd = canary.verdict({"candidate": None, "baseline": None,
                         "versions": {}, "timelines": {}})
    assert vd["decision"] == "hold"
    assert "fewer than two weight versions" in vd["evidence"][0]


def test_verdict_holds_on_thin_evidence_with_named_gaps():
    c = dict(_HEALTHY_CAND, probe_total=2, probe_match=2, requests=3)
    del c["ttft_p99_ms"]
    b = dict(_HEALTHY_BASE)
    del b["ttft_p99_ms"]
    vd = canary.verdict(_mk_summary(c, b))
    assert vd["decision"] == "hold"
    ev = " ".join(vd["evidence"])
    assert "only 2 candidate golden probe(s)" in ev
    assert "only 3 candidate user request(s)" in ev
    assert "no p99 latency sample on BOTH versions" in ev


def test_verdict_rollback_orders_quality_before_latency():
    """Both triggers fire: the evidence list is quality-first (fixed
    check order), and ANY probe mismatch fails the exact-greedy floor."""
    c = dict(_HEALTHY_CAND, probe_match=3, ttft_p99_ms=90.0)
    vd = canary.verdict(_mk_summary(c, dict(_HEALTHY_BASE)))
    assert vd["decision"] == "rollback"
    assert len(vd["evidence"]) == 2
    assert "golden-probe fingerprint match 3/4" in vd["evidence"][0]
    assert "ttft p99 ms 90.0 vs baseline 45.0" in vd["evidence"][1]
    assert "+100%" in vd["evidence"][1]


def test_verdict_rollback_on_critical_burn_only():
    """Perfect probes and flat latency, but a sustained candidate error
    burn: the round-9 two-window AND goes critical and rolls back —
    while a short blip (long window still clean) only holds."""
    # Sustained: ~50% errors over 800 s >> 14.4x of the 2% budget in
    # BOTH windows.
    t0 = 1754300000.0
    sustained = []
    bad = 0
    for i in range(200):
        bad += i % 2
        sustained.append([t0 + 4.0 * i, bad, i + 1])
    vd = canary.verdict(_mk_summary(dict(_HEALTHY_CAND),
                                    dict(_HEALTHY_BASE), sustained))
    assert vd["decision"] == "rollback"
    assert "burn-rate critical" in vd["evidence"][0]
    assert "two-window AND" in vd["evidence"][0]
    # Moderate sustained burn (~14% errors = ~7x of the 2% budget in
    # BOTH windows): warning-level, so the verdict HOLDS — naming the
    # burn — instead of rolling back.
    warn = []
    bad = 0
    for i in range(200):
        bad += 1 if i % 7 == 0 else 0
        warn.append([t0 + 4.0 * i, bad, i + 1])
    vd2 = canary.verdict(_mk_summary(dict(_HEALTHY_CAND),
                                     dict(_HEALTHY_BASE), warn))
    assert vd2["decision"] == "hold"
    assert any("burn-rate warning" in e for e in vd2["evidence"])


# -- summarize + the committed fixture ---------------------------------------


def _fixture_records():
    with open(FIXTURE) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_committed_fixture_is_the_synthetic_parity_scenario():
    """Fixture-drift guard: the committed JSONL is byte-for-byte the
    embedded generator's parity scenario, so self_check's hand-computed
    expectations can never silently diverge from the committed file."""
    with open(FIXTURE) as f:
        committed = [line.rstrip("\n") for line in f if line.strip()]
    expected = [json.dumps(r, sort_keys=True)
                for r in canary.synthetic_records("parity")]
    assert committed == expected


def test_summarize_excludes_probes_from_user_slis_exactly():
    """The fixture's 8 golden probes run at 500 ms TTFT — >10x the user
    traffic. Hand-computed: user TTFT p99 stays 45.0 ms on BOTH
    versions, probe counts land in probe_* fields, and the overhead
    share is exactly 8/32."""
    s = canary.summarize(_fixture_records())
    assert s["candidate"] == V_CAND and s["baseline"] == V_BASE
    assert s["canary"] == {"active": True, "candidate_version": V_CAND,
                           "frac": 0.25}
    c, b = s["versions"][V_CAND], s["versions"][V_BASE]
    assert (c["requests"], b["requests"]) == (8, 16)
    assert c["ttft_p99_ms"] == 45.0 and b["ttft_p99_ms"] == 45.0
    assert c["ttft_n"] == 8 and b["ttft_n"] == 16   # probes not counted
    assert c["probe_total"] == 4 and c["probe_match_frac"] == 1.0
    assert s["probe_decisions"] == 8
    assert s["probe_overhead_frac"] == pytest.approx(8 / 32)
    assert s["distinct_replica_versions"] == 2
    assert s["replica_versions"] == {"n0:9000": V_BASE, "n1:9000": V_BASE,
                                     "n2:9000": V_CAND}


def test_self_check_passes_on_synthetic_and_committed_fixture():
    rep = canary.self_check()
    assert rep["ok"], rep["checks"]
    rep = canary.self_check(fixture_path=FIXTURE)
    assert rep["ok"], rep["checks"]
    assert {c["check"] for c in rep["checks"]} >= {
        "verdict_promote_on_parity", "verdict_rollback_on_probe_regression",
        "verdict_rollback_on_ttft_regression",
        "probe_exclusion_from_user_slis", "byte_identical_report"}


def test_report_is_byte_identical_and_injectors_flip_verdict():
    rep1 = canary.report([FIXTURE])
    rep2 = canary.report([FIXTURE])
    assert json.dumps(rep1, sort_keys=True) == json.dumps(rep2,
                                                          sort_keys=True)
    assert rep1["verdict"]["decision"] == "promote"
    recs = _fixture_records()
    vq = canary.report_records(
        canary._inject_probe_regression(recs))["verdict"]
    assert vq["decision"] == "rollback" and vq["probe_match_frac"] == 0.0
    vt = canary.report_records(
        canary._inject_ttft_regression(recs))["verdict"]
    assert vt["decision"] == "rollback"
    assert vt["p99_delta_frac"] == 2.0        # 135 ms vs 45 ms: +200%


# -- golden-probe runner -----------------------------------------------------


class _FakeFleet:
    """Request-shaped stand-in for the router: greedy echo of the
    prompt, with the candidate version optionally diverging (the
    quality regression) and errors injectable."""

    def __init__(self):
        self.divergent = False
        self.fail_candidate = False
        self.requests = []

    def send(self, req):
        self.requests.append(req)
        pin = req.get("pin_version")
        if self.fail_candidate and pin == V_CAND:
            raise ConnectionResetError("replica died")
        off = 1 if (self.divergent and pin == V_CAND) else 0
        return {"tokens": [t + off for t in req["prompt"]]}


def test_prober_tags_requests_and_scores_matches():
    fleet = _FakeFleet()
    reg = MetricsRegistry()
    events = []
    pr = canary.CanaryProber(fleet.send, V_CAND, V_BASE, registry=reg,
                             emit=events.append)
    base = pr.record_baseline()
    assert len(base) == 4 and all(r["phase"] == "record" for r in base)
    assert len(pr.expected) == 4
    rnd = pr.run_round()
    assert rnd == {"sent": 8, "matched": 8, "errors": 0}
    # Every wire request is tagged probe traffic: shed-exempt priority,
    # greedy, pinned, and named so ledgers can join it back.
    for req in fleet.requests:
        assert req["probe"] is True and req["priority"] >= 1
        assert req["temperature"] == 0.0
        assert req["pin_version"] in (V_BASE, V_CAND)
        assert req["session"].startswith("canary-probe:")
    snap = reg.snapshot()

    def val(name):
        return sum(s["value"] for s in snap[name]["series"])

    assert val("slt_canary_probe_sent_total") == 12
    # The recording round itself scores 4 matches (fp == just-recorded
    # expectation), so 4 + 8 land in the match counter.
    assert val("slt_canary_probe_match_total") == 12
    assert val("slt_canary_probe_mismatch_total") == 0
    assert all(e["event"] == "canary_probe" for e in events)


def test_prober_catches_divergence_and_transport_errors():
    fleet = _FakeFleet()
    reg = MetricsRegistry()
    pr = canary.CanaryProber(fleet.send, V_CAND, V_BASE, registry=reg)
    pr.record_baseline()
    fleet.divergent = True
    rnd = pr.run_round()
    assert rnd == {"sent": 8, "matched": 4, "errors": 0}   # baseline ok
    assert pr.mismatched == 4
    fleet.divergent = False
    fleet.fail_candidate = True
    rnd2 = pr.run_round()
    assert rnd2["errors"] == 4                 # transport = probe error
    snap = reg.snapshot()
    mism = sum(s["value"]
               for s in snap["slt_canary_probe_mismatch_total"]["series"])
    assert mism == 4                           # errors are not mismatches


# -- router: version split, stickiness, probe exemption ----------------------


def _make_router(replicas, registry=None, events=None, **cfg_kw):
    from serverless_learn_tpu.config import FleetConfig
    from serverless_learn_tpu.fleet.router import FleetRouter

    defaults = dict(health_interval_s=0.05, dead_after_probes=5,
                    discover_interval_s=0.3, hedge_min_delay_s=5.0,
                    eject_s=0.4, upstream_timeout_s=5.0,
                    queue_timeout_s=2.0)
    defaults.update(cfg_kw)
    return FleetRouter(config=FleetConfig(**defaults), host="127.0.0.1",
                       port=0, replicas=tuple(replicas),
                       registry=registry or MetricsRegistry(),
                       emit=(events.append if events is not None
                             else lambda rec: None))


def _await_versions(router, n, deadline_s=5.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        with router._lock:
            if sum(1 for r in router._replicas.values()
                   if r.version) >= n:
                return True
        time.sleep(0.02)
    return False


def _two_version_fleet(events, registry=None):
    from serverless_learn_tpu.fleet.testing import StubEngine, stub_server

    base = stub_server(engine=StubEngine(latency_s=0.0,
                                         weight_version="basefp000001"))
    cand = stub_server(engine=StubEngine(latency_s=0.0,
                                         weight_version="candfp000002",
                                         reply_offset=1))
    router = _make_router([base.addr, cand.addr], registry=registry,
                          events=events).start()
    assert _await_versions(router, 2)
    return router, base, cand


def test_router_ingests_versions_and_splits_session_sticky():
    """Ping-reported fingerprints become fleet_version events and
    route_decision tags; the 50% split is md5-session-sticky — the SAME
    6/10 candidate/baseline assignment every run, and a re-sent session
    never moves."""
    from serverless_learn_tpu.inference.server import request

    events = []
    reg = MetricsRegistry()
    router, base, cand = _two_version_fleet(events, registry=reg)
    try:
        router.set_canary("candfp000002", 0.5)
        # Deterministic md5 bucketing: sess-{3,5,8,11,14,15} -> candidate
        # (precomputed; the same 6/16 every run on every machine).
        expect_cand = {3, 5, 8, 11, 14, 15}
        for rnd in range(2):
            for i in range(16):
                rep = request(router.addr,
                              {"prompt": [1 + i % 5, 2], "max_new_tokens": 2,
                               "session": f"sess-{i}"})
                assert "new_tokens" in rep, rep
                # The candidate stub's reply_offset shifts the output:
                # the COMPLETION itself proves which version served —
                # and round 2 reproducing round 1 proves stickiness.
                base0 = ((1 + i % 5 + 2) * 31) % 1000
                served_cand = rep["new_tokens"][0] == (base0 + 1) % 1000
                assert served_cand == (i in expect_cand), (rnd, i)
        deadline = time.monotonic() + 3.0
        decs = []
        while time.monotonic() < deadline and len(decs) < 32:
            decs = [e for e in events if e.get("event") == "route_decision"]
            time.sleep(0.02)
        for d in decs:
            assert d["version"] in ("basefp000001", "candfp000002")
            assert d["canary"] in ("candidate", "baseline")
        assert sum(1 for d in decs
                   if d["canary"] == "candidate") == 2 * len(expect_cand)
        fv = [e for e in events if e.get("event") == "fleet_version"]
        assert {e["version"] for e in fv} == {"basefp000001",
                                              "candfp000002"}
        cfg_ev = [e for e in events if e.get("event") == "canary_config"]
        assert cfg_ev and cfg_ev[-1]["frac"] == 0.5
        snap = reg.snapshot()
        assert sum(s["value"] for s in
                   snap["slt_fleet_weight_versions"]["series"]) == 2
        assert sum(s["value"] for s in
                   snap["slt_canary_candidate_frac"]["series"]) == 0.5
    finally:
        router.stop(), base.stop(), cand.stop()


def test_pin_version_routes_strictly_and_sheds_unknown():
    """pin_version is strict: the candidate fingerprint reaches the
    candidate replica (reply_offset proves it by OUTPUT, not just by
    addr), and an unknown fingerprint sheds with a typed reason instead
    of silently serving the wrong weights."""
    from serverless_learn_tpu.inference.server import request

    events = []
    router, base, cand = _two_version_fleet(events)
    try:
        rep_b = request(router.addr, {"prompt": [5, 6, 7],
                                      "max_new_tokens": 2,
                                      "pin_version": "basefp000001"})
        rep_c = request(router.addr, {"prompt": [5, 6, 7],
                                      "max_new_tokens": 2,
                                      "pin_version": "candfp000002"})
        # The candidate stub's reply_offset shifts every generated
        # token: versions produce different completions by construction.
        assert rep_c["new_tokens"] == [(t + 1) % 1000
                                       for t in rep_b["new_tokens"]]
        rep_x = request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                      "pin_version": "nope"})
        assert rep_x.get("code") == "overloaded"
        assert "no eligible replica serving version nope" in rep_x["error"]
        deadline = time.monotonic() + 3.0
        shed = []
        while time.monotonic() < deadline and not shed:
            shed = [e for e in events
                    if e.get("event") == "route_decision"
                    and e.get("reason") == "shed_no_version"]
            time.sleep(0.02)
        assert shed and shed[0]["pick"] is None
    finally:
        router.stop(), base.stop(), cand.stop()


def test_probe_traffic_excluded_from_user_slis_but_counted():
    """Probes route and serve, but the user latency histogram does not
    move — the probe counter and overhead gauge do, and the decision
    stream carries probe=True for the offline ledgers."""
    from serverless_learn_tpu.inference.server import request

    events = []
    reg = MetricsRegistry()
    router, base, cand = _two_version_fleet(events, registry=reg)
    try:
        for i in range(4):
            request(router.addr, {"prompt": [1, 2], "max_new_tokens": 2,
                                  "session": f"u{i}"})
        for i in range(2):
            rep = request(router.addr, {"prompt": [1, 2],
                                        "max_new_tokens": 2,
                                        "probe": True, "priority": 1})
            assert "tokens" in rep
        snap = reg.snapshot()

        def val(name):
            return sum(s["value"] for s in snap[name]["series"])

        hist = snap["slt_router_request_seconds"]["series"]
        assert sum(s["count"] for s in hist) == 4     # users only
        assert val("slt_canary_probe_requests_total") == 2
        assert val("slt_canary_probe_overhead_frac") == pytest.approx(
            2 / 6, abs=1e-3)
        deadline = time.monotonic() + 3.0
        probes = []
        while time.monotonic() < deadline and len(probes) < 2:
            probes = [e for e in events
                      if e.get("event") == "route_decision"
                      and e.get("probe")]
            time.sleep(0.02)
        assert len(probes) == 2
    finally:
        router.stop(), base.stop(), cand.stop()


def test_probe_is_shed_exempt_under_brownout():
    """A saturated replica browns out priority-0 users; the SAME shaped
    request tagged probe:true is priority-forced past the brownout gate
    (quality SLIs must keep flowing exactly when the fleet is sick)."""
    from serverless_learn_tpu.fleet.testing import StubEngine, stub_server
    from serverless_learn_tpu.inference.server import request

    slow = stub_server(engine=StubEngine(latency_s=0.5))
    router = _make_router([slow.addr], max_inflight=2,
                          shed_start_frac=0.5,
                          queue_timeout_s=3.0).start()
    try:
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            with router._lock:
                if router._replicas:
                    break
            time.sleep(0.02)
        occupied = threading.Thread(
            target=lambda: request(router.addr, {"prompt": [1],
                                                 "max_new_tokens": 2}),
            daemon=True)
        occupied.start()
        time.sleep(0.15)               # occupant holds 1 of 2 slots
        user = request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                     "priority": 0})
        assert user.get("code") == "overloaded"
        assert "brownout" in user["error"]
        probe = request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                      "priority": 0, "probe": True})
        assert "tokens" in probe, probe
        occupied.join(timeout=5.0)
    finally:
        router.stop(), slow.stop()


# -- satellite: mid-request weight swap in the waterfall ---------------------


def test_weight_swap_is_a_named_interval_stall_cause():
    from serverless_learn_tpu.telemetry import waterfall

    assert "weight_swap" in waterfall.STALL_CAUSES
    assert "weight_swap" not in waterfall.MARKER_CAUSES  # interval cause


def test_waterfall_attributes_mid_request_swap_exactly():
    """A request decoding THROUGH a weight swap: the swap window is
    noted as a boundary interval, the stalled gap names weight_swap,
    and the round-21 exactness invariant holds to the microsecond —
    base_s + sum(causes) == gap_s, with the swap claiming the excess."""
    from serverless_learn_tpu.telemetry import waterfall

    ev = waterfall.BoundaryEvents()
    wf = waterfall.RequestWaterfall(min_stall_s=0.001)
    t = 100.0
    wf.first_token(t)
    # Establish a 10 ms ITL baseline.
    for i in range(1, 6):
        out = wf.note_decode(t + 0.010 * i, 1, ev)
        assert out is not None and out[1] is None      # no stall yet
    # The engine swaps weights for 80 ms mid-decode ...
    t_swap0 = t + 0.055
    t_swap1 = t_swap0 + 0.080
    ev.note("weight_swap", t_swap0, t_swap1)
    # ... and the next harvest lands 90 ms after the previous one.
    itl, causes = wf.note_decode(t + 0.050 + 0.090, 1, ev)
    assert causes is not None and set(causes) == {"weight_swap"}
    (stall,) = wf.stalls
    assert stall["causes"].keys() == {"weight_swap"}
    assert stall["base_s"] + sum(stall["causes"].values()) \
        == pytest.approx(stall["gap_s"], abs=2e-6)
    assert stall["causes"]["weight_swap"] == pytest.approx(0.080, abs=0.005)
    assert wf.stall_totals["weight_swap"] > 0.07


def test_waterfall_finalize_and_summarize_keep_swap_invariants():
    """finalize() rebases the swap stall into the span record, the TTFT
    decomposition stays exact-by-construction, summarize() folds the
    cause into the fleet stall ledger, and the module's own self-check
    still passes with the round-23 cause in the taxonomy."""
    from serverless_learn_tpu.telemetry import waterfall
    from serverless_learn_tpu.telemetry.registry import Span

    ev = waterfall.BoundaryEvents()
    wf = waterfall.RequestWaterfall(min_stall_s=0.001)
    span = Span("request")
    t0 = span.t0
    span.marks["admit"] = 0.002
    span.marks["first_token"] = 0.040
    span.marks["done"] = 0.400
    wf.note_admit(t0, t0 + 0.001)
    wf.first_token(t0 + 0.040)
    for i in range(1, 6):
        wf.note_decode(t0 + 0.040 + 0.010 * i, 1, ev)
    ev.note("weight_swap", t0 + 0.095, t0 + 0.175)
    wf.note_decode(t0 + 0.090 + 0.090, 1, ev)
    rec = wf.finalize(span)
    decomp = rec["ttft_decomp_s"]
    assert sum(decomp.values()) == pytest.approx(rec["ttft_s"], abs=2e-6)
    (stall,) = rec["stalls"]
    assert set(stall["causes"]) == {"weight_swap"}
    assert stall["base_s"] + sum(stall["causes"].values()) \
        == pytest.approx(stall["gap_s"], abs=2e-6)
    assert rec["stall_s"]["weight_swap"] > 0.07
    summary = waterfall.summarize([{
        "t0_unix_s": 1754300000.0, "duration_s": 0.4, "node": "n0",
        "trace_id": "ab" * 16, "marks_s": dict(span.marks),
        "waterfall": rec, "router": None}])
    assert summary["stall_s"].keys() == {"weight_swap"}
    assert summary["dominant_stall_cause"] == "weight_swap"
    assert summary["invariants"] == {"ttft_decomp_bad": 0,
                                     "stall_sum_bad": 0}
    assert waterfall.self_check()["ok"]


# -- surfacing: exporter endpoint, top pane, doctor --------------------------


def _fetch_json(addr, path):
    import urllib.request

    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), \
            json.loads(r.read().decode())


def test_exporter_serves_canary_rollup():
    from serverless_learn_tpu.telemetry.exporter import MetricsExporter

    reg = MetricsRegistry()
    reg.gauge("slt_fleet_weight_versions", "n").set(2)
    reg.counter("slt_fleet_version_swaps_total", "n").inc(1)
    reg.gauge("slt_canary_candidate_frac", "frac").set(0.25)
    reg.counter("slt_canary_probe_requests_total", "n").inc(8)
    reg.gauge("slt_canary_probe_overhead_frac", "frac").set(0.25)
    reg.counter("slt_canary_probe_sent_total", "n").inc(12)
    reg.counter("slt_canary_probe_match_total", "n").inc(7)
    reg.counter("slt_canary_probe_mismatch_total", "n").inc(1)
    exp = MetricsExporter(registry=reg).start()
    try:
        code, ctype, cn = _fetch_json(exp.addr, "/canary")
    finally:
        exp.stop()
    assert code == 200 and ctype == "application/json"
    assert cn["enabled"] and cn["weight_versions"] == 2
    assert cn["candidate_frac"] == 0.25
    assert cn["probe_requests"] == 8
    assert cn["probe_match_frac"] == pytest.approx(7 / 8)
    assert cn["probe_overhead_frac"] == 0.25


def test_exporter_structured_errors_on_unknown_and_malformed():
    """Satellite: every exporter miss is a machine-readable JSON body
    with the SAME content type as the happy path — a scraper never has
    to parse an HTML error page."""
    import urllib.error
    import urllib.request

    from serverless_learn_tpu.telemetry import exporter as exp_mod
    from serverless_learn_tpu.telemetry.exporter import MetricsExporter

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        exp = MetricsExporter(registry=MetricsRegistry(),
                              profile_dir=td).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{exp.addr}/no/such/endpoint", timeout=5)
            err = ei.value
            assert err.code == 404
            assert err.headers.get("Content-Type") == "application/json"
            body = json.loads(err.read().decode())
            assert body["ok"] is False
            assert "unknown path '/no/such/endpoint'" in body["error"]
            assert "/canary" in body["endpoints"]
            assert set(body["endpoints"]) == set(exp_mod.ENDPOINTS)
            with pytest.raises(urllib.error.HTTPError) as ei2:
                urllib.request.urlopen(
                    f"http://{exp.addr}/debug/profile?seconds=abc",
                    timeout=5)
            assert ei2.value.code == 400
            body2 = json.loads(ei2.value.read().decode())
            assert body2 == {"ok": False,
                             "error": "seconds must be a number"}
        finally:
            exp.stop()


def test_top_renders_version_pane():
    from serverless_learn_tpu.telemetry import top as top_mod
    from serverless_learn_tpu.telemetry.exporter import MetricsExporter

    reg = MetricsRegistry()
    reg.gauge("slt_router_replicas", "n").set(2)
    reg.gauge("slt_fleet_weight_versions", "n").set(2)
    reg.counter("slt_fleet_version_swaps_total", "n").inc(3)
    reg.gauge("slt_canary_candidate_frac", "frac").set(0.25)
    reg.counter("slt_canary_probe_requests_total", "n").inc(8)
    reg.gauge("slt_canary_probe_overhead_frac", "frac").set(0.2)
    reg.counter("slt_canary_probe_sent_total", "n").inc(10)
    reg.counter("slt_canary_probe_match_total", "n").inc(10)
    exp = MetricsExporter(registry=reg).start()
    try:
        st = top_mod.EndpointState(exp.addr)
        st.poll()
        out = top_mod.render([st])
    finally:
        exp.stop()
    assert "VERSION" in out
    assert "canary frac" in out and "probe match" in out
    assert "25%" in out and "100%" in out and "20%" in out


def test_doctor_flags_unmanaged_version_skew():
    """Two fingerprints in service with NO canary split configured is an
    un-gated partial rollout — doctor names it from the event log alone
    and points at `slt canary`."""
    import tempfile

    from serverless_learn_tpu.telemetry import doctor

    recs = [
        {"event": "fleet_version", "replica": "n0:9000",
         "t_unix_s": 1754300000.0, "version": "aaaa00001111", "prev": None},
        {"event": "fleet_version", "replica": "n1:9000",
         "t_unix_s": 1754300001.0, "version": "bbbb22223333", "prev": None},
    ]
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    try:
        rep = doctor.diagnose(paths=[f.name])
    finally:
        os.unlink(f.name)
    verdict = rep["summary"]["verdict"]
    assert "fleet version skew: 2 weight fingerprints" in verdict
    assert "slt canary" in verdict
    assert rep["canary"]["summary"]["distinct_replica_versions"] == 2


def test_doctor_names_bad_canary_from_logs_alone():
    import tempfile

    from serverless_learn_tpu.telemetry import doctor

    recs = canary.synthetic_records("probe_regression")
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    try:
        rep = doctor.diagnose(paths=[f.name])
    finally:
        os.unlink(f.name)
    verdict = rep["summary"]["verdict"]
    assert "canary ROLLBACK" in verdict
    assert V_CAND in verdict and "golden-probe" in verdict
    assert rep["canary"]["verdict"]["decision"] == "rollback"
    # A healthy split must NOT page: parity logs produce no canary line.
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f2:
        for r in canary.synthetic_records("parity"):
            f2.write(json.dumps(r) + "\n")
    try:
        rep2 = doctor.diagnose(paths=[f2.name])
    finally:
        os.unlink(f2.name)
    assert "canary ROLLBACK" not in rep2["summary"]["verdict"]
    assert "version skew" not in rep2["summary"]["verdict"]


# -- bench gate --------------------------------------------------------------


def test_bench_rows_carry_canary_columns_and_gate():
    from serverless_learn_tpu.telemetry import benchgate
    from serverless_learn_tpu.utils.benchlog import load_history

    rows = canary.bench_rows(canary.report([FIXTURE]),
                             device_kind="cpu")
    (row,) = rows
    assert row["metric"] == "canary_candidate_p99_ms"
    assert row["value"] == 45.0
    assert row["canary_probe_match_frac"] == 1.0
    assert row["canary_verdict"] == "promote"
    assert row["canary_verdict_ok"] == 1.0
    for col in ("canary_probe_match_frac", "canary_ttft_p99_delta_frac",
                "canary_verdict_ok"):
        assert col in benchgate.ATTRIBUTION_COLUMNS
    rep = benchgate.gate_history(load_history(BENCH_FIXTURE),
                                 metric="canary_")
    assert rep["ok"] and rep["series"] == 1
    cols = {a["column"] for c in rep["checks"]
            for a in c.get("attribution", [])}
    assert cols >= {"canary_probe_match_frac",
                    "canary_ttft_p99_delta_frac", "canary_verdict_ok"}


def test_gate_fails_a_rollback_run_outright():
    """canary_verdict_ok gates with a ZERO gap: one rollback run fails
    the gate even if its latency value is the best ever seen."""
    from serverless_learn_tpu.telemetry import benchgate

    entry = {"metric": "canary_candidate_p99_ms", "value": 40.0,
             "unit": "ms", "device_kind": "cpu", "count": 35,
             "canary_probe_match_frac": 0.0,
             "canary_ttft_p99_delta_frac": 0.0,
             "canary_verdict": "rollback", "canary_verdict_ok": 0.0}
    rep = benchgate.run_gate(BENCH_FIXTURE, entry=entry,
                             key_fields=("metric", "device_kind"))
    assert not rep["ok"]
    bad = {a["column"] for c in rep["checks"]
           for a in c.get("attribution", []) if not a["ok"]}
    assert bad == {"canary_probe_match_frac", "canary_verdict_ok"}


# -- CLI ---------------------------------------------------------------------


def test_cli_canary_self_check_and_rollback_exit_code(capsys):
    import tempfile

    from serverless_learn_tpu.cli import main

    assert main(["canary", "--self-check", "--compact",
                 "--fixture", FIXTURE]) == 0
    out = capsys.readouterr().out
    assert '"ok": true' in out
    # Promote over the committed fixture: exit 0, verdict rendered.
    assert main(["canary", FIXTURE, "--compact"]) == 0
    assert "canary: PROMOTE" in capsys.readouterr().out
    # The deployment gate: a rollback verdict is a NON-ZERO exit.
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        for r in canary.synthetic_records("probe_regression"):
            f.write(json.dumps(r) + "\n")
    try:
        assert main(["canary", f.name, "--compact"]) == 1
        assert "canary: ROLLBACK" in capsys.readouterr().out
    finally:
        os.unlink(f.name)
    assert main(["canary", "/no/such/file.jsonl", "--compact"]) == 2


# -- acceptance: live 2-version fleet ----------------------------------------


@pytest.mark.slow
def test_canary_smoke_live_fleet_acceptance():
    """The round-23 acceptance on a live 2-version stub fleet: version
    ingestion via pings, deterministic session split, golden probes
    shed-exempt and excluded from user SLIs with bounded exported
    overhead, promote on the healthy leg, and the injected golden-probe
    regression flipping the verdict to rollback."""
    from serverless_learn_tpu.fleet.loadgen import run_canary_smoke

    rep = run_canary_smoke(seed=0)
    assert rep["ok"], rep["checks"]
    assert rep["healthy"]["verdict"]["decision"] == "promote"
    assert rep["regression"]["verdict"]["decision"] == "rollback"
    assert rep["bench_rows"] and \
        rep["bench_rows"][0]["canary_verdict"] == "promote"
