"""Chaos harness (round 11): FaultPlan DSL, the 100-node acceptance
scenario (kill 30% + 10s partition → bounded re-convergence, zero
training-progress loss, doctor names every incident), determinism, and
the soak CLI."""

import json
import math

import pytest

from serverless_learn_tpu.chaos.plan import FaultPlan
from serverless_learn_tpu.chaos.sim import ChaosSim
from serverless_learn_tpu.control.gossip import GossipConfig

ACCEPTANCE_PLAN = {"faults": [
    {"at": 3.0, "op": "kill", "frac": 0.3},
    {"at": 3.0, "op": "partition", "split": 0.5, "for": 10.0},
]}


# ---------------------------------------------------------------------------
# FaultPlan DSL
# ---------------------------------------------------------------------------


def test_plan_parses_and_sorts():
    plan = FaultPlan.from_json(json.dumps({"faults": [
        {"at": 5.0, "op": "heal"},
        {"at": 1.0, "op": "kill", "node": "node-3"},
        {"at": 2.0, "op": "partition", "groups": [["node-0"], ["node-1"]]},
        {"at": 2.5, "op": "pause", "count": 2, "for": 3.0},
        {"at": 3.0, "op": "drop", "rate": 0.5},
        {"at": 3.0, "op": "delay", "s": 0.02, "jitter": 0.01},
        {"at": 4.0, "op": "skew", "node": "node-1", "offset_s": 2.0},
    ]}))
    assert [f.at for f in plan.faults] == sorted(f.at for f in plan.faults)
    assert plan.end_time() == 5.5
    # bare-list form accepted too
    assert len(FaultPlan.from_obj(
        [{"at": 0, "op": "kill", "frac": 0.1}]).faults) == 1


@pytest.mark.parametrize("bad", [
    "not json",
    json.dumps({"faults": [{"at": 1.0, "op": "explode"}]}),
    json.dumps({"faults": [{"at": -1, "op": "heal"}]}),
    json.dumps({"faults": [{"at": 1, "op": "kill"}]}),          # no selector
    json.dumps({"faults": [{"at": 1, "op": "kill", "frac": 2}]}),
    json.dumps({"faults": [{"at": 1, "op": "kill", "node": "x",
                            "frac": 0.5}]}),                     # two selectors
    json.dumps({"faults": [{"at": 1, "op": "drop"}]}),           # no rate
    json.dumps({"faults": [{"at": 1, "op": "pause", "node": "x"}]}),  # no for
    json.dumps({"faults": [{"at": 1, "op": "kill", "node": "x",
                            "typo_key": 1}]}),
    json.dumps({"faults": [{"at": 1, "op": "partition",
                            "groups": [["a"]]}]}),               # 1 group
])
def test_plan_rejects_malformed(bad):
    with pytest.raises(ValueError):
        FaultPlan.from_json(bad)


# ---------------------------------------------------------------------------
# the acceptance scenario
# ---------------------------------------------------------------------------


def test_kill_30pct_plus_partition_converges_and_doctor_names_it(tmp_path):
    """ISSUE 6 acceptance: 100 nodes, 30% killed, the rest partitioned
    for 10 virtual seconds. Asserts (1) membership agreement restored
    within the O(log N) dissemination bound, (2) zero training-progress
    loss (monotone committed step, progress resumes post-heal), and
    (3) `slt doctor` names every killed node and the partition from the
    emitted telemetry alone."""
    from serverless_learn_tpu.telemetry import doctor

    events = str(tmp_path / "chaos-events.jsonl")
    sim = ChaosSim(100, seed=7, plan=FaultPlan.from_obj(ACCEPTANCE_PLAN),
                   events_log=events)
    rep = sim.run()
    assert rep["ok"], rep["violations"]
    assert len(rep["killed_live"]) == 30
    assert rep["converged"]
    assert rep["dissemination_periods"] is not None
    assert (rep["dissemination_periods"]
            <= rep["convergence_bound_periods"])
    # every killed node individually detected in O(log N) periods of its
    # death becoming observable (partition end for cross-side observers)
    for nid, periods in rep["detection_periods"].items():
        assert periods is not None, f"{nid} never detected"
    # training: monotone (asserted inside run) and it kept moving
    assert rep["training"]["committed_step"] > 0
    assert not any("backwards" in v for v in rep["violations"])

    # doctor, fed ONLY the telemetry log, names each incident
    d = doctor.diagnose([events], top=400)
    named_dead = {a.get("node") for a in d["alerts"]
                  if a.get("alert") == "gossip_member_dead"
                  and a.get("state") == "firing"}
    assert set(rep["killed_live"]) <= named_dead
    partition = [a for a in d["alerts"]
                 if a.get("alert") == "gossip_partition_suspected"]
    assert partition, "partition never surfaced as an alert"
    # and the partition alerts RESOLVED after the heal (no stuck pages)
    assert all(a["state"] == "resolved" for a in partition)


def test_same_seed_same_report():
    """Determinism: identical (plan, seed) ⇒ byte-identical reports
    (wall_time aside). This is what makes chaos failures debuggable."""
    def run():
        rep = ChaosSim(60, seed=13,
                       plan=FaultPlan.from_obj(ACCEPTANCE_PLAN)).run()
        rep.pop("wall_time_s")
        return rep

    assert run() == run()


def test_different_seed_different_faults():
    def faults(seed):
        sim = ChaosSim(60, seed=seed,
                       plan=FaultPlan.from_obj(ACCEPTANCE_PLAN))
        sim.run(duration_s=5.0)
        return json.dumps(sim.injected)

    assert faults(1) != faults(2)


def test_killed_node_detection_is_log_n_at_scale():
    """ISSUE 6 acceptance: a killed node in a 120-node cluster is
    detected (suspected → declared dead cluster-wide) in O(log N)
    protocol periods — no partition in the way."""
    plan = FaultPlan.from_obj([{"at": 4.0, "op": "kill", "count": 1}])
    sim = ChaosSim(120, seed=3, plan=plan)
    rep = sim.run()
    assert rep["ok"], rep["violations"]
    (periods,) = rep["detection_periods"].values()
    cfg = sim.cfg
    log_n = math.ceil(math.log2(120 + 1))
    assert periods <= 4 + (cfg.suspicion_mult + 3) * log_n, periods


def test_straggler_pause_refutes_no_flap():
    """A paused (straggling) process gets suspected but — resuming before
    the suspicion times out everywhere — refutes and is never declared
    dead: total membership churn (epoch delta) stays zero."""
    sim = ChaosSim(20, seed=5, plan=FaultPlan.from_obj(
        [{"at": 6.0, "op": "pause", "node": "node-7", "for": 1.2}]))
    # capture epochs after bootstrap converges, before the pause
    epochs_at = {}
    orig_apply = sim._apply_fault

    def capture_then_apply(f):
        if not epochs_at:
            epochs_at.update({nid: h.node.epoch
                              for nid, h in sim.hosts.items()})
        orig_apply(f)

    sim._apply_fault = capture_then_apply
    rep = sim.run(duration_s=25.0)
    assert rep["ok"], rep["violations"]
    assert rep["killed_live"] == []
    for nid, h in sim.hosts.items():
        members = h.node.members()
        if "node-7" in members:
            assert members["node-7"].state != "dead", nid
        # zero membership churn: suspicion + refutation bumps no epochs
        assert h.node.epoch == epochs_at[nid], nid


def test_quorum_loss_safe_pauses_training():
    """Partition the leader into a minority: the training model must
    SKIP rounds (safe-pause policy) rather than commit minority progress,
    then resume after the heal."""
    sim = ChaosSim(12, seed=2, plan=FaultPlan.from_obj([
        {"at": 5.0, "op": "partition",
         "groups": [["node-0", "node-1"],
                    ["node-%d" % i for i in range(2, 12)]],
         "for": 8.0}]))
    rep = sim.run()
    assert rep["training"]["safe_paused_rounds"] >= 1
    assert rep["ok"], rep["violations"]


# ---------------------------------------------------------------------------
# FaultPlan edge cases the herd leans on (round 19)
# ---------------------------------------------------------------------------


def test_random_soak_same_seed_identical_plans():
    """random_soak is the herd/soak schedule generator — two same-seed
    RNGs must yield byte-identical plans, different seeds must not."""
    import random

    def plan(seed):
        return FaultPlan.random_soak(30, 60.0, random.Random(f"s-{seed}"))

    assert plan(9) == plan(9)
    assert plan(9).faults  # non-trivial schedule
    assert plan(9) != plan(10)
    # and the generated plan re-validates through the strict parser
    as_json = json.dumps({"faults": [
        {k: v for k, v in {
            "at": f.at, "op": f.op, "node": f.node, "frac": f.frac,
            "count": f.count, "for": f.duration, "split": f.split,
            "rate": f.rate}.items() if v is not None}
        for f in plan(9).faults]})
    assert FaultPlan.from_json(as_json).faults


def test_pause_window_on_node_that_dies_mid_window():
    """'for'-windowed pause on a node that is KILLED inside the window,
    then restarted: the restart must clear the stale pause (a zombie
    paused_until would silently mute the reborn node), and the whole
    scenario stays deterministic."""
    plan = FaultPlan.from_obj([
        {"at": 3.0, "op": "pause", "node": "node-4", "for": 6.0},
        {"at": 5.0, "op": "kill", "node": "node-4"},
        {"at": 12.0, "op": "restart", "node": "node-4"}])

    def run():
        rep = ChaosSim(12, seed=8, plan=plan).run()
        rep.pop("wall_time_s")
        return rep

    rep = run()
    assert rep["ok"], rep["violations"]
    assert rep["killed_live"] == []  # restarted => alive at the end
    assert rep == run()  # deterministic through the pause+kill overlap
    sim = ChaosSim(12, seed=8, plan=plan)
    sim.run()
    assert sim.hosts["node-4"].paused_until < 0  # restart cleared it


def test_delay_for_schedules_auto_inverse():
    """plan.py documents 'for' auto-inverse for every windowed op; delay
    was the one op that never scheduled its inverse, quietly lagging
    links forever. Regression: after the window, the extra delay and
    jitter are gone and the inverse shows up in the injection record."""
    plan = FaultPlan.from_obj([
        {"at": 2.0, "op": "delay", "s": 0.05, "jitter": 0.02,
         "for": 4.0}])
    sim = ChaosSim(10, seed=1, plan=plan)
    rep = sim.run(duration_s=20.0)
    assert rep["ok"], rep["violations"]
    assert sim._extra_delay == 0.0
    assert sim._extra_jitter == 0.0
    delays = [f for f in sim.injected if f["op"] == "delay"]
    assert len(delays) == 2  # the fault and its auto-inverse
    assert delays[1]["t_virtual_s"] == pytest.approx(6.0)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_chaos_cli_run_and_soak(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps({"faults": [
        {"at": 2.0, "op": "kill", "count": 2}]}))
    rc = main(["chaos", "run", "--plan", str(plan_file), "--nodes", "20",
               "--seed", "1", "--compact"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"]
    assert out["detection_periods"]["n"] == 2

    rc = main(["chaos", "soak", "--nodes", "20", "--duration", "40",
               "--seed", "2", "--compact"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"]

    rc = main(["chaos", "run", "--plan", "/nonexistent.json"])
    assert rc == 2


def test_chaos_cli_rejects_bad_plan(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    plan_file = tmp_path / "bad.json"
    plan_file.write_text(json.dumps({"faults": [{"at": 1, "op": "nope"}]}))
    rc = main(["chaos", "run", "--plan", str(plan_file)])
    assert rc == 2
    assert "bad fault plan" in capsys.readouterr().err
