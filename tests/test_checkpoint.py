"""Checkpoint/resume: exact-resume equivalence (train 6 = train 3 + resume 3),
sharded restore, shard-server round-trips, latest/GC behavior."""

import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.checkpoint import (
    Checkpointer, LocalStore, ShardServerStore)
from serverless_learn_tpu.training.train_step import build_trainer


def _cfg(mesh=None, model="mlp_mnist"):
    return ExperimentConfig(
        model=model,
        mesh=mesh or MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3),
        train=TrainConfig(batch_size=16),
        data=DataConfig(),
        model_overrides={"dtype": jnp.float32},
    )


def _steps(trainer, state, src_iter, n):
    losses = []
    # range first: zip(iter, range) would pull one extra batch from the
    # shared iterator when range exhausts, desyncing resume replay.
    for _, batch in zip(range(n), src_iter):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(m["loss"]))
    return state, losses


def test_resume_is_exact(tmp_path, devices):
    cfg = _cfg()
    trainer = build_trainer(cfg)
    ckpt = Checkpointer(LocalStore(str(tmp_path)), async_save=False)

    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=5)
    it = iter(src)
    state = trainer.init()
    state, l_first3 = _steps(trainer, state, it, 3)
    ckpt.save(state)

    # continue 3 more steps — the "uninterrupted" run
    state_cont, l_cont = _steps(trainer, state, it, 3)

    # now simulate a crash: rebuild everything, restore, replay same batches
    trainer2 = build_trainer(cfg)
    template = trainer2.init()
    restored = ckpt.restore(template, shardings=trainer2.state_shardings)
    assert int(jax.device_get(restored.step)) == 3
    src2 = SyntheticSource(trainer2.bundle.make_batch, cfg.data, 16, seed=5)
    it2 = iter(src2)
    for _ in range(3):  # skip the batches consumed before the checkpoint
        next(it2)
    _, l_resumed = _steps(trainer2, restored, it2, 3)
    np.testing.assert_allclose(l_cont, l_resumed, rtol=1e-6)


def test_restore_lands_sharded(tmp_path, devices):
    cfg = _cfg(mesh=MeshConfig(dp=2, fsdp=4))
    trainer = build_trainer(cfg)
    state = trainer.init()
    ckpt = Checkpointer(LocalStore(str(tmp_path)), async_save=False)
    ckpt.save(state)
    restored = ckpt.restore(trainer.init(), shardings=trainer.state_shardings)
    leaf = restored.params["dense_0"]["kernel"]
    assert len(leaf.sharding.device_set) == 8
    shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
    assert shard_rows == {leaf.shape[0] // 4}, "fsdp=4 must shard dim 0"


def test_latest_and_gc(tmp_path, devices):
    cfg = _cfg()
    trainer = build_trainer(cfg)
    state = trainer.init()
    ckpt = Checkpointer(LocalStore(str(tmp_path)), keep=2, async_save=False)
    assert ckpt.latest_step() is None
    for s in (1, 2, 3, 4):
        ckpt.save(state, step=s)
    assert ckpt.latest_step() == 4
    assert ckpt._steps() == [3, 4], "keep=2 must GC older steps"


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_checkpoint_via_shard_server(tmp_path, devices):
    from serverless_learn_tpu.control.daemons import start_shard_server

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    try:
        cfg = _cfg()
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=0)
        state, _ = _steps(trainer, state, iter(src), 2)

        store = ShardServerStore(f"127.0.0.1:{port}")
        ckpt = Checkpointer(store, name="run1", async_save=True)
        ckpt.save(state)
        ckpt.wait()
        assert ckpt.latest_step() == 2

        restored = ckpt.restore(trainer.init(),
                                shardings=trainer.state_shardings)
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state)),
                        jax.tree_util.tree_leaves(jax.device_get(restored))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # GC works against the shard server too (delete RPC)
        ckpt2 = Checkpointer(store, name="run1", keep=1, async_save=False)
        for s in (3, 4, 5):
            ckpt2.save(state, step=s)
        assert ckpt2._steps() == [5]
    finally:
        proc.terminate()
        proc.wait(timeout=5)
