"""Sharded checkpointing: per-process chunk blobs + manifest, restore-time
resharding (VERDICT round 1 item 2).

The blob path gathers the whole TrainState through one host — fine for MNIST,
impossible for the Llama-8B rung (~100 GB through one TCP PUT) and wrong on a
real multi-host mesh where non-addressable shards can't be device_get at all.
These tests pin the sharded layout's contract: save under one mesh, restore
bit-exact under a different one, fetching only the byte ranges the target
shards need."""

import json
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.training.checkpoint import (
    Checkpointer, LocalStore, ShardServerStore)
from serverless_learn_tpu.training.train_step import build_trainer


def _cfg(mesh, **overrides):
    model_overrides = {"dtype": jnp.float32}
    model_overrides.update(overrides.pop("model_overrides", {}))
    return ExperimentConfig(
        model="mlp_mnist",
        mesh=mesh,
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3),
        train=TrainConfig(batch_size=16),
        data=DataConfig(),
        model_overrides=model_overrides,
        **overrides)


def _assert_state_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                    jax.tree_util.tree_leaves(jax.device_get(b))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class CountingStore(LocalStore):
    """LocalStore that records fetch traffic, to pin the ranged-read claim."""

    def __init__(self, root):
        super().__init__(root)
        self.full_gets = []
        self.range_bytes = 0

    def get(self, key):
        self.full_gets.append(key)
        return super().get(key)

    def get_range(self, key, offset, length):
        self.range_bytes += length
        return super().get_range(key, offset, length)


def test_save_dp_restore_fsdp_tp_bit_exact(tmp_path, devices):
    trainer = build_trainer(_cfg(MeshConfig(dp=8)))
    state = trainer.init()
    ckpt = Checkpointer(LocalStore(str(tmp_path)), sharded=True,
                        async_save=False)
    ckpt.save(state)
    assert ckpt._is_sharded(0)

    t2 = build_trainer(_cfg(MeshConfig(fsdp=4, tp=2)))
    restored = ckpt.restore(t2.abstract_state(), shardings=t2.state_shardings)
    _assert_state_equal(state, restored)
    # and it actually landed in the new layout
    leaf = restored.params["dense_0"]["kernel"]
    assert {s.data.shape[0] for s in leaf.addressable_shards} == \
        {leaf.shape[0] // 4}


def test_save_sharded_restore_onto_same_mesh(tmp_path, devices):
    trainer = build_trainer(_cfg(MeshConfig(dp=2, fsdp=4)))
    state = trainer.init()
    ckpt = Checkpointer(LocalStore(str(tmp_path)), sharded=True,
                        async_save=False)
    ckpt.save(state)
    restored = ckpt.restore(trainer.abstract_state(),
                            shardings=trainer.state_shardings)
    _assert_state_equal(state, restored)


def test_restore_fetches_ranges_not_blobs(tmp_path, devices):
    """The resharded restore must ranged-fetch chunk data, never pull whole
    .dat blobs, and move roughly one state's worth of bytes (the per-leaf
    chunk cache dedupes the replicated-leaf callbacks)."""
    trainer = build_trainer(_cfg(MeshConfig(dp=8)))
    state = trainer.init()
    store = CountingStore(str(tmp_path))
    ckpt = Checkpointer(store, sharded=True, async_save=False)
    ckpt.save(state)

    state_bytes = sum(np.asarray(x).nbytes for x in
                      jax.tree_util.tree_leaves(jax.device_get(state)))
    store.full_gets.clear()
    store.range_bytes = 0
    t2 = build_trainer(_cfg(MeshConfig(fsdp=4, tp=2)))
    ckpt.restore(t2.abstract_state(), shardings=t2.state_shardings)
    assert not any(k.endswith(".dat") for k in store.full_gets), \
        f"whole-blob fetches during resharded restore: {store.full_gets}"
    assert store.range_bytes <= 1.05 * state_bytes + 4096


def test_bf16_leaves_roundtrip(tmp_path, devices):
    trainer = build_trainer(_cfg(
        MeshConfig(dp=8), model_overrides={"dtype": jnp.bfloat16,
                                           "param_dtype": jnp.bfloat16}))
    state = trainer.init()
    ckpt = Checkpointer(LocalStore(str(tmp_path)), sharded=True,
                        async_save=False)
    ckpt.save(state)
    restored = ckpt.restore(trainer.abstract_state(),
                            shardings=trainer.state_shardings)
    _assert_state_equal(state, restored)
    kinds = {str(np.asarray(x).dtype) for x in
             jax.tree_util.tree_leaves(jax.device_get(restored.params))}
    assert "bfloat16" in kinds


def test_latest_gc_and_layout_autodetect(tmp_path, devices):
    """Blob and sharded steps coexist under one name; LATEST/GC/restore see
    both, and restore dispatches per-step on the COMMIT marker."""
    trainer = build_trainer(_cfg(MeshConfig(dp=8)))
    state = trainer.init()
    store = LocalStore(str(tmp_path))
    blob = Checkpointer(store, keep=10, async_save=False)
    shard = Checkpointer(store, keep=10, async_save=False, sharded=True)
    blob.save(state, step=1)
    shard.save(state, step=2)
    assert blob._steps() == [1, 2]
    assert shard.latest_step() == 2
    assert not shard._is_sharded(1) and shard._is_sharded(2)
    for s in (1, 2):
        restored = shard.restore(trainer.abstract_state(), step=s,
                                 shardings=trainer.state_shardings)
        _assert_state_equal(state, restored)

    gc = Checkpointer(store, keep=1, async_save=False, sharded=True)
    gc.save(state, step=3)
    assert gc._steps() == [3], "GC must remove blob AND sharded dirs"
    assert not store.list(f"{gc.name}/step-0000000002"), \
        "sharded step dir must be fully deleted"


def test_uncommitted_step_is_invisible(tmp_path, devices):
    """A crash between PUTs and COMMIT must leave no restorable step."""
    trainer = build_trainer(_cfg(MeshConfig(dp=8)))
    state = trainer.init()
    store = LocalStore(str(tmp_path))
    ckpt = Checkpointer(store, async_save=False, sharded=True)
    ckpt.save(state, step=5)
    store.delete(f"{ckpt.name}/step-{5:010d}/COMMIT")
    store.delete(f"{ckpt.name}/LATEST")
    assert ckpt.latest_step() is None


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_sharded_checkpoint_via_shard_server(tmp_path, devices):
    """The native data plane serves sharded checkpoints: ranged fetches ride
    the same offset/length chunk protocol as training shards."""
    from serverless_learn_tpu.control.daemons import start_shard_server

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    try:
        trainer = build_trainer(_cfg(MeshConfig(dp=2, fsdp=4)))
        state = trainer.init()
        store = ShardServerStore(f"127.0.0.1:{port}")
        ckpt = Checkpointer(store, name="sharded", async_save=False,
                            sharded=True)
        ckpt.save(state)
        assert ckpt.latest_step() == 0

        t2 = build_trainer(_cfg(MeshConfig(dp=8)))
        restored = ckpt.restore(t2.abstract_state(),
                                shardings=t2.state_shardings)
        _assert_state_equal(state, restored)
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_manifest_records_paths_and_shapes(tmp_path, devices):
    trainer = build_trainer(_cfg(MeshConfig(dp=8)))
    state = trainer.init()
    store = LocalStore(str(tmp_path))
    ckpt = Checkpointer(store, async_save=False, sharded=True)
    ckpt.save(state)
    meta = json.loads(store.get(f"{ckpt.name}/step-{0:010d}/META"))
    assert meta["n_procs"] == 1
    paths = [l["path"] for l in meta["leaves"]]
    assert any("dense_0" in p and "kernel" in p for p in paths)
    kernel = next(l for l in meta["leaves"]
                  if "dense_0" in l["path"] and "kernel" in l["path"]
                  and "params" in l["path"])
    leaf = state.params["dense_0"]["kernel"]
    assert tuple(kernel["shape"]) == leaf.shape
    assert kernel["dtype"] == str(np.dtype(leaf.dtype))


# -- ZeRO update sharding x checkpoints (round 18) ----------------------------


def _zero_cfg(mesh, stage):
    return _cfg(mesh).override(
        train=TrainConfig(batch_size=16, zero_stage=stage))


def test_pre_zero_checkpoint_restores_into_zero_layout(tmp_path, devices):
    """Compatibility forward: a replicated (pre-ZeRO) checkpoint restores
    bit-exact into dp-sharded optimizer state — and the restored state
    then steps IDENTICALLY to the replicated baseline (the moments are
    the same numbers, merely resident as 1/dp slices)."""
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.zero import bytes_per_chip

    t_pre = build_trainer(_zero_cfg(MeshConfig(dp=8), 0))
    state = t_pre.init()
    src = SyntheticSource(t_pre.bundle.make_batch, DataConfig(), 16, seed=5)
    batch = next(iter(src))
    state, _ = t_pre.step(state, t_pre.shard_batch(batch))
    ckpt = Checkpointer(LocalStore(str(tmp_path)), sharded=True,
                        async_save=False)
    ckpt.save(state)

    t_zero = build_trainer(_zero_cfg(MeshConfig(dp=8), 1))
    restored = ckpt.restore(t_zero.abstract_state(),
                            shardings=t_zero.state_shardings)
    _assert_state_equal(state, restored)
    assert bytes_per_chip(restored.opt_state) < \
        0.2 * bytes_per_chip(state.opt_state)
    # The restored sharded state continues training exactly as the
    # replicated one would have.
    next_ref, _ = t_pre.step(state, t_pre.shard_batch(batch))
    next_zero, _ = t_zero.step(restored, t_zero.shard_batch(batch))
    _assert_state_equal(next_ref.params, next_zero.params)


def test_zero_checkpoint_repartitions_on_dp_change(tmp_path, devices):
    """Compatibility backward + remesh: a ZeRO checkpoint saved at dp=8
    restores bit-exact onto a dp=2 x fsdp=4 world (the new dp
    composition re-partitions the slices) AND back onto a replicated
    zero_stage=0 trainer — the on-store layout is layout-agnostic."""
    t8 = build_trainer(_zero_cfg(MeshConfig(dp=8), 1))
    state = t8.init()
    ckpt = Checkpointer(LocalStore(str(tmp_path)), sharded=True,
                        async_save=False)
    ckpt.save(state)

    t24 = build_trainer(_zero_cfg(MeshConfig(dp=2, fsdp=4), 1))
    r = ckpt.restore(t24.abstract_state(), shardings=t24.state_shardings)
    _assert_state_equal(state, r)
    # A dp-sharded moment leaf physically re-partitioned to 1/2 slices
    # on the new mesh's dp axis.
    lead = [l for l in jax.tree_util.tree_leaves(r.opt_state)
            if getattr(l, "ndim", 0) == 2 and l.shape[0] % 8 == 0][0]
    assert {s.data.shape[0] for s in lead.addressable_shards} == \
        {lead.shape[0] // 8}, "dp=2 x fsdp=4 composition keeps 1/8 slices"

    t_rep = build_trainer(_zero_cfg(MeshConfig(dp=8), 0))
    back = ckpt.restore(t_rep.abstract_state(),
                        shardings=t_rep.state_shardings)
    _assert_state_equal(state, back)
