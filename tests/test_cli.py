"""CLI layer (L4) — successor of the reference's `./master`, `./worker ADDR`,
`./file_server` shell surface (reference src/Makefile:26-35), where the only
CLI argument in the whole system was the worker's address and every interval
change required recompiling (src/serverless_learn.h:5-12)."""

import json
import os
import socket

import pytest

from serverless_learn_tpu.cli import _config_from_args, build_parser, main


def _parse(argv):
    return build_parser().parse_args(argv)


def test_models_lists_registry(capsys):
    assert main(["models"]) == 0
    out = capsys.readouterr().out.split()
    assert "mlp_mnist" in out
    assert "resnet18_cifar" in out
    assert "llama_tiny" in out


def test_config_from_flags():
    args = _parse(["train", "--model", "llama_tiny", "--mesh", "dp=4,tp=2",
                   "--batch-size", "32", "--steps", "7", "--lr", "0.01",
                   "--optimizer", "sgd", "--seq-len", "64"])
    cfg = _config_from_args(args)
    assert cfg.model == "llama_tiny"
    assert (cfg.mesh.dp, cfg.mesh.tp) == (4, 2)
    assert cfg.train.batch_size == 32
    assert cfg.train.num_steps == 7
    assert cfg.optimizer.name == "sgd"
    assert cfg.optimizer.learning_rate == 0.01
    assert cfg.data.seq_len == 64


def test_config_file_set_and_flag_precedence(tmp_path):
    f = tmp_path / "cfg.json"
    f.write_text(json.dumps({
        "model": "mlp_mnist",
        "mesh": {"dp": 8},
        "train": {"batch_size": 64, "num_steps": 5},
    }))
    # --set overrides the file; dedicated flags override --set.
    args = _parse(["train", "--config", str(f),
                   "--set", "train.num_steps=9",
                   "--set", "train.seed=3",
                   "--batch-size", "16"])
    cfg = _config_from_args(args)
    assert cfg.train.num_steps == 9
    assert cfg.train.seed == 3
    assert cfg.train.batch_size == 16
    assert cfg.mesh.dp == 8


def test_default_mesh_uses_all_devices():
    import jax

    cfg = _config_from_args(_parse(["train", "--model", "mlp_mnist"]))
    assert cfg.mesh.size == len(jax.devices())


def test_bad_set_syntax():
    with pytest.raises(SystemExit):
        _config_from_args(_parse(["train", "--set", "nonsense"]))


def test_train_end_to_end(capsys, tmp_path):
    from serverless_learn_tpu.utils.tracing import get_tracer

    get_tracer().reset()  # the span registry is process-global
    rc = main(["train", "--model", "mlp_mnist", "--mesh", "dp=8",
               "--batch-size", "16", "--steps", "3",
               "--checkpoint-dir", str(tmp_path / "ck"),
               "--checkpoint-every", "2"])
    assert rc == 0
    done = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert done["event"] == "done"
    assert done["final_step"] == 3
    assert done["spans"]["train/step"]["count"] == 3
    # final checkpoint written
    ck_files = [p for _, _, fs in os.walk(tmp_path / "ck") for p in fs]
    assert any("step-" in p for p in ck_files)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_publish_stats_and_train_from_shard_server(capsys, tmp_path):
    from serverless_learn_tpu.control.daemons import start_shard_server

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path))
    addr = f"127.0.0.1:{port}"
    try:
        rc = main(["publish", "--shard-server", addr, "--dataset", "cli_ds",
                   "--model", "mlp_mnist", "--num-records", "128",
                   "--records-per-shard", "64"])
        assert rc == 0
        pub = json.loads(capsys.readouterr().out.strip())
        assert pub["num_shards"] == 2

        rc = main(["train", "--model", "mlp_mnist", "--mesh", "dp=8",
                   "--batch-size", "16", "--steps", "3",
                   "--dataset", "cli_ds", "--shard-server", addr])
        assert rc == 0
        done = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert done["final_step"] == 3

        rc = main(["stats", "--addr", addr, "--kind", "shard-server"])
        assert rc == 0
        stats = json.loads(capsys.readouterr().out)
        assert stats["bytes_served"] > 0
        assert stats["rpc"]["rpc/fetch"]["count"] >= 1
    finally:
        proc.terminate()
        proc.wait(timeout=5)
