"""Control-plane client hardening (round 11): backoff with full jitter,
per-RPC deadlines with reconnect-on-timeout (the poisoned-socket fix),
the per-peer circuit breaker, lease expiry under asymmetric partition,
and elastic's remesh-debounce hysteresis."""

import random
import threading
import time

import numpy as np
import pytest

from serverless_learn_tpu.chaos.shim import TcpChaosProxy
from serverless_learn_tpu.control.client import (
    MSG_MEMBERSHIP_REQ, MSG_STATS_REQ, CircuitBreaker, Transport,
    full_jitter_backoff)
from serverless_learn_tpu.control.py_daemons import (PyCoordinator,
                                                     PyShardServer)


def _counter_value(name):
    from serverless_learn_tpu.telemetry import get_registry

    fam = get_registry().snapshot().get(name)
    if not fam:
        return 0.0
    return sum(s.get("value", 0.0) for s in fam.get("series", []))


# ---------------------------------------------------------------------------
# backoff + breaker units
# ---------------------------------------------------------------------------


def test_full_jitter_backoff_bounds():
    rng = random.Random(42)
    seen = set()
    for attempt in range(6):
        for _ in range(50):
            s = full_jitter_backoff(attempt, rng, base_s=0.05, cap_s=2.0)
            assert 0.0 <= s <= min(2.0, 0.05 * 2 ** attempt)
            seen.add(round(s, 6))
    assert len(seen) > 100  # actually jittered, not a fixed ladder


def test_circuit_breaker_state_machine():
    b = CircuitBreaker("unit-test-peer-1", fail_threshold=3, open_s=0.15)
    assert b.allow() and b.state == CircuitBreaker.CLOSED
    b.record_failure()
    b.record_failure()
    assert b.allow()  # under threshold
    b.record_failure()
    assert b.state == CircuitBreaker.OPEN
    assert not b.allow()
    time.sleep(0.2)
    assert b.allow()          # half-open probe
    assert not b.allow()      # only ONE probe
    b.record_failure()        # probe failed -> straight back to open
    assert b.state == CircuitBreaker.OPEN
    time.sleep(0.2)
    assert b.allow()
    b.record_success()        # probe succeeded -> closed
    assert b.state == CircuitBreaker.CLOSED
    assert b.allow() and b.allow()


def test_breaker_trips_and_fails_fast(tmp_path):
    """After fail_threshold consecutive transport failures the breaker
    opens: further calls raise 'circuit open' WITHOUT touching the
    network, until the open window lapses (half-open probe heals it)."""
    srv = PyShardServer(port=0, root=str(tmp_path / "b"))
    srv.start()
    proxy = TcpChaosProxy(upstream=srv.addr).start()
    try:
        breaker = CircuitBreaker(proxy.addr, fail_threshold=2, open_s=0.5)
        t = Transport(proxy.addr, prefer_native=False, rpc_timeout_s=0.3,
                      max_attempts=1, breaker=breaker)
        t.call(MSG_STATS_REQ, b"")
        opens_before = _counter_value("slt_rpc_breaker_opens_total")
        proxy.set_fault("blackhole")
        for _ in range(2):
            with pytest.raises(OSError):
                t.call(MSG_STATS_REQ, b"")
        assert breaker.state == CircuitBreaker.OPEN
        assert _counter_value("slt_rpc_breaker_opens_total") > opens_before
        proxy.set_fault(None)  # upstream healthy again, but breaker open
        conns_before = proxy.stats["connections"]
        with pytest.raises(ConnectionError, match="circuit open"):
            t.call(MSG_STATS_REQ, b"")
        assert proxy.stats["connections"] == conns_before  # failed FAST
        time.sleep(0.6)
        t.call(MSG_STATS_REQ, b"")  # half-open probe succeeds -> closed
        assert breaker.state == CircuitBreaker.CLOSED
        t.close()
    finally:
        proxy.stop()
        srv.stop()


def test_breaker_metrics_in_scrape():
    CircuitBreaker("scrape-peer", fail_threshold=1, open_s=9).record_failure()
    from serverless_learn_tpu.telemetry import get_registry

    snap = get_registry().snapshot()
    fam = snap["slt_rpc_breaker_state"]
    series = {dict(s["labels"]).get("peer"): s["value"]
              for s in fam["series"]}
    assert series.get("scrape-peer") == CircuitBreaker.OPEN


# ---------------------------------------------------------------------------
# reconnect-on-timeout: the poisoned-socket regression (satellite 2)
# ---------------------------------------------------------------------------


@pytest.fixture()
def shard_server(tmp_path):
    srv = PyShardServer(port=0, root=str(tmp_path / "blobs"))
    srv.start()
    yield srv
    srv.stop()


def test_fetch_timeout_midstream_reconnects(shard_server):
    """An RPC that times out mid-stream must not leave the transport in an
    undefined state: the next call on the SAME Transport re-dials instead
    of parsing the stalled stream's leftovers."""
    proxy = TcpChaosProxy(upstream=shard_server.addr,
                          delay_s=0.005).start()
    blob = bytes(range(256)) * (1024 * 8)  # 2 MiB -> 2 chunk frames
    # publish via a direct connection; the hardened client under test
    # talks through the proxy
    direct = Transport(shard_server.addr, prefer_native=False)
    direct.put("chaos/a", blob)
    direct.close()
    try:
        t = Transport(proxy.addr, prefer_native=False, rpc_timeout_s=1.0,
                      max_attempts=1)
        dst = np.empty(len(blob), np.uint8)
        assert t.fetch_into("chaos/a", dst, 0, len(blob)) == len(blob)
        sock_before = t._sock
        timeouts_before = _counter_value("slt_rpc_timeouts_total")

        # stall the stream once the NEXT fetch is mid-flight
        fetch_err = []
        baseline = proxy.stats["bytes_down"]

        def fetch():
            try:
                t.fetch_into("chaos/a", np.empty(len(blob), np.uint8),
                             0, len(blob))
            except IOError as e:
                fetch_err.append(e)

        th = threading.Thread(target=fetch)
        th.start()
        deadline = time.time() + 5
        while (proxy.stats["bytes_down"] < baseline + 128 * 1024
               and time.time() < deadline):
            time.sleep(0.002)
        proxy.set_fault("stall")
        th.join(timeout=10)
        assert fetch_err, "stalled fetch did not surface an error"
        assert "mid-stream" in str(fetch_err[0])
        assert _counter_value("slt_rpc_timeouts_total") > timeouts_before
        # the poisoned socket is GONE; healing the proxy lets the same
        # transport re-dial and complete a clean exchange
        assert t._sock is None
        proxy.set_fault(None)
        dst2 = np.empty(len(blob), np.uint8)
        assert t.fetch_into("chaos/a", dst2, 0, len(blob)) == len(blob)
        assert bytes(dst2) == blob
        assert t._sock is not sock_before
        t.close()
    finally:
        proxy.stop()


def test_unary_timeout_poisons_then_recovers(shard_server):
    proxy = TcpChaosProxy(upstream=shard_server.addr).start()
    try:
        t = Transport(proxy.addr, prefer_native=False, rpc_timeout_s=0.4,
                      max_attempts=1)
        t.call(MSG_STATS_REQ, b"")  # healthy round trip
        proxy.set_fault("blackhole", direction="down")  # replies vanish
        with pytest.raises(OSError):
            t.call(MSG_STATS_REQ, b"")
        assert t._sock is None  # poisoned, not reused
        proxy.set_fault(None)
        t.call(MSG_STATS_REQ, b"")  # re-dialed transparently
        t.close()
    finally:
        proxy.stop()


def test_idempotent_retry_rides_through_reset(shard_server):
    """A connection reset between calls is retried (with backoff) for
    idempotent RPCs — and the retry counter shows it."""
    proxy = TcpChaosProxy(upstream=shard_server.addr).start()
    try:
        t = Transport(proxy.addr, prefer_native=False, rpc_timeout_s=2.0,
                      max_attempts=3)
        t.call(MSG_STATS_REQ, b"")
        retries_before = _counter_value("slt_rpc_retries_total")
        proxy.set_fault("reset")   # kills the live conns
        proxy.set_fault(None)      # but new dials go through
        mtype, _ = t.call(MSG_STATS_REQ, b"")
        assert mtype  # got a real reply on the re-dialed connection
        assert _counter_value("slt_rpc_retries_total") > retries_before
        t.close()
    finally:
        proxy.stop()


# ---------------------------------------------------------------------------
# lease expiry under asymmetric partition + remesh hysteresis (satellite 3)
# ---------------------------------------------------------------------------


def test_lease_expiry_asymmetric_partition_alert_and_no_flap(tmp_path):
    """Worker A talks to the coordinator through a proxy that gets
    blackholed (A cannot reach the master; B can). Asserts the failure
    chain: heartbeat failures → lease expiry + re-register under the same
    name → the health engine fires the lease_expiry alert — while B's
    debounced elastic-style epoch consumer coalesces the evict+rejoin
    epoch pair into at most one remesh decision (no flapping)."""
    from serverless_learn_tpu.config import HealthConfig
    from serverless_learn_tpu.control.client import WorkerAgent
    from serverless_learn_tpu.telemetry import get_registry
    from serverless_learn_tpu.telemetry.health import HealthEngine

    coord = PyCoordinator(port=0, lease_ttl_ms=700, sweep_ms=100)
    coord.start()
    proxy = TcpChaosProxy(upstream=coord.addr).start()
    engine = HealthEngine(registry=get_registry(),
                          config=HealthConfig(sample_interval_s=3600),
                          dump_on_critical=False)
    b_epoch_changes = []
    # must cover the evict -> re-register window: eviction lands one lease
    # TTL into the outage; the rejoin lands after the blackholed
    # heartbeat's in-transport retries finish (~2 deadlines + backoff)
    debounce_s = 2.5
    remeshes = []
    last_change = [0.0]
    t_fault = [0.0]

    def b_on_epoch(epoch, peers):
        b_epoch_changes.append((time.time(), epoch, len(peers)))
        last_change[0] = time.time()

    a = b = None
    try:
        a = WorkerAgent(proxy.addr, "local:a", name="wa",
                        heartbeat_interval_ms=150).start()
        b = WorkerAgent(coord.addr, "local:b", name="wb",
                        heartbeat_interval_ms=150,
                        on_epoch_change=b_on_epoch).start()
        time.sleep(0.5)
        # baseline sample AFTER the counters exist: the incident detector
        # fires on increments between samples
        engine.sample_once()
        exp_before = _counter_value("slt_lease_expiries_total")
        timeouts_before = _counter_value("slt_rpc_timeouts_total")

        # asymmetric partition: A <-> master only
        t_fault[0] = time.time()
        proxy.set_fault("blackhole")
        time.sleep(1.6)  # > lease TTL: master evicts A, A keeps trying
        proxy.set_fault(None)

        deadline = time.time() + 8
        while (_counter_value("slt_lease_expiries_total") <= exp_before
               and time.time() < deadline):
            time.sleep(0.05)
        assert _counter_value("slt_lease_expiries_total") > exp_before
        # the blackholed heartbeats hit their deadline and were retried
        # INSIDE the transport (the agent never even saw an error)
        assert _counter_value("slt_rpc_timeouts_total") > timeouts_before

        # the health engine names the incident
        engine.sample_once()
        firing = {al["alert"] for al in engine.alerts(firing_only=True)}
        assert "event.lease_expiry" in firing, firing

        # B saw (at least) two epoch bumps close together: the eviction
        # and A's re-registration. A debounced consumer collapses them.
        time.sleep(debounce_s + 0.3)
        changes = [t for t, _, _ in b_epoch_changes if t >= t_fault[0]]
        assert len(changes) >= 2, b_epoch_changes
        # walk the change stream the way _remesh_due does: a remesh only
        # fires when debounce_s elapses with no further change
        fired = 0
        i = 0
        while i < len(changes):
            j = i
            while j + 1 < len(changes) and \
                    changes[j + 1] - changes[j] < debounce_s:
                j += 1
            fired += 1
            i = j + 1
        remeshes.append(fired)
        assert fired <= 1, (fired, b_epoch_changes)
        # and the settled membership equals the pre-partition one: both
        # workers live — the correct number of remeshes is ZERO (a real
        # _remesh_due also compares the settled world and skips).
        _, peers = b.snapshot()
        assert sorted(p.name for p in peers) == ["wa", "wb"]
    finally:
        for agent in (a, b):
            if agent is not None:
                agent.stop(deregister=False)
        engine.stop()
        proxy.stop()
        coord.stop()


def test_elastic_remesh_debounce_skips_bounce(tmp_path):
    """The real ElasticTrainer._remesh_due: an epoch flap whose settled
    view equals the formed world clears the pending remesh without
    triggering drain→save→remesh."""
    from serverless_learn_tpu.config import ExperimentConfig
    from serverless_learn_tpu.training.checkpoint import LocalStore
    from serverless_learn_tpu.training.elastic import (ElasticTrainer,
                                                       EpochTransition)

    coord = PyCoordinator(port=0, lease_ttl_ms=5000, sweep_ms=200)
    coord.start()
    cfg = ExperimentConfig.from_dict({
        "membership": {"remesh_debounce_s": 0.3},
        "control": {"heartbeat_interval_ms": 100}})
    et = ElasticTrainer(cfg, LocalStore(str(tmp_path / "ckpt")),
                        coordinator_addr=coord.addr, name="debounce-w")
    try:
        et._start_agent()
        time.sleep(0.3)
        epoch, devices = et._current_world()
        et.transitions.append(EpochTransition(
            epoch=epoch, step=0, n_devices=len(devices),
            stripe=et._stripe()))
        et._remesh.clear()
        # a bounce: two quick epoch-change notifications
        et._on_epoch_change(epoch + 1, [])
        et._on_epoch_change(epoch + 2, [])
        assert not et._remesh_due()  # debounce holds it
        time.sleep(0.45)
        # settled view == formed world -> remesh skipped AND cleared
        assert not et._remesh_due()
        assert not et._remesh.is_set()
        # a REAL change (world size differs) does fire after the debounce
        et.transitions[-1].n_devices += 1
        et._on_epoch_change(epoch + 3, [])
        assert not et._remesh_due()
        time.sleep(0.45)
        assert et._remesh_due()
    finally:
        if et._agent is not None:
            et._agent.stop(deregister=False)
        coord.stop()


def test_gossip_suspicion_fires_health_alert():
    """Asymmetric partition, the other direction: a worker reaches the
    master but its PEER probes time out — the gossip suspicion counter is
    an incident signal and the health engine turns it into an alert."""
    from serverless_learn_tpu.config import HealthConfig
    from serverless_learn_tpu.control.gossip import (GossipConfig,
                                                     GossipNode)
    from serverless_learn_tpu.telemetry import get_registry
    from serverless_learn_tpu.telemetry.health import HealthEngine

    engine = HealthEngine(registry=get_registry(),
                          config=HealthConfig(sample_interval_s=3600),
                          dump_on_critical=False)
    try:
        cfg = GossipConfig(protocol_period_s=0.2, ping_timeout_s=0.05)
        node = GossipNode("hx", "ahx", cfg, rng=random.Random("hx"))
        engine.sample_once()  # baseline AFTER the counters exist
        # hand it a peer that will never ack
        import json as json_mod

        node.on_message(json_mod.dumps(
            {"v": 1, "t": "ping", "from": "ghost", "fa": "aghost",
             "seq": 1, "g": [{"id": "ghost", "a": "aghost", "i": 0,
                              "s": "alive", "m": {}}]}).encode(), 0.0)
        now = 0.0
        for _ in range(40):
            now += 0.1
            node.tick(now)
            if node.suspect_ids():
                break
        assert node.suspect_ids() == ["ghost"]
        engine.sample_once()
        firing = {al["alert"] for al in engine.alerts(firing_only=True)}
        assert "event.gossip_suspicion" in firing, firing
    finally:
        engine.stop()
