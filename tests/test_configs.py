"""The shipped config ladder must parse, and the small rungs must build a
real trainer on the virtual mesh."""

import glob
import os

import jax
import pytest

from serverless_learn_tpu.config import ExperimentConfig

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(ROOT, "configs", "*.json")))


def test_ladder_present():
    names = {os.path.basename(p) for p in CONFIGS}
    assert {"mnist_mlp.json", "cifar_resnet18_dp4.json",
            "imagenet_resnet50_v4_32.json", "bert_base_mlm.json",
            "llama8b_lora_elastic.json"} <= names


@pytest.mark.parametrize("path", CONFIGS, ids=os.path.basename)
def test_config_parses(path):
    cfg = ExperimentConfig.from_json(open(path).read())
    assert cfg.mesh.size >= 1
    assert cfg.train.batch_size % (cfg.mesh.dp * cfg.mesh.fsdp) == 0


@pytest.mark.parametrize("name", ["mnist_mlp.json", "cifar_resnet18_dp4.json"])
def test_small_rungs_build(devices, name):
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig.from_json(
        open(os.path.join(ROOT, "configs", name)).read())
    mesh = make_mesh(cfg.mesh, devices=devices[:cfg.mesh.size])
    trainer = build_trainer(cfg, mesh=mesh)
    state = trainer.init()
    assert int(jax.device_get(state.step)) == 0
