"""Continuous batching (round-5 verdict #2): slot-level scheduling must
not change greedy results, must admit mid-stream, retire at EOS, and
never starve a request the way the static engine's group keys could.

Exactness model: greedy continuations are byte-identical to solo
``generate`` calls (same pin as ``tests/test_serve_batching.py``);
sampled continuations are REPRODUCIBLE and BATCH-INVARIANT (per-slot
``fold_in(seed, position)`` streams — a stronger property than the
static engine's shared group stream, asserted here by re-running the
same seed under different traffic).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.continuous import (
    ContinuousBatchingEngine)
from serverless_learn_tpu.inference.generate import generate
from serverless_learn_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def model(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def _solo(module, params, prompt, n, eos_id=None):
    toks = generate(module, params, jnp.asarray([prompt], jnp.int32), n,
                    eos_id=eos_id)
    return [int(t) for t in jax.device_get(toks)[0][len(prompt):]]


def _engine(module, params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("chunk_size", 4)
    return ContinuousBatchingEngine(module, params, **kw)


def test_concurrent_greedy_exact(model):
    """Several unequal prompts submitted together: every reply equals the
    solo greedy continuation, and they shared the slot pool."""
    module, params = model
    eng = _engine(module, params)
    try:
        prompts = [[5, 9, 11], [7, 3, 2, 8, 1, 30, 12], [4], [1, 2]]
        results = [None] * len(prompts)

        def client(i):
            results[i] = eng.submit(prompts[i], 6, temperature=0.0,
                                    top_k=0, eos_id=None, seed=0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert "error" not in results[i], results[i]
            assert results[i]["new_tokens"] == _solo(module, params, p, 6), \
                f"request {i} diverged under continuous batching"
        assert eng.requests_finished == len(prompts)
        assert max(r["batch_size"] for r in results) > 1, \
            "requests never shared the slot pool"
    finally:
        eng.stop()


def test_mid_stream_admission_exact(model):
    """A request arriving while another is mid-decode joins at a chunk
    boundary and BOTH match their solo continuations — the static engine
    would have made the late arrival wait out the whole group."""
    module, params = model
    eng = _engine(module, params, chunk_size=2)
    try:
        long_prompt, short_prompt = [5, 9, 11, 7], [8, 2]
        res = {}

        def first():
            res["long"] = eng.submit(long_prompt, 20, temperature=0.0,
                                     top_k=0, eos_id=None, seed=0)

        def second():
            res["short"] = eng.submit(short_prompt, 4, temperature=0.0,
                                      top_k=0, eos_id=None, seed=0)

        t1 = threading.Thread(target=first)
        t1.start()
        # Let the first request start decoding before the second arrives.
        deadline = time.time() + 60
        while eng.chunks_run < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert eng.chunks_run >= 2, "first request never started decoding"
        t2 = threading.Thread(target=second)
        t2.start()
        t1.join(timeout=300)
        t2.join(timeout=300)
        assert res["long"]["new_tokens"] == _solo(module, params,
                                                  long_prompt, 20)
        assert res["short"]["new_tokens"] == _solo(module, params,
                                                   short_prompt, 4)
    finally:
        eng.stop()


def test_eos_retires_slot_early(model):
    """A sequence hitting EOS frees its slot while others keep decoding;
    the reply is EOS-filled to max_new exactly like solo generate."""
    module, params = model
    # Find the first greedy token of this prompt, then use it as the EOS
    # id so the request retires on its very first decode chunk.
    prompt = [5, 9, 11]
    first_tok = _solo(module, params, prompt, 1)[0]
    want = _solo(module, params, prompt, 8, eos_id=first_tok)
    eng = _engine(module, params, chunk_size=2)
    try:
        res = {}

        def eos_client():
            res["eos"] = eng.submit(prompt, 8, temperature=0.0, top_k=0,
                                    eos_id=first_tok, seed=0)

        def long_client():
            res["long"] = eng.submit([7, 3, 2], 16, temperature=0.0,
                                     top_k=0, eos_id=None, seed=0)

        ts = [threading.Thread(target=eos_client),
              threading.Thread(target=long_client)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        assert res["eos"]["new_tokens"] == want
        assert res["eos"]["new_tokens"][0] == first_tok
        assert all(t == first_tok for t in res["eos"]["new_tokens"])
        assert res["long"]["new_tokens"] == _solo(module, params,
                                                  [7, 3, 2], 16)
    finally:
        eng.stop()


def test_more_requests_than_slots(model):
    """6 requests through 2 slots: retirement must recycle slots until
    the queue drains; all replies exact."""
    module, params = model
    eng = _engine(module, params, max_slots=2, chunk_size=2)
    try:
        prompts = [[i + 1, i + 2] for i in range(6)]
        results = [None] * 6

        def client(i):
            results[i] = eng.submit(prompts[i], 4, temperature=0.0,
                                    top_k=0, eos_id=None, seed=0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert results[i]["new_tokens"] == _solo(module, params, p, 4)
    finally:
        eng.stop()


def test_mixed_sampling_in_one_batch_no_starvation(model):
    """The static engine's documented failure (round-4 verdict): sustained
    compatible traffic starves a mismatched request behind new arrivals.
    Here a sampled request rides the SAME slot pool as a stream of greedy
    traffic and completes promptly."""
    module, params = model
    eng = _engine(module, params, max_slots=4, chunk_size=2)
    try:
        stop_feeding = threading.Event()
        greedy_done = []

        def greedy_stream():
            while not stop_feeding.is_set():
                r = eng.submit([5, 9], 4, temperature=0.0, top_k=0,
                               eos_id=None, seed=0)
                greedy_done.append(r)

        feeders = [threading.Thread(target=greedy_stream)
                   for _ in range(2)]
        for t in feeders:
            t.start()
        res = eng.submit([7, 3, 2], 6, temperature=0.9, top_k=8,
                         eos_id=None, seed=123, timeout_s=120.0)
        stop_feeding.set()
        for t in feeders:
            t.join(timeout=300)
        assert "error" not in res, res
        assert len(res["new_tokens"]) == 6
        assert all("error" not in r for r in greedy_done)
    finally:
        eng.stop()


def test_sampled_is_reproducible_and_batch_invariant(model):
    """fold_in(seed, position) streams: the same request returns the same
    tokens whether it runs alone or alongside other traffic."""
    module, params = model
    req = dict(prompt=[7, 3, 2], max_new=6, temperature=0.9, top_k=8,
               eos_id=None, seed=42)

    def run_once(with_traffic: bool):
        eng = _engine(module, params, chunk_size=2)
        try:
            res = {}

            def target():
                res["r"] = eng.submit(req["prompt"], req["max_new"],
                                      req["temperature"], req["top_k"],
                                      req["eos_id"], req["seed"])

            ts = [threading.Thread(target=target)]
            if with_traffic:
                ts.append(threading.Thread(
                    target=lambda: eng.submit([5, 9, 11, 4], 10, 0.0, 0,
                                              None, 0)))
                ts.append(threading.Thread(
                    target=lambda: eng.submit([1, 2], 8, 0.7, 4, None, 7)))
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            assert "error" not in res["r"], res["r"]
            return res["r"]["new_tokens"]
        finally:
            eng.stop()

    alone = run_once(False)
    crowded = run_once(True)
    again = run_once(True)
    assert alone == crowded == again, \
        "sampled output must not depend on batch composition"


def test_validation_errors(model):
    module, params = model
    eng = _engine(module, params)
    try:
        assert "error" in eng.submit([], 4, 0.0, 0, None, 0)
        assert "error" in eng.submit([1] * 60, 10, 0.0, 0, None, 0)
        assert "error" in eng.submit([1], 4, 0.9, eng.max_top_k + 1,
                                     None, 0)
        assert eng.submit([1], 0, 0.0, 0, None, 0)["new_tokens"] == []
        # The engine still serves after rejections.
        r = eng.submit([5, 9], 3, 0.0, 0, None, 0)
        assert r["new_tokens"] == _solo(module, params, [5, 9], 3)
    finally:
        eng.stop()


def test_server_with_continuous_engine(model):
    """End to end over the wire with engine='continuous'."""
    from serverless_learn_tpu.inference.server import (
        GenerationServer, request)

    module, params = model
    srv = GenerationServer(module, params, engine="continuous").start()
    try:
        prompts = [[5, 9, 11], [7, 3, 2, 8], [4, 4], [1, 2, 3, 4, 5]]
        reps = [None] * 4

        def client(i):
            reps[i] = request(srv.addr, {"prompt": prompts[i],
                                         "max_new_tokens": 4})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert reps[i].get("new_tokens") == _solo(module, params, p, 4)
    finally:
        srv.stop()
