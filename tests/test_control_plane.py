"""Process-level integration tests for the native coordinator — elastic
membership, heartbeat leases, peer-list dissemination, epoch bumps, and
fault injection (kill a worker, assert eviction), mirroring how the
reference was exercised manually (SURVEY.md §4) but automated."""

import socket
import time

import pytest

from serverless_learn_tpu.control.client import (
    CoordinatorClient, WorkerAgent, ensure_native_built)
from serverless_learn_tpu.control.daemons import start_coordinator


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def coordinator():
    port = _free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=1200, sweep_ms=100)
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


def test_native_build():
    assert ensure_native_built()


def test_register_and_membership(coordinator):
    c = CoordinatorClient(coordinator)
    r1 = c.register("w1:9000", name="w1", n_chips=4)
    r2 = c.register("w2:9000", name="w2", n_chips=4)
    assert r1.ok and r2.ok
    assert r2.epoch > r1.epoch, "every join bumps the membership epoch"
    m = c.membership()
    assert {p.addr for p in m.peers} == {"w1:9000", "w2:9000"}
    assert m.epoch == r2.epoch
    c.close()


def test_heartbeat_carries_peer_list(coordinator):
    c = CoordinatorClient(coordinator)
    r1 = c.register("w1:9000")
    c.register("w2:9000")
    hb = c.heartbeat(r1.worker_id, step=7, metric=1.5)
    assert hb.ok
    assert {p.addr for p in hb.peers} == {"w1:9000", "w2:9000"}
    c.close()


def test_lease_expiry_evicts_dead_worker(coordinator):
    """Failure detection with actual handling — the reference only logged
    dead workers and kept them in the list forever (src/master.cc:191-195)."""
    c = CoordinatorClient(coordinator)
    r_dead = c.register("dead:9000")
    r_live = c.register("live:9000")
    epoch0 = r_live.epoch
    # keep the live worker's lease fresh; never heartbeat the dead one
    for _ in range(20):
        c.heartbeat(r_live.worker_id)
        time.sleep(0.1)
    m = c.membership()
    assert {p.addr for p in m.peers} == {"live:9000"}
    assert m.epoch > epoch0, "eviction must bump the epoch"
    # dead worker's next heartbeat is told to re-register
    hb = c.heartbeat(r_dead.worker_id)
    assert not hb.ok
    c.close()


def test_deregister(coordinator):
    c = CoordinatorClient(coordinator)
    r = c.register("w:9000")
    ack = c.deregister(r.worker_id)
    assert ack.ok
    assert len(c.membership().peers) == 0
    c.close()


def test_agent_callback_carries_peers_at_registration(coordinator):
    """Regression: the first epoch-change callback (at registration) must
    carry the actual membership, not an empty list."""
    seen = []
    a1 = WorkerAgent(coordinator, "w1:9001", heartbeat_interval_ms=100).start()
    a2 = WorkerAgent(coordinator, "w2:9002", heartbeat_interval_ms=100,
                     on_epoch_change=lambda e, p: seen.append((e, len(p)))
                     ).start()
    assert seen, "callback must fire at registration"
    assert seen[0][1] == 2, f"registration callback saw {seen[0][1]} peers"
    a1.stop()
    a2.stop()


def test_worker_agent_lifecycle_and_epoch_callback(coordinator):
    events = []
    agents = [
        WorkerAgent(coordinator, f"w{i}:900{i}", name=f"w{i}",
                    heartbeat_interval_ms=100,
                    on_epoch_change=lambda e, p, i=i: events.append((i, e)))
        .start()
        for i in range(3)
    ]
    time.sleep(0.5)
    # all agents converge on the same epoch and see all 3 peers
    epochs = {a.epoch for a in agents}
    assert len(epochs) == 1
    assert all(len(a.peers) == 3 for a in agents)
    # stop one -> deregister -> remaining agents observe an epoch bump
    e_before = agents[0].epoch
    agents[2].stop()
    time.sleep(0.5)
    assert agents[0].epoch > e_before
    assert len(agents[0].peers) == 2
    assert any(i == 0 for i, _ in events)
    for a in agents[:2]:
        a.stop()


def test_agent_rejoins_after_lease_loss(coordinator):
    """Elastic re-join: an agent whose lease lapsed (e.g. long GC pause /
    network partition) transparently re-registers with a fresh id."""
    agent = WorkerAgent(coordinator, "w:9000", heartbeat_interval_ms=100).start()
    time.sleep(0.3)
    first_id = agent.worker_id
    # simulate a partition: pause heartbeats past the 1.2 s lease
    agent._stop.set()
    agent._thread.join()
    time.sleep(1.5)
    c = CoordinatorClient(coordinator)
    assert len(c.membership().peers) == 0, "lease must have expired"
    # resume heartbeating
    agent._stop.clear()
    import threading

    agent._thread = threading.Thread(target=agent._run, daemon=True)
    agent._thread.start()
    time.sleep(0.5)
    assert agent.worker_id != first_id, "must have re-registered"
    assert len(c.membership().peers) == 1
    agent.stop()
    c.close()


def test_heartbeat_flow_surfaces_in_stats(coordinator):
    """HeartbeatRequest.flow (successor of the reference's reserved
    FlowFeedback, proto :73-75) must round-trip into the coordinator's
    stats RPC — the slow-consumer observability path (VERDICT item 6)."""
    c = CoordinatorClient(coordinator)
    rep = c.register("w:1", name="flowtest")
    # A starved worker (flow=0) and a healthy one side by side.
    c.heartbeat(rep.worker_id, step=7, metric=0.5, flow=0)
    rep2 = c.register("w:2", name="flowtest2")
    c.heartbeat(rep2.worker_id, step=9, metric=0.25, flow=3)
    flows = {f.worker_id: f for f in c.stats().flows}
    assert flows[rep.worker_id].flow == 0
    assert flows[rep.worker_id].step == 7
    assert flows[rep2.worker_id].flow == 3
    assert flows[rep2.worker_id].metric == pytest.approx(0.25)
    c.close()


def test_exclusive_name_enforced_by_coordinator(coordinator):
    """Name uniqueness is atomic at the registry (the single authority) —
    no client-side polling race. Non-exclusive names may still be shared
    (multihost bootstrap peers all register under one tag)."""
    c = CoordinatorClient(coordinator)
    a = c.register("w:1", name="job", exclusive_name=True)
    assert a.ok
    b = c.register("w:2", name="job", exclusive_name=True)
    assert not b.ok and "already held" in b.error
    # Exclusive claim also blocks against a non-exclusive holder, and
    # non-exclusive registration ignores collisions entirely.
    s1 = c.register("w:3", name="shared")
    s2 = c.register("w:4", name="shared")
    assert s1.ok and s2.ok
    s3 = c.register("w:5", name="shared", exclusive_name=True)
    assert not s3.ok
    # Deregistration frees the name.
    c.deregister(a.worker_id)
    again = c.register("w:6", name="job", exclusive_name=True)
    assert again.ok
    c.close()


def test_agent_fenced_out_when_name_taken_over(coordinator):
    """A lease-lapsed agent whose exclusive name was claimed by a successor
    must go fatal instead of silently re-registering into the successor's
    checkpoint namespace."""
    agent = WorkerAgent(coordinator, "w:1", name="fence",
                        heartbeat_interval_ms=100, exclusive_name=True)
    agent.start()
    old_id = agent.worker_id
    # Simulate a lease lapse + takeover: evict the agent's registration and
    # let a successor claim the name while the agent still heartbeats.
    c = CoordinatorClient(coordinator)
    c.deregister(old_id)
    succ = c.register("w:2", name="fence", exclusive_name=True)
    # The agent heartbeats every 100 ms: one can fire in the gap above, see
    # not-ok, and legitimately re-register before the successor claims the
    # name. Evict again and retry until the successor wins the race.
    deadline = time.time() + 5
    while not succ.ok and time.time() < deadline:
        c.deregister(agent.worker_id)
        succ = c.register("w:2", name="fence", exclusive_name=True)
    assert succ.ok
    deadline = time.time() + 5
    while agent.fatal is None and time.time() < deadline:
        time.sleep(0.05)
    assert agent.fatal is not None and "already held" in agent.fatal
    agent.stop(deregister=False)
    c.close()


def test_coordinator_state_survives_restart(tmp_path):
    """--state_file durability (round-1 backlog: 'a restart loses all
    leases/epochs'): a SIGTERM'd coordinator restarts with the same epoch
    and worker ids, so existing workers' heartbeats remain valid and new
    registrations never reuse an id."""
    from serverless_learn_tpu.control.daemons import start_coordinator

    state = str(tmp_path / "coord.state")
    port = _free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=60000, sweep_ms=200,
                             state_file=state)
    addr = f"127.0.0.1:{port}"
    try:
        c = CoordinatorClient(addr)
        r1 = c.register("w:1", name="alpha", n_chips=2)
        r2 = c.register("w:2", name="beta", n_chips=4)
        epoch_before = c.membership().epoch
        c.close()
    finally:
        proc.terminate()
        assert proc.wait(timeout=5) == 0, "SIGTERM must exit cleanly"

    proc = start_coordinator(port=port, lease_ttl_ms=60000, sweep_ms=200,
                             state_file=state)
    try:
        c = CoordinatorClient(addr)
        m = c.membership()
        assert m.epoch == epoch_before
        assert sorted(p.worker_id for p in m.peers) == [r1.worker_id,
                                                        r2.worker_id]
        assert sorted(p.name for p in m.peers) == ["alpha", "beta"]
        # an existing worker's id is still honored
        assert c.heartbeat(r1.worker_id, 7, 0.1, 0).ok
        # ids keep monotonically increasing across the restart
        r3 = c.register("w:3", name="gamma", n_chips=1)
        assert r3.worker_id > r2.worker_id
        # exclusive names are still enforced against restored workers
        refused = c.register("w:4", name="alpha", exclusive_name=True)
        assert not refused.ok
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)
