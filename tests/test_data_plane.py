"""Integration tests for the native shard server: manifest, ranged fetch,
synthetic datasets, atomic puts (checkpoint store), error paths."""

import os
import socket

import numpy as np
import pytest

from serverless_learn_tpu.control.client import ShardClient
from serverless_learn_tpu.control.daemons import start_shard_server


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def shard_server(tmp_path):
    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path))
    yield f"127.0.0.1:{port}", tmp_path
    proc.terminate()
    proc.wait(timeout=5)


def test_put_fetch_roundtrip(shard_server):
    addr, root = shard_server
    c = ShardClient(addr)
    data = os.urandom(3 * 1024 * 1024 + 17)  # >1 chunk, odd size
    c.put("ds/shard-000", data)
    assert (root / "ds" / "shard-000").read_bytes() == data
    out = c.fetch("ds/shard-000")
    assert out == data
    c.close()


def test_ranged_fetch(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    data = bytes(range(256)) * 1024
    c.put("blob", data)
    out = c.fetch("blob", offset=1000, length=5000)
    assert out == data[1000:6000]
    c.close()


def test_manifest_lists_keys_and_sizes(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    c.put("train/shard-0", b"a" * 100)
    c.put("train/shard-1", b"b" * 200)
    c.put("val/shard-0", b"c" * 50)
    blobs = {b.key: b.size for b in c.manifest("train")}
    assert blobs == {"train/shard-0": 100, "train/shard-1": 200}
    all_blobs = {b.key for b in c.manifest("")}
    assert "val/shard-0" in all_blobs
    c.close()


def test_synthetic_dataset_deterministic(shard_server):
    """Successor of the reference's synthesized random 100 MB file
    (src/file_server.cc:150-156): synthetic keys serve deterministic bytes
    at arbitrary offsets without server-side materialization."""
    addr, _ = shard_server
    c = ShardClient(addr)
    blobs = c.manifest("synthetic:10000000")
    assert blobs[0].size == 10_000_000
    a = c.fetch("synthetic:10000000", offset=0, length=4096)
    b = c.fetch("synthetic:10000000", offset=0, length=4096)
    assert a == b and len(a) == 4096
    # ranged fetch is consistent with a larger fetch
    big = c.fetch("synthetic:10000000", offset=0, length=65536)
    mid = c.fetch("synthetic:10000000", offset=16384, length=1024)
    assert big[16384:17408] == mid
    c.close()


def test_synthetic_unaligned_offsets_consistent(shard_server):
    """Regression: ranged reads at non-8-aligned offsets must agree with a
    full read (the stream is keyed by absolute position, not request offset)."""
    addr, _ = shard_server
    c = ShardClient(addr)
    full = c.fetch("synthetic:4096", offset=0, length=4096)
    for off, ln in [(3, 8), (1, 4095), (7, 9), (13, 100)]:
        part = c.fetch("synthetic:4096", offset=off, length=ln)
        assert part == full[off:off + ln], f"offset={off} len={ln}"
    c.close()


def test_fetch_default_length_past_eof_returns_empty(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    c.put("tiny", b"x" * 10)
    assert c.fetch("tiny", offset=50) == b""
    c.close()


def test_delete_rpc(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    c.put("doomed", b"bye")
    c.delete("doomed")
    assert "doomed" not in {b.key for b in c.manifest("")}
    with pytest.raises(IOError):
        c.delete("doomed")  # already gone
    c.close()


def test_fetch_into_numpy_buffer(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    data = os.urandom(2_000_000)
    c.put("x", data)
    buf = np.zeros(2_000_000, np.uint8)
    n = c.fetch_into("x", buf)
    assert n == 2_000_000
    assert buf.tobytes() == data
    c.close()


def test_unknown_key_errors_not_crashes(shard_server):
    """The reference exit(1)'d the whole file server on a bad file number
    (src/file_server.cc:107-110); ours returns an error and keeps serving."""
    addr, _ = shard_server
    c = ShardClient(addr)
    with pytest.raises(IOError):
        c.fetch("does/not/exist", length=10)
    # server still alive and serving
    c2 = ShardClient(addr)
    c2.put("alive", b"yes")
    assert c2.fetch("alive") == b"yes"
    c.close()
    c2.close()


def test_path_traversal_rejected(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    with pytest.raises(IOError):
        c.put("../escape", b"nope")
    with pytest.raises(IOError):
        c.fetch("../../etc/passwd", length=10)
    c.close()


def test_rejected_put_does_not_desync_connection(shard_server):
    """Regression: a rejected put streams chunks the server must drain;
    leaving them queued desyncs every later call on the connection."""
    addr, _ = shard_server
    c = ShardClient(addr)
    c.put("ok-key", b"d" * 2_000_000)
    with pytest.raises(IOError):
        c.put("../escape", b"x" * 2_000_000)  # 2 chunk frames to drain
    # same connection must still give coherent replies
    st = c.stats()
    assert st.bytes_stored >= 2_000_000
    assert c.fetch("ok-key", length=10) == b"d" * 10
    c.close()


def test_atomic_put_overwrite(shard_server):
    addr, root = shard_server
    c = ShardClient(addr)
    c.put("ckpt/step-1", b"v1" * 1000)
    c.put("ckpt/step-1", b"v2" * 1000)
    assert c.fetch("ckpt/step-1") == b"v2" * 1000
    # no tmp files left behind
    leftovers = [p for p in (root / "ckpt").iterdir() if ".tmp." in p.name]
    assert not leftovers
    c.close()


def test_fetch_offset_past_eof_returns_empty_not_hang(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    c.put("small", b"x" * 10)
    buf = np.zeros(100, np.uint8)
    n = c.fetch_into("small", buf, offset=50, length=10)
    assert n == 0
    # connection still usable
    assert c.fetch("small", length=10) == b"x" * 10
    c.close()


def test_concurrent_puts_same_key_not_interleaved(shard_server):
    """Regression: tmp-file suffix must be unique per put, not per process —
    all handler threads share one pid."""
    import threading

    addr, _ = shard_server
    payloads = [bytes([i]) * 3_000_000 for i in range(4)]

    def put_one(i):
        c = ShardClient(addr)
        c.put("contended", payloads[i])
        c.close()

    threads = [threading.Thread(target=put_one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    c = ShardClient(addr)
    out = c.fetch("contended")
    # Last rename wins, but the winner must be byte-uniform (no interleaving).
    assert len(out) == 3_000_000
    assert len(set(out)) == 1, "interleaved bytes from concurrent puts"
    c.close()


def test_stats_counters(shard_server):
    addr, _ = shard_server
    c = ShardClient(addr)
    c.put("s", b"z" * 1000)
    c.fetch("s")
    st = c.stats()
    assert st.bytes_stored >= 1000 and st.bytes_served >= 1000
    c.close()


def test_corrupted_blob_fails_fetch_loudly(shard_server):
    """Wire the dead crc32 field (VERDICT round 1 item 6): flipping one byte
    of a stored blob on disk must fail the next full fetch with a crc error,
    not silently serve garbage — and count in stats.crc_failures."""
    addr, root = shard_server
    c = ShardClient(addr)
    data = os.urandom(2 * 1024 * 1024 + 5)
    c.put("ckpt/weights", data)
    path = root / "ckpt" / "weights"
    raw = bytearray(path.read_bytes())
    raw[12345] ^= 0xFF
    path.write_bytes(bytes(raw))
    with pytest.raises(IOError):
        c.fetch("ckpt/weights")
    assert c.stats().crc_failures >= 1
    # Ranged fetches skip the whole-file disk check (no crc over a range to
    # compare with) but still verify transit integrity.
    assert len(c.fetch("ckpt/weights", offset=0, length=1000)) == 1000
    c.close()


def test_put_with_bad_crc_rejected(shard_server):
    """A put whose payload doesn't match its declared crc must be rejected
    (simulated in-transit corruption) and leave no blob behind."""
    import struct as _struct
    import zlib as _zlib

    from serverless_learn_tpu.control.client import (
        MSG_ACK, MSG_CHUNK, MSG_PUT_REQ, _pb2)

    addr, root = shard_server
    pb = _pb2()
    host, _, port = addr.rpartition(":")
    data = b"payload-bytes" * 1000
    with socket.create_connection((host, int(port))) as s:
        req = pb.PutRequest(key="bad", total_size=len(data),
                            crc32=_zlib.crc32(data) ^ 0xDEADBEEF,
                            crc_present=True)
        payload = req.SerializeToString()
        s.sendall(_struct.pack(">IB", len(payload), MSG_PUT_REQ) + payload)
        chunk = pb.ChunkMsg(data=data, offset=0, last=True)
        payload = chunk.SerializeToString()
        s.sendall(_struct.pack(">IB", len(payload), MSG_CHUNK) + payload)
        hdr = b""
        while len(hdr) < 5:
            hdr += s.recv(5 - len(hdr))
        length, mtype = _struct.unpack(">IB", hdr)
        body = b""
        while len(body) < length:
            body += s.recv(length - len(body))
        assert mtype == MSG_ACK
        ack = pb.Ack()
        ack.ParseFromString(body)
        assert not ack.ok and "crc" in ack.error
    assert not (root / "bad").exists()


def test_manifest_reports_put_crc(shard_server):
    import zlib as _zlib

    addr, _ = shard_server
    c = ShardClient(addr)
    data = b"shard-data" * 5000
    c.put("ds2/shard-0", data)
    blobs = {b.key: b for b in c.manifest("ds2")}
    assert blobs["ds2/shard-0"].crc32 == _zlib.crc32(data)
    c.close()


def test_crc_sidecars_hidden_and_key_namespace_reserved(shard_server):
    addr, root = shard_server
    c = ShardClient(addr)
    c.put("ds3/shard-0", b"x" * 100)
    assert (root / "ds3" / "shard-0.slt-crc").exists()
    keys = {b.key for b in c.manifest("")}
    assert keys == {"ds3/shard-0"}, "sidecar leaked into manifest"
    with pytest.raises(IOError):
        c.put("evil.slt-crc", b"y")
    c.close()


def test_pure_python_transport_crc_roundtrip(shard_server):
    """The socket fallback path computes and verifies crc too."""
    addr, _ = shard_server
    c = ShardClient(addr, prefer_native=False)
    data = os.urandom(1_500_000)
    c.put("pp/blob", data)
    assert c.fetch("pp/blob") == data
    c.close()
