"""DiLoCo over DCN (round-5 verdict #4): Local SGD composed with the
elastic coordinator + shard-server plane.

Islands here are threads, each owning a DISJOINT single-device mesh on
the 8-CPU-device harness — the closest in-process analogue of separate
hosts: islands share no jit, no collective, and meet only through the
native daemons (real subprocesses, real TCP). What the tests pin:

* convergence + loss parity: two islands over DCN land within tolerance
  of one island doing the same total steps (the verdict's "single world"
  bar), and both learn.
* wire discipline: model bytes on the store scale with ROUNDS, not
  steps — the inner phase moves zero model bytes (counted by a proxy
  store, asserted against the protocol's exact expected byte count).
* churn: a SIGKILL'd island (heartbeats stop, lease expires) does not
  wedge the survivors — the leader's round timeout + live-membership
  snapshot drop it; a LATE island joins at the current round and its
  deltas join the average.
* leader failover: killing the LOWEST-id island (the leader) hands
  leadership to the next live id.
"""

import socket
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, LocalSGDConfig, MeshConfig,
    OptimizerConfig, TrainConfig)
from serverless_learn_tpu.control.daemons import start_coordinator
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.training.checkpoint import LocalStore
from serverless_learn_tpu.training.diloco_dcn import DilocoIsland


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def coordinator():
    port = _free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=1500, sweep_ms=100)
    try:
        yield f"127.0.0.1:{port}"
    finally:
        proc.terminate()
        proc.wait(timeout=5)


class CountingStore(LocalStore):
    """LocalStore that counts model bytes by op, for the wire assertion."""

    def __init__(self, root):
        super().__init__(root)
        self.put_bytes = 0
        self.get_bytes = 0
        self.lock = threading.Lock()

    def put(self, key, data):
        with self.lock:
            self.put_bytes += len(data)
        return super().put(key, data)

    def get(self, key):
        data = super().get(key)
        with self.lock:
            self.get_bytes += len(data)
        return data


def _cfg(batch_size=16, seed=0):
    return ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=1),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=batch_size, seed=seed,
                          donate_state=False),
        data=DataConfig(learnable=True),
        # outer_lr=1, momentum=0: the outer step degenerates to plain
        # parameter averaging (anchor <- mean of island params) — the
        # stable classic for a tiny noisy task. The Nesterov formulation
        # itself is pinned against optax in test_nesterov_matches_optax;
        # at lr .7 / mu .9 on THIS 32-step toy it oscillates by design.
        local_sgd=LocalSGDConfig(outer="average", inner_steps=2,
                                 outer_lr=1.0, outer_momentum=0.0))


def _island(cfg, store, coord, run, device_ix, **kw):
    mesh = make_mesh(cfg.mesh, devices=[jax.devices()[device_ix]])

    def source_factory(wid):
        # Distinct data per island, deterministic per worker id.
        from serverless_learn_tpu.models.registry import get_model

        bundle = get_model(cfg.model, **cfg.model_overrides)
        return iter(SyntheticSource(bundle.make_batch, cfg.data,
                                    cfg.train.batch_size, seed=1000 + wid))

    kw.setdefault("round_timeout_s", 8.0)
    return DilocoIsland(cfg, store, coord, run, mesh=mesh,
                        source_factory=source_factory, **kw)


def _run_threads(islands, rounds):
    reports = [None] * len(islands)
    errs = []

    def go(i):
        try:
            reports[i] = islands[i].run_rounds(rounds)
        except Exception as e:  # surface in the main thread
            errs.append((i, e))

    ts = [threading.Thread(target=go, args=(i,))
          for i in range(len(islands))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=300)
    assert not errs, errs
    return reports


def _fixed_batch(cfg, seed):
    from serverless_learn_tpu.models.registry import get_model

    bundle = get_model(cfg.model, **cfg.model_overrides)
    return bundle.make_batch(np.random.default_rng(seed), cfg.data,
                             cfg.train.batch_size)


def _eval_loss(cfg, island, batches) -> float:
    """Mean loss of an island's final params over the given fixed batches
    (the pair's combined objective) — round-end training losses are
    single fresh-batch samples, far too noisy to compare runs with."""
    from serverless_learn_tpu.models.registry import get_model

    bundle = get_model(cfg.model, **cfg.model_overrides)
    if island.final_params is not None:
        params = island.final_params
    else:  # pre-training: the deterministic init every island shares
        params = jax.device_get(island.trainer.init().params)
    losses = [float(jax.device_get(bundle.loss_fn(params, b)[0]))
              for b in batches]
    return float(np.mean(losses))


def test_nesterov_matches_optax(devices):
    """The host-side outer step must be bit-compatible with
    LocalSGDTrainer's optax.sgd(lr, momentum, nesterov=True) outer_tx —
    leadership migrates by shipping (anchor, trace), so the formula
    cannot drift from the in-jit twin."""
    import optax

    from serverless_learn_tpu.training.diloco_dcn import _nesterov_step

    rng = np.random.default_rng(0)
    anchor = {"w": rng.standard_normal((4, 3)).astype(np.float32),
              "b": rng.standard_normal((3,)).astype(np.float32)}
    tx = optax.sgd(0.7, momentum=0.9, nesterov=True)
    opt_state = tx.init(anchor)
    a_opt, a_mine = anchor, anchor
    trace = jax.tree_util.tree_map(np.zeros_like, anchor)
    for i in range(3):
        grad = jax.tree_util.tree_map(
            lambda l: rng.standard_normal(l.shape).astype(np.float32),
            anchor)
        updates, opt_state = tx.update(grad, opt_state, a_opt)
        a_opt = jax.tree_util.tree_map(
            lambda a, u: a + np.asarray(u), a_opt, updates)
        a_mine, trace = _nesterov_step(a_mine, grad, trace, 0.7, 0.9)
        for k in anchor:
            np.testing.assert_allclose(a_mine[k], np.asarray(a_opt[k]),
                                       rtol=1e-6, atol=1e-6)


def test_two_islands_converge_and_track_single_world(coordinator, devices):
    """The DiLoCo claim, pinned the way test_local_sgd pins it
    (memorizable fixed data): island A owns batch_A, island B owns
    batch_B; after 4 rounds x 8 inner steps the SHARED anchor has learned
    BOTH batches — cross-island information moved only through the
    anchor-delta exchange on the store. A single world alternating both
    batches (the same total steps) is the golden; the DCN composition
    must land within tolerance of it."""
    rounds, inner = 4, 8
    cfg = _cfg()
    batch_a, batch_b = _fixed_batch(cfg, 100), _fixed_batch(cfg, 200)
    both = [batch_a, batch_b]
    import itertools

    with tempfile.TemporaryDirectory() as root:
        store = LocalStore(root + "/a")
        islands = [_island(cfg, store, coordinator, "pair", i,
                           inner_steps=inner) for i in range(2)]
        # Deterministic per-island data: A to the lower worker id.
        order = sorted(islands, key=lambda i: i.agent.worker_id)
        order[0].source_factory = lambda wid: itertools.repeat(batch_a)
        order[1].source_factory = lambda wid: itertools.repeat(batch_b)
        init_loss = _eval_loss(cfg, islands[0], both)  # shared init
        reports = _run_threads(islands, rounds)
        pair_losses = [_eval_loss(cfg, isl, both) for isl in islands]
        solo_store = LocalStore(root + "/b")
        solo = _island(cfg, solo_store, coordinator, "solo", 2,
                       inner_steps=inner)
        solo.source_factory = lambda wid: itertools.cycle(both)
        solo_rep = solo.run_rounds(rounds)
        solo_loss = _eval_loss(cfg, solo, both)
    for rep in reports:
        assert rep.rounds_done == rounds
        assert rep.steps_done == rounds * inner
    # All islands end on the SAME anchor-adopted params: identical evals.
    np.testing.assert_allclose(pair_losses[0], pair_losses[1], rtol=1e-5)
    # Parity on the INIT-loss scale: both runs must memorize (>20x down
    # from init) and land within 5% of init of each other — measured runs
    # reach ~2.5e-4 (pair; averaging dilutes per-batch memorization, the
    # known DiLoCo gap) vs ~1e-6 (solo joint training), init ~2.4.
    assert solo_rep.rounds_done == rounds
    assert pair_losses[0] < 0.05 * init_loss, (pair_losses, init_loss)
    assert solo_loss < 0.05 * init_loss, (solo_loss, init_loss)
    assert abs(pair_losses[0] - solo_loss) < 0.05 * init_loss, \
        (pair_losses[0], solo_loss, init_loss)
    # Exactly one leader per round across the pair.
    assert sum(r.led_rounds for r in reports) == rounds


def test_wire_bytes_scale_with_rounds_not_steps(coordinator, devices):
    """The DCN contract: model bytes move ONLY at outer boundaries. The
    same number of total steps under inner_steps=2 vs inner_steps=4 moves
    2x vs 1x the bytes — bytes follow rounds, never steps."""
    def run(inner, rounds):
        with tempfile.TemporaryDirectory() as root:
            store = CountingStore(root)
            isl = _island(_cfg(), store, coordinator,
                          f"wire{inner}", 0, inner_steps=inner)
            rep = isl.run_rounds(rounds)
            assert rep.steps_done == inner * rounds
            return store.put_bytes, store.get_bytes

    put4, get4 = run(4, 2)   # 8 steps, 2 rounds
    put2, get2 = run(2, 4)   # 8 steps, 4 rounds
    # Per round: one delta put + one anchor put (solo island leads) and
    # one anchor get; plus the bootstrap anchor put/get and LATEST json.
    # Bytes ratio therefore tracks (rounds+1)/(rounds+1) on anchors and
    # rounds on deltas — strictly increasing in rounds at equal steps.
    assert put2 > put4 * 1.4, (put2, put4)
    assert get2 > get4 * 1.4, (get2, get4)


def test_island_crash_does_not_wedge_survivors(coordinator, devices):
    """Three islands; one dies (stops heartbeating AND posting) after the
    first round. Survivors finish every round: the leader drops it via
    lease expiry / round timeout."""
    rounds = 3
    with tempfile.TemporaryDirectory() as root:
        store = LocalStore(root)
        islands = [_island(_cfg(), store, coordinator, "churn", i)
                   for i in range(3)]
        # The VICTIM is the highest worker id (not the leader here).
        victim = max(islands, key=lambda i: i.agent.worker_id)
        victim.abort = threading.Event()
        survivors = [i for i in islands if i is not victim]

        def kill_after_first_round():
            while victim.report.rounds_done < 1:
                time.sleep(0.05)
            victim.abort.set()
            victim.agent.stop(deregister=False)  # crash: lease expires

        killer = threading.Thread(target=kill_after_first_round)
        killer.start()
        reports = _run_threads(islands, rounds)
        killer.join(timeout=60)
    for isl, rep in zip(islands, reports):
        if isl is victim:
            assert rep.rounds_done < rounds
        else:
            # Liveness is this test's claim (convergence is the
            # two-islands test's); losses just must stay finite.
            assert rep.rounds_done == rounds, rep
            assert all(np.isfinite(l) for l in rep.losses), rep.losses


def test_leader_crash_hands_over(coordinator, devices):
    """Killing the LOWEST id (the leader) mid-run: the next live id
    assumes leadership and the run completes."""
    rounds = 3
    with tempfile.TemporaryDirectory() as root:
        store = LocalStore(root)
        islands = [_island(_cfg(), store, coordinator, "lead", i)
                   for i in range(2)]
        leader = min(islands, key=lambda i: i.agent.worker_id)
        other = max(islands, key=lambda i: i.agent.worker_id)
        leader.abort = threading.Event()

        def kill_leader():
            while leader.report.rounds_done < 1:
                time.sleep(0.05)
            leader.abort.set()
            leader.agent.stop(deregister=False)

        killer = threading.Thread(target=kill_leader)
        killer.start()
        reports = _run_threads(islands, rounds)
        killer.join(timeout=60)
    other_rep = reports[islands.index(other)]
    assert other_rep.rounds_done == rounds
    assert other_rep.led_rounds >= 1, "leadership never migrated"


def test_islands_are_sharded_worlds(coordinator, devices):
    """An island is an SPMD WORLD, not a chip: two islands, each an
    fsdp=2 mesh over its own device pair, train and sync through the
    store — the production shape where each island is an elastic
    multihost world. Cross-island traffic stays on the store; within an
    island GSPMD shards params over fsdp."""
    cfg = _cfg()
    from serverless_learn_tpu.config import MeshConfig
    import dataclasses as _dc

    cfg = _dc.replace(cfg, mesh=MeshConfig(dp=1, fsdp=2))
    rounds = 2
    with tempfile.TemporaryDirectory() as root:
        store = LocalStore(root)
        islands = []
        for i in range(2):
            devs = jax.devices()[2 * i:2 * i + 2]
            mesh = make_mesh(cfg.mesh, devices=devs)

            def source_factory(wid, _cfg=cfg):
                from serverless_learn_tpu.models.registry import get_model

                bundle = get_model(_cfg.model)
                return iter(SyntheticSource(bundle.make_batch, _cfg.data,
                                            _cfg.train.batch_size,
                                            seed=1000 + wid))

            islands.append(DilocoIsland(
                cfg, store, coordinator, "sharded", mesh=mesh,
                source_factory=source_factory, round_timeout_s=8.0))
        # Each island's params are genuinely fsdp-sharded on ITS devices.
        st = islands[0].trainer.init()
        leaf = jax.tree_util.tree_leaves(st.params)[0]
        assert len(leaf.sharding.device_set) == 2
        del st, leaf
        reports = _run_threads(islands, rounds)
    for rep in reports:
        assert rep.rounds_done == rounds
        assert all(np.isfinite(l) for l in rep.losses)


@pytest.fixture()
def clean_rounds():
    """The leader's round records land in the process-global health
    ring (health.note_round); scrub it so engine tests elsewhere don't
    score this test's fabricated stragglers."""
    from serverless_learn_tpu.telemetry import health

    health.clear_rounds()
    yield
    health.clear_rounds()


def _gate_island(tmp_path, run="gate", **attrs):
    """Harness-style island (``__new__`` + manual attributes, the
    test_telemetry liveness idiom): enough surface to drive ``_lead``
    without a coordinator or a trainer."""
    from serverless_learn_tpu.training import diloco_dcn as dd

    isl = dd.DilocoIsland.__new__(dd.DilocoIsland)
    isl.store = LocalStore(str(tmp_path))
    isl.run = run
    isl.outer_lr, isl.outer_momentum = 1.0, 0.0
    isl.report = dd.IslandReport()

    class FakeAgent:
        worker_id = 0

    isl.agent = FakeAgent()
    for k, v in attrs.items():
        setattr(isl, k, v)
    return isl


def test_leader_gate_quarantines_poisoned_delta(tmp_path, clean_rounds):
    """ISSUE-19 satellite: one poisoned (NaN) worker cannot destroy the
    round — the leader averages only the clean delta, and when EVERY
    delta is poisoned the anchor is republished unchanged."""
    from serverless_learn_tpu.telemetry import health
    from serverless_learn_tpu.training import diloco_dcn as dd

    isl = _gate_island(tmp_path)
    template = {"w": np.zeros((4,), np.float32)}
    anchor = {"w": np.ones((4,), np.float32)}
    trace = {"w": np.zeros((4,), np.float32)}
    isl.store.put("diloco-gate/round-0/delta-1",
                  dd._pack({"w": np.full((4,), 0.1, np.float32)}))
    isl.store.put("diloco-gate/round-0/delta-2",
                  dd._pack({"w": np.full((4,), np.nan, np.float32)}))
    health.clear_rounds()
    isl._lead(0, [1, 2], anchor, trace, template, live=[1, 2])
    pub = dd._unpack(isl.store.get("diloco-gate/round-1/anchor"),
                     {"params": template, "trace": template})
    # lr=1, mu=0: anchor - mean(accepted) = 1 - 0.1 — the NaN delta is
    # fully excluded, not folded in at weight 0.
    np.testing.assert_allclose(pub["params"]["w"], 0.9, rtol=1e-6)
    assert np.isfinite(pub["params"]["w"]).all()
    rec = health.recent_rounds()[-1]
    assert rec["quarantined"] == {"2": "nonfinite"}
    assert rec["participation"] == 0.5
    assert list(rec["delta_norms"]) == ["1"]

    # Round 1: ONLY the poisoned worker posts — the anchor must come
    # through unchanged (liveness over progress).
    isl.store.put("diloco-gate/round-1/delta-2",
                  dd._pack({"w": np.full((4,), np.nan, np.float32)}))
    anchor1 = pub["params"]
    isl._lead(1, [2], anchor1, pub["trace"], template, live=[2])
    pub2 = dd._unpack(isl.store.get("diloco-gate/round-2/anchor"),
                      {"params": template, "trace": template})
    np.testing.assert_allclose(pub2["params"]["w"], anchor1["w"])
    assert health.recent_rounds()[-1]["participation"] == 0.0


def test_leader_gate_rejects_norm_outlier(tmp_path, clean_rounds):
    """The outlier arm: five in-family deltas plus one at 1000x their
    scale — only the outlier is excluded."""
    from serverless_learn_tpu.telemetry import health
    from serverless_learn_tpu.training import diloco_dcn as dd

    isl = _gate_island(tmp_path, run="outlier")
    template = {"w": np.zeros((8,), np.float32)}
    anchor = {"w": np.ones((8,), np.float32)}
    trace = {"w": np.zeros((8,), np.float32)}
    rng = np.random.default_rng(0)
    posted = []
    for wid in range(1, 6):
        isl.store.put(f"diloco-outlier/round-0/delta-{wid}", dd._pack(
            {"w": (0.1 * rng.standard_normal(8)).astype(np.float32)}))
        posted.append(wid)
    isl.store.put("diloco-outlier/round-0/delta-6",
                  dd._pack({"w": np.full((8,), 100.0, np.float32)}))
    posted.append(6)
    health.clear_rounds()
    isl._lead(0, posted, anchor, trace, template, live=posted)
    rec = health.recent_rounds()[-1]
    assert rec["quarantined"] == {"6": "norm_outlier"}
    assert rec["participation"] == round(5 / 6, 4)
    pub = dd._unpack(isl.store.get("diloco-outlier/round-1/anchor"),
                     {"params": template, "trace": template})
    assert np.abs(pub["params"]["w"]).max() < 10.0  # 100x never averaged


def test_gate_disabled_folds_nan(tmp_path, clean_rounds):
    """Negative control: delta_gate=False restores the pre-round-19
    behavior — the NaN reaches the anchor. This is exactly what the
    gate exists to prevent."""
    from serverless_learn_tpu.training import diloco_dcn as dd

    isl = _gate_island(tmp_path, run="nogate", delta_gate=False)
    template = {"w": np.zeros((2,), np.float32)}
    anchor = {"w": np.ones((2,), np.float32)}
    trace = {"w": np.zeros((2,), np.float32)}
    isl.store.put("diloco-nogate/round-0/delta-1",
                  dd._pack({"w": np.full((2,), np.nan, np.float32)}))
    isl._lead(0, [1], anchor, trace, template, live=[1])
    pub = dd._unpack(isl.store.get("diloco-nogate/round-1/anchor"),
                     {"params": template, "trace": template})
    assert not np.isfinite(pub["params"]["w"]).any()


def test_quorum_closes_round_without_straggler(coordinator, devices, clean_rounds):
    """participation='quorum' at 2/3: the leader closes each round once
    two islands delivered instead of waiting out the slow third; the
    straggler still completes every round (it adopts each anchor late),
    and the round records show partial participation."""
    from serverless_learn_tpu.telemetry import health

    rounds = 3
    with tempfile.TemporaryDirectory() as root:
        store = LocalStore(root)
        islands = [_island(_cfg(), store, coordinator, "quorum", i,
                           participation="quorum", quorum_fraction=0.6,
                           round_timeout_s=60.0)
                   for i in range(3)]
        victim = max(islands, key=lambda i: i.agent.worker_id)

        def slow_source(wid, _inner=victim.source_factory):
            src = _inner(wid)

            def gen():
                while True:
                    time.sleep(0.25)
                    yield next(src)

            return gen()

        victim.source_factory = slow_source
        health.clear_rounds()
        reports = _run_threads(islands, rounds)
    for rep in reports:
        assert rep.rounds_done == rounds, rep
    # A 60s round timeout with a slow third island: only the quorum
    # close explains finishing, and the leader recorded the shortfall.
    recs = health.recent_rounds()
    assert any(r.get("participation", 1.0) < 1.0 for r in recs), recs


def _dcn_diloco_bytes():
    """Cumulative (wire, logical) diloco byte counters from the global
    registry — tests assert on DELTAS around a leg."""
    from serverless_learn_tpu.telemetry import get_registry

    snap = get_registry().snapshot()
    wire = logical = 0.0
    for name, key in (("slt_dcn_bytes_total", "wire"),
                      ("slt_dcn_logical_bytes_total", "logical")):
        for series in (snap.get(name) or {}).get("series", []):
            if series["labels"].get("consumer") == "diloco":
                if key == "wire":
                    wire += series["value"]
                else:
                    logical += series["value"]
    return wire, logical


def test_quantized_wire_shrinks_bytes_and_preserves_training(
        coordinator, devices):
    """Round 20 acceptance on REAL islands: the int8 leg moves >= 3.5x
    fewer store bytes than the f32 leg for the same protocol traffic
    (measured both by a counting store and by the
    slt_dcn_bytes_total{consumer=diloco} deltas), and lands on params
    within quantization tolerance of the f32 leg's — identical data, so
    the wire codec is the only difference."""
    import itertools

    rounds = 3
    cfg = _cfg()
    batch = _fixed_batch(cfg, 300)

    def leg(root, run, **kw):
        store = CountingStore(root)
        isl = _island(cfg, store, coordinator, run, 0, inner_steps=2, **kw)
        isl.source_factory = lambda wid: itertools.repeat(batch)
        w0, l0 = _dcn_diloco_bytes()
        rep = isl.run_rounds(rounds)
        w1, l1 = _dcn_diloco_bytes()
        assert rep.rounds_done == rounds
        assert all(np.isfinite(l) for l in rep.losses)
        return isl.final_params, store, (w1 - w0, l1 - l0)

    with tempfile.TemporaryDirectory() as root:
        p32, s32, (wire32, logical32) = leg(root + "/a", "wf32")
        p8, s8, (wire8, logical8) = leg(root + "/b", "wint8",
                                        wire_dtype="int8")
    # >= 3.5x fewer bytes on the wire, same logical bytes represented
    assert s32.put_bytes > 3.5 * s8.put_bytes, (s32.put_bytes,
                                                s8.put_bytes)
    assert s32.get_bytes > 3.5 * s8.get_bytes, (s32.get_bytes,
                                                s8.get_bytes)
    assert wire32 > 3.5 * wire8, (wire32, wire8)
    assert abs(logical32 - logical8) < 0.01 * logical32
    # same training signal within codec tolerance: the two trajectories
    # stay globally close (the rounds compound tiny per-round errors)
    # and score the SAME data within 5% of the init-loss scale — the
    # repo's standard parity bar.
    sq = sum(float(np.square(a - b).sum()) for a, b in zip(
        jax.tree_util.tree_leaves(p32), jax.tree_util.tree_leaves(p8)))
    norm = sum(float(np.square(a).sum())
               for a in jax.tree_util.tree_leaves(p32))
    assert np.sqrt(sq / norm) < 0.02, np.sqrt(sq / norm)

    from serverless_learn_tpu.models.registry import get_model
    from serverless_learn_tpu.training.train_step import build_trainer

    bundle = get_model(cfg.model, **cfg.model_overrides)
    tr = build_trainer(cfg, mesh=make_mesh(cfg.mesh,
                                           devices=[jax.devices()[0]]))
    init = float(jax.device_get(bundle.loss_fn(
        jax.device_get(tr.init().params), batch)[0]))
    l32 = float(jax.device_get(bundle.loss_fn(p32, batch)[0]))
    l8 = float(jax.device_get(bundle.loss_fn(p8, batch)[0]))
    assert abs(l32 - l8) < 0.05 * init, (l32, l8, init)


def test_quantized_anchor_publish_reuses_packed_blob(tmp_path,
                                                     clean_rounds):
    """Satellite: a republished-unchanged anchor (all deltas
    quarantined) reuses the blob fetched for that round — one serialize,
    N sends — and the saved serialization is counted."""
    from serverless_learn_tpu.training import diloco_dcn as dd

    class Counter:
        n = 0

        def inc(self, v=1):
            self.n += v

    isl = _gate_island(tmp_path, run="reuse")
    isl._m_pack_saved = Counter()
    template = {"w": np.zeros((4,), np.float32)}
    anchor = {"w": np.ones((4,), np.float32)}
    trace = {"w": np.zeros((4,), np.float32)}
    isl._publish(0, anchor, trace, 0)
    blob0 = isl.store.get("diloco-reuse/round-0/anchor")
    pub = isl._fetch_anchor(0, template)  # seeds the packed-blob cache
    # only a poisoned delta posts: the anchor republishes UNCHANGED
    isl.store.put("diloco-reuse/round-0/delta-1",
                  dd._pack({"w": np.full((4,), np.nan, np.float32)}))
    isl._lead(0, [1], pub["params"], pub["trace"], template, live=[1])
    assert isl._m_pack_saved.n == 1
    assert isl.store.get("diloco-reuse/round-1/anchor") == blob0


def test_nonfinite_delta_ships_uncompressed_and_is_quarantined(
        tmp_path, clean_rounds):
    """The codec REFUSES NaN (typed error); the island falls back to the
    uncompressed encoding so the leader's gate still sees the NaN and
    quarantines the worker — quarantine semantics survive quantization."""
    from serverless_learn_tpu.telemetry import health
    from serverless_learn_tpu.training import diloco_dcn as dd
    from serverless_learn_tpu.training import wire_codec as wc

    isl = _gate_island(tmp_path, run="wq", wire_dtype="int8")
    template = {"w": np.zeros((4,), np.float32)}
    bad = {"w": np.full((4,), np.nan, np.float32)}
    blob = isl._encode_delta(0, bad)
    assert wc.blob_dtype(blob) == "float32"  # the fallback, not int8
    assert np.isnan(dd._unpack(blob, template)["w"]).all()
    good = {"w": np.full((4,), 0.25, np.float32)}
    gblob = isl._encode_delta(0, good)
    assert wc.blob_dtype(gblob) == "int8"
    # end to end through the gate: quantized clean delta accepted at its
    # dequantized value, NaN worker quarantined
    isl.store.put("diloco-wq/round-0/delta-1", gblob)
    isl.store.put("diloco-wq/round-0/delta-2", blob)
    anchor = {"w": np.ones((4,), np.float32)}
    trace = {"w": np.zeros((4,), np.float32)}
    health.clear_rounds()
    isl._lead(0, [1, 2], anchor, trace, template, live=[1, 2])
    rec = health.recent_rounds()[-1]
    assert rec["quarantined"] == {"2": "nonfinite"}
    pub = dd._unpack(isl.store.get("diloco-wq/round-1/anchor"),
                     {"params": template, "trace": template})
    np.testing.assert_allclose(pub["params"]["w"], 0.75, atol=0.01)


def test_late_joiner_adopts_current_anchor(coordinator, devices):
    """An island started after round 1 joins at the CURRENT round (not 0)
    and contributes deltas from there on."""
    rounds = 4
    with tempfile.TemporaryDirectory() as root:
        store = LocalStore(root)
        first = _island(_cfg(), store, coordinator, "join", 0)
        late_holder = {}

        def run_first():
            late_holder["first"] = first.run_rounds(rounds)

        t1 = threading.Thread(target=run_first)
        t1.start()
        while first.report.rounds_done < 1:
            time.sleep(0.05)
        late = _island(_cfg(), store, coordinator, "join", 1)
        late_rep = late.run_rounds(2)
        t1.join(timeout=300)
    assert late_rep.joined_at_round >= 1, late_rep
    assert late_rep.rounds_done == 2
    assert late_holder["first"].rounds_done == rounds
