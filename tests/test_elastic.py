"""Elastic training: membership changes (join / crash) drive checkpoint +
mesh re-formation mid-run — the fault-injection tests SURVEY.md §4/§5 call
for. A second WorkerAgent stands in for another worker host; its chips grow
the world, its death (stopped heartbeats -> lease eviction) shrinks it."""

import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    ControlConfig, DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
    TrainConfig)
from serverless_learn_tpu.control.client import WorkerAgent
from serverless_learn_tpu.control.daemons import start_coordinator
from serverless_learn_tpu.training.checkpoint import LocalStore
from serverless_learn_tpu.training.elastic import ElasticTrainer


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def coordinator():
    port = _free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=800, sweep_ms=100)
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


def _config(num_steps):
    return ExperimentConfig(
        model="mlp_mnist",
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
        train=TrainConfig(batch_size=16, num_steps=num_steps),
        data=DataConfig(),
        control=ControlConfig(heartbeat_interval_ms=100),
        model_overrides={"dtype": jnp.float32},
    )


def test_solo_run_without_coordinator(tmp_path, devices):
    et = ElasticTrainer(_config(5), LocalStore(str(tmp_path)))
    state, losses = et.run()
    assert len(losses) == 5
    assert int(jax.device_get(state.step)) == 5
    assert [t.n_devices for t in et.transitions] == [8]


def test_join_grows_mesh_and_crash_shrinks_it(tmp_path, coordinator, devices):
    cfg = _config(num_steps=2000)  # effectively "until we stop it"
    et = ElasticTrainer(cfg, LocalStore(str(tmp_path)),
                        coordinator_addr=coordinator,
                        advertise_addr="trainer:1", n_chips=4)

    result = {}

    def train():
        result["out"] = et.run()

    t = threading.Thread(target=train, daemon=True)
    t.start()

    def wait_for(pred, timeout=20.0, what=""):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.05)
        raise TimeoutError(f"waiting for {what}; transitions={et.transitions}")

    # phase 1: solo trainer on 4 devices
    wait_for(lambda: len(et.transitions) >= 1, what="first mesh")
    assert et.transitions[0].n_devices == 4

    # phase 2: a second worker joins with 4 chips -> world grows to 8
    joiner = WorkerAgent(coordinator, "joiner:1", name="joiner", n_chips=4,
                         heartbeat_interval_ms=100).start()
    wait_for(lambda: len(et.transitions) >= 2, what="re-mesh after join")
    wait_for(lambda: any(tr.n_devices == 8 for tr in et.transitions[1:]),
             timeout=5, what="8-device mesh")

    step_at_join = et.transitions[1].step
    assert step_at_join > 0, "must have trained before the join"

    # phase 3: the joiner crashes (heartbeats stop; no deregister) ->
    # lease eviction -> world shrinks back to 4
    joiner._stop.set()  # simulate crash: kill the heartbeat thread only
    joiner._thread.join()
    n_before = len(et.transitions)
    wait_for(lambda: len(et.transitions) > n_before and
             et.transitions[-1].n_devices == 4,
             what="re-mesh after eviction")

    # let it train a bit in the shrunken world, then finish gracefully
    time.sleep(0.5)
    et.request_stop()
    t.join(timeout=30)
    # training never went backwards and stayed finite
    assert result, "run() did not return"
    _, losses = result["out"]
    assert all(np.isfinite(l) for l in losses)
    steps = [tr.step for tr in et.transitions]
    assert steps == sorted(steps), f"step went backwards across re-mesh: {steps}"
    sizes = [tr.n_devices for tr in et.transitions]
    assert 8 in sizes and sizes[0] == 4 and sizes[-1] == 4, sizes


def test_state_survives_remesh_exactly(tmp_path, coordinator, devices):
    """Params after (train 3, re-mesh 4->8, train 0 more) equal params after
    plain (train 3): the checkpoint/restore across mesh shapes is lossless."""
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.training.checkpoint import Checkpointer
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = _config(3)
    mesh4 = make_mesh(MeshConfig(dp=4), devices=devices[:4])
    tr4 = build_trainer(cfg.override(mesh=MeshConfig(dp=4)), mesh=mesh4)
    state = tr4.init()
    src = iter(SyntheticSource(tr4.bundle.make_batch, cfg.data, 16, seed=3))
    for _ in range(3):
        state, _ = tr4.step(state, tr4.shard_batch(next(src)))
    ck = Checkpointer(LocalStore(str(tmp_path)), async_save=False)
    ck.save(state)

    mesh8 = make_mesh(MeshConfig(dp=8), devices=devices)
    tr8 = build_trainer(cfg.override(mesh=MeshConfig(dp=8)), mesh=mesh8)
    restored = ck.restore(tr8.init(), shardings=tr8.state_shardings)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state.params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the 8-way world can actually step from it
    restored, m = tr8.step(restored, tr8.shard_batch(next(src)))
    assert np.isfinite(float(m["loss"]))
