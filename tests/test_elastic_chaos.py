"""Elastic chaos soak (VERDICT r2 item 10).

A randomized, seeded join/kill schedule over the multi-host elastic harness
(tests/emh_host.py): hosts join and are SIGKILLed (whole process group, so
wedgeable inners die with their supervisors) at random points until the run
has lived through >= 6 world generations. Invariants asserted per schedule:

* no supervisor wedge — every surviving host EXITS with a clean RESULT
  (status complete) within the deadline;
* the committed step (store LATEST) is MONOTONE throughout the churn —
  kills roll back only to the last commit, never backwards in the store;
* bounded rollback — each re-formed generation resumes within
  checkpoint_every + 1 steps of the farthest committed progress;
* the loss trajectory survives every kill: the learnable synthetic task
  ends well below where it started, across all the restarts.
"""

import json
import os
import signal
import subprocess
import sys
import time
import random

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOST = os.path.join(REPO, "tests", "emh_host.py")

STEPS = 120
CKPT_EVERY = 4


def _spawn_host(label, coordinator, store_root, steps=STEPS):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return subprocess.Popen(
        [sys.executable, "-u", HOST,
         "--coordinator", coordinator, "--store-root", store_root,
         "--label", label, "--steps", str(steps),
         "--min-hosts", "1", "--ckpt-every", str(CKPT_EVERY),
         "--step-delay", "0.3", "--chips", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True, cwd=REPO)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (IOError, OSError, ValueError):
        return None


def _result(proc, label):
    out, err = proc.communicate(timeout=60)
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(
        f"host {label} produced no RESULT (rc={proc.returncode})\n"
        f"--- stderr ---\n{err[-3000:]}")


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_chaos_schedule(tmp_path, seed):
    from serverless_learn_tpu.control.daemons import start_coordinator

    import socket as socket_mod

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = start_coordinator(port=port, lease_ttl_ms=1200, sweep_ms=200)
    coordinator = f"127.0.0.1:{port}"
    store = str(tmp_path / "store")
    latest_path = os.path.join(store, "emh-t", "LATEST")
    form_path = os.path.join(store, "emh-t", "FORM")
    rng = random.Random(seed)

    procs = {}
    next_label = 0

    def spawn():
        nonlocal next_label
        label = f"h{next_label}"
        next_label += 1
        procs[label] = _spawn_host(label, coordinator, store)
        return label

    def live():
        return [l for l, p in procs.items() if p.poll() is None]

    committed_seen = [-1]
    gens_seen = set()

    def observe():
        """Poll invariant state; assert monotone committed step."""
        latest = _read_json(latest_path)
        if latest is not None:
            step = int(latest["step"])
            assert step >= committed_seen[-1], (
                f"committed step went BACKWARDS: {committed_seen[-1]} -> "
                f"{step}")
            if step != committed_seen[-1]:
                committed_seen.append(step)
        form = _read_json(form_path)
        if form is not None:
            gens_seen.add(form["gen"])

    def wait_progress(min_new_commits, timeout):
        """Let the system breathe between chaos events: wait for the
        committed step to advance (or the run to finish)."""
        start = committed_seen[-1]
        deadline = time.time() + timeout
        while time.time() < deadline:
            observe()
            if committed_seen[-1] >= STEPS:
                return
            if committed_seen[-1] >= start + min_new_commits:
                return
            assert live(), "every host died without completing the run"
            time.sleep(0.3)
        raise AssertionError(
            f"no committed progress within {timeout}s "
            f"(stuck at {committed_seen[-1]}, live={live()}, "
            f"gens={sorted(gens_seen)})")

    try:
        spawn()
        spawn()
        wait_progress(2, timeout=240)

        # Randomized churn until we have lived >= 6 generations. Events
        # pace on the GENERATION counter, not just commits: under load the
        # lease sweep coalesces near-simultaneous membership changes into
        # one re-formation, so a fixed event budget paced on commits alone
        # can run out with fewer generations than events (seed 23 hit
        # exactly that). After each event we wait (bounded) for the world
        # to actually re-form before scheduling the next one; a coalesced
        # event just falls through and the loop tries again.
        events = 0
        while (len(gens_seen) < 6 and committed_seen[-1] < STEPS
               and events < 30):
            events += 1
            gen_before = max(gens_seen, default=0)
            alive = live()
            if len(alive) <= 1 or (len(alive) < 4 and rng.random() < 0.55):
                spawn()
            else:
                victim = procs[rng.choice(alive)]
                try:
                    os.killpg(victim.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
            gen_deadline = time.time() + 45
            while (time.time() < gen_deadline
                   and committed_seen[-1] < STEPS
                   and max(gens_seen, default=0) == gen_before):
                observe()
                assert live(), "every host died without completing the run"
                time.sleep(0.3)
            # Breathe: commits must keep flowing after every event.
            wait_progress(1, timeout=240)

        # Drain to completion.
        deadline = time.time() + 360
        while committed_seen[-1] < STEPS and time.time() < deadline:
            observe()
            assert live(), "every host died without completing the run"
            time.sleep(0.5)
        observe()
        assert committed_seen[-1] >= STEPS, (
            f"run never completed: committed {committed_seen[-1]}, "
            f"gens {sorted(gens_seen)}")
        assert len(gens_seen) >= 6, (
            f"schedule produced only {sorted(gens_seen)} generations")

        # Survivors exit cleanly with consistent generation records.
        results = []
        for label, p in procs.items():
            if p.poll() is None or p.returncode == 0:
                try:
                    results.append(_result(p, label))
                except AssertionError:
                    if p.returncode == -signal.SIGKILL:
                        continue  # a chaos victim, not a wedge
                    raise
        assert results, "no survivor produced a RESULT"
        finals = [r["generations"][-1] for r in results
                  if r["generations"]]
        assert any(g["status"] == "complete" and g["end_step"] == STEPS
                   for g in finals), finals

        losses = {}
        for r in results:
            losses.update({int(s): l for s, l in r["losses"]})
        for r in results:
            gens = [g for g in r["generations"] if g["start_step"] >= 0]
            for prev, nxt in zip(gens, gens[1:]):
                # Bounded rollback: a re-formed world resumes from a
                # committed step no older than one checkpoint interval
                # behind its predecessor's last report.
                if prev["end_step"] >= 0:
                    assert nxt["start_step"] >= prev["end_step"] \
                        - CKPT_EVERY - 1, (prev, nxt)
                assert nxt["start_step"] >= prev["start_step"], (prev, nxt)
        # Per-generation resumed-loss invariant (round-3 verdict #9): at
        # every re-formation boundary the resumed world's first losses
        # must CONTINUE the committed trajectory — within the rollback
        # window's own variation — not restart from a stale state (which
        # would jump back toward the ~1.5 init loss and silently re-learn).
        steps_sorted = sorted(losses)
        for r in results:
            gens = [g for g in r["generations"] if g["start_step"] > 0]
            for g in gens:
                s = g["start_step"]
                before = [losses[t] for t in steps_sorted
                          if s - (CKPT_EVERY + 2) <= t < s]
                after = [losses[t] for t in steps_sorted if s <= t < s + 3]
                if before and after:
                    assert min(after) <= max(before) * 1.35 + 0.05, (
                        f"gen {g['gen']} resumed at {s} with losses "
                        f"{after} vs pre-kill committed {before}")
        # The learnable task trained through all of it.
        first = [losses[s] for s in steps_sorted[:5]]
        last = [losses[s] for s in steps_sorted[-5:]]
        assert sum(last) / len(last) < 0.7 * (sum(first) / len(first)), (
            first, last)
    finally:
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
        coord.terminate()
        coord.wait(timeout=5)
