"""Elastic worlds honor the configured mesh (VERDICT r2 item 2).

Round 2's elastic paths hardcoded dp-only meshes, silently discarding the
config — an 8B state cannot fit dp-only, so the Llama-8B LoRA elastic rung
was unrunnable. Round 3 threads ``config.scale_mesh`` through both elastic
paths: model axes (tp/pp/sp/ep) stay fixed, fsdp is a memory floor, dp
stretches with the world, and unsatisfiable shapes are rejected loudly.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig,
    UnsatisfiableMeshError, scale_mesh)
from serverless_learn_tpu.training.checkpoint import LocalStore
from serverless_learn_tpu.training.elastic import ElasticTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- scale_mesh unit behavior -------------------------------------------------


def test_trivial_mesh_scales_dp_only():
    for n in (1, 3, 8):
        assert scale_mesh(MeshConfig(), n) == MeshConfig(dp=n)
    # a configured dp value is elastic — overridden by the world size
    assert scale_mesh(MeshConfig(dp=4), 8) == MeshConfig(dp=8)


def test_model_axes_fixed_dp_stretches():
    base = MeshConfig(tp=2)
    assert scale_mesh(base, 2) == MeshConfig(dp=1, tp=2)
    assert scale_mesh(base, 8) == MeshConfig(dp=4, tp=2)
    base = MeshConfig(tp=2, pp=2)
    assert scale_mesh(base, 8) == MeshConfig(dp=2, tp=2, pp=2)


def test_fsdp_is_a_memory_floor():
    base = MeshConfig(fsdp=4, tp=2)
    # exactly the floor
    assert scale_mesh(base, 8) == MeshConfig(dp=1, fsdp=4, tp=2)
    # growth beyond the floor goes to dp first
    assert scale_mesh(base, 16) == MeshConfig(dp=2, fsdp=4, tp=2)
    # plane of 6 has no divisor in [4, 6] except 6: fsdp grows past the floor
    assert scale_mesh(MeshConfig(fsdp=4), 6) == MeshConfig(dp=1, fsdp=6)


def test_unsatisfiable_shapes_rejected_loudly():
    with pytest.raises(UnsatisfiableMeshError):
        scale_mesh(MeshConfig(tp=2), 3)  # not a multiple of the model axes
    with pytest.raises(UnsatisfiableMeshError):
        scale_mesh(MeshConfig(fsdp=4, tp=2), 4)  # plane 2 under the floor
    with pytest.raises(UnsatisfiableMeshError):
        scale_mesh(MeshConfig(tp=2), 0)


def test_llama8b_elastic_config_mesh_honored():
    """The exact config the verdict named: fsdp=4,tp=2 must survive elastic
    scaling instead of being discarded for dp-only."""
    with open(os.path.join(REPO, "configs", "llama8b_lora_elastic.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    assert cfg.mesh == MeshConfig(fsdp=4, tp=2)
    assert scale_mesh(cfg.mesh, 8) == MeshConfig(dp=1, fsdp=4, tp=2)
    assert scale_mesh(cfg.mesh, 32) == MeshConfig(dp=4, fsdp=4, tp=2)
    with pytest.raises(UnsatisfiableMeshError):
        scale_mesh(cfg.mesh, 4)  # half a pod slice below the memory floor


# -- single-host elastic trainer ---------------------------------------------


def _config(num_steps, mesh):
    return ExperimentConfig(
        model="mlp_mnist",
        mesh=mesh,
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
        train=TrainConfig(batch_size=16, num_steps=num_steps),
        data=DataConfig(),
        model_overrides={"dtype": jnp.float32},
    )


def test_solo_trainer_forms_config_mesh(tmp_path, devices):
    et = ElasticTrainer(_config(3, MeshConfig(fsdp=2, tp=2)),
                        LocalStore(str(tmp_path)))
    state, losses = et.run()
    assert len(losses) == 3 and np.isfinite(losses).all()
    assert int(jax.device_get(state.step)) == 3
    assert et.transitions[0].mesh == {"dp": 2, "fsdp": 2, "tp": 2}


def test_solo_trainer_trims_unsatisfiable_world(tmp_path, devices):
    """5 visible devices with tp=2: the trainer idles one device rather than
    dying (or silently dropping tp)."""
    et = ElasticTrainer(_config(2, MeshConfig(tp=2)), LocalStore(str(tmp_path)),
                        device_policy=lambda peers, devs: list(devs)[:5])
    state, losses = et.run()
    assert len(losses) == 2
    assert et.transitions[0].n_devices == 4
    assert et.transitions[0].mesh == {"dp": 2, "tp": 2}


def test_solo_trainer_unsatisfiable_world_raises(tmp_path, devices):
    """A memory floor no local subset can satisfy must be a loud failure."""
    et = ElasticTrainer(_config(2, MeshConfig(fsdp=16)),
                        LocalStore(str(tmp_path)))
    with pytest.raises(UnsatisfiableMeshError):
        et.run()


# -- multi-host active-set selection ------------------------------------------


def test_active_ids_subset_sum(tmp_path):
    """The supervisor's world selection handles heterogeneous chip counts:
    it maximizes the satisfiable chip TOTAL over member subsets (not just
    id-ordered prefixes), deterministically, preferring lower ids on ties."""
    import socket as socket_mod

    from serverless_learn_tpu.control.daemons import start_coordinator
    from serverless_learn_tpu.training.elastic_multihost import (
        ElasticHostSupervisor)

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = start_coordinator(port=port, lease_ttl_ms=5000, sweep_ms=500)
    try:
        def sup(mesh, min_hosts=1):
            return ElasticHostSupervisor(
                _config(2, mesh), LocalStore(str(tmp_path)),
                f"127.0.0.1:{port}", min_hosts=min_hosts)

        tp2 = sup(MeshConfig(tp=2))
        # heterogeneous: prefixes total 1, 3, 5 (all odd) but {2,3} = 4 works
        assert tp2._active_ids([1, 2, 3], {1: 1, 2: 2, 3: 2}) == [2, 3]
        # homogeneous: lowest-id pair wins, third stands by
        assert tp2._active_ids([1, 2, 3], {1: 1, 2: 1, 3: 1}) == [1, 2]
        # everything usable -> everyone in
        assert tp2._active_ids([1, 2], {1: 2, 2: 2}) == [1, 2]
        # nothing satisfiable
        assert tp2._active_ids([1], {1: 1}) is None

        # fsdp floor: needs a subset totaling a multiple of 2 with plane >= 4
        f4 = sup(MeshConfig(fsdp=4, tp=2))
        assert f4._active_ids([1, 2, 3], {1: 4, 2: 3, 3: 4}) == [1, 3]
        assert f4._active_ids([1, 2], {1: 4, 2: 3}) is None

        # min_hosts constrains the subset size, not just the view size
        mh = sup(MeshConfig(tp=2), min_hosts=2)
        assert mh._active_ids([1, 2], {1: 2, 2: 2}) == [1, 2]
        assert mh._active_ids([1, 2], {1: 2, 2: 1}) is None  # {1} alone is big enough but lonely

        # Brute-force cross-check vs exhaustive subset enumeration: the DP
        # must return a VALID subset (distinct members, satisfiable total)
        # achieving the optimal total. (A 1-D backpointer version of this
        # DP once returned [3, 5, 19, 19] — a duplicated member whose real
        # total the mesh could not host.)
        import itertools
        import random as random_mod

        from serverless_learn_tpu.config import (
            UnsatisfiableMeshError as UME, scale_mesh as sm)

        rng = random_mod.Random(0)
        for mesh, min_hosts in ((MeshConfig(tp=4), 1),
                                (MeshConfig(fsdp=2, tp=2), 2)):
            s = sup(mesh, min_hosts=min_hosts)
            for trial in range(60):
                n = rng.randint(1, 6)
                ids = sorted(rng.sample(range(1, 40), n))
                chips = {i: rng.randint(1, 7) for i in ids}
                got = s._active_ids(ids, chips)
                best = -1
                for r in range(min_hosts, n + 1):
                    for combo in itertools.combinations(ids, r):
                        t = sum(chips[i] for i in combo)
                        try:
                            sm(mesh, t)
                        except UME:
                            continue
                        best = max(best, t)
                if best < 0:
                    assert got is None, (ids, chips, got)
                    continue
                assert got is not None, (ids, chips, best)
                assert len(set(got)) == len(got) >= min_hosts, (ids, chips, got)
                assert set(got) <= set(ids), (ids, chips, got)
                total = sum(chips[i] for i in got)
                sm(mesh, total)  # must not raise
                assert total == best, (ids, chips, got, total, best)
    finally:
        coord.terminate()
        coord.wait(timeout=5)


# -- ZeRO opt-state re-partitioning across worlds (round 18) ------------------


def test_quantized_remesh_stream_preserves_values(tmp_path, devices):
    """Round 20: with elastic.remesh_wire_dtype=int8 a REAL mid-run
    remesh (8 -> 4 devices) streams the drained state as one quantized
    blob instead of a full-precision checkpoint save; the restored state
    matches the drained state within codec tolerance (and the
    numerics_fingerprint reason=remesh_restore trail records it), while
    the durable final checkpoint stays bit-exact through the untouched
    CRC-verified path and the transient stream is cleaned up."""
    import json as json_mod

    from serverless_learn_tpu.config import ElasticConfig, NumericsConfig
    from serverless_learn_tpu.telemetry import tracing as ttrace

    events = str(tmp_path / "events.jsonl")
    ttrace.init_tracing(node="remesh-wire-test", events_log=events,
                        install_flight=False)
    cfg = _config(4, MeshConfig()).override(
        elastic=ElasticConfig(remesh_wire_dtype="int8"),
        numerics=NumericsConfig(enabled=True))
    store = LocalStore(str(tmp_path / "store"))
    et = ElasticTrainer(cfg, store)

    # Trigger a real remesh after step 2 and shrink the world to 4
    # devices for the successor epoch; capture the drained params and
    # what the stream restore produced.
    snap, cap = {}, {}
    calls = {"n": 0}
    orig_note = et.ckpt.note_state

    def note(state):
        calls["n"] += 1
        if calls["n"] == 3:  # restore-note + 2 step-notes
            snap["params"] = jax.tree_util.tree_map(
                lambda l: np.asarray(jax.device_get(l), np.float32),
                state.params)
            et._remesh.set()
        return orig_note(state)

    et.ckpt.note_state = note
    et.device_policy = (
        lambda peers, devs: list(devs)[:4 if snap else 8])
    orig_load = et._load_remesh_stream

    def load(trainer):
        cap["stream"] = orig_load(trainer)
        return cap["stream"]

    et._load_remesh_stream = load
    state, losses = et.run()

    assert len(losses) == 4 and np.isfinite(losses).all()
    assert [t.n_devices for t in et.transitions] == [8, 4]
    # the stream carried the drained step-2 state
    assert cap["stream"] is not None
    step, host_state = cap["stream"]
    assert step == 2
    engaged = False
    for a, b in zip(jax.tree_util.tree_leaves(snap["params"]),
                    jax.tree_util.tree_leaves(host_state.params)):
        b = np.asarray(b, np.float32)
        amax = float(np.abs(a).max()) or 1.0
        # within codec tolerance (per-value bound is block-max/127;
        # bound leaf-wide by the leaf max), and NOT bit-exact — the
        # quantizer really ran
        assert float(np.abs(a - b).max()) <= amax / 64, "out of tolerance"
        engaged = engaged or not np.array_equal(a, b)
    assert engaged, "stream was bit-exact: codec never engaged"
    # transient stream cleaned up at the final (durable, exact) save...
    assert not store.exists("elastic/remesh-stream")
    # ...and that save restores bit-exactly through the verified path
    assert et.ckpt.latest_step() == 4
    final_host = jax.tree_util.tree_map(
        lambda l: np.asarray(jax.device_get(l)), state.params)
    restored = type(et.ckpt)(store, name="elastic",
                             sharded=True).restore_params_host()
    for a, b in zip(jax.tree_util.tree_leaves(final_host),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(a, b)
    # telemetry: dcn_wire remesh records both directions + fingerprints
    # at both world formations (the second one over the stream restore)
    with open(events) as f:
        recs = [json_mod.loads(l) for l in f if l.strip()]
    wires = [r for r in recs if r.get("event") == "dcn_wire"
             and r.get("consumer") == "remesh"]
    assert {r["direction"] for r in wires} == {"tx", "rx"}
    tx = [r for r in wires if r["direction"] == "tx"][0]
    assert tx["logical_bytes"] > 3 * tx["wire_bytes"]
    fps = [r for r in recs if r.get("event") == "numerics_fingerprint"
           and r.get("reason") == "remesh_restore"]
    assert len(fps) >= 2


def test_zero_opt_state_repartitions_across_worlds(tmp_path, devices):
    """An elastic worker training with zero_stage=1 re-partitions its
    dp-sharded optimizer state when the world (and so dp) changes: the
    8-device world's 1/8 slices restore into the 4-device successor's
    1/4 slices through the ordinary drain->save->remesh->restore cycle,
    with the round-15 verify/fallback machinery untouched."""
    from serverless_learn_tpu.training.zero import bytes_per_chip

    def zcfg(num_steps):
        cfg = _config(num_steps, MeshConfig())
        return cfg.override(train=TrainConfig(
            batch_size=16, num_steps=num_steps, zero_stage=1))

    store = LocalStore(str(tmp_path))
    et8 = ElasticTrainer(zcfg(2), store)
    state8, losses8 = et8.run()
    assert len(losses8) == 2 and np.isfinite(losses8).all()
    assert et8.transitions[0].mesh == {"dp": 8}
    bytes8 = bytes_per_chip(state8.opt_state)

    et4 = ElasticTrainer(zcfg(4), store,
                         device_policy=lambda peers, devs: list(devs)[:4])
    state4, losses4 = et4.run()
    assert len(losses4) == 2 and np.isfinite(losses4).all()
    assert et4.transitions[0].mesh == {"dp": 4}
    assert int(jax.device_get(state4.step)) == 4
    # Same logical state, twice the per-chip slice: dp 8 -> 4.
    bytes4 = bytes_per_chip(state4.opt_state)
    assert 1.6 * bytes8 < bytes4 < 2.4 * bytes8, (bytes8, bytes4)
    # And a moment leaf is physically a 1/4 slice in the new world.
    lead = [l for l in jax.tree_util.tree_leaves(state4.opt_state)
            if getattr(l, "ndim", 0) == 2 and l.shape[0] % 8 == 0][0]
    assert {s.data.shape[0] for s in lead.addressable_shards} == \
        {lead.shape[0] // 4}
