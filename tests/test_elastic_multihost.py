"""Multi-host elastic re-meshing (VERDICT round 1 item 1, the top ask).

Process-level: N real OS processes, each a supervisor + inner-trainer chain
(training/elastic_multihost.py), a real native coordinator for membership,
and a shared local store for rendezvous + sharded checkpoints. The scenario
is the one the verdict prescribes: a 2-process world grows to 3 on a join,
then shrinks back to 2 on a SIGKILL, with step continuity and decreasing
loss asserted across both transitions.

Each host process gets 2 virtual CPU devices, so world sizes 2/3/2 exercise
4-, 6- and 4-device global meshes with restore-time resharding in between.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOST = os.path.join(REPO, "tests", "emh_host.py")


def _spawn_host(label, coordinator, store_root, min_hosts, steps=60,
                step_delay=0.35, chips=2, mesh=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={chips}"
    cmd = [sys.executable, "-u", HOST,
           "--coordinator", coordinator, "--store-root", store_root,
           "--label", label, "--steps", str(steps),
           "--min-hosts", str(min_hosts), "--ckpt-every", "4",
           "--step-delay", str(step_delay), "--chips", str(chips)]
    if mesh:
        cmd += ["--mesh", json.dumps(mesh)]
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True, cwd=REPO)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (IOError, OSError, ValueError):
        return None


def _wait_for(pred, timeout, what, poll=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {what}")


def _result(proc, label):
    out, err = proc.communicate(timeout=30)
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(
        f"host {label} produced no RESULT (rc={proc.returncode})\n"
        f"--- stdout ---\n{out[-2000:]}\n--- stderr ---\n{err[-3000:]}")


@pytest.mark.slow
def test_world_grows_then_survives_kill(tmp_path):
    from serverless_learn_tpu.control.daemons import start_coordinator

    import socket as socket_mod

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = start_coordinator(port=port, lease_ttl_ms=1200, sweep_ms=200)
    coordinator = f"127.0.0.1:{port}"
    store = str(tmp_path / "store")
    latest_path = os.path.join(store, "emh-t", "LATEST")
    form_path = os.path.join(store, "emh-t", "FORM")
    procs = []
    # The configured mesh the elastic worlds must honor (VERDICT r2 item 2):
    # fsdp is a memory floor, tp is fixed, dp stretches with the world.
    # 4 chips/host: world-2 = 8 devices -> dp2.fsdp2.tp2; world-3 = 12
    # devices -> dp3.fsdp2.tp2.
    MESH = {"fsdp": 2, "tp": 2}
    try:
        a = _spawn_host("A", coordinator, store, min_hosts=2, chips=4,
                        mesh=MESH)
        b = _spawn_host("B", coordinator, store, min_hosts=2, chips=4,
                        mesh=MESH)
        procs += [a, b]

        # Phase 1: the two hosts form a world and make committed progress.
        _wait_for(lambda: (_read_json(latest_path) or {}).get("step", -1) >= 4,
                  timeout=120, what="world-2 progress")
        form = _read_json(form_path)
        assert form and len(form["ids"]) == 2

        # Phase 2: a third host joins; survivors drain and re-form at 3.
        c = _spawn_host("C", coordinator, store, min_hosts=1, chips=4,
                        mesh=MESH)
        procs.append(c)
        _wait_for(lambda: len((_read_json(form_path) or {}).get("ids", []))
                  == 3, timeout=120, what="world-3 formation")
        step3 = (_read_json(latest_path) or {}).get("step", 0)
        _wait_for(lambda: (_read_json(latest_path) or {}).get("step", -1)
                  >= step3 + 8, timeout=120, what="world-3 progress")

        # Phase 3: SIGKILL the joiner's whole process tree (supervisor +
        # wedgeable inner). Lease eviction must shrink the world to 2.
        os.killpg(c.pid, signal.SIGKILL)
        c.wait(timeout=10)
        _wait_for(lambda: (lambda f: f and len(f["ids"]) == 2 and
                           f["gen"] > 2)(_read_json(form_path)),
                  timeout=120, what="post-kill world-2 re-formation")

        ra = _result(a, "A")
        rb = _result(b, "B")
        assert a.returncode == 0 and b.returncode == 0

        for r in (ra, rb):
            gens = [g for g in r["generations"] if g["start_step"] >= 0]
            worlds = [g["world"] for g in gens]
            # 2 -> 3 -> 2 (formation retries may interleave, but every
            # *formed* world must follow the membership trajectory)
            assert worlds[0] == 2, worlds
            assert 3 in worlds, worlds
            assert worlds[-1] == 2, worlds
            i3 = worlds.index(3)
            assert all(w == 2 for w in worlds[:i3]), worlds

            # Every formed world honored the CONFIGURED mesh: tp fixed,
            # fsdp at its floor, dp stretched to the world's chips — never
            # the old silent dp-only fallback.
            for g in gens:
                assert g["mesh"] == {"dp": g["world"], "fsdp": 2, "tp": 2}, g

            # Step continuity: each world resumes from a committed step of
            # its predecessor — never from scratch, never from the future.
            for prev, nxt in zip(gens, gens[1:]):
                if prev["end_step"] >= 0:
                    assert nxt["start_step"] <= prev["end_step"], (prev, nxt)
                assert nxt["start_step"] >= prev["start_step"], (prev, nxt)
            # The kill may roll back to the last commit, but by at most the
            # checkpoint interval (ckpt-every=4 plus the in-flight step).
            g3 = gens[i3]
            after = gens[i3 + 1:]
            assert after, "no world formed after the kill"
            if g3["end_step"] >= 0:  # inner reported before wedging
                assert after[0]["start_step"] >= g3["end_step"] - 5

            # The run completed its full step budget.
            assert gens[-1]["status"] == "complete"
            assert gens[-1]["end_step"] == 60

            # Decreasing loss across both transitions: the learnable
            # synthetic task must show real training progress end to end.
            losses = dict(tuple(x) for x in r["losses"])
            first = [losses[s] for s in sorted(losses)[:5]]
            last = [losses[s] for s in sorted(losses)[-5:]]
            assert sum(last) / 5 < 0.6 * (sum(first) / 5), (first, last)

        # Both surviving hosts observed the same committed trajectory.
        assert ra["generations"][-1]["end_step"] == \
            rb["generations"][-1]["end_step"]
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
        coord.terminate()
        coord.wait(timeout=5)


@pytest.mark.slow
def test_unsatisfiable_join_stands_by_until_needed(tmp_path):
    """With tp=2 and 1-chip hosts, a 3rd host makes the chip total odd —
    unsatisfiable. The world must NOT fall back to dp-only (the r2 bug) or
    wedge: the joiner stands by as a hot spare, and when an active host is
    SIGKILLed the spare takes its place in the re-formed satisfiable world."""
    from serverless_learn_tpu.control.daemons import start_coordinator

    import socket as socket_mod

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = start_coordinator(port=port, lease_ttl_ms=1200, sweep_ms=200)
    coordinator = f"127.0.0.1:{port}"
    store = str(tmp_path / "store")
    latest_path = os.path.join(store, "emh-t", "LATEST")
    form_path = os.path.join(store, "emh-t", "FORM")
    MESH = {"tp": 2}
    procs = []
    try:
        a = _spawn_host("A", coordinator, store, min_hosts=2, chips=1,
                        mesh=MESH, steps=40)
        b = _spawn_host("B", coordinator, store, min_hosts=2, chips=1,
                        mesh=MESH, steps=40)
        procs += [a, b]
        _wait_for(lambda: (_read_json(latest_path) or {}).get("step", -1) >= 4,
                  timeout=120, what="world-2 progress")
        ids2 = (_read_json(form_path) or {}).get("ids")
        assert ids2 and len(ids2) == 2

        # The joiner makes the total 3 chips — unsatisfiable for tp=2. The
        # active pair must keep training (new FORMs stay 2-member) while the
        # spare waits.
        c = _spawn_host("C", coordinator, store, min_hosts=2, chips=1,
                        mesh=MESH, steps=40)
        procs.append(c)
        step_at_join = (_read_json(latest_path) or {}).get("step", 0)
        _wait_for(lambda: (_read_json(latest_path) or {}).get("step", -1)
                  >= step_at_join + 6, timeout=120,
                  what="progress with spare standing by")
        form = _read_json(form_path)
        assert form and len(form["ids"]) == 2, form

        # Kill active host A (whole process group): the spare must join the
        # next world so the run still completes on 2 hosts.
        os.killpg(a.pid, signal.SIGKILL)
        a.wait(timeout=10)
        _wait_for(lambda: (lambda f: f and f["ids"] != ids2
                           and len(f["ids"]) == 2)(_read_json(form_path)),
                  timeout=120, what="spare absorbed into re-formed world")

        rb = _result(b, "B")
        rc = _result(c, "C")
        assert b.returncode == 0 and c.returncode == 0
        for r in (rb, rc):
            gens = [g for g in r["generations"] if g["start_step"] >= 0]
            assert gens, r
            # every formed world is a tp=2 pair — never a dp-only fallback
            for g in gens:
                assert g["world"] == 2, gens
                assert g["mesh"] == {"tp": 2}, gens
            assert gens[-1]["status"] == "complete"
            assert gens[-1]["end_step"] == 40
        # the spare resumed from committed progress, not from scratch
        assert rc["generations"][0]["start_step"] >= 1, rc["generations"]
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
        coord.terminate()
        coord.wait(timeout=5)


@pytest.mark.slow
def test_single_host_world_completes(tmp_path):
    """Degenerate case: one host forms a world of 1 and trains to the step
    budget — the elastic path must not require peers."""
    from serverless_learn_tpu.control.daemons import start_coordinator

    import socket as socket_mod

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = start_coordinator(port=port, lease_ttl_ms=2000, sweep_ms=500)
    store = str(tmp_path / "store")
    try:
        a = _spawn_host("solo", f"127.0.0.1:{port}", store, min_hosts=1,
                        steps=6, step_delay=0.0)
        ra = _result(a, "solo")
        assert a.returncode == 0
        gens = ra["generations"]
        assert gens[-1]["status"] == "complete"
        assert gens[-1]["end_step"] == 6
        assert gens[-1]["world"] == 1
    finally:
        coord.terminate()
        coord.wait(timeout=5)
