"""Two concurrent elastic workers on one coordinator must coexist:
disjoint checkpoint namespaces (no clobbering) and disjoint data stripes
(shards divided by rank-in-membership) — VERDICT round 1 item 7.

The reference's workers all received the SAME 100 MB push
(``src/master.cc:220-237``); here concurrent workers divide the published
dataset between themselves and keep independent training state.
"""

import socket
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from serverless_learn_tpu.config import (
    ControlConfig, DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
    TrainConfig)
from serverless_learn_tpu.control.daemons import (
    start_coordinator, start_shard_server)
from serverless_learn_tpu.data.shard_client import ShardStreamSource
from serverless_learn_tpu.models.registry import get_model
from serverless_learn_tpu.training.checkpoint import LocalStore
from serverless_learn_tpu.training.elastic import ElasticTrainer


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def coordinator():
    port = _free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=800, sweep_ms=100)
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture()
def shard_server():
    port = _free_port()
    with tempfile.TemporaryDirectory() as root:
        proc = start_shard_server(port=port, root=root)
        yield f"127.0.0.1:{port}"
        proc.terminate()
        proc.wait(timeout=5)


def _config(num_steps, shard_addr=None, dataset=""):
    return ExperimentConfig(
        model="mlp_mnist",
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
        train=TrainConfig(batch_size=16, num_steps=num_steps),
        data=DataConfig(shard_server_addr=shard_addr or "", dataset=dataset),
        control=ControlConfig(heartbeat_interval_ms=100),
        model_overrides={"dtype": jnp.float32},
    )


@pytest.mark.slow
def test_two_workers_disjoint_namespaces_and_stripes(
        tmp_path, coordinator, shard_server, devices):
    from serverless_learn_tpu.data.shard_client import publish_from_bundle

    cfg = _config(30, shard_addr=shard_server, dataset="mw")
    bundle = get_model("mlp_mnist")
    publish_from_bundle(shard_server, "mw", bundle.make_batch, cfg.data,
                        num_records=512, records_per_shard=64)  # 8 shards

    stores = [LocalStore(str(tmp_path / "a")), LocalStore(str(tmp_path / "b"))]
    trainers = [
        ElasticTrainer(cfg, stores[i], coordinator_addr=coordinator,
                       name=f"w{i}", n_chips=4)
        for i in range(2)
    ]
    results = [None, None]
    errors = []

    def run(i):
        try:
            results[i] = trainers[i].run()
        except BaseException as e:  # surfaced below, not swallowed
            errors.append((i, e))

    # Staggered start so registration order (and so stripe ranks) is
    # deterministic: w0 -> rank 0, w1 -> rank 1.
    t0 = threading.Thread(target=run, args=(0,))
    t0.start()
    time.sleep(1.0)
    t1 = threading.Thread(target=run, args=(1,))
    t1.start()
    t0.join(timeout=180)
    t1.join(timeout=180)
    assert not errors, errors
    assert results[0] is not None and results[1] is not None

    # Independent progress, independent state.
    for i, (state, losses) in enumerate(results):
        assert int(jax.device_get(state.step)) == 30, f"worker {i}"
    # Checkpoints landed in disjoint namespaces (separate stores here;
    # the NAME provides the separation when they share one store).
    assert stores[0].list("w0"), "w0 checkpoint missing"
    assert stores[1].list("w1"), "w1 checkpoint missing"
    assert not stores[0].list("w1") and not stores[1].list("w0")

    # Both workers saw the 2-worker stripe at some point, with distinct
    # ranks — by the striping rule (shard i -> rank i % size) their shard
    # sets are disjoint.
    stripes0 = {t.stripe for t in trainers[0].transitions}
    stripes1 = {t.stripe for t in trainers[1].transitions}
    assert (0, 2) in stripes0, trainers[0].transitions
    assert (1, 2) in stripes1, trainers[1].transitions
    a = ShardStreamSource(shard_server, "mw", 16, dp_rank=0, dp_size=2)
    b = ShardStreamSource(shard_server, "mw", 16, dp_rank=1, dp_size=2)
    try:
        assert set(a._my_shards).isdisjoint(b._my_shards)
        assert set(a._my_shards) | set(b._my_shards) == set(range(8))
    finally:
        a.close()
        b.close()


@pytest.mark.slow
def test_same_name_refused(tmp_path, coordinator, devices):
    """The worker name is the checkpoint namespace: a second live worker
    under the same name must be refused atomically by the coordinator, not
    allowed to clobber."""
    cfg = _config(2000)
    first = ElasticTrainer(cfg, LocalStore(str(tmp_path)),
                           coordinator_addr=coordinator, name="dup")
    t = threading.Thread(target=first.run)
    t.start()
    try:
        deadline = time.time() + 10
        while not first.transitions and time.time() < deadline:
            time.sleep(0.05)
        assert first.transitions, "first worker never formed a mesh"
        second = ElasticTrainer(cfg, LocalStore(str(tmp_path)),
                                coordinator_addr=coordinator, name="dup",
                                name_wait_s=2.0)
        with pytest.raises(RuntimeError, match="already held"):
            second.run()
    finally:
        first.request_stop()
        t.join(timeout=60)


def test_restart_under_stable_name_succeeds_after_lease_sweep(
        tmp_path, coordinator, devices):
    """A crashed worker's replacement under the SAME stable name must get in
    once the dead lease is swept (the resume flow), within the retry
    window — a live holder is the only thing that may refuse it."""
    from serverless_learn_tpu.control.client import WorkerAgent

    # A "crashed" predecessor: registered exclusively, never heartbeats.
    ghost = WorkerAgent(coordinator, "g:0", name="stable",
                        heartbeat_interval_ms=10_000, exclusive_name=True)
    rep = ghost.client.register("g:0", "stable", 1, True)
    assert rep.ok
    cfg = _config(3)
    et = ElasticTrainer(cfg, LocalStore(str(tmp_path)),
                        coordinator_addr=coordinator, name="stable",
                        name_wait_s=10.0)
    t0 = time.time()
    state, losses = et.run()  # must wait out the 800ms lease, then proceed
    assert len(losses) == 3
    assert time.time() - t0 >= 0.5, "should have waited for the sweep"
    ghost.client.close()
