"""The elastic worker must stream the configured dataset from the shard
server — not silently train on synthetic data (regression: the CLI accepted
--shard-server/--dataset but ElasticTrainer ignored them)."""

import socket

import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.control.client import ShardClient
from serverless_learn_tpu.control.daemons import start_shard_server
from serverless_learn_tpu.training.checkpoint import LocalStore
from serverless_learn_tpu.training.elastic import ElasticTrainer


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_elastic_worker_streams_from_shard_server(devices, tmp_path):
    from serverless_learn_tpu.data.shard_client import publish_from_bundle
    from serverless_learn_tpu.models.registry import get_model

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    addr = f"127.0.0.1:{port}"
    try:
        bundle = get_model("mlp_mnist")
        data_cfg = DataConfig(dataset="mnist", shard_server_addr=addr)
        publish_from_bundle(addr, "mnist", bundle.make_batch, data_cfg,
                            num_records=512, records_per_shard=128)
        cfg = ExperimentConfig(
            model="mlp_mnist",
            mesh=MeshConfig(dp=8),
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
            train=TrainConfig(batch_size=64, num_steps=4),
            data=data_cfg,
        )
        et = ElasticTrainer(cfg, LocalStore(str(tmp_path / "ckpt")),
                            coordinator_addr=None)
        state, losses = et.run()
        assert len(losses) == 4
        c = ShardClient(addr)
        served = c.stats().bytes_served
        c.close()
        # Must exceed metadata traffic: 4 steps x 64 records of
        # (28*28*1 f32 image + i32 label) ~= 800 KB of shard payload. A
        # bare `> 0` would pass on the meta.json fetch alone.
        assert served > 200_000, (
            f"only {served} bytes served — worker didn't stream batches")
    finally:
        proc.terminate()
        proc.wait(timeout=5)
