"""Pallas flash attention vs dense reference (forward + gradients), run in
interpreter mode on CPU; the same kernel compiles for TPU (exercised by
bench.py on the real chip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.ops.attention import xla_attention
from serverless_learn_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(seed, B, T, H, D, K=None, dtype=jnp.float32):
    K = K or H
    rng = jax.random.PRNGKey(seed)
    q = jax.random.normal(rng, (B, T, H, D), dtype)
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, T, K, D), dtype)
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, T, K, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv(0, 2, 256, 2, 64)
    ref = xla_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_gqa():
    q, k, v = _qkv(1, 1, 256, 8, 32, K=2)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_dense(causal):
    q, k, v = _qkv(2, 1, 256, 2, 32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=causal) ** 2).sum()

    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_flash_fallback_on_untileable_shapes():
    # seq 100 isn't a multiple of the block size: silently uses dense path
    q, k, v = _qkv(3, 1, 100, 2, 16)
    out = flash_attention(q, k, v, causal=True)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("mesh_kw", [dict(dp=8), dict(dp=4, tp=2)])
def test_flash_sharded_train_step_matches_xla(devices, mesh_kw):
    """Under a live mesh, flash runs shard_mapped (batch/heads local) and
    must reproduce the GSPMD-partitioned dense path."""
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    def run(impl):
        cfg = ExperimentConfig(
            model="llama_tiny",
            model_overrides={"attention_impl": impl, "dtype": jnp.float32,
                             "max_seq_len": 128},
            mesh=MeshConfig(**mesh_kw),
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
            train=TrainConfig(batch_size=16, num_steps=2),
            data=DataConfig(seq_len=128),
        )
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=9)
        batch = trainer.shard_batch(next(iter(src)))
        losses = []
        for _ in range(2):
            state, metrics = trainer.step(state, batch)
            losses.append(float(jax.device_get(metrics["loss"])))
        return losses

    np.testing.assert_allclose(run("xla"), run("flash"), rtol=2e-5)


def test_flash_inside_pipeline_stage(devices):
    """flash inside a GPipe stage (enclosing shard_map) must run its local
    kernel instead of nesting shard_map over the same mesh (trace error)."""
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig(
        model="llama_tiny",
        model_overrides={"attention_impl": "flash", "dtype": jnp.float32,
                         "max_seq_len": 128, "pipeline": True,
                         "pipeline_microbatches": 2, "n_layers": 4},
        mesh=MeshConfig(dp=4, pp=2),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16, num_steps=1),
        data=DataConfig(seq_len=128),
    )
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=2)
    state, metrics = trainer.step(state, trainer.shard_batch(next(iter(src))))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_transformer_with_flash_impl():
    """llama_tiny forward with attention_impl='flash' (seq 256) matches the
    default dense implementation."""
    from serverless_learn_tpu.models.registry import get_model
    from serverless_learn_tpu.config import DataConfig

    b_flash = get_model("llama_tiny", attention_impl="flash",
                        dtype=jnp.float32, max_seq_len=256)
    b_dense = get_model("llama_tiny", dtype=jnp.float32, max_seq_len=256)
    import numpy as onp

    rng = onp.random.default_rng(0)
    batch = b_dense.make_batch(rng, DataConfig(seq_len=256), 2)
    params = b_dense.module.init(jax.random.PRNGKey(0), batch["tokens"])["params"]
    l_dense, _ = b_dense.loss_fn(params, batch)
    l_flash, _ = b_flash.loss_fn(params, batch)
    np.testing.assert_allclose(float(l_dense), float(l_flash), rtol=1e-4)
