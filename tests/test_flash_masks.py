"""Flash attention padding masks + Pallas backward (VERDICT round 1 item 4).

Covers the two kernel-resident padding mechanisms (arbitrary [B, S] masks
and suffix-padding kv_lengths), their gradients (the backward is a pair of
Pallas kernels, not an XLA scan), GQA without KV expansion, and the proof
that a BERT train step with a padding mask actually executes the flash
path instead of silently falling back to dense (the round-1 gap)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.ops.attention import xla_attention
from serverless_learn_tpu.ops.pallas.flash_attention import flash_attention


def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def _suffix_mask(lens, T):
    return (np.arange(T)[None, :] < np.asarray(lens)[:, None]).astype(np.int32)


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 256, 4, 64
    return tuple(_rand(rng, B, T, H, D) for _ in range(3))


def _check_grads(f_flash, f_dense, args, weight, tol=2e-4):
    gf = jax.grad(lambda *a: (f_flash(*a) * weight).sum(),
                  tuple(range(len(args))))(*args)
    gx = jax.grad(lambda *a: (f_dense(*a) * weight).sum(),
                  tuple(range(len(args))))(*args)
    for name, a, b in zip("qkv", gf, gx):
        err = float(jnp.abs(a - b).max())
        assert err < tol, f"d{name} err {err}"
        assert not bool(jnp.isnan(a).any())


@pytest.mark.parametrize("how", ["rows", "len"])
def test_padding_parity_and_grads(qkv, how):
    q, k, v = qkv
    B, T = q.shape[:2]
    lens = [T, 100]  # one full row, one padded row (incl. an empty K block)
    mask2 = _suffix_mask(lens, T)
    m4 = jnp.asarray(mask2)[:, None, None, :]
    w = jnp.asarray(mask2)[:, :, None, None]  # score only valid queries
    kwargs = (dict(mask=m4) if how == "rows"
              else dict(kv_lengths=jnp.asarray(lens, jnp.int32)))

    o_f = flash_attention(q, k, v, **kwargs)
    o_x = xla_attention(q, k, v, mask=m4)
    assert float(jnp.abs((o_f - o_x) * w).max()) < 1e-5
    _check_grads(lambda *a: flash_attention(*a, **kwargs),
                 lambda *a: xla_attention(*a, mask=m4), (q, k, v), w)


@pytest.mark.parametrize("how", ["rows", "len"])
def test_padding_composes_with_causal(qkv, how):
    q, k, v = qkv
    T = q.shape[1]
    lens = [200, 100]
    mask2 = _suffix_mask(lens, T)
    m4 = jnp.asarray(mask2)[:, None, None, :]
    w = jnp.asarray(mask2)[:, :, None, None]
    kwargs = (dict(mask=m4) if how == "rows"
              else dict(kv_lengths=jnp.asarray(lens, jnp.int32)))
    o_f = flash_attention(q, k, v, causal=True, **kwargs)
    o_x = xla_attention(q, k, v, causal=True, mask=m4)
    assert float(jnp.abs((o_f - o_x) * w).max()) < 1e-5


def test_non_suffix_rows_mask_is_exact(qkv):
    """The rows path handles arbitrary (non-contiguous) key masks — the
    case kv_lengths must NOT be used for."""
    q, k, v = qkv
    B, T = q.shape[:2]
    rng = np.random.default_rng(3)
    mask2 = (rng.random((B, T)) < 0.7).astype(np.int32)
    mask2[:, 0] = 1  # every query keeps at least one valid key
    m4 = jnp.asarray(mask2)[:, None, None, :]
    o_f = flash_attention(q, k, v, mask=m4)
    o_x = xla_attention(q, k, v, mask=m4)
    assert float(jnp.abs(o_f - o_x).max()) < 1e-5
    _check_grads(lambda *a: flash_attention(*a, mask=m4),
                 lambda *a: xla_attention(*a, mask=m4), (q, k, v),
                 jnp.float32(1.0))


def test_gqa_with_padding_no_kv_expansion(qkv):
    q, _, _ = qkv
    rng = np.random.default_rng(1)
    B, T = q.shape[:2]
    kg, vg = _rand(rng, B, T, 2, 64), _rand(rng, B, T, 2, 64)
    lens = [T, 128]
    mask2 = _suffix_mask(lens, T)
    m4 = jnp.asarray(mask2)[:, None, None, :]
    w = jnp.asarray(mask2)[:, :, None, None]
    o_f = flash_attention(q, kg, vg, kv_lengths=jnp.asarray(lens, jnp.int32))
    o_x = xla_attention(q, kg, vg, mask=m4)
    assert float(jnp.abs((o_f - o_x) * w).max()) < 1e-5
    _check_grads(
        lambda *a: flash_attention(*a, kv_lengths=jnp.asarray(lens, jnp.int32)),
        lambda *a: xla_attention(*a, mask=m4), (q, kg, vg), w)


def test_float_masks_fall_back_to_dense(qkv):
    """A float mask could be additive (zeros mean KEEP); only bool/int
    masks may enter the kernel's nonzero-means-keep contract."""
    from serverless_learn_tpu.ops.pallas.flash_attention import as_kv_mask

    B, T = 2, 256
    assert as_kv_mask(jnp.ones((B, 1, 1, T), jnp.float32), B, T) is None
    assert as_kv_mask(jnp.ones((B, 1, T, T), jnp.int32), B, T) is None
    assert as_kv_mask(jnp.ones((B, 1, 1, T), jnp.int32), B, T) is not None
    assert as_kv_mask(jnp.ones((B, T), jnp.bool_), B, T) is not None


def test_bert_step_executes_flash_path(devices):
    """The round-1 gap: BERT always passes a padding mask, which silently
    forced dense attention. Prove the masked train-step now lowers through
    pallas_call (suffix_padding_mask contract -> kv_lengths path)."""
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig(
        model="bert_tiny",
        model_overrides={"max_seq_len": 512},
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4),
        train=TrainConfig(batch_size=8, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig(seq_len=512))
    trainer = build_trainer(cfg)
    rng = np.random.default_rng(0)
    batch = trainer.bundle.make_batch(rng, cfg.data, 8)
    batch["attn_mask"][:, 400:] = 0  # suffix padding
    batch["mlm_mask"][:, 400:] = 0

    def loss(params):
        l, _ = trainer.bundle.loss_fn(params, batch)
        return l

    state = trainer.init()
    jaxpr = str(jax.make_jaxpr(jax.grad(loss))(state.params))
    assert "pallas_call" in jaxpr, \
        "masked BERT fwd+bwd must lower through the flash kernels"
    # and it trains without NaNs through the masked backward (jitted: the
    # eager op-by-op dispatch of this graph has aborted the CPU backend
    # with memory churn on the 8-device mesh)
    g = jax.jit(jax.grad(loss))(state.params)
    assert not any(bool(jnp.isnan(x).any())
                   for x in jax.tree_util.tree_leaves(g))


@pytest.mark.parametrize("T", [128, 256, 512])
@pytest.mark.parametrize("impl", ["auto", "xla", "flash"])
def test_dispatcher_honors_kv_lengths_alone(impl, T):
    """Round-3 verdict #5: every dispatch branch must honor kv_lengths even
    when the caller passes NO mask — in particular impl="xla" with T < 512,
    which previously ignored padding silently."""
    from serverless_learn_tpu.ops.attention import dot_product_attention

    rng = np.random.default_rng(7)
    B, H, D = 2, 4, 64
    q, k, v = (_rand(rng, B, T, H, D) for _ in range(3))
    lens = jnp.asarray([T, T // 3], jnp.int32)
    m4 = jnp.asarray(_suffix_mask([T, T // 3], T))[:, None, None, :]
    w = jnp.asarray(_suffix_mask([T, T // 3], T))[:, :, None, None]

    out = dot_product_attention(q, k, v, kv_lengths=lens, impl=impl)
    ref = xla_attention(q, k, v, mask=m4)
    assert float(jnp.abs((out - ref) * w).max()) < 1e-5, \
        f"impl={impl} T={T}: padding ignored on the dispatch path"


def test_fully_padded_row_is_nan_free(qkv):
    """A row with zero valid keys must produce output 0 and, with zero
    upstream gradient (the loss masks it), NaN-free input gradients."""
    q, k, v = qkv
    B, T = q.shape[:2]
    lens = [T, 0]
    mask2 = _suffix_mask(lens, T)
    w = jnp.asarray(mask2)[:, :, None, None]
    out = flash_attention(q, k, v, kv_lengths=jnp.asarray(lens, jnp.int32))
    assert float(jnp.abs(out[1]).max()) == 0.0
    g = jax.grad(lambda *a: (flash_attention(
        *a, kv_lengths=jnp.asarray(lens, jnp.int32)) * w).sum(),
        (0, 1, 2))(q, k, v)
    assert not any(bool(jnp.isnan(x).any()) for x in g)
