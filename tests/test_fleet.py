"""Serving fleet (round 12): router failure modes, registration,
autoscaler, chaos.

Everything here drives REAL sockets (the stub replicas run the actual
``GenerationServer`` wire loop over deterministic fake compute —
``fleet/testing.py``), so hedging, shedding, draining and death
detection are exercised where they live: in the connection handling, not
in a mock."""

import hashlib
import json
import socket
import threading
import time

import pytest

from serverless_learn_tpu.config import FleetConfig
from serverless_learn_tpu.fleet.router import FleetRouter, Replica
from serverless_learn_tpu.fleet.testing import StubEngine, stub_server
from serverless_learn_tpu.inference.server import request
from serverless_learn_tpu.telemetry.registry import MetricsRegistry


def make_router(replicas, registry=None, events=None, **cfg_kw):
    defaults = dict(health_interval_s=0.15, dead_after_probes=2,
                    discover_interval_s=0.3, hedge_min_delay_s=0.05,
                    eject_s=0.4, upstream_timeout_s=5.0,
                    queue_timeout_s=1.0)
    defaults.update(cfg_kw)
    cfg = FleetConfig(**defaults)
    return FleetRouter(config=cfg, host="127.0.0.1", port=0,
                       replicas=tuple(replicas),
                       registry=registry or MetricsRegistry(),
                       emit=(events.append if events is not None
                             else lambda rec: None))


def reg_val(registry, name):
    fam = registry.snapshot().get(name) or {}
    return sum(s.get("value", 0) for s in fam.get("series", []))


# -- basics ------------------------------------------------------------------


def test_router_routes_and_matches_direct():
    r1, r2 = stub_server(), stub_server()
    router = make_router([r1.addr, r2.addr]).start()
    try:
        time.sleep(0.3)
        via = request(router.addr, {"prompt": [5, 9, 11],
                                    "max_new_tokens": 4})
        direct = request(r1.addr, {"prompt": [5, 9, 11],
                                   "max_new_tokens": 4})
        assert via["tokens"] == direct["tokens"]
        assert via["new_tokens"] == direct["new_tokens"]
    finally:
        router.stop(), r1.stop(), r2.stop()


def test_session_affinity_is_sticky_and_health_gated():
    r1, r2 = stub_server(), stub_server()
    router = make_router([r1.addr, r2.addr]).start()
    try:
        time.sleep(0.3)
        for _ in range(4):
            request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                  "session": "alpha"})
        served = [(r.engine, len(r.engine.submitted)) for r in (r1, r2)]
        counts = sorted(n for _, n in served)
        assert counts == [0, 4], counts  # all four on ONE replica
        # The session's replica dies -> the session re-pins, not fails.
        sticky = r1 if len(r1.engine.submitted) == 4 else r2
        other = r2 if sticky is r1 else r1
        sticky.stop()
        time.sleep(0.6)  # prober marks it dead
        rep = request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                    "session": "alpha"})
        assert "tokens" in rep
        assert len(other.engine.submitted) >= 1
    finally:
        router.stop()
        for s in (r1, r2):
            try:
                s.stop()
            except Exception:
                pass


# -- hedging -----------------------------------------------------------------


def test_hedging_no_duplicate_completions():
    """A slow primary gets hedged on a second replica; the client sees
    EXACTLY one reply (and it equals the deterministic completion)."""
    slow = StubEngine(latency_s=0.8)
    fast = StubEngine(latency_s=0.0)
    r1, r2 = stub_server(engine=slow), stub_server(engine=fast)
    reg = MetricsRegistry()
    router = make_router([r1.addr, r2.addr], registry=reg).start()
    try:
        time.sleep(0.3)
        # Pin the pick to the slow replica so the hedge races the fast one.
        session = next(
            s for s in (f"s{i}" for i in range(64))
            if max((r1.addr, r2.addr), key=lambda a: hashlib.md5(
                f"{s}|{a}".encode()).hexdigest()) == r1.addr)
        host, _, port = router.addr.rpartition(":")
        t0 = time.monotonic()
        with socket.create_connection((host, int(port)), timeout=10) as s:
            f = s.makefile("rwb")
            f.write(json.dumps({"prompt": [3, 4], "max_new_tokens": 3,
                                "session": session}).encode() + b"\n")
            f.flush()
            rep = json.loads(f.readline())
            took = time.monotonic() - t0
            # Exactly one reply line: nothing further arrives.
            s.settimeout(0.4)
            try:
                extra = s.recv(4096)
            except socket.timeout:
                extra = b""
        assert "tokens" in rep, rep
        assert extra == b"", "duplicate completion leaked to the client"
        assert took < 0.7, f"hedge never fired ({took:.2f}s)"
        assert reg_val(reg, "slt_router_hedges_total") == 1
        assert reg_val(reg, "slt_router_hedge_wins_total") == 1
        # Both replicas ran it (idempotent duplicate execution is the
        # accepted cost); the losing reply was discarded.
        assert len(slow.submitted) == 1 and len(fast.submitted) == 1
    finally:
        router.stop(), r1.stop(), r2.stop()


def test_hedge_opt_out_is_honored():
    slow = StubEngine(latency_s=0.4)
    r1, r2 = stub_server(engine=slow), stub_server(engine=slow)
    reg = MetricsRegistry()
    router = make_router([r1.addr, r2.addr], registry=reg).start()
    try:
        time.sleep(0.3)
        rep = request(router.addr, {"prompt": [2], "max_new_tokens": 2,
                                    "idempotent": False}, timeout=10)
        assert "tokens" in rep
        assert reg_val(reg, "slt_router_hedges_total") == 0
    finally:
        router.stop(), r1.stop(), r2.stop()


# -- shedding ----------------------------------------------------------------


def test_shed_before_meltdown_typed_overload():
    """Above capacity the router answers with the TYPED overload error
    instead of queueing without bound; admitted requests still finish."""
    eng = StubEngine(latency_s=0.5)
    r1 = stub_server(engine=eng)
    router = make_router([r1.addr], max_inflight=2, queue_timeout_s=0.15,
                         shed_start_frac=0.5, hedge=False).start()
    try:
        time.sleep(0.3)
        results = []
        lock = threading.Lock()

        def fire():
            rep = request(router.addr, {"prompt": [1], "max_new_tokens": 1},
                          timeout=10)
            with lock:
                results.append(rep)

        threads = [threading.Thread(target=fire) for _ in range(6)]
        for t in threads:
            t.start()
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=10)
        ok = [r for r in results if "tokens" in r]
        shed = [r for r in results if r.get("code") == "overloaded"]
        assert len(results) == 6
        assert ok and shed, results
        assert len(ok) + len(shed) == 6, results  # nothing hard-failed
        for r in shed:
            assert r.get("shed") is True
            assert "retry_after_ms" in r
    finally:
        router.stop(), r1.stop()


def test_brownout_sheds_lowest_priority_first():
    eng = StubEngine(latency_s=0.4)
    r1 = stub_server(engine=eng)
    router = make_router([r1.addr], max_inflight=4, queue_timeout_s=1.0,
                         shed_start_frac=0.5, hedge=False).start()
    try:
        time.sleep(0.3)
        # Fill past the brownout threshold (2 of 4 slots).
        bg = [threading.Thread(target=request, args=(
            router.addr, {"prompt": [1], "max_new_tokens": 1}))
            for _ in range(3)]
        for t in bg:
            t.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        low = request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                    "priority": 0}, timeout=5)
        instant = time.monotonic() - t0
        assert low.get("code") == "overloaded", low
        assert instant < 0.2, "priority-0 must shed instantly, not queue"
        # Normal-priority traffic in the same band still completes.
        ok = request(router.addr, {"prompt": [1], "max_new_tokens": 1},
                     timeout=5)
        assert "tokens" in ok
        for t in bg:
            t.join(timeout=5)
    finally:
        router.stop(), r1.stop()


def test_kv_pressure_shapes_picking_and_sheds_background_traffic():
    """Round 13: paged replicas report KV pool pressure on ping; the
    router prefers headroom among equally-loaded replicas and sheds
    priority<=0 traffic (typed overload) when EVERY eligible replica is
    out of blocks. Stub replicas report nothing -> never memory-shed."""
    r1, r2 = stub_server(), stub_server()
    registry = MetricsRegistry()
    router = make_router([r1.addr, r2.addr], registry=registry).start()
    try:
        time.sleep(0.4)  # probes mark both healthy
        # Stub replicas carry no kv stats: pressure reads 1.0 (never
        # shed) and picking is unaffected.
        assert router._kv_pressure() == 1.0
        reps = {r.addr: r for r in router._replicas.values()}
        a, b = reps[r1.addr], reps[r2.addr]
        # Memory-aware picking: equal load, unequal KV headroom.
        a.kv_free_frac, b.kv_free_frac = 0.05, 0.9
        picked = {router._pick([a, b], session=None).addr
                  for _ in range(4)}
        assert picked == {r2.addr}, \
            "equally-loaded pick must prefer KV headroom"
        # Fleet-wide exhaustion: background traffic sheds instantly,
        # interactive traffic still routes (backpressure belongs to the
        # replicas' admission, not to a hard router error).
        a.kv_free_frac = b.kv_free_frac = 0.0
        assert router._kv_pressure() == 0.0
        shed = request(router.addr, {"prompt": [1], "max_new_tokens": 1,
                                     "priority": 0}, timeout=5)
        assert shed.get("code") == "overloaded" and shed.get("shed"), shed
        assert "KV pool pressure" in shed["error"]
        ok = request(router.addr, {"prompt": [1], "max_new_tokens": 1},
                     timeout=5)
        assert "tokens" in ok
    finally:
        router.stop(), r1.stop(), r2.stop()


# -- draining ----------------------------------------------------------------


def test_drain_completes_in_flight():
    """remove_replica(drain=True) while a request is in flight: the
    client still gets its completion; afterwards the replica takes no
    new connections."""
    eng = StubEngine(latency_s=0.5)
    r1 = stub_server(engine=eng)
    fast = stub_server()
    router = make_router([r1.addr, fast.addr], hedge=False).start()
    try:
        time.sleep(0.3)
        session = next(
            s for s in (f"d{i}" for i in range(64))
            if max((r1.addr, fast.addr), key=lambda a: hashlib.md5(
                f"{s}|{a}".encode()).hexdigest()) == r1.addr)
        out = []
        t = threading.Thread(target=lambda: out.append(request(
            router.addr, {"prompt": [7], "max_new_tokens": 2,
                          "session": session}, timeout=10)))
        t.start()
        time.sleep(0.15)  # request is now inside the slow engine
        router.remove_replica(r1.addr, drain=True)
        t.join(timeout=10)
        assert out and "tokens" in out[0], out
        assert all(r["addr"] != r1.addr for r in router.replicas())
        # The drained server refuses new connections once idle.
        deadline = time.monotonic() + 5
        refused = False
        while time.monotonic() < deadline and not refused:
            try:
                request(r1.addr, {"op": "ping"}, timeout=1)
                time.sleep(0.05)
            except OSError:
                refused = True
        assert refused, "drained replica still accepting connections"
        # New traffic flows to the surviving replica.
        assert "tokens" in request(router.addr, {"prompt": [1],
                                                 "max_new_tokens": 1})
    finally:
        router.stop(), fast.stop()
        try:
            r1.stop()
        except Exception:
            pass


def test_server_drain_op_finishes_inflight():
    """The wire-level {"op": "drain"} admin: in-flight completes, the
    listener closes."""
    eng = StubEngine(latency_s=0.4)
    srv = stub_server(engine=eng)
    out = []
    t = threading.Thread(target=lambda: out.append(
        request(srv.addr, {"prompt": [2], "max_new_tokens": 2},
                timeout=10)))
    t.start()
    time.sleep(0.1)
    ack = request(srv.addr, {"op": "drain"}, timeout=5)
    assert ack.get("draining") is True
    t.join(timeout=10)
    assert out and "tokens" in out[0]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        try:
            request(srv.addr, {"op": "ping"}, timeout=1)
            time.sleep(0.05)
        except OSError:
            break
    else:
        pytest.fail("drained server still accepting connections")
    srv.stop()


# -- ejection + death --------------------------------------------------------


def test_outlier_ejection_and_readmission():
    """Consecutive transport errors eject a replica (doubling window);
    a later success readmits it."""
    from serverless_learn_tpu.chaos.shim import TcpChaosProxy

    r1 = stub_server()
    proxy = TcpChaosProxy(upstream=r1.addr).start()
    reg = MetricsRegistry()
    events = []
    router = make_router([proxy.addr], registry=reg, events=events,
                         hedge=False, max_retries=0,
                         eject_consecutive_errors=2,
                         eject_s=0.3, health_interval_s=30.0,
                         dead_after_probes=99).start()
    try:
        time.sleep(0.2)
        proxy.set_fault("reset")
        for _ in range(2):
            rep = request(router.addr, {"prompt": [1], "max_new_tokens": 1},
                          timeout=5)
            assert rep.get("code") == "upstream_unavailable", rep
        assert reg_val(reg, "slt_router_ejections_total") == 1
        assert any(e.get("alert") == "fleet.replica_ejected"
                   for e in events)
        states = {r["addr"]: r["state"] for r in router.replicas()}
        assert states[proxy.addr] == Replica.EJECTED
        # While ejected: no candidates -> typed overload, instantly.
        rep = request(router.addr, {"prompt": [1], "max_new_tokens": 1},
                      timeout=5)
        assert rep.get("code") == "overloaded"
        # Heal + wait out the window: the next request readmits it.
        proxy.set_fault(None)
        time.sleep(0.45)
        rep = request(router.addr, {"prompt": [1], "max_new_tokens": 1},
                      timeout=5)
        assert "tokens" in rep, rep
        states = {r["addr"]: r["state"] for r in router.replicas()}
        assert states[proxy.addr] == Replica.HEALTHY
    finally:
        router.stop(), proxy.stop(), r1.stop()


def test_replica_kill_mid_stream_client_still_completes():
    """The round-12 e2e satellite: a replica dies mid-request through
    TcpChaosProxy; the client sees a successful (re-routed or hedged)
    completion — never an error."""
    from serverless_learn_tpu.chaos.shim import TcpChaosProxy

    slow = StubEngine(latency_s=1.2)
    r1 = stub_server(engine=slow)
    proxy = TcpChaosProxy(upstream=r1.addr).start()
    r2 = stub_server()
    reg = MetricsRegistry()
    router = make_router([proxy.addr, r2.addr], registry=reg).start()
    try:
        time.sleep(0.3)
        session = next(
            s for s in (f"k{i}" for i in range(64))
            if max((proxy.addr, r2.addr), key=lambda a: hashlib.md5(
                f"{s}|{a}".encode()).hexdigest()) == proxy.addr)

        def killer():
            time.sleep(0.3)
            r1.stop()          # replica process dies...
            proxy.set_fault("reset")  # ...and its connections RST

        t = threading.Thread(target=killer)
        t.start()
        rep = request(router.addr, {"prompt": [9, 9], "max_new_tokens": 3,
                                    "session": session}, timeout=15)
        t.join()
        assert "tokens" in rep, rep
        direct = request(r2.addr, {"prompt": [9, 9], "max_new_tokens": 3})
        assert rep["tokens"] == direct["tokens"]
        assert (reg_val(reg, "slt_router_hedges_total")
                + reg_val(reg, "slt_router_retries_total")) >= 1
    finally:
        router.stop(), proxy.stop(), r2.stop()
        try:
            r1.stop()
        except Exception:
            pass


def test_dead_replica_alert_names_addr_and_resolves_on_restart():
    events = []
    r1 = stub_server()
    addr = r1.addr
    router = make_router([addr], events=events).start()
    try:
        time.sleep(0.4)
        r1.stop()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(e.get("alert") == "fleet.replica_dead"
                   and e.get("state") == "firing" for e in events):
                break
            time.sleep(0.05)
        fired = [e for e in events if e.get("alert") == "fleet.replica_dead"
                 and e.get("state") == "firing"]
        assert fired and fired[0]["labels"]["replica"] == addr
        # Restart on the same port: the obituary resolves.
        host, _, port = addr.rpartition(":")
        r1b = stub_server(host=host, port=int(port))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(e.get("alert") == "fleet.replica_dead"
                   and e.get("state") == "resolved" for e in events):
                break
            time.sleep(0.05)
        assert any(e.get("state") == "resolved" for e in events
                   if e.get("alert") == "fleet.replica_dead")
        r1b.stop()
    finally:
        router.stop()
        try:
            r1.stop()
        except Exception:
            pass


# -- self-registration -------------------------------------------------------


def test_replica_self_registration_and_discovery():
    """serve --fleet machinery: a replica registers with the (python)
    coordinator; the router discovers it with no static list; stopping
    the registration (the SIGTERM path) drains it out of the fleet."""
    from serverless_learn_tpu.control.py_daemons import PyCoordinator
    from serverless_learn_tpu.fleet.registration import (FleetRegistration,
                                                         parse_replica,
                                                         replica_name)

    assert parse_replica(replica_name("svc", "1.2.3.4:9"), "a:1") == {
        "service": "svc", "serve_addr": "a:1", "metrics_addr": "1.2.3.4:9",
        "version": None}
    assert parse_replica("worker-7", "a:1") is None
    with pytest.raises(ValueError):
        replica_name("has:colon")

    coord = PyCoordinator(port=0, lease_ttl_ms=2000).start()
    r1 = stub_server()
    registration = FleetRegistration(coord.addr, r1.addr, service="serve",
                                     heartbeat_interval_ms=200).start()
    router = make_router([], discover_interval_s=0.2)
    router.coordinator_addr = coord.addr
    router.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(r["addr"] == r1.addr for r in router.replicas()):
                break
            time.sleep(0.05)
        assert any(r["addr"] == r1.addr for r in router.replicas()), \
            router.replicas()
        time.sleep(0.3)  # let a probe mark it healthy
        assert "tokens" in request(router.addr, {"prompt": [1],
                                                 "max_new_tokens": 1})
        # Deregistration (SIGTERM path) -> the router drains it out.
        registration.stop()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if not router.replicas():
                break
            time.sleep(0.05)
        assert not router.replicas(), router.replicas()
    finally:
        router.stop(), r1.stop(), coord.stop()


# -- autoscaler --------------------------------------------------------------


def test_autoscaler_scales_out_on_critical_and_in_after_calm():
    from serverless_learn_tpu.fleet.autoscaler import (CallbackLauncher,
                                                       FleetAutoscaler)

    n = [1]
    launcher = CallbackLauncher(
        lambda: n[0],
        lambda: n.__setitem__(0, n[0] + 1),
        lambda: n.__setitem__(0, n[0] - 1))
    alerts = []
    clock = [1000.0]
    scaler = FleetAutoscaler(
        launcher, lambda: alerts, min_replicas=1, max_replicas=3,
        alert_substr="queue_wait", scale_out_cooldown_s=5.0,
        scale_in_cooldown_s=10.0, scale_in_calm_s=8.0,
        clock=lambda: clock[0], registry=MetricsRegistry())

    crit = {"alert": "slo.router_queue_wait", "severity": "critical"}
    warn = {"alert": "slo.router_queue_wait", "severity": "warning"}
    other = {"alert": "slo.ttft", "severity": "critical"}

    assert scaler.tick() is None          # calm: nothing to do
    alerts[:] = [other]
    assert scaler.tick() is None          # unrelated alert: no action
    alerts[:] = [crit]
    assert scaler.tick() == "out" and n[0] == 2
    clock[0] += 1.0
    assert scaler.tick() is None          # cooldown holds
    clock[0] += 5.0
    assert scaler.tick() == "out" and n[0] == 3
    clock[0] += 6.0
    assert scaler.tick() is None and n[0] == 3   # max_replicas cap
    # Warning alone neither scales out nor counts as calm.
    alerts[:] = [warn]
    clock[0] += 10.0
    assert scaler.tick() is None
    # Full calm: scale-in waits for the calm window, then drains one.
    alerts[:] = []
    assert scaler.tick() is None          # calm starts now
    clock[0] += 7.0
    assert scaler.tick() is None          # calm_s not yet reached
    clock[0] += 2.0
    assert scaler.tick() == "in" and n[0] == 2
    clock[0] += 5.0
    assert scaler.tick() is None          # scale-in cooldown
    clock[0] += 6.0
    assert scaler.tick() == "in" and n[0] == 1
    clock[0] += 60.0
    assert scaler.tick() is None and n[0] == 1   # min_replicas floor
    assert [e["direction"] for e in scaler.events] == \
        ["out", "out", "in", "in"]


# -- chaos fleet + doctor ----------------------------------------------------


def test_chaos_fleet_plan_doctor_names_dead_replica(tmp_path):
    """`slt chaos` fleet plan: kill one replica (no restart) under load;
    `slt doctor` over the events log ALONE must name the dead replica."""
    from serverless_learn_tpu.chaos.fleet import FleetChaosRun
    from serverless_learn_tpu.chaos.plan import FaultPlan
    from serverless_learn_tpu.telemetry import doctor

    events_log = str(tmp_path / "fleet-events.jsonl")
    plan = FaultPlan.from_obj({"faults": [
        {"at": 0.6, "op": "kill", "node": "replica-1"}]})
    run = FleetChaosRun(n_replicas=3, plan=plan, seed=5, rate_rps=25.0,
                        events_log=events_log)
    rep = run.run(2.5)
    assert rep["ok"], rep
    assert rep["client"]["hard_failures"] == 0
    assert rep["detections"].get("replica-1") is not None
    dead_addr = next(f["addr"] for f in rep["faults_injected"]
                     if f.get("op") == "kill")

    diag = doctor.diagnose([events_log], bench_history="/nonexistent")
    assert diag["summary"]["critical_firing"] >= 1
    assert dead_addr in diag["summary"]["verdict"]
    named = [a for a in diag["alerts"]
             if a["alert"] == "fleet.replica_dead"
             and (a.get("labels") or {}).get("replica") == dead_addr]
    assert named, diag["alerts"]


def test_chaos_fleet_rejects_unsupported_ops():
    from serverless_learn_tpu.chaos.fleet import FleetChaosRun
    from serverless_learn_tpu.chaos.plan import FaultPlan

    plan = FaultPlan.from_obj({"faults": [
        {"at": 1.0, "op": "partition", "split": 0.5}]})
    with pytest.raises(ValueError, match="fleet chaos supports"):
        FleetChaosRun(n_replicas=2, plan=plan)


def test_chaos_fleet_stall_absorbed_by_hedging(tmp_path):
    """A stalled (not dead) replica: hedges keep completions flowing and
    the run stays failure-free."""
    from serverless_learn_tpu.chaos.fleet import FleetChaosRun
    from serverless_learn_tpu.chaos.plan import FaultPlan

    plan = FaultPlan.from_obj({"faults": [
        {"at": 0.5, "op": "pause", "node": "replica-0", "for": 1.0}]})
    rep = FleetChaosRun(n_replicas=2, plan=plan, seed=9,
                        rate_rps=20.0).run(2.2)
    assert rep["client"]["hard_failures"] == 0, rep["client"]
    assert rep["client"]["ok"] > 0
