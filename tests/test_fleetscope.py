"""Fleetscope (round 22): resident-prefix digests, windowed hit rates,
router decision provenance, fleet redundancy accounting, and the
deterministic counterfactual replay.

The digest contract under test: one 64-bit chain hash names one exact
token prefix (chunk i's hash folds in chunk i-1's, so equal hashes mean
equal full prefixes, not just equal chunks); digests truncate
shallow-first so a capped digest UNDER-counts redundancy; and the whole
pipeline — trie digest -> ping -> router accounting -> `slt fleetscope`
replay — is deterministic: same logs, byte-identical reports. The slow
acceptance at the bottom proves it end to end on a live stub fleet with
the redundancy injected by construction.
"""

import json
import os
import threading
import time

import pytest

from serverless_learn_tpu.inference.kvcache import (BlockPool, PrefixTrie,
                                                    chunk_hashes)
from serverless_learn_tpu.telemetry import fleetscope
from serverless_learn_tpu.telemetry.registry import MetricsRegistry

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "fleetscope",
                       "fleetscope_fixture.jsonl")
BENCH_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                             "fleetscope", "bench_history_fleetscope.json")

BS = 16


def _ingest(trie: PrefixTrie, pool: BlockPool, prompt):
    """The engine's register pattern: matched nodes keep their refs,
    fresh blocks pass ownership to the trie."""
    hit = trie.lookup(prompt)
    need = len(prompt) // trie.block_size - len(hit.blocks)
    if need > 0:
        fresh = pool.alloc(need)
        trie.register(prompt, list(hit.blocks) + fresh)
        pool.decref(fresh)


# -- digest semantics --------------------------------------------------------


def test_chunk_hashes_chain_names_exact_prefix():
    """Chained hashing: chunk i's hash commits to every token before it,
    so two streams agree on hash i iff they agree on the whole prefix —
    and diverge on every hash after their first differing token."""
    a = list(range(64))
    b = list(range(64))
    b[3] = 999                       # early divergence
    ha, hb = chunk_hashes(a, BS), chunk_hashes(b, BS)
    assert len(ha) == len(hb) == 4
    assert all(len(h) == 16 for h in ha)          # 64-bit hex
    assert ha[0] != hb[0] and all(x != y for x, y in zip(ha, hb))
    # Same chunk CONTENT at a different position hashes differently.
    c = a[16:32] + a[16:32]
    hc = chunk_hashes(c, BS)
    assert hc[0] != hc[1]
    # Pure function: a second call is bit-identical (restart-stable).
    assert chunk_hashes(a, BS) == ha


def test_collision_bound_is_documented_and_unexercised():
    """64-bit digests: the birthday bound (~n^2 / 2^65) is documented at
    the definition site, and a few thousand distinct prefixes produce
    zero collisions in practice — a collision would only over-count
    redundancy by one block-chunk, never corrupt the cache itself."""
    import inspect

    import serverless_learn_tpu.inference.kvcache as kvcache

    doc = inspect.getsource(kvcache)
    assert "collision" in doc.lower()
    seen = set()
    for i in range(200):
        for h in chunk_hashes([i * 1000 + j for j in range(160)], BS):
            assert h not in seen
            seen.add(h)
    assert len(seen) == 200 * 10


def test_trie_digest_deterministic_across_restarts():
    """Two fresh tries (a restart) fed the same prompts — in DIFFERENT
    arrival orders — export identical digest hash sets: the digest
    depends on what is resident, never on insertion history."""
    prompts = [list(range(100, 164)) + [i] * 16 for i in range(4)]
    digests = []
    for order in (prompts, prompts[::-1]):
        pool = BlockPool(64, BS)
        trie = PrefixTrie(pool)
        for p in order:
            _ingest(trie, pool, p)
        digests.append(trie.digest(max_hashes=64))
    assert sorted(digests[0]["hashes"]) == sorted(digests[1]["hashes"])
    assert digests[0]["block_size"] == BS


def test_digest_truncation_drops_deepest_chunks_first():
    """A capped digest keeps the SHALLOW chunks (BFS): the router then
    sees a shorter resident run and UNDER-counts redundancy — capping
    must never fabricate residency."""
    prompt = list(range(160))        # 10 chunks, one chain
    pool = BlockPool(32, BS)
    trie = PrefixTrie(pool)
    _ingest(trie, pool, prompt)
    full = chunk_hashes(prompt, BS)
    dg = trie.digest(max_hashes=4)
    assert dg["hashes"] == full[:4]
    assert trie.digest(max_hashes=64)["hashes"] == full


def test_digest_top_tracks_hot_deepest_prefix():
    """Hot-prefix stats land on the DEEPEST matched node — one lookup is
    one hit on its longest resident prefix, with resident token counts
    and a last-hit age."""
    prompt = list(range(64))
    pool = BlockPool(32, BS)
    trie = PrefixTrie(pool)
    _ingest(trie, pool, prompt)
    for _ in range(3):
        trie.lookup(prompt)
    top = trie.digest(top_k=4)["top"]
    assert top and top[0]["tokens"] == 64
    assert top[0]["hits"] == 3
    assert top[0]["hash"] == chunk_hashes(prompt, BS)[-1]
    assert top[0]["age_s"] >= 0.0


# -- windowed hit rate (satellite: the stale lifetime-rate fix) --------------


def test_windowed_hit_rate_tracks_traffic_shift():
    """The replica ping's prefix_hit_rate must MOVE when traffic moves:
    after a shift from all-hit to all-miss traffic the windowed rate
    collapses while the lifetime rate (still exported, renamed) lags —
    the round-21 bug was shipping the lifetime number as the rate."""
    pool = BlockPool(256, BS)
    trie = PrefixTrie(pool, hit_window=8)
    hot = list(range(64))
    _ingest(trie, pool, hot)
    for _ in range(16):
        trie.lookup(hot)                       # phase A: all hits
    assert trie.window_hit_rate() == 1.0
    for i in range(8):                         # phase B: all misses
        trie.lookup([1000 + 64 * i + j for j in range(64)])
    assert trie.window_hit_rate() == 0.0       # window: misses only
    lifetime = trie.hits / trie.lookups
    assert lifetime > 0.5                      # the stale number lags


def test_kv_stats_ping_carries_digest_and_both_rates():
    from serverless_learn_tpu.fleet.testing import KVStubEngine

    eng = KVStubEngine(num_blocks=64, block_size=BS, hit_window=8)
    prompt = list(range(64))
    eng.submit(prompt, 2)
    eng.submit(prompt, 2)
    kv = eng.kv_stats()
    assert kv["paged"] and kv["block_size"] == BS
    assert 0.0 <= kv["prefix_hit_rate"] <= 1.0
    assert "prefix_hit_rate_lifetime" in kv
    dg = kv["prefix_digest"]
    assert dg["hashes"] == chunk_hashes(prompt, BS)
    assert dg["top"] and dg["top"][0]["tokens"] == 64


# -- router decision provenance ----------------------------------------------


def _make_router(replicas, registry=None, events=None, **cfg_kw):
    from serverless_learn_tpu.config import FleetConfig
    from serverless_learn_tpu.fleet.router import FleetRouter

    defaults = dict(health_interval_s=0.15, dead_after_probes=2,
                    discover_interval_s=0.3, hedge_min_delay_s=5.0,
                    eject_s=0.4, upstream_timeout_s=5.0,
                    queue_timeout_s=1.0)
    defaults.update(cfg_kw)
    return FleetRouter(config=FleetConfig(**defaults), host="127.0.0.1",
                       port=0, replicas=tuple(replicas),
                       registry=registry or MetricsRegistry(),
                       emit=(events.append if events is not None
                             else lambda rec: None))


def _decisions(events):
    return [e for e in events if e.get("event") == "route_decision"]


def test_route_decision_event_and_hop_join():
    """Every admission emits a route_decision with full candidate
    provenance, and the waterfall hop carries the decision id + pick
    reason — the satellite-2 join that lets `slt waterfall` say WHY a
    hop chose its replica."""
    from serverless_learn_tpu.fleet.testing import KVStubEngine, stub_server
    from serverless_learn_tpu.inference.server import request

    r1 = stub_server(engine=KVStubEngine(num_blocks=64, block_size=BS))
    r2 = stub_server(engine=KVStubEngine(num_blocks=64, block_size=BS))
    events = []
    router = _make_router([r1.addr, r2.addr], events=events).start()
    try:
        time.sleep(0.4)                # first probes: digests land
        rep = request(router.addr,
                      {"prompt": list(range(40)), "max_new_tokens": 2})
        assert "tokens" in rep
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not (
                _decisions(events)
                and any(e.get("event") == "waterfall_hop"
                        for e in events)):
            time.sleep(0.02)
        (dec,) = _decisions(events)
        assert dec["reason"] == "least_loaded" and not dec["session"]
        assert dec["pick"] in (r1.addr, r2.addr)
        assert dec["prompt_tokens"] == 40
        cands = {c["addr"]: c for c in dec["candidates"]}
        assert set(cands) == {r1.addr, r2.addr}
        for c in cands.values():
            assert c["eligible"] is True and c["inflight"] >= 0
            assert "kv_pressure_bucket" in c and "resident_tokens" in c
        # Digests probed -> the prompt's chain hashes ride the event.
        assert dec["block_size"] == BS
        assert dec["prompt_hashes"] == chunk_hashes(list(range(40)), BS)
        (hop,) = [e for e in events if e.get("event") == "waterfall_hop"]
        assert hop["decision_id"] == dec["decision_id"]
        assert hop["pick_reason"] == "least_loaded"
        assert hop["trace_id"] == dec["trace_id"]
    finally:
        router.stop(), r1.stop(), r2.stop()


def test_session_affinity_reason_and_shed_decision():
    from serverless_learn_tpu.fleet.testing import stub_server
    from serverless_learn_tpu.inference.server import request

    r1 = stub_server()
    events = []
    router = _make_router([r1.addr], events=events).start()
    try:
        time.sleep(0.3)
        request(router.addr, {"prompt": [1, 2], "max_new_tokens": 2,
                              "session": "s1"})
        deadline = time.monotonic() + 3.0
        while not _decisions(events) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert _decisions(events)[0]["reason"] == "session_affinity"
        assert _decisions(events)[0]["session"] is True
    finally:
        router.stop(), r1.stop()
    # A fleet with no live replicas sheds — and says so in a decision.
    events2 = []
    router2 = _make_router([], events=events2).start()
    try:
        rep = request(router2.addr, {"prompt": [1], "max_new_tokens": 1})
        assert rep.get("code") == "overloaded"
        deadline = time.monotonic() + 3.0
        while not _decisions(events2) and time.monotonic() < deadline:
            time.sleep(0.02)
        dec = _decisions(events2)[0]
        assert dec["reason"] == "shed_no_replicas"
        assert dec["pick"] is None and dec["candidates"] == []
    finally:
        router2.stop()


def test_waterfall_render_shows_decision_provenance():
    """`slt waterfall` phase bars carry via:<reason>[<decision_id>] once
    the router stamps hops (and hedge losers show their provenance)."""
    from serverless_learn_tpu.telemetry import waterfall

    recs = waterfall.synthetic_records()
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    try:
        out = waterfall.render(waterfall.report([f.name]))
    finally:
        os.unlink(f.name)
    assert "via:least_loaded[aaaaaaaaaaaaaaaa-1]" in out
    assert "via:session_affinity[bbbbbbbbbbbbbbbb-2]" in out
    assert "(lost:" in out                    # hedge loser provenance


# -- accounting + replay over the fabricated fixture -------------------------


def _fixture_records():
    with open(FIXTURE) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_redundancy_accounting_exact_on_fixture():
    """The fabricated 3-replica fixture has hand-computable redundancy:
    the 64-token shared prefix is re-prefilled exactly twice (n1, n2)
    under the recorded least-loaded spread = 128 redundant tokens of
    480 routed; prefix-aware consolidation re-prefills it never."""
    recs = _fixture_records()
    summary = fleetscope.summarize(recs)
    assert summary["primary_decisions"] == 6
    assert summary["routed_prompt_tokens"] == 480
    assert summary["redundant_prefill_tokens"] == 128
    assert summary["redundant_prefill_frac"] == pytest.approx(128 / 480,
                                                              abs=1e-5)
    assert summary["replica_spread_hist"] == {"0": 1, "1": 1, "2": 1,
                                              "3": 3}
    assert summary["prefix_dup_factor"] == pytest.approx(2.4)
    assert set(summary["digests"]) == {"n0:9000", "n1:9000", "n2:9000"}
    # The replay simulator, fed the SAME picks, reproduces the in-event
    # accounting exactly — and the counterfactuals order as designed.
    assert fleetscope.replay(recs, "recorded")[
        "redundant_prefill_tokens"] == 128
    assert fleetscope.replay(recs, "least_loaded")[
        "redundant_prefill_tokens"] == 128
    assert fleetscope.replay(recs, "prefix_aware")[
        "redundant_prefill_tokens"] == 0
    assert fleetscope.replay(recs, "prefill_decode_split")[
        "redundant_prefill_tokens"] == 0


def test_replay_excludes_hedge_retry_and_shed_decisions():
    recs = _fixture_records()
    prim = fleetscope.primary_decisions(recs)
    ids = {d["decision_id"] for d in prim}
    assert len(prim) == 6
    assert not any("." in i for i in ids)          # no hedge/retry
    assert "eeeeeeeeeeeeeeee-9" not in ids         # no shed


def test_report_is_byte_identical_and_bounds_ttft():
    rep1 = fleetscope.report([FIXTURE])
    rep2 = fleetscope.report([FIXTURE])
    assert json.dumps(rep1, sort_keys=True) == json.dumps(rep2,
                                                          sort_keys=True)
    pa = rep1["replay"]["prefix_aware"]
    assert pa["redundant_tokens_saved_vs_recorded"] == 128
    # The TTFT bound scales saved prefill tokens by the waterfall's
    # observed prefill s/token — never below zero, never above recorded.
    assert pa["ttft_p99_bound_ms"] <= rep1["ttft_recorded_p99_ms"]
    assert rep1["savings"]["prefill_tokens"] == 128
    assert rep1["savings"]["ttft_p99_ms"] > 0


def test_self_check_passes_on_synthetic_and_committed_fixture():
    rep = fleetscope.self_check()
    assert rep["ok"], rep["checks"]
    rep = fleetscope.self_check(fixture_path=FIXTURE)
    assert rep["ok"], rep["checks"]
    assert {c["check"] for c in rep["checks"]} >= {
        "recorded_replay_exact", "prefix_aware_strictly_lower",
        "byte_identical_replay", "ttft_bound"}


def test_bench_rows_carry_redundancy_columns_and_gate():
    """The fleetscope rows gate as *_ms (better=min) with the redundancy
    fraction + dup factor as attribution columns — a standalone fraction
    row would gate better=max, the wrong direction."""
    from serverless_learn_tpu.telemetry import benchgate
    from serverless_learn_tpu.utils.benchlog import load_history

    rows = fleetscope.bench_rows(fleetscope.report([FIXTURE]))
    (row,) = rows
    assert row["metric"] == "fleetscope_ttft_p99_ms"
    assert row["fleet_redundant_prefill_frac"] == pytest.approx(128 / 480,
                                                                abs=1e-5)
    assert row["fleet_prefix_dup_factor"] == pytest.approx(2.4)
    assert "fleet_redundant_prefill_frac" in benchgate.ATTRIBUTION_COLUMNS
    assert "fleet_prefix_dup_factor" in benchgate.ATTRIBUTION_COLUMNS
    rep = benchgate.gate_history(load_history(BENCH_FIXTURE),
                                 metric="fleetscope_")
    assert rep["ok"] and rep["series"] == 2
    cols = {a["column"] for c in rep["checks"]
            for a in c.get("attribution", [])}
    assert cols >= {"fleet_redundant_prefill_frac",
                    "fleet_prefix_dup_factor"}


# -- surfacing: top pane, exporter endpoint, doctor --------------------------


def test_top_and_exporter_surface_fleet_redundancy():
    from serverless_learn_tpu.telemetry import top as top_mod
    from serverless_learn_tpu.telemetry.exporter import MetricsExporter

    reg = MetricsRegistry()
    reg.gauge("slt_router_replicas", "n").set(3)
    reg.gauge("slt_router_replicas_healthy", "n").set(3)
    reg.counter("slt_fleet_routed_prompt_tokens_total", "tok").inc(480)
    reg.counter("slt_fleet_redundant_prefill_tokens_total",
                "tok").inc(128)
    reg.gauge("slt_fleet_redundant_prefill_frac", "frac").set(0.2667)
    reg.gauge("slt_fleet_prefix_dup_factor", "x").set(2.4)
    exp = MetricsExporter(registry=reg).start()
    try:
        st = top_mod.EndpointState(exp.addr)
        st.poll()
        out = top_mod.render([st])
        scope = json.loads(top_mod.fetch_text(exp.addr,
                                              path="/fleetscope"))
    finally:
        exp.stop()
    assert "rdnt pfl" in out and "pfx dup" in out
    assert "26.7%" in out and "2.40" in out
    assert scope["enabled"]
    assert scope["routed_prompt_tokens"] == 480
    assert scope["redundant_prefill_tokens"] == 128
    assert scope["redundant_prefill_frac"] == pytest.approx(0.2667)
    assert scope["prefix_dup_factor"] == pytest.approx(2.4)


def test_doctor_names_redundancy_opportunity_from_logs_alone():
    from serverless_learn_tpu.telemetry import doctor

    rep = doctor.diagnose(paths=[FIXTURE], bench_history=BENCH_FIXTURE)
    verdict = rep["summary"]["verdict"]
    assert "fleet prefix redundancy" in verdict
    assert "slt fleetscope" in verdict
    assert rep["fleetscope"]["redundant_prefill_tokens"] == 128


# -- acceptance: live stub fleet with constructed redundancy -----------------


@pytest.mark.slow
def test_fleetscope_smoke_live_fleet_acceptance():
    """The round-22 acceptance on a live 3-replica stub fleet: real
    prefix tries behind real sockets, one replica pre-warmed with the
    shared prefix by construction — live counters account the
    redundancy, digests snapshot, prefix-aware replay strictly beats
    the recorded stream, reports byte-identical."""
    from serverless_learn_tpu.fleet.loadgen import run_fleetscope_smoke

    rep = run_fleetscope_smoke(seed=0)
    assert rep["ok"], rep["checks"]
    assert rep["router"]["redundant_prefill_tokens_total"] > 0
    assert rep["bench_rows"] and \
        "fleet_redundant_prefill_frac" in rep["bench_rows"][0]
