"""FLOPs / MFU accounting (VERDICT round 1 item 6): XLA-cost-model step
FLOPs, peak lookup by device kind, and the ThroughputMeter wiring."""

import jax
import jax.numpy as jnp
import numpy as np

from serverless_learn_tpu.utils.flops import (
    PEAK_TFLOPS_BF16, compiled_step_flops, mfu, peak_flops_per_chip)
from serverless_learn_tpu.utils.metrics import ThroughputMeter


def test_compiled_flops_matches_analytic_matmul():
    n = 512
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((n, n), jnp.float32)
    flops = compiled_step_flops(f, a, a)
    if flops is None:  # backend without a cost model: nothing to assert
        return
    # XLA counts 2*M*N*K for a matmul.
    assert abs(flops - 2 * n ** 3) / (2 * n ** 3) < 0.05, flops


def test_peak_lookup_unknown_device_is_none():
    class Fake:
        device_kind = "abacus"

    assert peak_flops_per_chip(Fake()) is None
    assert mfu(1e12, 1.0, device=Fake()) is None


def test_mfu_math():
    class V5e:
        device_kind = "TPU v5 lite"

    peak = PEAK_TFLOPS_BF16["TPU v5 lite"] * 1e12
    # half the peak for one second on one chip
    assert abs(mfu(peak / 2, 1.0, n_chips=1, device=V5e()) - 0.5) < 1e-9
    # same work over two chips halves utilization again
    assert abs(mfu(peak / 2, 1.0, n_chips=2, device=V5e()) - 0.25) < 1e-9
    assert mfu(None, 1.0) is None
    assert mfu(1.0, 0.0) is None


def test_meter_reports_mfu_fields():
    meter = ThroughputMeter(batch_size=8, n_chips=1, flops_per_step=1e9)
    meter.start()
    for i in range(5):
        meter.record(i, {})
    out = meter.steady_state()
    assert "tflops_per_sec_per_chip" in out
    assert out["tflops_per_sec_per_chip"] > 0
    # mfu present only when the device kind is known (CPU here -> absent)
    if peak_flops_per_chip() is None:
        assert "mfu" not in out


def test_run_training_attaches_flops(devices):
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.training.loop import run_training

    cfg = ExperimentConfig(
        model="mlp_mnist", mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16, num_steps=3, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig())
    _, meter = run_training(cfg)
    if meter.flops_per_step is not None:  # CPU exposes a cost model
        assert meter.flops_per_step > 1e6
        assert meter.steady_state()["tflops_per_sec_per_chip"] > 0
