"""Flow control at the data plane (VERDICT r2 item 9).

Round 2 recorded per-worker backpressure (heartbeat ``flow`` surfaced in
coordinator stats) but nothing acted on it. Now the same signal rides each
FetchRequest (``flow_present``/``flow``: the consumer's prefetch-queue
depth; 0 = starving) and the shard server paces well-fed streams while a
starved stream is in flight — bandwidth shifts to the consumer that is
actually blocked on input.
"""

import socket
import threading
import time

import numpy as np
import pytest

from serverless_learn_tpu.control.client import ShardClient
from serverless_learn_tpu.control.daemons import start_shard_server


@pytest.fixture()
def shard_server(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = start_shard_server(port=port, root=str(tmp_path))
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


BLOB = "synthetic:33554432"  # 32 MB, server-side generated


def _timed_fetch(addr, flow, out, key_idx):
    c = ShardClient(addr)
    try:
        c.set_flow(flow)
        t0 = time.perf_counter()
        data = c.fetch(BLOB)
        out[key_idx] = (time.perf_counter() - t0, len(data))
    finally:
        c.close()


def _contended(addr, probe_flow, other_flow, n_others=3):
    """One probe fetch vs ``n_others`` competitors, all concurrent 32 MB.
    Returns (probe_s, [other_s...])."""
    out = {}
    ts = [threading.Thread(target=_timed_fetch,
                           args=(addr, probe_flow, out, "probe"))]
    ts += [threading.Thread(target=_timed_fetch,
                            args=(addr, other_flow, out, f"o{i}"))
           for i in range(n_others)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(v[1] == 33554432 for v in out.values())
    return out["probe"][0], [out[f"o{i}"][0] for i in range(n_others)]


@pytest.mark.parametrize("prefer_native", [True, False])
def test_fetch_carries_flow(shard_server, prefer_native):
    """Both transports mark their fetches; the server's stats prove the
    starved stream was recognized."""
    c = ShardClient(shard_server, prefer_native=prefer_native)
    try:
        c.set_flow(0)
        assert len(c.fetch("synthetic:1000000")) == 1000000
        c.set_flow(None)
        assert len(c.fetch("synthetic:1000000")) == 1000000
    finally:
        c.close()
    probe = ShardClient(shard_server)
    try:
        stats = probe.stats()
        assert stats.starved_streams_served >= 1
    finally:
        probe.close()


@pytest.mark.slow
def test_starved_stream_prioritized_under_contention(shard_server):
    """The done-criterion: a starved worker's fetch latency drops under
    contention once flow is reported. Measurements (1 probe vs 3
    competitors, 32 MB each):

    1. everyone unreported -> symmetric baseline for the probe
    2. probe starved (0) vs well-fed (8) competitors -> the probe
       finishes ahead of every competitor and faster than its own
       symmetric baseline (median of 3 trials: absolute localhost
       timings are noisy; the ORDERING is the contract)
    """
    _contended(shard_server, None, None)  # warm server + page cache
    # INTERLEAVE baseline and starved trials: the two medians must see the
    # same external machine load, or a box-wide load swing between the
    # baseline block and the trial block fails the comparison spuriously
    # (observed once under a fully contended core).
    baselines, trials = [], []
    for _ in range(3):
        baselines.append(_contended(shard_server, None, None)[0])
        trials.append(_contended(shard_server, 0, 8))
    baseline = sorted(baselines)[1]
    starved = sorted(t[0] for t in trials)[1]
    # Every trial: the starved probe beats every well-fed competitor.
    for probe_s, others in trials:
        assert probe_s < min(others), (probe_s, others)
    # And the median beats the symmetric-contention baseline: the signal
    # moved real bandwidth, not just reordered bookkeeping.
    assert starved < baseline, (starved, baseline)

    probe = ShardClient(shard_server)
    try:
        stats = probe.stats()
        assert stats.throttled_chunks > 0
        assert stats.starved_streams_served >= 1
    finally:
        probe.close()


def test_shard_stream_source_reports_queue_depth(shard_server, monkeypatch):
    """The training input pipeline wires its prefetch-queue depth into the
    fetches it issues."""
    from serverless_learn_tpu.data.shard_client import (
        ShardStreamSource, publish_dataset)

    rng = np.random.default_rng(0)
    publish_dataset(shard_server, "ds", {
        "x": rng.standard_normal((64, 8)).astype(np.float32)},
        records_per_shard=16)
    flows = []
    real = ShardClient.set_flow

    def spy(self, flow):
        flows.append(flow)
        return real(self, flow)

    monkeypatch.setattr(ShardClient, "set_flow", spy)
    src = ShardStreamSource(shard_server, "ds", batch_size=8)
    it = iter(src)
    for _ in range(4):
        next(it)
    src.close()
    assert flows, "fetches must carry the queue depth"
    assert all(isinstance(f, int) and f >= 0 for f in flows)
