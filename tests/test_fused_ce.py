"""Fused (Pallas) softmax cross-entropy: must match optax exactly in value
and gradient, fall back off-tile, and compose with the sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from serverless_learn_tpu.ops.pallas.cross_entropy import (
    fused_cross_entropy_with_integer_labels)


def _ref(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels)


@pytest.mark.parametrize("shape,v", [((4, 16), 512), ((3, 7), 1024), ((21,), 512)])
def test_matches_optax_forward_and_grad(devices, shape, v):
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (*shape, v), jnp.float32) * 3.0
    labels = jax.random.randint(key, shape, 0, v)
    got = fused_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_ref(logits, labels)),
                               rtol=1e-5, atol=1e-5)
    g_ref = jax.grad(lambda x: _ref(x, labels).mean())(logits)
    g_got = jax.grad(
        lambda x: fused_cross_entropy_with_integer_labels(x, labels).mean()
    )(logits)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-6)


def test_bf16_logits(devices):
    key = jax.random.PRNGKey(1)
    logits = (jax.random.normal(key, (8, 512)) * 2).astype(jnp.bfloat16)
    labels = jax.random.randint(key, (8,), 0, 512)
    got = fused_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_ref(logits, labels)),
                               rtol=1e-2, atol=1e-2)
    # grads keep the input dtype
    g = jax.grad(
        lambda x: fused_cross_entropy_with_integer_labels(x, labels).mean()
    )(logits)
    assert g.dtype == jnp.bfloat16


def test_untiled_vocab_falls_back(devices):
    key = jax.random.PRNGKey(2)
    logits = jax.random.normal(key, (4, 100), jnp.float32)
    labels = jax.random.randint(key, (4,), 0, 100)
    got = fused_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_ref(logits, labels)),
                               rtol=1e-6)


def test_fused_train_step_matches_unfused(devices):
    """llama_tiny, dp=8 mesh: fused loss must reproduce the standard step."""
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    def run(fused):
        cfg = ExperimentConfig(
            model="llama_tiny",
            model_overrides={"fused_ce": fused, "dtype": jnp.float32},
            mesh=MeshConfig(dp=8),
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
            train=TrainConfig(batch_size=16, num_steps=2),
            data=DataConfig(seq_len=16),
        )
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=5)
        batch = trainer.shard_batch(next(iter(src)))
        out = []
        for _ in range(2):
            state, metrics = trainer.step(state, batch)
            out.append(float(jax.device_get(metrics["loss"])))
        return out

    np.testing.assert_allclose(run(False), run(True), rtol=2e-5)
