"""KV-cache decoding: incremental logits must match the full forward pass
position-for-position (the golden equivalence for any cache implementation),
and generation must be deterministic/greedy, EOS-sticky, and shape-stable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.generate import generate, init_cache
from serverless_learn_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def llama(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def test_decode_matches_full_forward(llama):
    module, params = llama
    B, T = 2, 10
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, 512)
    full = module.apply({"params": params}, tokens)  # [B, T, V]

    cache = init_cache(module, B)
    step_logits = []
    for t in range(T):
        logits, updated = module.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            decode=True, mutable=["cache"])
        cache = updated["cache"]
        step_logits.append(logits[:, 0])
    inc = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_greedy_generation_matches_full_forward_argmax(llama):
    """Greedy continuation must equal step-by-step argmax of full forwards."""
    module, params = llama
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0, 512)
    out = generate(module, params, prompt, max_new_tokens=6)
    assert out.shape == (1, 11)
    # Reference: repeatedly run the full (uncached) forward and take argmax.
    seq = prompt
    for _ in range(6):
        logits = module.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_generation_deterministic_and_batched(llama):
    module, params = llama
    prompt = jax.random.randint(jax.random.PRNGKey(3), (3, 4), 0, 512)
    a = generate(module, params, prompt, max_new_tokens=5)
    b = generate(module, params, prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (3, 9)


def test_sampled_generation_runs(llama):
    module, params = llama
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, 512)
    out = generate(module, params, prompt, max_new_tokens=5,
                   temperature=0.8, top_k=16, rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 9)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < 512).all()


def test_eos_is_sticky(llama):
    module, params = llama
    prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0, 512)
    first = generate(module, params, prompt, max_new_tokens=1)
    eos = int(first[0, -1])  # force the very first sampled token to be "eos"
    out = np.asarray(generate(module, params, prompt, max_new_tokens=6,
                              eos_id=eos))
    assert (out[0, 4:] == eos).all(), out


def test_zero_new_tokens_returns_prompt(llama):
    module, params = llama
    prompt = jnp.ones((2, 3), jnp.int32)
    out = generate(module, params, prompt, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_extend_matches_full_forward(llama):
    """The speculative-verify primitive directly: prefill a prompt, then
    feed the continuation in two multi-token ``extend`` chunks — logits
    must match the full uncached forward position-for-position, and the
    cache index must advance per chunk."""
    module, params = llama
    B, P, E1, E2 = 2, 6, 4, 3
    tokens = jax.random.randint(jax.random.PRNGKey(9), (B, P + E1 + E2),
                                0, 512)
    full = module.apply({"params": params}, tokens)  # [B, T, V]

    cache = init_cache(module, B)
    _, upd = module.apply({"params": params, "cache": cache},
                          tokens[:, :P], prefill=True, mutable=["cache"])
    cache = upd["cache"]
    got = []
    for lo, hi in ((P, P + E1), (P + E1, P + E1 + E2)):
        logits, upd = module.apply({"params": params, "cache": cache},
                                   tokens[:, lo:hi], extend=True,
                                   mutable=["cache"])
        cache = upd["cache"]
        got.append(logits)
    inc = jnp.concatenate(got, axis=1)
    np.testing.assert_allclose(np.asarray(inc),
                               np.asarray(full[:, P:]),
                               rtol=2e-4, atol=2e-4)
    # Rollback: resetting the per-row index re-decodes the same position
    # with identical logits (the speculative loop's rejection path).
    from serverless_learn_tpu.inference.speculative import (
        _set_cache_index)

    back = _set_cache_index(cache, jnp.full((B,), P, jnp.int32))
    relog, _ = module.apply({"params": params, "cache": back},
                            tokens[:, P:P + 1], extend=True,
                            mutable=["cache"])
    np.testing.assert_allclose(np.asarray(relog[:, 0]),
                               np.asarray(full[:, P]),
                               rtol=2e-4, atol=2e-4)


def test_too_long_generation_rejected(llama):
    module, params = llama
    prompt = jnp.zeros((1, 60), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(module, params, prompt, max_new_tokens=10)
