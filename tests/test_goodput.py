"""Goodput/badput accounting, shared profiler, and `slt bench --gate`
(`telemetry/goodput.py`, `telemetry/profiler.py`, `telemetry/benchgate.py`).

Fast tier: PhaseLedger nesting/exclusivity math on fabricated timelines
(injected clock — the arithmetic is asserted exact), /goodput endpoint
round-trip, phase records merging into `slt trace` output, the bench
gate passing flat history and failing an injected 20% regression,
alert-triggered capture rate-limiting, `slt goodput --self-check`, and
the tracing narration gate (silent by default).

Slow tier: a tiny real train run asserts goodput in (0, 1] with compile
badput recorded on the first step and the breakdown summing to the run's
wall-clock within 1%.
"""

import json
import threading

import pytest

from serverless_learn_tpu.telemetry import benchgate, goodput, profiler
from serverless_learn_tpu.telemetry.exporter import MetricsExporter, fetch_text
from serverless_learn_tpu.telemetry.goodput import (PhaseLedger,
                                                    aggregate_events,
                                                    build_report)
from serverless_learn_tpu.telemetry.registry import MetricsRegistry


# -- ledger math (fast) ------------------------------------------------------

def _fake_clock():
    t = [0.0]

    def clock():
        return t[0]

    return t, clock


def test_ledger_nesting_exclusivity_exact():
    """Entering a child pauses the parent: exclusive attribution is
    exact on a fabricated timeline."""
    t, clock = _fake_clock()
    led = PhaseLedger(clock=clock, emit=False)
    led.ensure_started()
    with led.phase("step"):
        t[0] += 4.0
        with led.phase("checkpoint"):
            t[0] += 2.0
            with led.phase("data_wait"):  # double nesting
                t[0] += 1.0
        t[0] += 3.0
    snap = led.snapshot()
    ph = snap["phases"]
    assert ph["step"]["seconds"] == 7.0       # 10 total - 3 child
    assert ph["checkpoint"]["seconds"] == 2.0  # 3 total - 1 child
    assert ph["data_wait"]["seconds"] == 1.0
    assert ph["step"]["count"] == 1
    assert snap["total_s"] == 10.0
    # Sibling phases and direct credit.
    with led.phase("idle"):
        t[0] += 5.0
    led.add("remesh", 0.5)
    snap = led.snapshot()
    assert snap["phases"]["idle"]["seconds"] == 5.0
    assert snap["phases"]["remesh"]["seconds"] == 0.5


def test_ledger_open_phase_counts_in_snapshot():
    """A live scrape mid-phase credits the open phase its elapsed time —
    a 10-minute step must not read as unattributed."""
    t, clock = _fake_clock()
    led = PhaseLedger(clock=clock, emit=False)
    cm = led.phase("step")
    cm.__enter__()
    t[0] += 6.0
    snap = led.snapshot()
    assert snap["phases"]["step"]["seconds"] == 6.0
    assert snap["total_s"] == 6.0
    t[0] += 1.0
    cm.__exit__(None, None, None)
    assert led.snapshot()["phases"]["step"]["seconds"] == 7.0


def test_report_sums_to_total_and_weights_mfu():
    rep = build_report(
        {"step": {"seconds": 6.0, "count": 3},
         "compile": {"seconds": 2.0, "count": 1},
         "data_wait": {"seconds": 1.0, "count": 4}},
        total_s=10.0, mfu=0.5)
    assert rep["goodput"] == pytest.approx(0.6)
    assert rep["mfu_weighted_goodput"] == pytest.approx(0.3)
    summed = sum(p["seconds"] for p in rep["phases"].values())
    assert summed == pytest.approx(rep["total_s"])  # incl. unattributed
    assert rep["phases"]["unattributed"]["seconds"] == pytest.approx(1.0)
    assert "compile" in rep["badput_breakdown"]
    assert "step" not in rep["badput_breakdown"]


def test_ledger_threads_keep_separate_stacks():
    """Contextvar scoping: a phase opened in one thread is never the
    parent of a phase in another; both threads' totals accumulate."""
    led = PhaseLedger(emit=False)
    errs = []

    def worker(name):
        try:
            with led.phase(name):
                pass
        except Exception as e:  # pragma: no cover
            errs.append(e)

    with led.phase("step"):
        ts = [threading.Thread(target=worker, args=("idle",))
              for _ in range(4)]
        for th in ts:
            th.start()
        for th in ts:
            th.join()
    assert not errs
    snap = led.snapshot()
    assert snap["phases"]["idle"]["count"] == 4
    assert snap["phases"]["step"]["count"] == 1


# -- /goodput endpoint (fast) ------------------------------------------------

def test_goodput_endpoint_roundtrip():
    """A live /goodput scrape returns the ledger report, MFU-weighted
    when the registry publishes slt_train_mfu."""
    t, clock = _fake_clock()
    led = PhaseLedger(clock=clock, emit=False)
    led.ensure_started()
    with led.phase("step"):
        t[0] += 8.0
    with led.phase("data_wait"):
        t[0] += 2.0
    reg = MetricsRegistry()
    reg.gauge("slt_train_mfu").set(0.5)
    prev = goodput.set_ledger(led)
    exp = MetricsExporter(reg).start()
    try:
        rep = json.loads(fetch_text(exp.addr, "/goodput"))
    finally:
        exp.stop()
        goodput.set_ledger(prev)
    assert rep["enabled"] is True
    assert rep["goodput"] == pytest.approx(0.8)
    assert rep["mfu_weighted_goodput"] == pytest.approx(0.4)
    summed = sum(p["seconds"] for p in rep["phases"].values())
    assert abs(summed - rep["total_s"]) <= 0.01 * rep["total_s"]


# -- phase records -> slt trace (fast) ---------------------------------------

def test_phase_events_merge_into_trace_output(tmp_path):
    from serverless_learn_tpu.telemetry import timeline

    log = tmp_path / "node-a.jsonl"
    recs = [
        {"event": "phase", "phase": "compile", "node": "a",
         "t0_unix_s": 100.0, "duration_s": 3.0, "self_s": 3.0},
        {"event": "phase", "phase": "step", "node": "a",
         "t0_unix_s": 103.0, "duration_s": 7.0, "self_s": 7.0},
        {"event": "span", "span": "train/run", "node": "a",
         "trace_id": "a" * 32, "span_id": "b" * 16,
         "t0_unix_s": 100.0, "duration_s": 10.0, "marks_s": {"done": 10.0}},
    ]
    with open(log, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    tl = timeline.reconstruct([str(log)])
    names = sorted(s.name for s in tl.spans)
    assert "phase/compile" in names and "phase/step" in names
    events = timeline.to_trace_events(tl)["traceEvents"]
    bands = [e for e in events if e.get("name", "").startswith("phase/")]
    assert len(bands) == 2
    # The synthetic phase lane never ranks as a "slowest trace".
    summary = timeline.summarize(tl)
    assert summary["phase_lanes"] == 1
    assert summary["traces"] == 1
    assert all(not r["trace_id"].startswith("phase-")
               for r in summary["slowest_traces"])


def test_aggregate_events_per_node_breakdown():
    recs = [
        {"event": "phase", "phase": "step", "node": "a",
         "t0_unix_s": 0.0, "duration_s": 8.0, "self_s": 8.0},
        {"event": "phase", "phase": "checkpoint", "node": "a",
         "t0_unix_s": 8.0, "duration_s": 2.0, "self_s": 2.0},
        {"event": "phase", "phase": "decode", "node": "b",
         "t0_unix_s": 50.0, "duration_s": 5.0, "self_s": 5.0},
        {"event": "other", "node": "a"},
    ]
    by_node = aggregate_events(recs)
    assert by_node["a"]["goodput"] == pytest.approx(0.8)
    assert by_node["a"]["total_s"] == pytest.approx(10.0)
    assert by_node["b"]["goodput"] == pytest.approx(1.0)


def test_aggregate_events_agrees_with_live_ledger(monkeypatch):
    """Offline path vs live path over the SAME run (round 24): the
    per-node report rebuilt by ``aggregate_events`` from the emitted
    phase records agrees with the live ledger's own ``report()`` within
    tolerance. Real clock on purpose — emitted ``t0_unix_s`` comes from
    ``time.time()`` regardless of the injected clock, so a fake clock
    would give the two paths different denominators by construction."""
    import time as _time

    from serverless_learn_tpu.telemetry import tracing

    captured = []
    monkeypatch.setattr(tracing, "emit_event",
                        lambda rec: captured.append(dict(rec, node="t")))
    led = PhaseLedger(emit=True, emit_min_s=0.0)
    with led.phase("compile"):
        _time.sleep(0.08)
    for _ in range(2):
        with led.phase("step"):
            _time.sleep(0.1)
    with led.phase("data_wait"):
        _time.sleep(0.06)
    live = led.report()
    offline = aggregate_events(captured)["t"]
    assert offline["goodput"] == pytest.approx(live["goodput"], abs=0.05)
    assert offline["total_s"] == pytest.approx(live["total_s"], abs=0.05)
    for name, ph in live["phases"].items():
        if name == "unattributed":
            continue
        assert offline["phases"][name]["seconds"] == pytest.approx(
            ph["seconds"], rel=0.1, abs=0.02)
        assert offline["phases"][name]["count"] == ph["count"]


# -- CLI: goodput (fast) -----------------------------------------------------

def test_goodput_cli_from_events(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    log = tmp_path / "run.jsonl"
    with open(log, "w") as f:
        for rec in (
            {"event": "phase", "phase": "compile", "node": "n",
             "t0_unix_s": 0.0, "duration_s": 2.0, "self_s": 2.0},
            {"event": "phase", "phase": "step", "node": "n",
             "t0_unix_s": 2.0, "duration_s": 8.0, "self_s": 8.0},
        ):
            f.write(json.dumps(rec) + "\n")
    assert main(["goodput", "--from-events", str(log)]) == 0
    rep = json.loads(capsys.readouterr().out)
    node = rep["nodes"]["n"]
    assert node["goodput"] == pytest.approx(0.8)
    # Acceptance: the printed per-phase breakdown sums to the total run
    # time within 1%.
    summed = sum(p["seconds"] for p in node["phases"].values())
    assert abs(summed - node["total_s"]) <= 0.01 * node["total_s"]


def test_goodput_cli_self_check(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["goodput", "--self-check", "--compact"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is True


def test_goodput_cli_needs_input(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["goodput"]) == 2


# -- bench gate (fast) -------------------------------------------------------

def _hist_row(value, **extra):
    return {"metric": "resnet18_cifar_train_samples_per_sec_per_chip",
            "value": value, "unit": "samples/sec/chip",
            "device_kind": "TPU v5 lite", "batch_per_chip": 4096, **extra}


def test_bench_gate_passes_flat_history(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps(
        [_hist_row(100.0), _hist_row(101.0),
         _hist_row(100.0, goodput=0.97,
                   badput_breakdown={"compile": 0.03}),
         {"metric": "corrupt", "value": "n/a"}]))
    assert main(["bench", "--gate", "--dry-run",
                 "--history", str(hist)]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is True and rep["series"] >= 1


def test_bench_gate_fails_injected_regression(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps(
        [_hist_row(100.0), _hist_row(101.0), _hist_row(80.0)]))  # -20%
    assert main(["bench", "--gate", "--dry-run",
                 "--history", str(hist)]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is False
    assert rep["regressions"][0]["loss_rel"] == pytest.approx(0.208, abs=1e-3)
    # Without --gate the same report is informational: exit 0.
    assert main(["bench", "--dry-run", "--history", str(hist)]) == 0


def test_bench_gate_noise_widening_and_missing_history(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    # A 10% drop with a recorded 6% spread widens the gate to 12%: pass.
    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps(
        [_hist_row(100.0), _hist_row(90.0, spread_rel=0.06)]))
    assert main(["bench", "--gate", "--dry-run",
                 "--history", str(hist)]) == 0
    capsys.readouterr()
    # A gate pointed at a missing file fails loudly, not vacuously.
    assert main(["bench", "--gate", "--dry-run",
                 "--history", str(tmp_path / "nope.json")]) == 1


def test_gate_entry_first_run_passes_vacuously():
    check = benchgate.gate_entry(_hist_row(50.0), [])
    assert check["ok"] is True and check["n_baseline"] == 0


# -- committed history stays gate-clean (fast) -------------------------------

def test_committed_bench_history_passes_gate():
    """CI acceptance: the repo's own bench_history.json must pass the
    dry-run gate (regressed entries were retried/explained at record
    time; the latest comparable entries are within threshold)."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_history.json")
    rep = benchgate.run_gate(path)
    assert rep["ok"] is True, rep["regressions"]


# -- alert-triggered capture (fast) ------------------------------------------

def test_alert_triggered_capture_is_rate_limited():
    from serverless_learn_tpu.telemetry.health import HealthEngine

    captured = []

    def fake_capture(seconds, reason=""):
        captured.append((seconds, reason))
        return {"ok": True}

    eng = HealthEngine(registry=MetricsRegistry(), emit=lambda r: None,
                       dump_on_critical=False)
    profiler.on_alert(eng, seconds=1.5, cooldown_s=3600.0,
                      capture_fn=fake_capture, in_thread=False)
    # A warning never captures; the first critical does; the second
    # critical inside the cooldown is suppressed.
    eng._fire(1.0, "w", "warning", "structural", "m", 1.0, 0.0)
    assert captured == []
    eng._fire(2.0, "stale.train_step", "critical", "structural",
              "m", 1.0, 0.0)
    eng._fire(3.0, "stale.decode_chunk", "critical", "structural",
              "m", 1.0, 0.0)
    assert len(captured) == 1
    assert captured[0] == (1.5, "alert:stale.train_step")


def test_profiler_capture_stamps_meta_and_rejects_nested(tmp_path):
    out = tmp_path / "cap"
    rep = profiler.capture(0.05, out_dir=str(out))
    assert rep["ok"] is True
    meta = json.loads((out / "capture-meta.json").read_text())
    assert meta["reason"] == "on-demand"
    assert "ledger_at_trigger" in meta
    with pytest.raises(RuntimeError):
        profiler.capture(0.05)  # nothing armed, no out_dir
    with profiler.capture_session(str(tmp_path / "sess")):
        with pytest.raises(profiler.ProfilerBusy):
            profiler.capture(0.05, out_dir=str(tmp_path / "x"))


# -- narration gate (fast) ---------------------------------------------------

def test_tracer_narration_silent_by_default(capsys, monkeypatch):
    from serverless_learn_tpu.utils.tracing import NARRATE_ENV, Tracer

    monkeypatch.delenv(NARRATE_ENV, raising=False)
    tr = Tracer()
    with tr.span("rpc/fetch", annotate_device=False):
        pass
    out = capsys.readouterr()
    assert out.out == "" and out.err == ""  # no per-RPC narration
    tr2 = Tracer(narrate=True)
    with tr2.span("rpc/fetch", annotate_device=False):
        pass
    out = capsys.readouterr()
    assert out.out == ""            # stdout stays machine-readable
    assert "rpc/fetch" in out.err   # opt-in narration goes to stderr
    monkeypatch.setenv(NARRATE_ENV, "1")
    with tr.span("rpc/env", annotate_device=False):
        pass
    assert "rpc/env" in capsys.readouterr().err


# -- the real thing (slow) ---------------------------------------------------

def test_train_run_records_goodput():
    """Acceptance: a tiny real training run books compile badput on the
    first step, lands goodput in (0, 1], and its breakdown sums to the
    run's wall-clock within 1%."""
    from serverless_learn_tpu.config import (DataConfig, ExperimentConfig,
                                             MeshConfig, TrainConfig)
    from serverless_learn_tpu.training.loop import run_training

    led = PhaseLedger(emit=False)
    prev = goodput.set_ledger(led)
    try:
        cfg = ExperimentConfig(
            model="mlp_mnist", mesh=MeshConfig(dp=8),
            train=TrainConfig(batch_size=16, num_steps=4),
            data=DataConfig())
        run_training(cfg)
        rep = led.report()
    finally:
        goodput.set_ledger(prev)
    assert 0.0 < rep["goodput"] <= 1.0
    ph = rep["phases"]
    assert ph["compile"]["count"] == 1          # first step only
    assert ph["compile"]["seconds"] > 0.0
    assert ph["step"]["count"] == 3
    assert "data_wait" in ph                    # Prefetcher consumer wait
    summed = sum(p["seconds"] for p in ph.values())
    assert abs(summed - rep["total_s"]) <= 0.01 * rep["total_s"]


# -- ZeRO layout columns gate (round 18) --------------------------------------


def test_gate_holds_opt_state_bytes_column():
    """opt_state_bytes_per_chip regresses UP with a RELATIVE gap: a row
    whose opt state quietly un-sharded (8x the bytes) fails even when
    throughput held; rows predating the column neither gate nor mask."""
    old = _hist_row(100.0)  # pre-column row: must not mask
    good = _hist_row(100.0, opt_state_bytes_per_chip=670_000,
                     grad_reduce_scatter_s=0.004)
    ok = benchgate.gate_entry(
        _hist_row(101.0, opt_state_bytes_per_chip=700_000,
                  grad_reduce_scatter_s=0.005), [old, good])
    assert ok["ok"] is True, ok
    bad = benchgate.gate_entry(
        _hist_row(101.0, opt_state_bytes_per_chip=5_360_000), [old, good])
    assert bad["ok"] is False
    assert any(c["column"] == "opt_state_bytes_per_chip" and not c["ok"]
               for c in bad["attribution"])
    # A row without the new columns gates only on value + round-16 cols.
    legacy = benchgate.gate_entry(_hist_row(100.5), [old, good])
    assert legacy["ok"] is True, legacy


def test_zero_fixture_history_passes_gate():
    """CI acceptance twin: the committed ZeRO-column fixture history
    (the `bench --gate --dry-run --history tests/fixtures/zero/...` CI
    step) must stay gate-clean."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fixtures", "zero",
        "bench_history_zero.json")
    rep = benchgate.run_gate(path)
    assert rep["ok"] is True, rep["regressions"]
    checks = rep["checks"][0]
    cols = {c["column"] for c in checks.get("attribution", [])}
    assert "opt_state_bytes_per_chip" in cols, checks
