"""SWIM gossip membership (round 11): protocol core, wire fuzzing, and
the live UDP agent/coordinator integration."""

import json
import os
import random
import time

import pytest

from serverless_learn_tpu.control.gossip import (
    ALIVE, DEAD, SUSPECT, GossipConfig, GossipNode, decode_payload)


# ---------------------------------------------------------------------------
# deterministic in-line harness (no sockets, explicit clock)
# ---------------------------------------------------------------------------


class Loopnet:
    """Tiny synchronous message bus for driving GossipNodes directly."""

    def __init__(self, cfg, n, seed=0):
        self.cfg = cfg
        self.nodes = {f"n{i}": GossipNode(
            f"n{i}", f"a{i}", cfg, rng=random.Random(f"t-{seed}-{i}"))
            for i in range(n)}
        self.addr2id = {f"a{i}": f"n{i}" for i in range(n)}
        self.alive = set(self.nodes)
        self.pending = []
        self.now = 0.0
        self.blocked = set()  # (src, dst) pairs dropped

    def dispatch(self, src_id, outs):
        for addr, payload in outs:
            dst = self.addr2id.get(addr)
            if dst and (src_id, dst) not in self.blocked:
                self.pending.append((self.now + 0.01, dst, src_id, payload))

    def join_all(self, seed_addr="a0"):
        for nid, node in self.nodes.items():
            if node.addr != seed_addr:
                self.dispatch(nid, node.join([seed_addr], self.now))

    def run(self, duration, dt=0.05):
        end = self.now + duration
        while self.now < end:
            self.now += dt
            due = [p for p in self.pending if p[0] <= self.now]
            for p in due:
                self.pending.remove(p)
                _, dst, src, payload = p
                if dst in self.alive and (src, dst) not in self.blocked:
                    self.dispatch(dst, self.nodes[dst].on_message(
                        payload, self.now))
            for nid in list(self.alive):
                self.dispatch(nid, self.nodes[nid].tick(self.now))

    def views_agree(self):
        want = sorted(self.alive)
        return all(self.nodes[n].alive_ids() == want for n in self.alive)


CFG = GossipConfig(protocol_period_s=0.5, ping_timeout_s=0.15)


def test_membership_forms_and_agrees():
    net = Loopnet(CFG, 10)
    net.join_all()
    net.run(8.0)
    assert net.views_agree()
    # epochs settle: every confirmed join bumped them, nothing after
    epochs = [net.nodes[n].epoch for n in sorted(net.alive)]
    net.run(4.0)
    assert [net.nodes[n].epoch for n in sorted(net.alive)] == epochs


def test_killed_node_detected_and_disseminated():
    net = Loopnet(CFG, 10)
    net.join_all()
    net.run(8.0)
    net.alive.discard("n3")
    t_kill = net.now
    for _ in range(200):
        net.run(0.5)
        if all("n3" not in net.nodes[n].alive_ids() for n in net.alive):
            break
    else:
        pytest.fail("n3 never declared dead everywhere")
    periods = (net.now - t_kill) / CFG.protocol_period_s
    # detection (probe + suspicion timeout) + dissemination, all O(log N)
    import math
    log_n = math.ceil(math.log2(len(net.nodes) + 1))
    assert periods <= 4 + (CFG.suspicion_mult + 3) * log_n


def test_suspected_but_alive_refutes_without_flapping():
    """The no-remesh-flap contract: a member that merely STOPS ANSWERING
    for a while (blocked links, paused process) is suspected, refutes with
    an incarnation bump once reachable, and no node ever (a) declares it
    dead or (b) bumps its membership epoch — suspicion is invisible to
    elastic."""
    net = Loopnet(CFG, 8)
    net.join_all()
    net.run(8.0)
    assert net.views_agree()
    epochs_before = {n: net.nodes[n].epoch for n in net.alive}
    # block everyone's path to n5 (and back) long enough to be suspected
    # but shorter than the suspicion timeout
    victim = "n5"
    net.blocked = {(a, b) for a in net.nodes for b in net.nodes
                   if victim in (a, b) and a != b}
    suspicion_timeout = (CFG.suspicion_mult *
                         __import__("math").ceil(
                             __import__("math").log2(9))
                         * CFG.protocol_period_s)
    net.run(min(2.5 * CFG.protocol_period_s, 0.8 * suspicion_timeout))
    suspected = any(victim in net.nodes[n].suspect_ids()
                    for n in net.alive if n != victim)
    assert suspected, "victim was never suspected while unreachable"
    inc_before = net.nodes[victim].incarnation
    net.blocked = set()
    net.run(6.0)
    # refuted: alive everywhere, incarnation bumped, never dead
    for n in net.alive:
        members = net.nodes[n].members()
        if victim in members:
            assert members[victim].state == ALIVE
    assert net.nodes[victim].incarnation > inc_before
    # zero epoch churn: suspicion + refutation is not a membership change
    assert {n: net.nodes[n].epoch for n in net.alive} == epochs_before


def test_graceful_leave_skips_suspicion():
    net = Loopnet(CFG, 6)
    net.join_all()
    net.run(6.0)
    leaver = net.nodes["n4"]
    net.dispatch("n4", leaver.leave(net.now))
    net.alive.discard("n4")
    net.run(3.0)
    for n in net.alive:
        m = net.nodes[n].members().get("n4")
        assert m is not None and m.state in ("left", "dead")
        assert "n4" not in net.nodes[n].alive_ids()


# ---------------------------------------------------------------------------
# wire fuzzing: malformed payloads must be counted, never raised
# ---------------------------------------------------------------------------


def _counter_value(name):
    from serverless_learn_tpu.telemetry import get_registry

    fam = get_registry().snapshot().get(name)
    if not fam:
        return 0.0
    return sum(s.get("value", 0.0) for s in fam.get("series", []))


def test_fuzz_malformed_payloads_never_crash():
    node = GossipNode("x", "ax", CFG, rng=random.Random("fuzz"))
    rng = random.Random(1234)
    bad_before = _counter_value("slt_gossip_bad_payloads_total")
    cases = [
        b"", b"{", b"null", b"[]", b'"str"', b"\xff\xfe\x00",
        json.dumps({"v": 99, "t": "ping", "from": "a", "fa": "x",
                    "seq": 1}).encode(),
        json.dumps({"v": 1, "t": 3, "from": "a", "fa": "x",
                    "seq": 1}).encode(),
        json.dumps({"v": 1, "t": "ping", "from": None, "fa": "x",
                    "seq": 1}).encode(),
        json.dumps({"v": 1, "t": "ping", "from": "a", "fa": "x",
                    "seq": "NaN"}).encode(),
        json.dumps({"v": 1, "t": "ping", "from": "a", "fa": "x",
                    "seq": True}).encode(),
        json.dumps({"v": 1, "t": "ping", "from": "a", "fa": "x", "seq": 1,
                    "g": {"not": "a list"}}).encode(),
        b"x" * (70 * 1024),  # oversized datagram
    ]
    # seeded-random byte soup, including truncations of a VALID packet
    valid = node.tick(0.0)
    base = json.dumps({"v": 1, "t": "ping", "from": "z", "fa": "az",
                       "seq": 7, "g": [{"id": "q", "a": "aq", "i": 3,
                                        "s": "alive", "m": {}}]}).encode()
    for _ in range(300):
        cases.append(bytes(rng.randrange(256) for _ in
                           range(rng.randrange(0, 200))))
        cases.append(base[:rng.randrange(0, len(base))])
    for data in cases:
        node.on_message(data, 1.0)  # must never raise
    assert _counter_value("slt_gossip_bad_payloads_total") > bad_before
    # malformed g-entries inside a valid packet are skipped silently
    mixed = json.dumps({"v": 1, "t": "ping", "from": "z", "fa": "az",
                        "seq": 8, "g": [
                            {"id": "ok", "a": "aok", "i": 1, "s": "alive",
                             "m": {}},
                            {"id": 5, "a": "bad"},
                            {"id": "neg", "a": "x", "i": -3, "s": "alive",
                             "m": {}},
                            "not a dict"]}).encode()
    node.on_message(mixed, 2.0)
    assert "ok" in node.members()
    assert "neg" not in node.members()


def test_stale_incarnation_replay_dropped_with_counter():
    node = GossipNode("x", "ax", CFG, rng=random.Random("stale"))

    def pkt(inc, state, seq):
        return json.dumps({"v": 1, "t": "ping", "from": "peer", "fa": "ap",
                           "seq": seq, "g": [{"id": "m1", "a": "am1",
                                              "i": inc, "s": state,
                                              "m": {}}]}).encode()

    node.on_message(pkt(5, "alive", 1), 1.0)
    assert node.members()["m1"].incarnation == 5
    stale_before = _counter_value("slt_gossip_stale_updates_total")
    node.on_message(pkt(2, "alive", 2), 2.0)    # old-incarnation replay
    node.on_message(pkt(5, "alive", 3), 3.0)    # same-rank duplicate
    node.on_message(pkt(2, "suspect", 4), 4.0)  # stale suspicion replay
    m = node.members()["m1"]
    assert m.incarnation == 5 and m.state == ALIVE
    assert _counter_value("slt_gossip_stale_updates_total") > stale_before
    # fresher suspicion still lands
    node.on_message(pkt(5, "suspect", 5), 5.0)
    assert node.members()["m1"].state == SUSPECT


def test_decode_payload_contract():
    assert decode_payload(b"nope") is None
    assert decode_payload(json.dumps(
        {"v": 1, "t": "ping", "from": "a", "fa": "b", "seq": 0,
         "g": []}).encode()) is not None


# ---------------------------------------------------------------------------
# live UDP integration: agents + gossip-mode py-coordinator
# ---------------------------------------------------------------------------


def _wait_until(fn, timeout=10.0, dt=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(dt)
    return False


def test_gossip_agents_with_coordinator():
    """Three GossipAgents + a gossip-mode PyCoordinator over real UDP:
    everyone sees everyone; killing one agent's process (no graceful
    leave) gets it detected by gossip and evicted by the coordinator
    without waiting out a lease."""
    from serverless_learn_tpu.config import MembershipConfig
    from serverless_learn_tpu.control.gossip import GossipAgent
    from serverless_learn_tpu.control.py_daemons import PyCoordinator

    coord = PyCoordinator(port=0, lease_ttl_ms=60000, sweep_ms=100,
                          gossip_port=0)
    coord.start()
    mcfg = MembershipConfig(mode="gossip",
                            seed=coord.gossip_runtime.addr,
                            protocol_period_ms=100, ping_timeout_ms=30)
    agents = []
    try:
        for i in range(3):
            a = GossipAgent(coord.addr, f"local:{i}", name=f"g{i}",
                            heartbeat_interval_ms=200,
                            membership=mcfg).start()
            agents.append(a)
        assert _wait_until(
            lambda: all(len(a.snapshot()[1]) == 3 for a in agents)), \
            [a.snapshot() for a in agents]
        victim = agents[2]
        victim_id = victim.worker_id
        # hard kill: no leave broadcast, no deregister
        victim._runtime._stop.set()
        victim._runtime.sock.close()
        victim._inner._stop.set()
        assert _wait_until(
            lambda: all(len(a.snapshot()[1]) == 2 for a in agents[:2]),
            timeout=15.0), [a.snapshot() for a in agents[:2]]
        # the coordinator's gossip node evicted it (lease was 60s)
        assert _wait_until(
            lambda: victim_id not in {
                p.worker_id for p in
                agents[0]._inner.client.membership().peers},
            timeout=15.0)
    finally:
        for a in agents:
            try:
                a.stop(deregister=False)
            except Exception:
                pass
        coord.stop()


def test_make_membership_agent_mode_switch():
    from serverless_learn_tpu.config import ExperimentConfig
    from serverless_learn_tpu.control.client import WorkerAgent
    from serverless_learn_tpu.control.gossip import (
        GossipAgent, make_membership_agent)
    from serverless_learn_tpu.control.py_daemons import PyCoordinator

    coord = PyCoordinator(port=0, gossip_port=0)
    coord.start()
    try:
        cfg = ExperimentConfig.from_dict({})
        a = make_membership_agent(cfg, coord.addr, "local:0", name="m0")
        assert isinstance(a, WorkerAgent)
        cfg2 = ExperimentConfig.from_dict({"membership": {
            "mode": "gossip", "seed": coord.gossip_runtime.addr,
            "protocol_period_ms": 100, "ping_timeout_ms": 30}})
        b = make_membership_agent(cfg2, coord.addr, "local:1", name="m1")
        assert isinstance(b, GossipAgent)
        b.start()
        assert _wait_until(lambda: any(
            p.name == "m1" for p in b.snapshot()[1]))
        b.stop()
    finally:
        coord.stop()


def test_membership_config_roundtrip():
    from serverless_learn_tpu.config import ExperimentConfig

    cfg = ExperimentConfig.from_json(json.dumps({
        "membership": {"mode": "gossip", "remesh_debounce_s": 1.5,
                       "safe_pause": True}}))
    assert cfg.membership.mode == "gossip"
    assert cfg.membership.remesh_debounce_s == 1.5
    assert cfg.membership.safe_pause
    back = json.loads(cfg.to_json())
    assert back["membership"]["quorum_fraction"] == 0.5
