"""Gradient accumulation and eval-path tests.

Gradient accumulation must be a pure memory/latency trade: with fp32 math,
SGD and a deterministic model, ``grad_accum=k`` over a batch must produce the
same parameter update as a single whole-batch step. The eval path must run in
inference mode (ResNet uses running statistics) and never mutate state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.analysis import shardcheck
from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.loop import run_eval, run_training
from serverless_learn_tpu.training.train_step import build_trainer


def _cfg(model="mlp_mnist", mesh=None, model_overrides=None, **train_kw):
    train_kw.setdefault("batch_size", 32)
    train_kw.setdefault("num_steps", 3)
    return ExperimentConfig(
        model=model,
        model_overrides=model_overrides or {},
        mesh=mesh or MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(**train_kw),
        data=DataConfig(seq_len=16),
    )


def _one_step(cfg):
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data,
                          cfg.train.batch_size, seed=7)
    state, metrics = trainer.step(state, trainer.shard_batch(next(iter(src))))
    return (jax.device_get(state.params),
            {k: float(v) for k, v in jax.device_get(metrics).items()})


def test_grad_accum_matches_whole_batch(devices):
    """accum=4 must reproduce the accum=1 update exactly (fp32, SGD, MLP)."""
    base = _cfg(model_overrides={"dtype": jnp.float32})
    p1, m1 = _one_step(base)
    p4, m4 = _one_step(base.override(
        train=TrainConfig(batch_size=32, num_steps=3, grad_accum=4)))
    np.testing.assert_allclose(m1["loss"], m4["loss"], rtol=1e-5)
    np.testing.assert_allclose(m1["grad_norm"], m4["grad_norm"], rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_grad_accum_sharded_transformer_runs(devices):
    """accum composes with dp/fsdp/tp shardings on a transformer."""
    cfg = _cfg(model="llama_tiny", mesh=MeshConfig(dp=2, fsdp=2, tp=2),
               batch_size=8, grad_accum=2)
    _, metrics = _one_step(cfg)
    assert np.isfinite(metrics["loss"])


def test_grad_accum_validation(devices):
    with pytest.raises(ValueError, match="divisible by grad_accum"):
        build_trainer(_cfg(batch_size=32, grad_accum=3))


def test_resnet_eval_uses_running_stats_and_keeps_state(devices):
    cfg = _cfg(model="resnet18_cifar", batch_size=16)
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=3)
    batch = trainer.shard_batch(next(iter(src)))
    # A couple of train steps so running stats move off their init.
    for _ in range(2):
        state, _ = trainer.step(state, batch)
    before = jax.device_get(state.model_state)
    metrics = jax.device_get(trainer.eval_step(state, batch))
    assert np.isfinite(float(metrics["loss"]))
    assert "accuracy" in metrics
    after = jax.device_get(state.model_state)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_run_eval_mean_metrics(devices):
    cfg = _cfg(batch_size=16)
    trainer = build_trainer(cfg)
    state = trainer.init()
    out = run_eval(cfg, trainer, state, num_batches=3)
    assert set(out) >= {"eval_loss", "eval_accuracy"}
    assert np.isfinite(out["eval_loss"])


def test_mlm_grad_accum_matches_whole_batch(devices):
    """Masked-LM normalizes by the microbatch's masked-token count; the
    loss_weight plumbing must still reproduce the whole-batch update."""
    base = _cfg(model="bert_tiny", batch_size=32,
                model_overrides={"dtype": jnp.float32})
    p1, m1 = _one_step(base)
    p4, m4 = _one_step(base.override(
        train=TrainConfig(batch_size=32, num_steps=3, grad_accum=4)))
    np.testing.assert_allclose(m1["loss"], m4["loss"], rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_in_loop_eval_fires(devices, monkeypatch):
    import serverless_learn_tpu.training.loop as loop_mod

    calls = []
    real = loop_mod.run_eval

    def spy(config, trainer, state, **kw):
        out = real(config, trainer, state, **kw)
        calls.append(out)
        return out

    monkeypatch.setattr(loop_mod, "run_eval", spy)
    cfg = _cfg(batch_size=16, num_steps=4, eval_every=2, eval_steps=2)
    state, meter = run_training(cfg)
    assert int(jax.device_get(state.step)) == 4
    assert len(calls) == 2, "eval_every=2 over 4 steps must eval twice"
    assert all(np.isfinite(c["eval_loss"]) for c in calls)


def test_run_eval_streams_from_shard_server(devices, tmp_path):
    """With a shard server configured, eval must consume the published
    eval split — not synthetic noise."""
    import socket

    from serverless_learn_tpu.control.daemons import start_shard_server
    from serverless_learn_tpu.data.shard_client import publish_from_bundle

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = start_shard_server(port=port, root=str(tmp_path))
    try:
        addr = f"127.0.0.1:{port}"
        cfg = _cfg(batch_size=16)
        cfg = cfg.override(data=DataConfig(
            dataset="toy", eval_dataset="toy_eval",
            shard_server_addr=addr, seq_len=16))
        trainer = build_trainer(cfg)
        publish_from_bundle(addr, "toy_eval", trainer.bundle.make_batch,
                            cfg.data, num_records=64, records_per_shard=32)
        state = trainer.init()
        out = run_eval(cfg, trainer, state, num_batches=2)
        assert np.isfinite(out["eval_loss"])
        assert "eval_on_train_data" not in out
        # No eval split published => falls back to the train dataset and
        # says so.
        publish_from_bundle(addr, "toy", trainer.bundle.make_batch,
                            cfg.data, num_records=64, records_per_shard=32)
        cfg2 = cfg.override(data=DataConfig(
            dataset="toy", shard_server_addr=addr, seq_len=16))
        out2 = run_eval(cfg2, trainer, state, num_batches=2)
        assert out2.get("eval_on_train_data") == 1.0
    finally:
        proc.terminate()
        proc.wait(timeout=5)


# -- ZeRO x grad-accum (round 18) ---------------------------------------------


def test_zero2_grad_accum_matches_whole_batch(devices):
    """accum=4 under ZeRO-2 must still reproduce the replicated accum=1
    update (fp32, SGD, MLP): sharding the reduce changes layout, never
    the accumulated math."""
    base = _cfg(model_overrides={"dtype": jnp.float32})
    p1, m1 = _one_step(base)
    p4, m4 = _one_step(base.override(
        train=TrainConfig(batch_size=32, num_steps=3, grad_accum=4,
                          zero_stage=2)))
    np.testing.assert_allclose(m1["loss"], m4["loss"], rtol=1e-5)
    np.testing.assert_allclose(m1["grad_norm"], m4["grad_norm"], rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_zero_reduce_scatter_once_per_step_not_per_microbatch(devices):
    """The regression audit ISSUE 13 asks for: under ZeRO-2 + grad_accum
    the microbatch scan must accumulate LOCALLY — the dp-sharding
    constraint that becomes the reduce-scatter is applied exactly once,
    after the scan, never inside its body (a constraint in the body
    would force one cross-replica collective per microbatch). Since
    round 25 the jaxpr walk lives in ``analysis/shardcheck.py``
    (SLT013's runtime harness) so every sharding-sensitive test shares
    one audit."""
    cfg = _cfg(model_overrides={"dtype": jnp.float32}).override(
        train=TrainConfig(batch_size=32, num_steps=1, grad_accum=4,
                          zero_stage=2))
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 32, seed=7)
    batch = trainer.shard_batch(next(iter(src)))
    report = shardcheck.audit(trainer.step_fn, state, batch)
    report.assert_no_loop_constraints()
    # The grads/updates constraints exist and sit outside the scan: at
    # least the microbatch input constraints plus dp-sharded grad specs
    # whose leading entry IS the dp axis (the batch constraints shard
    # dim 0 over the scan axis — spec starts with None).
    dp_grads = [s for s in report.outside
                if "PartitionSpec('dp'" in s or 'PartitionSpec("dp"' in s]
    assert len(dp_grads) >= 2, report.outside
    # And every axis the traced program mentions is a declared one —
    # the runtime face of SLT013's axis-drift check.
    from serverless_learn_tpu.config import MeshConfig
    assert report.axes_used <= set(MeshConfig.AXIS_NAMES), \
        report.axes_used
