"""Cluster health engine + `slt doctor` (`telemetry/health.py`, `doctor.py`).

Fast tier: detector math (EWMA/MAD determinism, burn-rate window
arithmetic at budget boundaries, staleness watchdog), straggler scoring
from a fabricated 3-worker round log, /healthz state transitions and the
/alerts endpoint, event-log rotation, the `slt top` ALERTS pane, doctor
end-to-end over fixture logs, and `doctor --self-check`.

Slow tier: the demo acceptance path — a real training run with an
injected stall fires a staleness alert on /alerts, flips /healthz to 503,
triggers a flight dump, and `slt doctor` names the offending node with a
correlated trace id.
"""

import glob
import json
import os
import threading
import time

import pytest

from serverless_learn_tpu.config import HealthConfig
from serverless_learn_tpu.telemetry import (HealthEngine, JsonlEventLog,
                                            MetricsExporter, MetricsRegistry,
                                            fetch_text)
from serverless_learn_tpu.telemetry.health import (BurnRate, EwmaMad,
                                                   StalenessWatch,
                                                   flatten_snapshot,
                                                   hist_good_total,
                                                   parse_slos,
                                                   score_stragglers)


# -- detector math (fast) ----------------------------------------------------

def test_ewma_mad_is_deterministic_and_flags_spikes():
    det = EwmaMad(alpha=0.3, window=64, min_samples=10, rel_floor=0.05)
    # Warmup: no z until min_samples history exists.
    for i in range(10):
        assert det.update(1.0) is None, i
    # Steady series: z exactly 0 (ewma == sample, MAD floor positive).
    assert det.update(1.0) == 0.0
    # A 10x spike against a constant baseline: z = .6745*(10-1)/(.05*1).
    z = det.update(10.0)
    assert z == pytest.approx(0.6745 * 9.0 / 0.05)
    # Determinism: an identical series yields the identical score.
    det2 = EwmaMad(alpha=0.3, window=64, min_samples=10, rel_floor=0.05)
    for _ in range(11):
        det2.update(1.0)
    assert det2.update(10.0) == z
    # The spike was absorbed: the baseline adapts instead of latching.
    assert det.ewma == pytest.approx(0.3 * 10.0 + 0.7 * 1.0)


def test_ewma_mad_low_tail():
    det = EwmaMad(min_samples=5, rel_floor=0.05)
    for _ in range(6):
        det.update(100.0)
    z = det.update(10.0)  # a throughput collapse is a NEGATIVE z
    assert z < -6.0


def test_burn_rate_window_arithmetic_at_budget_boundaries():
    # objective 0.99 -> budget 0.01; fast burn 14.4 means a bad fraction
    # of exactly 0.144 over both windows.
    br = BurnRate(budget=0.01, short_s=60, long_s=720,
                  fast_burn=14.4, slow_burn=6.0)
    assert br.update(0, 0, 0)["severity"] is None  # no history yet
    r = br.update(30, 144, 1000)
    assert r["short_burn"] == pytest.approx(14.4)
    assert r["long_burn"] == pytest.approx(14.4)
    assert r["severity"] == "critical"
    # One bad event fewer: 0.1439 -> burn 14.39 < 14.4 but >= 6 -> warning.
    br2 = BurnRate(budget=0.01, short_s=60, long_s=720)
    br2.update(0, 0, 0)
    r2 = br2.update(30, 143, 1000)
    assert r2["severity"] == "warning"
    # Under the slow threshold entirely: 59/1000 -> 5.9x.
    br3 = BurnRate(budget=0.01, short_s=60, long_s=720)
    br3.update(0, 0, 0)
    assert br3.update(30, 59, 1000)["severity"] is None
    # Zero traffic burns nothing.
    br4 = BurnRate(budget=0.01)
    br4.update(0, 5, 100)
    assert br4.update(30, 5, 100)["short_burn"] == 0.0
    with pytest.raises(ValueError):
        BurnRate(budget=0.0)


def test_burn_rate_needs_both_windows():
    """A long-ago incident must not page: the short window recovers and
    the severity drops even while the long window still burns."""
    br = BurnRate(budget=0.01, short_s=60, long_s=720,
                  fast_burn=14.4, slow_burn=6.0)
    br.update(0, 0, 0)
    assert br.update(60, 200, 1000)["severity"] == "critical"
    # 10 minutes of clean (light) traffic: the short window is clean even
    # though the long window still burns hot — no page.
    br.update(600, 200, 1150)
    r = br.update(660, 200, 1200)
    assert r["short_burn"] == 0.0
    assert r["long_burn"] > 14.4
    assert r["severity"] is None


def test_hist_good_total_threshold_between_edges():
    reg = MetricsRegistry()
    h = reg.histogram("slt_t_seconds", buckets=(0.1, 0.25, 0.5))
    for v in (0.05, 0.2, 0.3, 0.6):
        h.observe(v)
    snap = h.snapshot()
    # Threshold on an edge: observations <= 0.25 are good.
    assert hist_good_total(snap, 0.25) == (2.0, 4.0)
    # Between edges: conservative (largest edge <= threshold).
    assert hist_good_total(snap, 0.4) == (2.0, 4.0)
    assert hist_good_total(snap, 0.05) == (0.0, 4.0)


def test_staleness_watch_learns_interval_and_rearms():
    w = StalenessWatch(factor=3.0, min_interval_s=0.5)
    assert w.update(0.0, 10.0) is None   # first observation arms nothing
    assert w.update(1.0, 11.0) is None   # first increment: interval epoch
    assert w.update(2.0, 12.0) is None   # ewma interval ~1s
    assert w.update(4.0, 12.0) is None   # age 2 < 3*1
    stale = w.update(6.0, 12.0)          # age 4 > 3
    assert stale is not None
    age, threshold = stale
    assert age == pytest.approx(4.0)
    assert threshold == pytest.approx(3.0)
    assert w.update(7.0, 13.0) is None   # recovered
    # Counter restart (process restart) re-arms instead of alarming.
    assert w.update(8.0, 2.0) is None
    assert w.update(100.0, 2.0) is None


def test_parse_slos_validates_loudly():
    ok = parse_slos([
        {"name": "ttft", "kind": "latency",
         "metric": "slt_request_ttft_seconds", "threshold_s": 0.5,
         "objective": 0.95},
        {"name": "err", "kind": "ratio", "bad": "slt_server_errors_total",
         "total": "slt_server_requests_total", "objective": 0.999}])
    assert [s["name"] for s in ok] == ["ttft", "err"]
    for bad in (
            [{"kind": "latency"}],                      # no name
            [{"name": "x", "objective": 2.0,            # objective > 1
              "metric": "m", "threshold_s": 1}],
            [{"name": "x", "objective": 0.9}],          # latency, no metric
            [{"name": "x", "kind": "ratio", "objective": 0.9}],  # no bad
            [{"name": "x", "kind": "nope", "objective": 0.9}],
            ["not-a-dict"]):
        with pytest.raises(ValueError):
            parse_slos(bad)


def test_score_stragglers_fabricated_three_worker_rounds():
    rounds = []
    for r in range(4):
        rounds.append({"round": r, "live": [1, 2, 9],
                       "arrivals_s": {"1": 0.2 + 0.01 * r, "2": 0.25,
                                      "9": 6.0 + r}})
    # Worker 9 also misses a round entirely.
    rounds.append({"round": 4, "live": [1, 2, 9],
                   "arrivals_s": {"1": 0.2, "2": 0.22}})
    scores = score_stragglers(rounds, factor=4.0, min_rounds=2)
    assert scores["9"]["flagged"] is True
    assert scores["9"]["late"] == 4 and scores["9"]["missing"] == 1
    assert scores["9"]["mean_lag_s"] > 5.0
    assert scores["1"]["flagged"] is False
    assert scores["2"]["flagged"] is False
    # One slow round out of many is noise, not a straggler.
    noise = [{"round": r, "live": [1, 2],
              "arrivals_s": {"1": 0.2, "2": 5.0 if r == 0 else 0.2}}
             for r in range(6)]
    assert score_stragglers(noise)["2"]["flagged"] is False


def test_score_stragglers_degenerate_rounds():
    """Round-19 satellite: rounds with ZERO recorded arrivals (a quorum
    or timeout round that closed empty), workers that never report at
    all, empty live lists and torn non-numeric arrival values must
    score without div-by-zero or KeyError."""
    rounds = [
        {"round": 0, "live": [1, 2], "arrivals_s": {}},
        {"round": 1, "live": [1, 2], "arrivals_s": {"1": 0.1}},
        {"round": 2, "live": [], "arrivals_s": {}},  # skipped entirely
        {"round": 3, "live": [1, 2],
         "arrivals_s": {"1": "garbage", "2": 0.2}},
        {"round": 4},  # no live, no arrivals at all
    ]
    out = score_stragglers(rounds, min_rounds=2)
    assert set(out) == {"1", "2"}
    # worker 2 never reported in rounds 0/1, reported in round 3
    assert out["2"]["rounds_seen"] == 3
    assert out["2"]["missing"] == 2
    assert out["2"]["flagged"] is True  # 2/3 bad >= 0.5
    # worker 1's garbage arrival counts as missing, not a crash
    assert out["1"]["missing"] == 2  # round 0 (empty) + round 3 (torn)
    assert out["1"]["mean_lag_s"] == 0.0
    # a worker that NEVER appears anywhere simply has no entry
    assert "7" not in out
    # all-empty input
    assert score_stragglers([]) == {}
    assert score_stragglers([{"round": 0}]) == {}


def test_quarantine_event_rule_registered():
    """The leader's quarantine counter feeds the generic event-rule
    alert family, so a health engine sampling the island's registry
    surfaces event.diloco_delta_quarantined on /alerts."""
    from serverless_learn_tpu.telemetry.health import _EVENT_RULES

    assert ("diloco_delta_quarantined", "slt_diloco_quarantined_total",
            "warning") in _EVENT_RULES


# -- engine ticks (fast, fake clock) -----------------------------------------

def _engine(reg, sink, **cfg_kw):
    cfg = HealthConfig(**{
        "stale_factor": 3.0, "stale_min_interval_s": 1.0,
        "clear_after_ticks": 2, **cfg_kw})
    return HealthEngine(registry=reg, config=cfg, emit=sink.append,
                        dump_on_critical=False)


def test_engine_staleness_fire_and_resolve_cycle():
    reg = MetricsRegistry()
    steps = reg.counter("slt_train_steps_total")
    sink = []
    eng = _engine(reg, sink)
    t = 1000.0
    for _ in range(6):
        steps.inc()
        eng.sample_once(now=t)
        t += 1.0
    assert eng.alerts(firing_only=True) == []
    # Stall: interval ~1s, factor 3 -> fires once age > 3s.
    for _ in range(5):
        eng.sample_once(now=t)
        t += 2.0
    firing = eng.alerts(firing_only=True)
    assert [a["alert"] for a in firing] == ["stale.train_step"]
    assert firing[0]["severity"] == "critical"
    fired_events = [r for r in sink if r.get("event") == "alert"]
    assert fired_events and fired_events[0]["state"] == "firing"
    # Recovery + clear_after_ticks clean ticks -> resolved, emitted once.
    for _ in range(3):
        steps.inc()
        eng.sample_once(now=t)
        t += 1.0
    assert eng.alerts(firing_only=True) == []
    resolved = [r for r in sink if r.get("event") == "alert"
                and r["state"] == "resolved"]
    assert len(resolved) == 1


def test_engine_anomaly_step_time_spike():
    reg = MetricsRegistry()
    steps = reg.counter("slt_train_steps_total")
    h = reg.histogram("slt_train_step_seconds")
    sink = []
    eng = _engine(reg, sink, anomaly_min_samples=5, anomaly_z=6.0)
    t = 0.0
    for _ in range(8):
        steps.inc()
        h.observe(0.1)
        eng.sample_once(now=t)
        t += 1.0
    assert eng.alerts(firing_only=True) == []
    steps.inc()
    h.observe(2.0)  # 20x step-time spike in this window
    eng.sample_once(now=t)
    firing = [a["alert"] for a in eng.alerts(firing_only=True)]
    assert "anomaly.step_time" in firing


def test_engine_slo_latency_burn():
    reg = MetricsRegistry()
    h = reg.histogram("slt_request_ttft_seconds")
    sink = []
    eng = _engine(reg, sink, slos=(
        {"name": "ttft", "kind": "latency",
         "metric": "slt_request_ttft_seconds", "threshold_s": 0.25,
         "objective": 0.95},))
    t = 0.0
    for _ in range(3):  # healthy: all under target
        for _ in range(20):
            h.observe(0.01)
        eng.sample_once(now=t)
        t += 10.0
    assert eng.alerts(firing_only=True) == []
    for _ in range(12):  # regression: everything lands at 1s
        for _ in range(20):
            h.observe(1.0)
        eng.sample_once(now=t)
        t += 10.0
    firing = eng.alerts(firing_only=True)
    assert [a["alert"] for a in firing] == ["slo.ttft"]
    # Long enough that the bad fraction dominates both windows: critical.
    assert firing[0]["severity"] == "critical"


def test_engine_event_counter_and_straggler_alerts():
    from serverless_learn_tpu.telemetry import health as hmod

    reg = MetricsRegistry()
    lease = reg.counter("slt_lease_expiries_total")
    sink = []
    eng = _engine(reg, sink)
    hmod.clear_rounds()
    try:
        eng.sample_once(now=0.0)
        lease.inc()
        eng.sample_once(now=1.0)
        firing = {a["alert"] for a in eng.alerts(firing_only=True)}
        assert "event.lease_expiry" in firing
        for r in range(3):
            hmod.note_round({"round": r, "live": [1, 2, 9],
                             "arrivals_s": {"1": 0.1, "2": 0.12,
                                            "9": 8.0}})
        eng.sample_once(now=2.0)
        strag = [a for a in eng.alerts(firing_only=True)
                 if a["alert"] == "straggler.diloco_worker"]
        assert len(strag) == 1
        assert strag[0]["labels"] == {"worker_id": "9"}
    finally:
        hmod.clear_rounds()


def test_flatten_snapshot_sums_series():
    reg = MetricsRegistry()
    reg.counter("slt_requests_total", engine="continuous").inc(3)
    reg.counter("slt_requests_total", engine="static").inc(2)
    reg.histogram("slt_t_seconds", buckets=(1.0,), engine="a").observe(0.5)
    reg.histogram("slt_t_seconds", buckets=(1.0,), engine="b").observe(2.0)
    flat = flatten_snapshot(reg.snapshot())
    assert flat["values"]["slt_requests_total"] == 5
    assert flat["hists"]["slt_t_seconds"]["count"] == 2
    assert flat["hists"]["slt_t_seconds"]["cumulative"] == [1, 2]


# -- /healthz + /alerts (fast) -----------------------------------------------

def test_healthz_transitions_and_alerts_endpoint():
    import urllib.error

    reg = MetricsRegistry()
    steps = reg.counter("slt_train_steps_total")
    sink = []
    eng = _engine(reg, sink)
    exp = MetricsExporter(reg).start()
    exp.attach_health(eng)
    try:
        t = 1000.0
        for _ in range(4):
            steps.inc()
            eng.sample_once(now=t)
            t += 1.0
        # Healthy: 200, real components, no firing criticals.
        rep = json.loads(fetch_text(exp.addr, "/healthz"))
        assert rep["ok"] is True
        assert rep["components"]["engine"]["warm"] is True
        assert rep["components"]["last_step_age_s"] is not None
        assert rep["firing_critical"] == []
        payload = json.loads(fetch_text(exp.addr, "/alerts"))
        assert payload["enabled"] is True and payload["firing"] == []
        # Stall -> critical firing -> 503 with the alert named.
        for _ in range(5):
            eng.sample_once(now=t)
            t += 2.0
        with pytest.raises(urllib.error.HTTPError) as ei:
            fetch_text(exp.addr, "/healthz")
        assert ei.value.code == 503
        body = json.loads(ei.value.read().decode())
        assert body["ok"] is False
        assert "stale.train_step" in body["firing_critical"]
        payload = json.loads(fetch_text(exp.addr, "/alerts"))
        assert [a["alert"] for a in payload["firing"]] \
            == ["stale.train_step"]
        # Recovery: steps resume, clean ticks pass -> 200 again.
        for _ in range(3):
            steps.inc()
            eng.sample_once(now=t)
            t += 1.0
        assert json.loads(fetch_text(exp.addr, "/healthz"))["ok"] is True
    finally:
        exp.stop()


def test_healthz_without_engine_stays_legacy():
    exp = MetricsExporter(MetricsRegistry()).start()
    try:
        assert json.loads(fetch_text(exp.addr, "/healthz"))["ok"] is True
        payload = json.loads(fetch_text(exp.addr, "/alerts"))
        assert payload == {"enabled": False, "firing": [], "resolved": []}
    finally:
        exp.stop()


def test_top_renders_alerts_pane():
    from serverless_learn_tpu.telemetry.top import EndpointState, render

    reg = MetricsRegistry()
    steps = reg.counter("slt_train_steps_total")
    eng = _engine(reg, [])
    exp = MetricsExporter(reg).start()
    exp.attach_health(eng)
    try:
        t = 0.0
        for _ in range(4):
            steps.inc()
            eng.sample_once(now=t)
            t += 1.0
        for _ in range(5):
            eng.sample_once(now=t)
            t += 2.0
        st = EndpointState(exp.addr)
        st.poll()
        screen = render([st])
        assert "ALERTS" in screen
        assert "stale.train_step" in screen
        assert "CRITICAL" in screen
    finally:
        exp.stop()


# -- event-log rotation (fast) -----------------------------------------------

def test_event_log_rotation_and_trace_merge(tmp_path):
    from serverless_learn_tpu.telemetry import timeline

    path = str(tmp_path / "events.jsonl")
    log = JsonlEventLog(path, max_bytes=4096)
    for i in range(100):
        log.emit({"event": "span", "span": f"s{i}", "trace_id": f"t{i}",
                  "span_id": f"{i:016x}", "t0_unix_s": 1000.0 + i,
                  "duration_s": 0.1, "node": "n1",
                  "pad": "x" * 80})
    log.close()
    assert os.path.exists(path + ".1"), "no rotation happened"
    assert os.path.getsize(path) <= 4096
    # Every line in both generations is intact JSON.
    recs = []
    for p in (path, path + ".1"):
        with open(p) as f:
            recs += [json.loads(line) for line in f if line.strip()]
    assert len(recs) <= 100  # middle generations age out (.1 overwritten)
    names = {r["span"] for r in recs}
    assert "s99" in names  # the newest record survives
    # `slt trace` directory expansion merges both generations.
    tl = timeline.reconstruct([str(tmp_path)])
    assert len(tl.spans) == len(recs)


def test_event_log_recovers_after_external_delete(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = JsonlEventLog(path)
    log.emit({"event": "a"})
    os.remove(path)
    log.emit({"event": "b"})  # must not raise; appends via the old handle
    log.close()
    log.emit({"event": "c"})  # reopened handle recreates the file
    log.close()
    with open(path) as f:
        events = [json.loads(line)["event"] for line in f]
    assert "c" in events


# -- doctor (fast) -----------------------------------------------------------

def _write_fixture_logs(tmp_path):
    """A fabricated incident trail: alerts, spans, rounds, a flight dump,
    and a bench history with a regression."""
    events = tmp_path / "events.jsonl"
    with open(events, "w") as f:
        base = 1_700_000_000.0
        f.write(json.dumps({
            "event": "alert", "alert": "stale.train_step",
            "severity": "critical", "detector": "structural",
            "state": "firing", "node": "worker-a",
            "message": "slt_train_steps_total has not advanced in 42.0s",
            "value": 42.0, "threshold": 5.0, "count": 1,
            "first_fired_unix_s": base + 100,
            "last_fired_unix_s": base + 100}) + "\n")
        f.write(json.dumps({
            "event": "alert", "alert": "anomaly.queue_wait",
            "severity": "warning", "detector": "anomaly",
            "state": "firing", "node": "serve-b",
            "message": "queue wait anomalous", "value": 2.0,
            "threshold": 6.0, "count": 3,
            "first_fired_unix_s": base + 90,
            "last_fired_unix_s": base + 120}) + "\n")
        f.write(json.dumps({
            "event": "span", "span": "train/run", "node": "worker-a",
            "trace_id": "aa11", "span_id": "s1",
            "t0_unix_s": base + 50, "duration_s": 120.0}) + "\n")
        f.write(json.dumps({
            "event": "span", "span": "unrelated", "node": "other-c",
            "trace_id": "zz99", "span_id": "s2",
            "t0_unix_s": base + 100, "duration_s": 1.0}) + "\n")
        for r in range(3):
            f.write(json.dumps({
                "event": "diloco_round", "round": r, "live": [1, 2, 9],
                "arrivals_s": {"1": 0.2, "2": 0.3, "9": 9.0}}) + "\n")
    flight = tmp_path / "flight-worker-a-1700000150.json"
    with open(flight, "w") as f:
        json.dump({"event": "flight_dump", "node": "worker-a",
                   "reason": "alert:stale.train_step", "pid": 1234,
                   "dumped_at_unix_s": 1_700_000_150.0,
                   "events": [{"event": "train_step", "step": 7}],
                   "metrics": {}}, f)
    bench = tmp_path / "bench_history.json"
    with open(bench, "w") as f:
        json.dump([
            {"metric": "decode_tokens_per_sec", "device_kind": "cpu",
             "value": 1000.0, "time": "2026-08-01T00:00:00"},
            {"metric": "decode_tokens_per_sec", "device_kind": "cpu",
             "value": 600.0, "time": "2026-08-03T00:00:00"},
        ], f)
    return str(events), str(flight), str(bench)


def test_doctor_end_to_end_over_fixture_logs(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    events, flight, bench = _write_fixture_logs(tmp_path)
    rc = main(["doctor", events, flight, "--bench-history", bench])
    out = capsys.readouterr().out
    assert rc == 1  # critical alert firing -> nonzero for scripting
    rep = json.loads(out)
    # Ranked: critical staleness first, named node, correlated trace.
    top_alert = rep["alerts"][0]
    assert top_alert["alert"] == "stale.train_step"
    assert top_alert["node"] == "worker-a"
    assert top_alert["traces"][0]["trace_id"] == "aa11"
    assert all(t["trace_id"] != "zz99" for t in top_alert["traces"])
    assert rep["alerts"][1]["alert"] == "anomaly.queue_wait"
    # Straggler scoring from the round records in the log.
    assert rep["stragglers"]["9"]["flagged"] is True
    # The flight dump (with its reason) is surfaced.
    assert rep["flight_dumps"][0]["node"] == "worker-a"
    assert rep["flight_dumps"][0]["reason"] == "alert:stale.train_step"
    # Cross-run bench regression vs history.
    regs = rep["bench"]["regressions"]
    assert regs and regs[0]["metric"] == "decode_tokens_per_sec"
    assert regs[0]["value"] == 600.0 and regs[0]["best"] == 1000.0
    # Verdict names the worst problem.
    assert "stale.train_step" in rep["summary"]["verdict"]
    assert rep["summary"]["healthy"] is False


def test_doctor_healthy_logs_exit_zero(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    events = tmp_path / "events.jsonl"
    with open(events, "w") as f:
        f.write(json.dumps({"event": "span", "span": "train/run",
                            "trace_id": "ab", "span_id": "cd",
                            "t0_unix_s": 1.0, "duration_s": 1.0}) + "\n")
    # Point --bench-history away from any repo-root bench_history.json so
    # the verdict reflects only this fixture.
    rc = main(["doctor", str(events),
               "--bench-history", str(tmp_path / "none.json")])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["summary"]["healthy"] is True
    assert "healthy" in rep["summary"]["verdict"]


def test_doctor_self_check_cli(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["doctor", "--self-check"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is True
    assert {c["check"] for c in rep["checks"]} >= {
        "rules_parse", "healthy_fixture_quiet", "stall_detected"}


def test_doctor_scrapes_live_alerts_endpoint():
    from serverless_learn_tpu.telemetry import doctor

    reg = MetricsRegistry()
    steps = reg.counter("slt_train_steps_total")
    eng = _engine(reg, [])
    exp = MetricsExporter(reg).start()
    exp.attach_health(eng)
    try:
        t = 0.0
        for _ in range(4):
            steps.inc()
            eng.sample_once(now=t)
            t += 1.0
        for _ in range(5):
            eng.sample_once(now=t)
            t += 2.0
        rep = doctor.diagnose(endpoints=[exp.addr])
        assert rep["summary"]["critical_firing"] == 1
        assert rep["alerts"][0]["alert"] == "stale.train_step"
        # A dead endpoint is reported, not fatal.
        rep2 = doctor.diagnose(endpoints=["127.0.0.1:1"])
        assert rep2["scrapes"][0]["ok"] is False
        assert "unreachable" in rep2["summary"]["verdict"]
    finally:
        exp.stop()


# -- demo acceptance (slow): stall -> alert -> dump -> doctor ----------------

@pytest.mark.slow
def test_stalled_training_fires_alert_dump_and_doctor(tmp_path, capsys):
    """A training run with an injected stall produces a firing staleness
    alert on /alerts, a 503 /healthz, a flight dump, and an `slt doctor`
    report naming the offending node with a correlated trace id."""
    from serverless_learn_tpu.cli import main
    from serverless_learn_tpu.config import (DataConfig, ExperimentConfig,
                                             MeshConfig, OptimizerConfig,
                                             TrainConfig)
    from serverless_learn_tpu.telemetry import get_registry, init_tracing
    from serverless_learn_tpu.training.loop import run_training

    events = str(tmp_path / "events.jsonl")
    init_tracing(node="stall-node", events_log=events,
                 flight_dir=str(tmp_path))
    reg = get_registry()  # run_training publishes here
    eng = HealthEngine(
        registry=reg,
        config=HealthConfig(sample_interval_s=0.05, stale_factor=3.0,
                            stale_min_interval_s=0.25,
                            clear_after_ticks=3),
        flight_dir=str(tmp_path)).start()
    exp = MetricsExporter(reg).start()
    exp.attach_health(eng)

    def stall(step, state, stats):
        if step == 4:
            time.sleep(4.0)  # the injected stall

    cfg = ExperimentConfig(
        model="mlp_mnist", mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16, num_steps=8, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig())
    t = threading.Thread(target=run_training, args=(cfg,),
                         kwargs={"step_callback": stall})
    t.start()
    try:
        deadline = time.time() + 120
        firing = []
        while time.time() < deadline:
            try:
                payload = json.loads(fetch_text(exp.addr, "/alerts"))
                firing = [a for a in payload["firing"]
                          if a["alert"] == "stale.train_step"]
                if firing:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert firing, "staleness alert never fired during the stall"
        assert firing[0]["severity"] == "critical"
        assert firing[0]["node"] == "stall-node"
        # /healthz is an orchestrator-probeable 503 while critical fires.
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            fetch_text(exp.addr, "/healthz")
        assert ei.value.code == 503
    finally:
        t.join(timeout=300)
        eng.stop()
        exp.stop()
    # The critical alert triggered a flight dump into our dir.
    dumps = list(glob.glob(str(tmp_path / "flight-*.json")))
    assert dumps, "critical alert produced no flight dump"
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["reason"].startswith("alert:stale.train_step")
    # The dump itself names what was wrong (flight context provider).
    assert "stale.train_step" in [a["alert"] for a in dump["alerts"]]
    # Doctor over the persisted trail: names the node, links a trace.
    rc = main(["doctor", events] + dumps)
    rep = json.loads(capsys.readouterr().out)
    assert rc in (0, 1)  # resolved after recovery (0) or still firing (1)
    stale = [a for a in rep["alerts"] if a["alert"] == "stale.train_step"]
    assert stale, rep["alerts"]
    assert stale[0]["node"] == "stall-node"
    trace_ids = [tr["trace_id"] for tr in stale[0]["traces"]]
    assert trace_ids, "no correlated trace ids in the doctor report"
    # The correlated trace is the training run's own span.
    with open(events) as f:
        run_spans = [json.loads(line) for line in f if line.strip()]
    run_trace = [r["trace_id"] for r in run_spans
                 if r.get("event") == "span" and r.get("span") == "train/run"]
    assert run_trace and run_trace[0] in trace_ids
