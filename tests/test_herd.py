"""`slt herd` (round 19): the vmapped many-client DiLoCo harness.

What the tests pin:

* the ISSUE-19 acceptance: 256 vmapped clients with non-IID shards and
  speed skew, a FaultPlan killing >20% of the herd mid-round, quorum-0.8
  participation — byte-identical same-seed reports, the poisoned
  worker's NaN delta quarantined (never reaching the anchor), and
  `slt doctor` naming the quarantined worker + partial participation
  from the events log alone, with membership agreement (real SWIM
  gossip) asserted with training in the loop;
* loss parity of partial (quorum 0.8) vs full participation under
  heterogeneity — the degradation policy's "safe to run degraded" claim;
* the norm-outlier arm of the quarantine gate + readmission;
* late-delta policies (drop vs staleness-discount);
* churn: a killed-and-restarted worker rejoins with fresh inner
  optimizer state and contributes deltas again;
* the `slt chaos herd` CLI incl. `--smoke`.
"""

import dataclasses
import json
import os

import pytest

from serverless_learn_tpu.chaos.plan import FaultPlan
from serverless_learn_tpu.training.herd import (HerdSim, HerdSpec,
                                                parity_specs, run_smoke,
                                                run_wire_ab,
                                                wire_parity_specs)

ACCEPT_SPEC = HerdSpec(
    n_workers=256, rounds=5, inner_steps=2, batch_size=4, features=(16,),
    quorum_fraction=0.8, round_timeout_s=1.0, speed_skew=0.5,
    poison_worker=200, poison_round=2)

# Kill 21% of the herd while round 0's deltas are in flight (round 0
# starts at bootstrap_s=2.0; arrivals land from ~2.05 on).
ACCEPT_PLAN = [{"at": 2.08, "op": "kill", "frac": 0.21}]


def _load_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def test_herd_acceptance_churn_determinism_quarantine(tmp_path):
    """The ISSUE-19 acceptance scenario, end to end."""
    from serverless_learn_tpu.telemetry import doctor

    events = str(tmp_path / "herd-events.jsonl")

    def run(log=None):
        rep = HerdSim(ACCEPT_SPEC, seed=3,
                      plan=FaultPlan.from_obj(ACCEPT_PLAN),
                      events_log=log).run(duration_s=45.0)
        rep.pop("wall_time_s")
        return rep

    rep = run(events)
    assert rep["ok"], rep["violations"]
    herd = rep["herd"]
    # >= 20% of 256 workers killed mid-round, and the run still
    # completed every scheduled round at quorum.
    assert len(rep["killed_live"]) >= 52
    assert herd["rounds_completed"] == 5
    assert herd["committed_step"] == 10
    # real membership agreement WITH training in the loop
    assert rep["converged"], rep["violations"]
    assert rep["dissemination_periods"] <= rep["convergence_bound_periods"]
    # quorum 0.8 closed rounds short of full participation
    assert all(0.5 <= p <= 1.0 for p in herd["participation"])
    assert herd["mean_participation"] < 1.0
    # the poisoned worker was quarantined and the anchor stayed finite
    assert "200" in herd["quarantined"]
    assert herd["quarantined"]["200"]["reason"] == "nonfinite"
    assert 2 in herd["quarantined"]["200"]["rounds"]
    assert herd["anchor_finite"]
    # training learned through all of it
    assert herd["final_eval_loss"] < herd["init_eval_loss"] - 0.2

    # byte-identical same-seed reports (the debuggability contract)
    assert json.dumps(rep, sort_keys=True) == \
        json.dumps(run(), sort_keys=True)

    # doctor, fed ONLY the events log, names the quarantined worker and
    # the partial participation
    verdict = doctor.diagnose([events], bench_history="/nonexistent"
                              )["summary"]["verdict"]
    assert "quarantin" in verdict and "200" in verdict, verdict
    assert "participation" in verdict, verdict
    # and the per-round records score stragglers (slow workers missed
    # quorum repeatedly under speed skew)
    d = doctor.diagnose([events], bench_history="/nonexistent")
    assert any(s["flagged"] for s in d["stragglers"].values())
    # ground truth for every kill is in the same log
    recs = _load_events(events)
    kills = [r for r in recs if r.get("event") == "fault_injected"
             and r.get("op") == "kill"]
    assert kills and len(kills[0]["nodes"]) >= 52


def test_partial_participation_loss_parity():
    """Quorum 0.8 under speed skew must land within tolerance of full
    participation — partial participation degrades wall-clock waits,
    not the model."""
    part_spec, full_spec = parity_specs(256, 0.8)
    rp = HerdSim(part_spec, seed=7).run(duration_s=14.0)
    rf = HerdSim(full_spec, seed=7).run(duration_s=14.0)
    hp, hf = rp["herd"], rf["herd"]
    assert not [v for v in rp["violations"]], rp["violations"]
    assert not [v for v in rf["violations"]], rf["violations"]
    assert hp["rounds_completed"] == part_spec.rounds
    assert hf["rounds_completed"] == full_spec.rounds
    assert hp["mean_participation"] < hf["mean_participation"]
    init = hp["init_eval_loss"]
    assert hf["init_eval_loss"] == init  # same seed => same init
    # both learn, and partial tracks full within 5% of the init scale
    assert hp["final_eval_loss"] < init - 0.25
    assert hf["final_eval_loss"] < init - 0.25
    assert abs(hp["final_eval_loss"] - hf["final_eval_loss"]) \
        < 0.05 * init, (hp["final_eval_loss"], hf["final_eval_loss"])


def test_norm_outlier_quarantined_then_readmitted(tmp_path):
    """A finite but wildly out-of-family delta (scaled 1000x) trips the
    outlier arm of the gate; the worker's next clean round resolves the
    alert (readmission)."""
    events = str(tmp_path / "outlier.jsonl")
    spec = HerdSpec(n_workers=24, rounds=3, inner_steps=2, batch_size=4,
                    features=(16,), round_timeout_s=2.0,
                    scale_worker=5, scale_round=1)
    rep = HerdSim(spec, seed=1, events_log=events).run(duration_s=20.0)
    assert rep["ok"], rep["violations"]
    q = rep["herd"]["quarantined"]
    assert q == {"5": {"rounds": [1], "reason": "norm_outlier"}}
    assert rep["herd"]["anchor_finite"]
    alerts = [r for r in _load_events(events)
              if r.get("alert") == "diloco.delta_quarantined"]
    states = [a["state"] for a in alerts]
    assert "firing" in states and "resolved" in states, alerts


def test_late_delta_policies_drop_vs_discount():
    """Heavy speed skew + a tight quorum strands stragglers past the
    close; 'drop' discards their deltas, 'discount' folds them in as
    stale discounted updates — the two runs must actually diverge."""
    base = HerdSpec(n_workers=16, rounds=3, inner_steps=2, batch_size=4,
                    features=(16,), quorum_fraction=0.5,
                    speed_skew=1.0, round_timeout_s=4.0)
    import dataclasses

    drop = HerdSim(base, seed=2).run(duration_s=25.0)
    disc = HerdSim(dataclasses.replace(base, late_policy="discount"),
                   seed=2).run(duration_s=25.0)
    assert drop["herd"]["late_deltas"]["dropped"] > 0
    assert drop["herd"]["late_deltas"]["discounted"] == 0
    assert disc["herd"]["late_deltas"]["discounted"] > 0
    # the discounted stale updates moved the anchor
    assert drop["herd"]["final_eval_loss"] != disc["herd"]["final_eval_loss"]


def test_restarted_worker_rejoins_and_contributes(tmp_path):
    """Kill one worker mid-run, restart it two rounds later: it must
    post deltas again (with reset inner optimizer state) and the herd
    report must stay clean."""
    events = str(tmp_path / "rejoin.jsonl")
    spec = HerdSpec(n_workers=12, rounds=8, inner_steps=2, batch_size=4,
                    features=(16,), round_timeout_s=2.0,
                    base_step_s=0.2, quorum_fraction=0.8)
    plan = FaultPlan.from_obj([
        {"at": 2.5, "op": "kill", "node": "node-5"},
        {"at": 4.5, "op": "restart", "node": "node-5"}])
    rep = HerdSim(spec, seed=6, plan=plan,
                  events_log=events).run(duration_s=40.0)
    assert rep["ok"], rep["violations"]
    rounds = [r for r in _load_events(events)
              if r.get("event") == "diloco_round"]
    posted_by_round = {r["round"]: r["posted"] for r in rounds}
    gone = [r for r, posted in posted_by_round.items() if 5 not in posted]
    back = [r for r, posted in posted_by_round.items() if 5 in posted]
    assert gone, "worker 5 was never absent despite the kill"
    assert back and max(back) > min(gone), \
        "worker 5 never contributed after its restart"


def test_spec_validation():
    for bad in (dict(n_workers=1), dict(quorum_fraction=0.0),
                dict(quorum_fraction=1.5), dict(late_policy="maybe"),
                dict(rounds=0), dict(wire_dtype="int4"),
                dict(wire_block=0)):
        with pytest.raises(ValueError):
            HerdSpec(**bad).validate()


@pytest.mark.skipif(os.environ.get("SLT_RACECHECK") == "1",
                    reason="3 sequential 256-worker sims are ~10x "
                           "slower under write instrumentation; the "
                           "24-worker quantized herd test exercises "
                           "the same code under the monitor")
def test_wire_ab_parity_at_256_under_churn():
    """ROUND-20 ACCEPTANCE: int8-with-error-feedback vs f32 at 256
    workers with churn (quorum 0.8, mid-round kill): final eval loss
    within 5% of the f32 leg on the init scale, wire bytes >= 3.5x
    smaller, and the no-feedback negative control never beats the
    feedback leg. run_wire_ab performs the checks; re-assert the load-
    bearing ones here so a loosened harness can't silently pass."""
    rep = run_wire_ab(workers=256, seed=3)
    assert rep["ok"], rep["violations"]
    init = rep["init_eval_loss"]
    assert abs(rep["final_eval_loss"]["quant"]
               - rep["final_eval_loss"]["f32"]) < 0.05 * init
    assert rep["bytes"]["ratio"] >= 3.5
    # negative control: feedback either measurably helps, or both gaps
    # sit under the 0.5%-of-init noise floor (256-worker averaging
    # already cancels per-round noise; the bias proof is codec-level)
    assert rep["feedback_verdict"] in ("matters",
                                       "equivalent_below_noise_floor")
    if rep["feedback_verdict"] == "equivalent_below_noise_floor":
        assert rep["parity_gap"]["with_feedback"] < 0.0005 * init
    # both legs actually trained through the churn
    assert rep["final_eval_loss"]["f32"] < init - 0.2
    assert rep["final_eval_loss"]["quant"] < init - 0.2


def test_quantized_herd_deterministic_and_poison_still_quarantined(
        tmp_path):
    """The quantizer under vmap keeps the determinism contract
    (byte-identical same-seed reports), and a poisoned NaN delta — now
    passing THROUGH the codec's NaN-propagating in-graph path — still
    trips the quarantine gate on the dequantized values."""
    events = str(tmp_path / "wire-herd.jsonl")
    spec = HerdSpec(n_workers=24, rounds=3, inner_steps=2, batch_size=4,
                    features=(16,), quorum_fraction=0.8,
                    round_timeout_s=1.5, wire_dtype="int8",
                    poison_worker=21, poison_round=1)

    def run(log=None):
        rep = HerdSim(spec, seed=0, events_log=log).run(duration_s=20.0)
        rep.pop("wall_time_s")
        return rep

    rep = run(events)
    assert rep["ok"], rep["violations"]
    assert json.dumps(rep, sort_keys=True) == \
        json.dumps(run(), sort_keys=True)
    assert "21" in rep["herd"]["quarantined"]
    assert rep["herd"]["quarantined"]["21"]["reason"] == "nonfinite"
    assert rep["herd"]["anchor_finite"]
    wire = rep["herd"]["wire"]
    assert wire["dtype"] == "int8" and wire["error_feedback"]
    assert wire["compression_ratio"] > 3.5
    # dcn_wire telemetry reached the events log; doctor reports the
    # engaged codec (and would name a ~1.0 ratio as misconfiguration)
    recs = _load_events(events)
    wires = [r for r in recs if r.get("event") == "dcn_wire"]
    assert wires and all(r["wire_dtype"] == "int8" for r in wires)
    from serverless_learn_tpu.telemetry import doctor

    verdict = doctor.diagnose([events], bench_history="/nonexistent"
                              )["summary"]["verdict"]
    assert "quantized DCN exchange" in verdict, verdict
    assert "misconfigured" not in verdict, verdict


def test_doctor_names_ratio_one_misconfiguration(tmp_path):
    """An int8-configured consumer whose transfers ship ~1:1 (codec not
    engaging — e.g. every round falling back uncompressed) is named as a
    misconfiguration from the telemetry alone."""
    events = tmp_path / "flat.jsonl"
    with open(events, "w") as f:
        for rnd in range(4):
            f.write(json.dumps({
                "event": "dcn_wire", "consumer": "diloco",
                "direction": "tx", "wire_dtype": "int8",
                "logical_bytes": 1000, "wire_bytes": 990,
                "fallback": "nonfinite", "round": rnd}) + "\n")
    from serverless_learn_tpu.telemetry import doctor

    verdict = doctor.diagnose([str(events)],
                              bench_history="/nonexistent"
                              )["summary"]["verdict"]
    assert "quantized exchange misconfigured for diloco" in verdict
    assert "non-finite fallback" in verdict


def test_wire_parity_specs_shape():
    q, f = wire_parity_specs(64, 0.8, "int8")
    assert q.wire_dtype == "int8" and f.wire_dtype == "float32"
    assert dataclasses.replace(q, wire_dtype="float32") == f


def test_run_smoke_is_self_contained(tmp_path):
    """The CI smoke: determinism + quarantine asserted inside, events
    written for the CLI's doctor half."""
    events = str(tmp_path / "smoke.jsonl")
    rep = run_smoke(workers=24, seed=0, events_log=events)
    assert rep["ok"], rep["violations"]
    assert rep["deterministic"]
    assert "21" in rep["herd"]["quarantined"]  # workers - 3
    assert any(r.get("alert") == "diloco.delta_quarantined"
               for r in _load_events(events))


def test_herd_cli_run_and_smoke(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    rc = main(["chaos", "herd", "--workers", "16", "--rounds", "2",
               "--inner-steps", "2", "--quorum", "0.75", "--seed", "1",
               "--duration", "20", "--compact"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"]
    assert out["herd"]["rounds_completed"] == 2

    rc = main(["chaos", "herd", "--workers", "16", "--quorum", "1.5"])
    assert rc == 2
    assert "bad herd spec" in capsys.readouterr().err

    rc = main(["chaos", "herd", "--smoke", "--workers", "24",
               "--seed", "0", "--compact"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"], out.get("violations")
    assert out["deterministic"]
    assert "quarantin" in out["doctor_verdict"]


def test_herd_cli_wire_ab_and_record(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    history = str(tmp_path / "hist.json")
    rc = main(["chaos", "herd", "--wire-ab", "--workers", "16",
               "--seed", "1", "--record", "--history", history,
               "--compact"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"], out.get("violations")
    assert out["bytes"]["ratio"] >= 3.5
    with open(history) as f:
        rows = json.load(f)
    assert {r["wire_dtype"] for r in rows} == {"float32", "int8"}
    assert all(r["metric"] == "herd_diloco_round_wait_ms" for r in rows)
    assert all("dcn_bytes_per_round" in r
               and "diloco_round_wait_s" in r for r in rows)
    # the recorded pair passes the gate (int8 must not regress the pair)
    from serverless_learn_tpu.telemetry.benchgate import run_gate

    assert run_gate(history, metric="herd_diloco")["ok"]

    rc = main(["chaos", "herd", "--wire-ab", "--wire-dtype", "f32"])
    assert rc == 2
    assert "int8|fp8" in capsys.readouterr().err
