"""ImageNet-class ingestion (VERDICT r2 item 4).

The shard plane had only carried 32x32 CIFAR records; these tests cover the
224-scale path end to end: imagefolder decode (PIL, decode-once-at-publish
to 256x256 uint8), the 224-from-256 crop/flip bridge in the host pipeline,
the uint8-end-to-end contract (device-side normalization), and — slow tier —
ResNet-50 actually training from published oversized shards with
augmentation.
"""

import os
import socket

import numpy as np
import pytest

from serverless_learn_tpu.data.raw import (
    IMAGEFOLDER_STORE_SIZE, decode_image, load_imagefolder)
from serverless_learn_tpu.data.shard_client import FieldSpec
from serverless_learn_tpu.data.transforms import auto_transform, image_transform


def _write_tree(root, classes, sizes, fmt="JPEG"):
    """Synthesize an ImageNet-layout folder: root/<cls>/<i>.jpeg."""
    from PIL import Image

    rng = np.random.default_rng(0)
    for cls in classes:
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        for i, (w, h) in enumerate(sizes):
            arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(
                os.path.join(root, cls, f"img_{i:03d}.jpeg"), fmt)


def test_decode_image_resizes_and_center_crops(tmp_path):
    from PIL import Image

    # A wide image: shorter side (height) -> 64, then center-crop 64x64.
    arr = np.zeros((100, 300, 3), np.uint8)
    arr[:, 150:, :] = 255  # right half white: crop must keep the center
    p = str(tmp_path / "wide.png")
    Image.fromarray(arr).save(p)
    out = decode_image(p, size=64)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8
    # center of a 300-wide image spans the black->white boundary
    assert out[:, 0].mean() < 10 and out[:, -1].mean() > 245


def test_load_imagefolder_layout_and_labels(tmp_path):
    _write_tree(str(tmp_path), ["a_first", "z_last"],
                [(300, 200), (80, 120), (256, 256)])
    got = load_imagefolder(str(tmp_path), image_size=96)
    assert got["image"].shape == (6, 96, 96, 3)
    assert got["image"].dtype == np.uint8
    # classes sort to label ids: a_first -> 0, z_last -> 1
    np.testing.assert_array_equal(got["label"], [0, 0, 0, 1, 1, 1])


def test_load_imagefolder_split_subdir(tmp_path):
    _write_tree(str(tmp_path / "train"), ["c0"], [(64, 64)])
    got = load_imagefolder(str(tmp_path), split="train", image_size=32)
    assert got["image"].shape == (1, 32, 32, 3)
    with pytest.raises(FileNotFoundError):
        load_imagefolder(str(tmp_path / "empty"), image_size=32)


def test_crop_bridge_train_random_eval_center():
    import jax

    rng = np.random.default_rng(1)
    stored = rng.integers(0, 256, (8, 40, 40, 3), dtype=np.uint8)
    spec = {"image": jax.ShapeDtypeStruct((8, 32, 32, 3), np.float32),
            "label": jax.ShapeDtypeStruct((8,), np.int32)}
    fields = [FieldSpec("image", "uint8", (40, 40, 3)),
              FieldSpec("label", "int32", ())]
    batch = {"image": stored, "label": np.zeros(8, np.int32)}

    fn = auto_transform(fields, spec, task="classification", train=False,
                        seed=0)
    out = fn(batch)
    assert out["image"].shape == (8, 32, 32, 3)
    assert out["image"].dtype == np.float32
    # eval is the deterministic center crop, scaled to [0, 1)
    np.testing.assert_allclose(
        out["image"], stored[:, 4:36, 4:36].astype(np.float32) / 255.0)

    fn = auto_transform(fields, spec, task="classification", train=True,
                        seed=0, augment=True)
    a, b = fn(batch)["image"], fn(batch)["image"]
    assert a.shape == (8, 32, 32, 3)
    assert not np.array_equal(a, b), "train crops must be random per batch"


def test_uint8_bridge_stays_uint8():
    """spec dtype uint8 (device-side normalization): the host transform
    must crop/flip WITHOUT converting — and never divide a uint8 by 255."""
    import jax

    rng = np.random.default_rng(2)
    stored = rng.integers(0, 256, (4, 48, 48, 3), dtype=np.uint8)
    spec = {"image": jax.ShapeDtypeStruct((4, 32, 32, 3), np.uint8),
            "label": jax.ShapeDtypeStruct((4,), np.int32)}
    fields = [FieldSpec("image", "uint8", (48, 48, 3)),
              FieldSpec("label", "int32", ())]
    fn = auto_transform(fields, spec, task="classification", train=True,
                        seed=0, augment=True)
    out = fn({"image": stored, "label": np.zeros(4, np.int32)})
    assert out["image"].dtype == np.uint8
    assert out["image"].shape == (4, 32, 32, 3)
    # crops come from the stored data, not from a rescaled copy
    assert out["image"].max() > 1


def test_flip_only_when_size_matches():
    rng = np.random.default_rng(3)
    img = rng.integers(0, 256, (16, 8, 8, 3), dtype=np.uint8)
    fn = image_transform(train=True, seed=5, crop_pad=0, flip=True,
                         dtype=np.uint8)
    out = fn({"image": img})["image"]
    flipped = sum(np.array_equal(o, i[:, ::-1]) and not np.array_equal(o, i)
                  for o, i in zip(out, img))
    kept = sum(np.array_equal(o, i) for o, i in zip(out, img))
    assert flipped + kept == 16 and 0 < flipped < 16


def test_streaming_publish_matches_eager(tmp_path):
    """publish_imagefolder (bounded-memory, one shard decoded at a time)
    produces byte-identical shards to the eager load+publish path."""
    from serverless_learn_tpu.control.daemons import start_shard_server
    from serverless_learn_tpu.data.shard_client import (
        ShardStreamSource, publish_dataset, publish_imagefolder)

    _write_tree(str(tmp_path / "imgs"), ["a", "b"],
                [(120, 90), (64, 64), (90, 120)])
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    addr = f"127.0.0.1:{port}"
    try:
        meta_s = publish_imagefolder(addr, "stream", str(tmp_path / "imgs"),
                                     records_per_shard=4, image_size=48)
        eager = load_imagefolder(str(tmp_path / "imgs"), image_size=48)
        meta_e = publish_dataset(addr, "eager", eager, records_per_shard=4)
        assert meta_s == meta_e
        assert meta_s.num_records == 6 and meta_s.num_shards == 2

        def read_all(name):
            src = ShardStreamSource(addr, name, batch_size=6, seed=0,
                                    loop=False)
            batches = list(iter(src))
            src.close()
            return batches

        for bs, be in zip(read_all("stream"), read_all("eager")):
            np.testing.assert_array_equal(bs["image"], be["image"])
            np.testing.assert_array_equal(bs["label"], be["label"])
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_resnet50_uint8_input_normalizes_on_device(devices):
    """uint8 and float32 inputs of the same underlying pixels produce the
    same loss — /255 moved into the jitted step, not lost."""
    import jax
    import jax.numpy as jnp

    from serverless_learn_tpu.models.registry import get_model

    u8 = get_model("resnet50_imagenet", num_classes=8, input_dtype="uint8",
                   dtype=jnp.float32)
    f32 = get_model("resnet50_imagenet", num_classes=8, input_dtype="float32",
                    dtype=jnp.float32)
    rng = np.random.default_rng(4)
    img_u8 = rng.integers(0, 256, (2, 224, 224, 3), dtype=np.uint8)
    label = rng.integers(0, 8, 2).astype(np.int32)
    variables = u8.module.init(jax.random.PRNGKey(0),
                               jnp.asarray(img_u8, jnp.float32) / 255.0,
                               train=False)
    state = {k: v for k, v in variables.items() if k != "params"}
    l_u8, _ = u8.loss_fn(variables["params"], {"image": img_u8,
                                               "label": label},
                         model_state=state)
    l_f32, _ = f32.loss_fn(variables["params"],
                           {"image": img_u8.astype(np.float32) / 255.0,
                            "label": label}, model_state=state)
    np.testing.assert_allclose(float(l_u8), float(l_f32), rtol=1e-5)


@pytest.mark.slow
def test_resnet50_trains_from_published_imagefolder(tmp_path, devices):
    """The rung-3 contract end to end: imagefolder -> decode-at-publish
    256x256 uint8 shards -> stream -> random 224-crop+flip (uint8) ->
    device-side normalize -> ResNet-50 train steps with finite loss."""
    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.control.daemons import start_shard_server
    from serverless_learn_tpu.data.shard_client import publish_dataset
    from serverless_learn_tpu.training.loop import make_source
    from serverless_learn_tpu.training.train_step import build_trainer

    _write_tree(str(tmp_path / "imgs"), ["c0", "c1"],
                [(300, 240), (256, 256), (224, 300)])
    arrays = load_imagefolder(str(tmp_path / "imgs"),
                              image_size=IMAGEFOLDER_STORE_SIZE)
    assert arrays["image"].shape == (6, 256, 256, 3)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    addr = f"127.0.0.1:{port}"
    try:
        publish_dataset(addr, "tiny_imagenet", arrays, records_per_shard=3)
        from serverless_learn_tpu.parallel.mesh import make_mesh

        cfg = ExperimentConfig(
            model="resnet50_imagenet",
            model_overrides=dict(num_classes=2),
            mesh=MeshConfig(),  # single device: r50 compute is the cost here
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.01,
                                      momentum=0.9),
            train=TrainConfig(batch_size=2, num_steps=2),
            data=DataConfig(dataset="tiny_imagenet", shard_server_addr=addr,
                            augment=True),
        )
        trainer = build_trainer(
            cfg, mesh=make_mesh(cfg.mesh, devices=devices[:1]))
        source = make_source(cfg, trainer, dp_rank=0, dp_size=1)
        it = iter(source)
        state = trainer.init()
        for _ in range(2):
            batch = next(it)
            assert batch["image"].dtype == np.uint8  # u8 to the device
            assert batch["image"].shape == (2, 224, 224, 3)
            state, m = trainer.step(state, trainer.shard_batch(batch))
        assert np.isfinite(float(jax.device_get(m["loss"])))
        if hasattr(source, "close"):
            source.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)
