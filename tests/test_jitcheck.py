"""Tier-1 tests for the round-25 JAX program analysis: the four static
rules (SLT010 dtype flow, SLT011 donation safety, SLT012 recompile
hazards, SLT013 sharding drift), the runtime compile monitor
(analysis/jitcheck.py), the jaxpr harness (analysis/shardcheck.py) and
the `slt jit` replay CLI.

Static-rule tests use the test_analysis fixture idiom (known-bad code
fires, known-good passes); monitor tests use LOCAL JitMonitor instances
via jitcheck.scoped() so they stay deterministic under a session-global
SLT_JITCHECK=1 install; session-failure tests run a seeded pytest
subprocess and assert exit code 5 (lockcheck=3, racecheck=4,
jitcheck=5).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from serverless_learn_tpu.analysis import jitcheck, shardcheck
from serverless_learn_tpu.analysis.engine import discover, run_check
from serverless_learn_tpu.analysis.rules import (slt010_dtype_flow,
                                                 slt011_donation_safety,
                                                 slt012_recompile_hazard,
                                                 slt013_sharding_drift)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def _run_rule(rule, root):
    return rule.run(discover(root))


# -- SLT010: dtype flow ------------------------------------------------------

def test_slt010_bf16_reduction_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def loss(x):
            h = x.astype(jnp.bfloat16)
            return jnp.sum(h)
        """})
    fs = _run_rule(slt010_dtype_flow, root)
    assert any("sum()" in f.message and "bfloat16" in f.message
               for f in fs), fs


def test_slt010_f32_accumulator_escape_hatch_is_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def loss(x):
            h = x.astype(jnp.bfloat16)
            a = jnp.sum(h, dtype=jnp.float32)
            b = jnp.sum(h.astype(jnp.float32))
            return a + b
        """})
    assert _run_rule(slt010_dtype_flow, root) == []


def test_slt010_method_reduction_and_unknown_dtype(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad(x):
            h = x.astype(jnp.bfloat16)
            return h.mean()

        @jax.jit
        def unknown_is_quiet(x, d):
            h = x.astype(d)
            return jnp.sum(h)
        """})
    fs = _run_rule(slt010_dtype_flow, root)
    assert len(fs) == 1 and "mean()" in fs[0].message, fs


def test_slt010_f64_in_jit_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x + jnp.zeros((4,), dtype=jnp.float64)
        """})
    fs = _run_rule(slt010_dtype_flow, root)
    assert any("float64" in f.message for f in fs), fs


def test_slt010_mixed_precision_binop_warns(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = x.astype(jnp.bfloat16)
            b = jnp.zeros((4,), jnp.float32)
            return a + b
        """})
    fs = _run_rule(slt010_dtype_flow, root)
    assert any(f.severity == "warning" and "upcast" in f.message
               for f in fs), fs


def test_slt010_param_dtype_contract(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/config.py": """\
        from dataclasses import dataclass

        @dataclass
        class TrainConfig:
            dtype: str = "bfloat16"
            param_dtype: str = "bfloat16"
        """})
    fs = _run_rule(slt010_dtype_flow, root)
    assert any("param_dtype" in f.message and "master" in f.message
               for f in fs), fs


# -- SLT011: donation safety -------------------------------------------------

def test_slt011_read_after_donation_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            return state, 1.0

        def train(state, batches):
            for batch in batches:
                new_state, loss = step(state, batch)
                emit(state["params"])
                state = new_state
        """})
    fs = _run_rule(slt011_donation_safety, root)
    assert any("donated to step()" in f.message for f in fs), fs


def test_slt011_rebind_is_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            return state, 1.0

        def train(state, batches):
            for batch in batches:
                state, loss = step(state, batch)
                emit(state["params"])
            return state
        """})
    assert _run_rule(slt011_donation_safety, root) == []


def test_slt011_self_attr_and_factory_paths(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax

        def make_step():
            inner = jax.jit(lambda s, b: (s, 1.0), donate_argnums=(0,))
            return inner

        def factory_bug(state, batch):
            fn = make_step()
            out, _ = fn(state, batch)
            return state.params

        class Engine:
            def __init__(self):
                self._step = jax.jit(lambda s, b: (s, 1.0),
                                     donate_argnums=(0,))

            def run(self, batch):
                st, _ = self._step(self._state, batch)
                x = self._state["pages"]
                self._state = st

            def run_ok(self, batch):
                self._state, _ = self._step(self._state, batch)
                return self._state["pages"]
        """})
    fs = _run_rule(slt011_donation_safety, root)
    msgs = "\n".join(f.message for f in fs)
    assert "state.params read in factory_bug" in msgs, fs
    assert "self._state['pages'] read in run " in msgs, fs
    assert "run_ok" not in msgs, fs


def test_slt011_branch_union_and_loop_second_iteration(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            return state, 1.0

        def branch_bug(state, batch, flag):
            if flag:
                out, _ = step(state, batch)
            else:
                out = state
            return state

        def loop_bug(state, batch):
            for _ in range(3):
                out, _ = step(state, batch)
            return out
        """})
    fs = _run_rule(slt011_donation_safety, root)
    assert any(f.message.startswith("state read in branch_bug")
               for f in fs), fs
    assert any(f.message.startswith("state read in loop_bug")
               for f in fs), fs


def test_slt011_non_literal_donate_mask_is_quiet(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        from functools import partial

        donate = (0,) if True else ()

        @partial(jax.jit, donate_argnums=donate)
        def step(state, batch):
            return state, 1.0

        def train(state, batch):
            out, _ = step(state, batch)
            return state
        """})
    assert _run_rule(slt011_donation_safety, root) == []


# -- SLT012: recompile hazards -----------------------------------------------

def test_slt012_traced_branch_fires_static_and_none_are_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        from functools import partial

        @jax.jit
        def bad(x, n):
            if n > 4:
                return x * 2
            return x

        @partial(jax.jit, static_argnums=(1,))
        def ok_static(x, n):
            if n > 4:
                return x * 2
            return x

        @jax.jit
        def ok_none(x, mask=None):
            if mask is None:
                return x
            return x * mask
        """})
    fs = _run_rule(slt012_recompile_hazard, root)
    assert len(fs) == 1, fs
    assert "bad branches on traced parameter(s) n" in fs[0].message


def test_slt012_unhashable_static_arg_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def make(x, shape):
            return jnp.zeros(shape) + x

        def caller(x):
            return make(x, [4, 4])
        """})
    fs = _run_rule(slt012_recompile_hazard, root)
    assert any("unhashable" in f.message.lower()
               or "hashable" in f.message for f in fs), fs


def test_slt012_jit_in_loop_warns_memoized_is_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax

        def bad(fs, x):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(x))
            return outs

        def ok(fs, cache):
            for i, f in enumerate(fs):
                cache[i] = jax.jit(f)
            return cache
        """})
    fs = _run_rule(slt012_recompile_hazard, root)
    assert len(fs) == 1 and fs[0].severity == "warning", fs
    assert "loop" in fs[0].message


def test_slt012_raw_len_shape_key_fires_bucketed_is_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        import jax
        from serverless_learn_tpu.analysis import jitcheck

        @jitcheck.bucket
        def _nb(n):
            return max(8, 1 << (n - 1).bit_length())

        class Eng:
            def _shape_jit(self, nb):
                key = (nb,)
                if key not in self._cache:
                    self._cache[key] = jax.jit(lambda s: s)
                return self._cache[key]

            def good(self, rows):
                nb = _nb(len(rows))
                return self._shape_jit(nb)

            def clamped(self, rows, cap):
                nb = min(_nb(len(rows)), cap)
                return self._shape_jit(nb)

            def bad(self, rows):
                nb = len(rows)
                return self._shape_jit(nb)
        """})
    fs = _run_rule(slt012_recompile_hazard, root)
    assert len(fs) == 1, fs
    assert "raw len()" in fs[0].message and "_shape_jit" in fs[0].message


# -- SLT013: sharding drift --------------------------------------------------

_SLT013_BASE = """\
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.lax import with_sharding_constraint

    AXIS_NAMES = ("dp", "tp")
"""


def test_slt013_undeclared_axis_fires_declared_is_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": _SLT013_BASE + """\

    def good(x):
        return with_sharding_constraint(x, P("dp", None))

    def typo(x):
        return with_sharding_constraint(x, P("ftp", None))

    def tuple_drift(x):
        return with_sharding_constraint(x, P(("dp", "fsdp"),))
    """})
    fs = _run_rule(slt013_sharding_drift, root)
    axes = sorted(f.message.split("'")[1] for f in fs)
    assert axes == ["fsdp", "ftp"], fs


def test_slt013_compose_axis_drift_fires(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": _SLT013_BASE + """\

    def f(spec, shape, mesh):
        return compose_axis(spec, shape, mesh, "zp")
    """})
    fs = _run_rule(slt013_sharding_drift, root)
    assert any("compose_axis" in f.message and "'zp'" in f.message
               for f in fs), fs


def test_slt013_constraint_in_scan_body_fires_outside_is_clean(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": _SLT013_BASE + """\

    def accum(params, batches):
        def body(acc, mb):
            g = jnp.zeros((4,))
            g = with_sharding_constraint(g, P("dp"))
            return acc + g, None
        out, _ = jax.lax.scan(body, jnp.zeros((4,)), batches)
        return with_sharding_constraint(out, P("dp"))
    """})
    fs = _run_rule(slt013_sharding_drift, root)
    assert len(fs) == 1 and "scan body" in fs[0].message, fs


def test_slt013_no_declared_axes_stays_quiet(tmp_path):
    root = _tree(tmp_path, {"serverless_learn_tpu/m.py": """\
        from jax.sharding import PartitionSpec as P

        SPEC = P("whatever")
        """})
    assert _run_rule(slt013_sharding_drift, root) == []


# -- seeded-defect tree: all four rules at once ------------------------------

_SEEDED = {
    "serverless_learn_tpu/dtypes.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def loss(x):
            h = x.astype(jnp.bfloat16)
            return jnp.sum(h)
        """,
    "serverless_learn_tpu/donate.py": """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            return state, 1.0

        def train(state, batch):
            out, _ = step(state, batch)
            return state
        """,
    "serverless_learn_tpu/recompile.py": """\
        import jax

        @jax.jit
        def bad(x, n):
            if n > 4:
                return x * 2
            return x
        """,
    "serverless_learn_tpu/shard.py": """\
        from jax.sharding import PartitionSpec as P
        from jax.lax import with_sharding_constraint

        AXIS_NAMES = ("dp", "tp")

        def f(x):
            return with_sharding_constraint(x, P("ftp"))
        """,
}


def test_seeded_defect_tree_fails_all_four_rules(tmp_path):
    root = _tree(tmp_path, _SEEDED)
    rep = run_check(root, baseline_path="baseline.json")
    assert not rep["ok"]
    rules_hit = {f["rule"] for f in rep["findings"]}
    assert {"SLT010", "SLT011", "SLT012", "SLT013"} <= rules_hit, \
        rules_hit


def test_repo_at_head_is_clean_for_new_rules():
    rep = run_check(REPO, rule_ids=["SLT010", "SLT011", "SLT012",
                                    "SLT013"])
    assert rep["ok"], rep["findings"]


# -- jitcheck monitor (local monitors via scoped()) --------------------------

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _instrumented_step():
    """A donating jit created from THIS file (tests/ is in scope)."""
    was = jitcheck.installed()
    jitcheck.install()
    step = jax.jit(lambda s, b: (s + b, s.sum()), donate_argnums=(0,))
    if not was and not jitcheck.enabled_by_env():
        jitcheck.uninstall()  # leave the global patch as we found it
    if not isinstance(step, jitcheck._InstrumentedJit):
        pytest.skip("jax.jit already bound before instrumentation")
    return step


def test_monitor_counts_compiles_within_budget():
    step = _instrumented_step()
    mon = jitcheck.JitMonitor("unit")
    with jitcheck.scoped(mon):
        s, b = jnp.zeros((4,)), jnp.ones((4,))
        s, _ = step(s, b)
        s, _ = step(s, b)   # same shape: cached, no second compile
    assert mon.site_compiles() == {step.site: 1}
    assert mon.violations() == []
    rec = mon.records()[0]
    assert rec["donate"] == [0]
    assert rec["args"][0].startswith("float32[4]")
    assert rec["elapsed_ms"] > 0


def test_monitor_budget_overrun_fails():
    step = _instrumented_step()
    mon = jitcheck.JitMonitor("unit")
    mon.declare_budget(step.site, max_compiles_per_jit=1)
    with jitcheck.scoped(mon):
        s, _ = step(jnp.zeros((4,)), jnp.ones((4,)))
        s, _ = step(jnp.zeros((8,)), jnp.ones((8,)))  # 2nd signature
    kinds = [v["kind"] for v in mon.violations()]
    assert kinds == ["budget"], mon.violations()
    with pytest.raises(jitcheck.JitCheckViolation):
        mon.assert_clean()


def test_monitor_frozen_window_recompile_fails():
    step = _instrumented_step()
    mon = jitcheck.JitMonitor("unit")
    with jitcheck.scoped(mon):
        step(jnp.zeros((4,)), jnp.ones((4,)))       # warm
        with jitcheck.frozen("measured"):
            step(jnp.zeros((4,)), jnp.ones((4,)))   # cached: fine
            step(jnp.zeros((8,)), jnp.ones((8,)))   # compile: violation
    vio = mon.violations()
    assert [v["kind"] for v in vio] == ["frozen"], vio
    assert vio[0]["label"] == "measured"
    assert vio[0]["stack"], "frozen violation must carry the stack"


def test_monitor_detects_donated_buffer_reuse():
    step = _instrumented_step()
    mon = jitcheck.JitMonitor("unit")
    with jitcheck.scoped(mon):
        s, b = jnp.zeros((4,)), jnp.ones((4,))
        s, _ = step(s, b)
        out, _ = step(s, b)   # donates s, NOT rebound
        try:
            step(s, b)        # reuse: logical violation...
        except ValueError:
            pass              # ...and jax itself may also object
    vio = [v for v in mon.violations() if v["kind"] == "donation_reuse"]
    assert len(vio) == 1, mon.violations()
    assert vio[0]["donated"]["site"] == step.site
    assert "rebound" in vio[0]["why"]


def test_monitor_rebind_pattern_is_clean():
    step = _instrumented_step()
    mon = jitcheck.JitMonitor("unit")
    with jitcheck.scoped(mon):
        s, b = jnp.zeros((4,)), jnp.ones((4,))
        for _ in range(4):
            s, _ = step(s, b)  # the sanctioned rebind loop
    assert mon.violations() == []


def test_monitor_jsonl_replay_round_trip(tmp_path):
    step = _instrumented_step()
    log = tmp_path / "jit.jsonl"
    mon = jitcheck.JitMonitor("unit", log_path=str(log))
    mon.declare_budget(step.site, max_compiles_per_jit=1)
    with jitcheck.scoped(mon):
        step(jnp.zeros((4,)), jnp.ones((4,)))
        with jitcheck.frozen("w"):
            step(jnp.zeros((8,)), jnp.ones((8,)))  # frozen AND over budget
    mon.close_log()
    rep = jitcheck.replay_log(str(log))
    kinds = sorted(v["kind"] for v in rep["violations"])
    assert kinds == ["budget", "frozen"], rep["violations"]
    assert rep["sites"][step.site] == 2
    # live monitor and replay agree
    assert sorted(v["kind"] for v in mon.violations()) == kinds


def test_self_check_passes():
    assert jitcheck.self_check() == []


# -- slt jit CLI -------------------------------------------------------------

def test_cli_jit_replay_exit_codes(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    site = "serverless_learn_tpu/inference/continuous.py:_admit_jit"
    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(json.dumps(e) + "\n" for e in [
        {"ev": "declare", "site": site, "budget": 1},
        {"ev": "compile", "site": site, "n": 2, "args": ["f32[8]"],
         "stack": ["a.py:1 in hot"]},
    ]))
    rc = main(["jit", str(bad), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 2 and out["ok"] is False
    assert out["violations"][0]["kind"] == "budget"

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(
        {"ev": "compile", "site": site, "n": 1, "args": ["f32[8]"]})
        + "\n")
    assert main(["jit", str(good)]) == 0
    capsys.readouterr()


def test_cli_jit_self_check(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["jit", "--self-check"]) == 0
    assert "verdict engine OK" in capsys.readouterr().out


# -- session failure end-to-end (exit 5) -------------------------------------

_SUB_CONFTEST = """\
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, {repo!r})
    from serverless_learn_tpu.analysis import jitcheck
    jitcheck.install()
    import pytest

    def pytest_sessionfinish(session, exitstatus):
        mon = jitcheck.monitor()
        print()
        print(mon.report())
        if mon.violations():
            pytest.exit("jitcheck violations", returncode=5)
"""


def _run_sub_session(tmp_path, test_body):
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "conftest.py").write_text(
        textwrap.dedent(_SUB_CONFTEST).format(repo=REPO))
    (tests / "test_seeded.py").write_text(textwrap.dedent(test_body))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO, SLT_JITCHECK="1")
    env.pop("SLT_JITCHECK_LOG", None)
    return subprocess.run(
        [sys.executable, "-m", "pytest", str(tests), "-q", "-p",
         "no:cacheprovider"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=str(tmp_path))


def test_surprise_recompile_fails_the_session(tmp_path):
    """A compile past a declared budget exits 5 with both traces."""
    proc = _run_sub_session(tmp_path, """\
        import jax, jax.numpy as jnp
        from serverless_learn_tpu.analysis import jitcheck

        def test_budget_breach():
            f = jax.jit(lambda x: x * 2)
            jitcheck.monitor().declare_budget(f.site, 1)
            f(jnp.zeros((4,)))
            f(jnp.zeros((8,)))   # second signature on one jit object
        """)
    assert proc.returncode == 5, proc.stdout + proc.stderr
    assert "VIOLATION [budget]" in proc.stdout


def test_donated_reuse_fails_the_session(tmp_path):
    proc = _run_sub_session(tmp_path, """\
        import jax, jax.numpy as jnp
        from serverless_learn_tpu.analysis import jitcheck

        def test_reuse():
            step = jax.jit(lambda s, b: (s + b, s.sum()),
                           donate_argnums=(0,))
            s, b = jnp.zeros((4,)), jnp.ones((4,))
            s, _ = step(s, b)
            out, _ = step(s, b)      # donates s without rebinding
            try:
                step(s, b)           # reuse
            except ValueError:
                pass
        """)
    assert proc.returncode == 5, proc.stdout + proc.stderr
    assert "VIOLATION [donation_reuse]" in proc.stdout


# -- shardcheck harness ------------------------------------------------------

def test_shardcheck_flags_constraint_inside_scan(devices):
    from jax.sharding import PartitionSpec as P

    mesh = jax.sharding.Mesh(jax.devices(), ("dp",))

    def bad(xs):
        def body(acc, x):
            y = jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, P("dp")))
            return acc + y, None
        out, _ = jax.lax.scan(body, jnp.zeros((8,)), xs)
        return out

    report = shardcheck.audit(bad, jnp.ones((4, 8)))
    assert report.in_scan, "constraint inside scan body must be seen"
    assert "dp" in report.axes_used
    with pytest.raises(AssertionError, match="PER ITERATION"):
        report.assert_no_loop_constraints()


def test_shardcheck_constraint_outside_scan_is_clean(devices):
    from jax.sharding import PartitionSpec as P

    mesh = jax.sharding.Mesh(jax.devices(), ("dp",))

    def good(xs):
        def body(acc, x):
            return acc + x, None
        out, _ = jax.lax.scan(body, jnp.zeros((8,)), xs)
        return jax.lax.with_sharding_constraint(
            out, jax.sharding.NamedSharding(mesh, P("dp")))

    report = shardcheck.audit(good, jnp.ones((4, 8)))
    assert report.in_scan == []
    assert report.outside_with_axis("dp")
    report.assert_no_loop_constraints()


# -- acceptance: warmed engine + train loop under the monitor ----------------

def test_warmed_engine_and_train_loop_have_no_unexpected_compiles(devices):
    """The ISSUE 20 acceptance path: a warmed ContinuousBatchingEngine
    decode and a tiny train loop, both under the monitor — every
    compile lands inside a declared budget and the post-warmup frozen
    window sees none."""
    was = jitcheck.installed()
    jitcheck.install()
    try:
        mon = jitcheck.JitMonitor("acceptance")
        with jitcheck.scoped(mon):
            # -- engine: warm one admit bucket, then decode frozen ----
            from serverless_learn_tpu.inference.continuous import (
                ContinuousBatchingEngine)
            from serverless_learn_tpu.models.registry import get_model

            bundle = get_model("llama_tiny", dtype=jnp.float32,
                               param_dtype=jnp.float32, max_seq_len=64)
            params = bundle.module.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 8), jnp.int32))["params"]
            eng = ContinuousBatchingEngine(bundle.module, params,
                                           max_slots=4, chunk_size=4)
            try:
                # first request compiles the admit bucket + chunk step
                eng.submit([5, 9, 11], 4, temperature=0.0, top_k=0,
                           eos_id=None, seed=0)
                warm_sites = dict(mon.site_compiles())
                with jitcheck.frozen("post-warmup decode"):
                    # same buckets: zero new compiles allowed
                    out = eng.submit([7, 3, 2], 4, temperature=0.0,
                                     top_k=0, eos_id=None, seed=0)
                assert "error" not in out
            finally:
                eng.stop()
            assert [v for v in mon.violations()] == [], mon.report()
            assert any("continuous.py" in s for s in warm_sites), \
                warm_sites

            # -- tiny train loop: one compile per jit object ----------
            from serverless_learn_tpu.config import (
                DataConfig, ExperimentConfig, MeshConfig,
                OptimizerConfig, TrainConfig)
            from serverless_learn_tpu.data.datasets import SyntheticSource
            from serverless_learn_tpu.training.train_step import (
                build_trainer)

            cfg = ExperimentConfig(
                model="mlp_mnist", mesh=MeshConfig(dp=8),
                optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
                train=TrainConfig(batch_size=16, num_steps=3),
                data=DataConfig(seq_len=16))
            trainer = build_trainer(cfg)
            state = trainer.init()
            src = SyntheticSource(trainer.bundle.make_batch, cfg.data,
                                  16, seed=7)
            it = iter(src)
            state, _ = trainer.step(state, trainer.shard_batch(next(it)))
            with jitcheck.frozen("steady-state training"):
                for _ in range(2):
                    state, _ = trainer.step(
                        state, trainer.shard_batch(next(it)))
        assert mon.violations() == [], mon.report()
        ts = "serverless_learn_tpu/training/train_step.py:build_trainer"
        assert mon.site_compiles().get(ts, 0) >= 2  # init + step
    finally:
        if not was and not jitcheck.enabled_by_env():
            jitcheck.uninstall()
