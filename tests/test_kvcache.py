"""Paged KV cache (round 13): allocator/trie primitives, block-table
padding semantics, and the token-identical paged-vs-monolithic
equivalence suite (greedy + seeded, mixed slot configs, chunked prefill,
shared prefixes, exhaustion backpressure, preemption).

The exactness bar: the paged continuous engine must be byte-identical to
solo ``generate`` (greedy) and to the monolithic engine (seeded
sampling) — paging changes WHERE K/V live, never what attention reads.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import ExperimentConfig, KVCacheConfig
from serverless_learn_tpu.inference import kvcache
from serverless_learn_tpu.inference.continuous import (
    ContinuousBatchingEngine)
from serverless_learn_tpu.inference.generate import generate, init_cache
from serverless_learn_tpu.inference.kvcache import (BlockPool,
                                                    KVBlocksExhausted,
                                                    PrefixTrie, pages_for)
from serverless_learn_tpu.models.registry import get_model
from serverless_learn_tpu.telemetry.registry import MetricsRegistry


# -- allocator / trie primitives (jax-free) ----------------------------------


def test_block_pool_alloc_refcount_exhaustion():
    pool = BlockPool(4, block_size=8)
    a = pool.alloc(3)
    assert len(a) == 3 and pool.free_blocks == 1
    # All-or-nothing: a failed alloc leaves the pool untouched.
    with pytest.raises(KVBlocksExhausted) as ei:
        pool.alloc(2)
    assert ei.value.need == 2 and ei.value.free == 1
    assert pool.free_blocks == 1
    # Sharing: a second ref keeps the block allocated through one decref.
    pool.incref(a[:1])
    assert pool.decref(a[:1]) == 0
    assert pool.decref(a[:1]) == 1
    assert pool.free_blocks == 2
    # Double-free is a typed error, not silent corruption.
    with pytest.raises(kvcache.KVCacheError):
        pool.decref(a[:1])
    assert pages_for(0, 8) == 0 and pages_for(1, 8) == 1
    assert pages_for(8, 8) == 1 and pages_for(9, 8) == 2


def test_prefix_trie_lookup_register_cow_evict():
    pool = BlockPool(16, block_size=4)
    trie = PrefixTrie(pool)
    prompt = list(range(10))  # 2 full blocks + remainder [8, 9]
    blocks = pool.alloc(3)
    assert trie.register(prompt, blocks[:2]) == 2  # full blocks only
    assert trie.blocks_held == 2
    assert pool.refcount(blocks[0]) == 2  # owner + trie
    # Full-prefix hit.
    hit = trie.lookup(prompt)
    assert hit.blocks == blocks[:2] and hit.tokens_matched == 8
    # Divergent mid-block: first full block matches, second diverges.
    other = [0, 1, 2, 3, 99, 98, 97, 96]
    hit = trie.lookup(other)
    assert hit.blocks == blocks[:1] and hit.tokens_matched == 4
    # COW donor: remainder [4, 5] matches block 1's first two tokens.
    hit = trie.lookup([0, 1, 2, 3, 4, 5])
    assert hit.blocks == blocks[:1]
    assert hit.cow_src == blocks[1] and hit.cow_tokens == 2
    # Retire the owner; trie refs keep the blocks allocated.
    pool.decref(blocks)
    assert pool.free_blocks == 16 - 2
    # Eviction prefers trie-only leaves and frees real memory.
    freed = trie.release(1)
    assert freed == 1 and trie.blocks_held == 1
    assert trie.clear() == 1
    assert pool.free_blocks == 16


def test_trie_eviction_respects_live_refs():
    pool = BlockPool(8, block_size=2)
    trie = PrefixTrie(pool, max_blocks=1)
    b1 = pool.alloc(1)
    trie.register([1, 2], b1)
    b2 = pool.alloc(1)
    trie.register([3, 4], b2)  # max_blocks=1 -> evicts the LRU node
    assert trie.blocks_held == 1
    # The evicted block was still owned by its slot: NOT freed.
    assert pool.refcount(b1[0]) == 1
    pool.decref(b1)
    pool.decref(b2)
    assert pool.refcount(b2[0]) == 1  # trie still holds it


def test_kv_config_roundtrip():
    cfg = ExperimentConfig.from_json(json.dumps({
        "model": "llama_tiny",
        "kv": {"paged": True, "block_size": 8, "num_blocks": 64,
               "prefill_chunk": 16, "prefix_cache": False}}))
    assert cfg.kv.block_size == 8 and cfg.kv.num_blocks == 64
    assert not cfg.kv.prefix_cache
    back = json.loads(cfg.to_json())
    assert back["kv"]["prefill_chunk"] == 16


def test_doctor_names_kv_pressure(tmp_path):
    """Satellite: the verdict names a KV-pressure incident (blocks
    exhausted -> admit_wait badput) from metrics + events alone."""
    from serverless_learn_tpu.telemetry.doctor import diagnose

    now = time.time()
    events = tmp_path / "events.jsonl"
    recs = [
        {"event": "alert", "alert": "kv.blocks_exhausted",
         "severity": "warning", "detector": "kvcache", "state": "firing",
         "message": "KV block pool exhausted (0/64 free)",
         "labels": {"engine": "continuous"}, "node": "serve-1",
         "value": 0.0, "threshold": 0.0, "count": 3,
         "first_fired_unix_s": now - 30, "last_fired_unix_s": now},
        # The symptom: admissions waiting, little decode.
        {"event": "phase", "phase": "admit_wait", "node": "serve-1",
         "t0_unix_s": now - 30, "duration_s": 20.0, "self_s": 20.0},
        {"event": "phase", "phase": "decode", "node": "serve-1",
         "t0_unix_s": now - 10, "duration_s": 5.0, "self_s": 5.0},
    ]
    events.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    rep = diagnose(paths=[str(events)])
    verdict = rep["summary"]["verdict"]
    assert "KV pressure" in verdict and "serve-1" in verdict
    assert "admit" in verdict  # badput correlation named
    assert any(a["alert"] == "kv.blocks_exhausted" for a in rep["alerts"])


# -- model-backed equivalence ------------------------------------------------


@pytest.fixture(scope="module")
def model(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def _solo(module, params, prompt, n, eos_id=None):
    toks = generate(module, params, jnp.asarray([prompt], jnp.int32), n,
                    eos_id=eos_id)
    return [int(t) for t in jax.device_get(toks)[0][len(prompt):]]


def _paged_engine(module, params, **kw):
    kv = kw.pop("kv", None) or KVCacheConfig(block_size=4,
                                             prefill_chunk=4,
                                             prefill_budget=8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("chunk_size", 4)
    kw.setdefault("registry", MetricsRegistry())
    return ContinuousBatchingEngine(module, params, kv=kv, **kw)


def test_paged_generate_matches_monolithic(model):
    """Module-level equivalence: the paged cache path of ``generate``
    (dense row-major tables, the static engine's shape) is byte-identical
    to the monolithic cache — greedy AND sampled (same PRNG stream)."""
    module, params = model
    ps, B = 8, 2
    max_pages = pages_for(module.cfg.max_seq_len, ps)
    pm = kvcache.paged_module(module, ps, B * max_pages)
    prompts = jnp.asarray([[5, 9, 11, 3], [7, 3, 2, 1]], jnp.int32)
    lengths = jnp.asarray([4, 2], jnp.int32)

    def paged_cache():
        tbl = jnp.asarray(kvcache.sequential_table(B, max_pages,
                                                   pm.cfg.kv_pages))
        return kvcache.with_tables(init_cache(pm, B), tbl,
                                   jnp.zeros((B,), jnp.int32))

    for kw in ({}, {"temperature": 0.8, "top_k": 8,
                    "rng": jax.random.PRNGKey(3)}):
        mono = generate(module, params, prompts, 10,
                        prompt_lengths=lengths, **kw)
        paged = generate(pm, params, prompts, 10, prompt_lengths=lengths,
                         cache=paged_cache(), **kw)
        assert np.array_equal(np.asarray(mono), np.asarray(paged)), \
            f"paged generate diverged ({kw or 'greedy'})"


def test_paged_engine_greedy_exact_with_chunked_prefill(model):
    """Concurrent unequal prompts — including one long enough to prefill
    in 4 chunks — are byte-identical to solo generate through the paged
    engine's admit/prefill/decode scheduler."""
    module, params = model
    eng = _paged_engine(module, params)
    try:
        prompts = [[5, 9, 11],
                   [7, 3, 2, 8, 1, 30, 12, 9, 4, 2, 6, 1, 8],  # 13 toks
                   [4], [1, 2]]
        results = [None] * len(prompts)

        def client(i):
            results[i] = eng.submit(prompts[i], 6, temperature=0.0,
                                    top_k=0, eos_id=None, seed=0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert "error" not in results[i], results[i]
            assert results[i]["new_tokens"] == _solo(module, params, p, 6), \
                f"request {i} diverged under the paged engine"
        assert eng.prefill_chunks_run > 0
        # Retirement returned every non-cached block to the free list.
        st = eng.kv_stats()
        assert (st["blocks_total"] - st["blocks_free"]
                == st["prefix_blocks_cached"])
    finally:
        eng.stop()


def test_paged_engine_seeded_sampling_matches_monolithic(model):
    """Seeded sampling: identical tokens from the paged and monolithic
    engines (the fold_in(seed, position) streams are layout-blind)."""
    module, params = model
    req = dict(prompt=[7, 3, 2, 9, 1, 4], max_new=6, temperature=0.9,
               top_k=8, eos_id=None, seed=42)

    def run(paged):
        kv = (KVCacheConfig(block_size=4, prefill_chunk=4) if paged
              else KVCacheConfig(paged=False))
        eng = ContinuousBatchingEngine(module, params, max_slots=3,
                                       chunk_size=2, kv=kv,
                                       registry=MetricsRegistry())
        try:
            res = {}

            def target():
                res["r"] = eng.submit(req["prompt"], req["max_new"],
                                      req["temperature"], req["top_k"],
                                      req["eos_id"], req["seed"])

            ts = [threading.Thread(target=target),
                  threading.Thread(target=lambda: eng.submit(
                      [5, 9, 11, 4], 8, 0.0, 0, None, 0))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            assert "error" not in res["r"], res["r"]
            return res["r"]["new_tokens"]
        finally:
            eng.stop()

    assert run(paged=True) == run(paged=False), \
        "paged seeded sampling diverged from the monolithic engine"


def test_paged_engine_eos_retires_and_frees_blocks(model):
    module, params = model
    prompt = [5, 9, 11]
    first_tok = _solo(module, params, prompt, 1)[0]
    want = _solo(module, params, prompt, 8, eos_id=first_tok)
    eng = _paged_engine(module, params, kv=KVCacheConfig(
        block_size=4, prefill_chunk=4, prefix_cache=False))
    try:
        r = eng.submit(prompt, 8, 0.0, 0, first_tok, 0)
        assert r["new_tokens"] == want
        st = eng.kv_stats()
        assert st["blocks_free"] == st["blocks_total"], \
            "EOS retirement must return every block to the free list"
    finally:
        eng.stop()


def test_shared_prefix_reuse_hits_and_stays_exact(model):
    """Two prompts sharing a 12-token system prefix: the second admission
    reuses the published blocks (hit counters move) and both replies stay
    byte-identical to solo generate. A third prompt diverging mid-block
    exercises the COW path."""
    module, params = model
    reg = MetricsRegistry()
    eng = _paged_engine(module, params, registry=reg)
    try:
        sysp = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
        a = eng.submit(sysp + [11, 2], 5, 0.0, 0, None, 0)
        assert a["new_tokens"] == _solo(module, params, sysp + [11, 2], 5)
        hits0 = eng._trie.hits
        b = eng.submit(sysp + [9, 7], 5, 0.0, 0, None, 0)
        assert b["new_tokens"] == _solo(module, params, sysp + [9, 7], 5)
        assert eng._trie.hits > hits0, "second prompt missed the trie"
        # Mid-block divergence: shares sysp[:8] fully, then diverges
        # inside the third block -> COW donor.
        c_prompt = sysp[:10] + [44, 45]
        c = eng.submit(c_prompt, 5, 0.0, 0, None, 0)
        assert c["new_tokens"] == _solo(module, params, c_prompt, 5)
        snap = reg.snapshot()
        hits = sum(s["value"] for s in
                   snap["slt_kv_prefix_hits_total"]["series"])
        toks = sum(s["value"] for s in
                   snap["slt_kv_prefix_tokens_total"]["series"])
        assert hits >= 2 and toks > 0
    finally:
        eng.stop()


def test_exhaustion_backpressure_and_preemption_stay_exact(model):
    """A pool sized for ONE max-length sequence under 4 concurrent
    long-budget requests: admissions defer (typed backpressure, counted),
    decode-time pressure preempts the youngest (deterministic restart),
    and every reply is still byte-identical. No crash, no leak."""
    module, params = model
    reg = MetricsRegistry()
    kv = KVCacheConfig(block_size=4, num_blocks=16, prefill_chunk=4,
                       prefix_cache=False)
    eng = ContinuousBatchingEngine(module, params, max_slots=4,
                                   chunk_size=4, kv=kv, registry=reg)
    try:
        prompts = [[i + 1, i + 2, 3, 4, 5, 1, 2, 9] for i in range(4)]
        results = [None] * 4

        def client(i):
            results[i] = eng.submit(prompts[i], 24, 0.0, 0, None, 0,
                                    timeout_s=300)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert results[i] is not None and "error" not in results[i], \
                (i, results[i])
            assert results[i]["new_tokens"] == _solo(module, params, p, 24)
        st = eng.kv_stats()
        assert st["blocks_free"] == st["blocks_total"], "blocks leaked"
        snap = reg.snapshot()
        blocked = sum(s["value"] for s in
                      snap["slt_kv_admit_blocked_total"]["series"])
        assert blocked > 0 or eng.preemptions > 0, \
            "a 16-block pool under 4x32-token demand never felt pressure?"
    finally:
        eng.stop()


def test_decode_cost_tracks_live_slots(model):
    """Satellite (retired-slot FLOP burn): the paged decode chunk runs a
    COMPACTED live batch, so after the short request retires, boundaries
    decode 1 row, not max_slots. decoded_rows_total is the step-cost
    proxy: it must be far below chunks_run * max_slots."""
    module, params = model
    eng = _paged_engine(module, params, max_slots=4, chunk_size=2)
    try:
        res = {}

        def long_client():
            res["long"] = eng.submit([5, 9, 11], 24, 0.0, 0, None, 0)

        def short_client():
            res["short"] = eng.submit([7, 3], 2, 0.0, 0, None, 0)

        ts = [threading.Thread(target=long_client),
              threading.Thread(target=short_client)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        assert res["long"]["new_tokens"] == _solo(module, params,
                                                  [5, 9, 11], 24)
        assert res["short"]["new_tokens"] == _solo(module, params,
                                                   [7, 3], 2)
        # 4 slots, ~14 chunks: the monolithic engine would have decoded
        # chunks_run * 4 rows. Compaction must keep it near the live
        # count (2 rows briefly, then 1).
        assert eng.chunks_run >= 2
        assert eng.decoded_rows_total <= eng.chunks_run + 4, \
            (f"decode cost not tracking live slots: "
             f"{eng.decoded_rows_total} rows over {eng.chunks_run} chunks")
    finally:
        eng.stop()


def test_block_table_write_padding_drops(model):
    """Gather/scatter padding semantics: a ragged paged extend must not
    write beyond a row's valid length — pages belong to OTHER sequences.
    Proven by diffing the pool before/after an extend whose second row is
    pure padding."""
    module, params = model
    ps = 4
    pm = kvcache.paged_module(module, ps, 8)
    cache = init_cache(pm, 2)
    # Row 0 owns page 0; row 1 owns page 1. Window W=1.
    tbl = jnp.asarray([[0], [1]], jnp.int32)
    cache = kvcache.with_tables(cache, tbl, jnp.zeros((2,), jnp.int32))
    toks = jnp.asarray([[5, 9, 11], [7, 7, 7]], jnp.int32)
    lens = jnp.asarray([3, 0], jnp.int32)  # row 1: all padding
    _, upd = pm.apply({"params": params, "cache": cache}, toks,
                      extend=True, mutable=["cache"], seq_lengths=lens)
    pages, ci = kvcache.split_cache(upd["cache"])
    leaf = jax.tree_util.tree_leaves(pages)[0]
    assert np.asarray(ci).tolist() == [3, 0]
    # Row 1's page (id 1) must still be all zeros: every write dropped.
    assert not np.asarray(leaf[1]).any(), \
        "padding row wrote K/V into the shared pool"
    # Row 0's page has real K/V at offsets 0..2.
    assert np.asarray(leaf[0][:3]).any()


def test_static_engine_paged_matches_monolithic(model):
    """The static engine shares the pool abstraction: paged groups are
    byte-identical to the monolithic groups."""
    from serverless_learn_tpu.inference.batching import BatchingEngine

    module, params = model

    def run(kv):
        eng = BatchingEngine(module, params, max_batch=4,
                             registry=MetricsRegistry(), kv=kv)
        try:
            prompts = [[5, 9, 11], [7, 3, 2, 8], [4, 4]]
            results = [None] * 3

            def client(i):
                results[i] = eng.submit(prompts[i], 4, 0.0, 0, None, 0)

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            return results
        finally:
            eng.stop()

    mono = run(None)
    paged = run(KVCacheConfig(block_size=8))
    for i, (m, p) in enumerate(zip(mono, paged)):
        assert "error" not in m and "error" not in p, (m, p)
        assert m["new_tokens"] == p["new_tokens"], \
            f"static paged group diverged on request {i}"


def test_server_ping_reports_kv_and_prompt_histogram(model):
    """The serving wire's admin ping carries paged-pool pressure (the
    router's memory-aware picking input) and submit() feeds the
    prompt-length histogram (the prefix-hit-rate denominator)."""
    from serverless_learn_tpu.inference.server import (GenerationServer,
                                                       request)

    module, params = model
    reg = MetricsRegistry()
    srv = GenerationServer(module, params, registry=reg,
                           kv=KVCacheConfig(block_size=4,
                                            prefill_chunk=4)).start()
    try:
        rep = request(srv.addr, {"prompt": [5, 9, 11],
                                 "max_new_tokens": 3})
        assert rep.get("new_tokens") == _solo(module, params, [5, 9, 11],
                                              3)
        ping = request(srv.addr, {"op": "ping"})
        assert ping["ok"] and "kv" in ping
        assert ping["kv"]["blocks_total"] > 0
        assert ping["kv"]["blocks_free"] <= ping["kv"]["blocks_total"]
        snap = reg.snapshot()
        fam = snap.get("slt_request_prompt_tokens")
        assert fam and sum(s["count"] for s in fam["series"]) >= 1
    finally:
        srv.stop()


@pytest.mark.slow
def test_kv_smoke_paged_beats_monolithic(tmp_path):
    """The round-13 acceptance, measured: on the seeded shared-prefix +
    long-prompt workload at equal offered load, the paged engine shows
    lower short-class p99 AND higher decode goodput share than the
    monolithic engine, recorded as gated rows in bench_history."""
    from serverless_learn_tpu.fleet.loadgen import run_kv_smoke

    history = tmp_path / "bench_history.json"
    rep = run_kv_smoke(seed=3, rate_rps=8.0, duration_s=4.0,
                       warmup_s=3.0, history_path=str(history))
    assert rep["monolithic"]["hard_failures"] == 0
    assert rep["paged"]["hard_failures"] == 0
    assert rep["improved"], (rep["monolithic"], rep["paged"])
    rows = json.loads(history.read_text())
    names = {r["metric"] for r in rows}
    assert any("serve_kv_paged" in n and "p99" in n for n in names)
    # The recorded rows pass the gate they will be held by.
    from serverless_learn_tpu.telemetry import benchgate

    gate = benchgate.run_gate(str(history), metric="serve_kv")
    assert gate.get("ok"), gate
