"""8B-scale validation without 8B hardware (VERDICT r2 item 3).

``llama_8b`` had never been instantiated beyond config parsing. These tests
pin down, on abstract shapes (zero materialization):

* the parameter census and the LoRA trainable mask at 8B scale,
* that the fsdp=4,tp=2 sharding actually shards every large tensor and the
  per-device resident state fits a v5e (16 GB) / v4 (32 GB) HBM budget with
  headroom for grads + remat'd activations,
* the sharded-checkpoint chunk-index math (manifest size, per-device byte
  balance, exact partition coverage) at 8B leaf shapes,
* (slow) that the FULL jitted train step at 8B widths — 2-layer override —
  AOT-compiles against the virtual 8-device mesh, with XLA's own per-device
  memory accounting bounded. Execution is deliberately not attempted:
  XLA:CPU's in-process collectives have a hardcoded 40 s rendezvous abort,
  and on a 1-core host the 8 virtual devices serialize past it at these
  widths. Compilation exercises everything sharding-related (GSPMD
  partitioning, collective insertion, memory planning); the numerics of the
  same step are covered at tiny widths by the rest of the suite.
"""

import json
import math
import os

import jax
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig,
    scale_mesh)
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.training.checkpoint import _norm_index
from serverless_learn_tpu.training.train_step import build_trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GIB = 1 << 30


def _leaf_local_bytes(leaf, sharding) -> int:
    """Bytes of one device's shard of an abstract leaf."""
    n = 1
    for entry in sharding.spec:
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            n *= sharding.mesh.shape[ax]
    return int(math.prod(leaf.shape) * leaf.dtype.itemsize // n)


@pytest.fixture(scope="module")
def trainer8b(devices):
    """Full 32-layer llama_8b trainer on the fsdp=4,tp=2 mesh the elastic
    config names — abstract construction only (nothing materialized)."""
    with open(os.path.join(REPO, "configs", "llama8b_lora_elastic.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    mesh_cfg = scale_mesh(cfg.mesh, 8)
    assert mesh_cfg == MeshConfig(dp=1, fsdp=4, tp=2)
    mesh = make_mesh(mesh_cfg, devices=devices)
    return build_trainer(cfg.override(mesh=mesh_cfg), mesh=mesh)


def test_llama8b_param_census_and_lora_mask(trainer8b):
    abstract = trainer8b.abstract_state()
    n_params = sum(math.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(abstract.params))
    # Llama-3-8B shape: ~6.98B in 32 blocks + 2 x 0.53B embed/head, plus
    # ~7M of rank-16 LoRA adapters.
    assert 7.9e9 < n_params < 8.3e9, n_params

    mask = trainer8b.bundle.trainable_mask(abstract.params)
    flat_p = jax.tree_util.tree_leaves(abstract.params)
    flat_m = jax.tree_util.tree_leaves(mask)
    trainable = sum(math.prod(p.shape) for p, m in zip(flat_p, flat_m) if m)
    assert 0 < trainable < 2e7, trainable  # adapters only, base frozen
    # Frozen base params must carry no optimizer moments: the opt state's
    # total element count is O(trainable), not O(n_params).
    n_opt = sum(math.prod(l.shape) for l in
                jax.tree_util.tree_leaves(abstract.opt_state))
    assert n_opt < 3 * trainable + 1e6, (n_opt, trainable)


def test_llama8b_per_device_state_fits_hbm(trainer8b):
    abstract = trainer8b.abstract_state()
    sh = trainer8b.state_shardings
    per_device = 0
    unsharded_large = []
    for (path, leaf), s in zip(
            jax.tree_util.tree_flatten_with_path(abstract)[0],
            jax.tree_util.tree_leaves(
                sh, is_leaf=lambda x: hasattr(x, "spec"))):
        local = _leaf_local_bytes(leaf, s)
        per_device += local
        if math.prod(leaf.shape) >= (1 << 24) and local == leaf.dtype.itemsize \
                * math.prod(leaf.shape):
            unsharded_large.append(jax.tree_util.keystr(path))
    # Every >=16M-element tensor must be sharded — a rule-table miss that
    # replicates one 0.5 GB embed table per chip is a silent HBM leak.
    assert not unsharded_large, unsharded_large
    # Resident state (f32 params sharded 8-way + LoRA moments): ~4 GB. The
    # 16 GB v5e budget then leaves >= 10 GB for bf16 gathers, f32 grads of
    # the LoRA slice, and remat'd activations at the configured
    # grad_accum=4 microbatching.
    assert per_device < 6 * GIB, per_device / GIB


def test_llama8b_sharded_checkpoint_chunk_index_math(trainer8b):
    """save_sharded's chunk-index layout, computed on abstract shapes: the
    replica-0 chunks partition every leaf exactly, per-device payloads stay
    balanced, and the JSON indices stay small enough to fetch eagerly at
    restore (the _ShardedReader contract)."""
    abstract = trainer8b.abstract_state()
    shardings = trainer8b.state_shardings
    per_device_bytes: dict = {}
    n_chunks = 0
    index_entries = []
    for leaf, s in zip(
            jax.tree_util.tree_leaves(abstract),
            jax.tree_util.tree_leaves(
                shardings, is_leaf=lambda x: hasattr(x, "spec"))):
        shape = tuple(leaf.shape)
        seen_boxes = set()
        vol = 0
        for dev, index in s.devices_indices_map(shape).items():
            box = _norm_index(index, shape)
            if box in seen_boxes:
                continue  # replica (replica_id != 0): not written
            seen_boxes.add(box)
            nbytes = (math.prod(b - a for a, b in box) * leaf.dtype.itemsize
                      if box else leaf.dtype.itemsize)
            per_device_bytes[dev.id] = per_device_bytes.get(dev.id, 0) + nbytes
            vol += math.prod(b - a for a, b in box) if box else 1
            n_chunks += 1
            index_entries.append({"leaf": n_chunks,
                                  "start": [a for a, _ in box],
                                  "stop": [b for _, b in box],
                                  "offset": 0, "nbytes": nbytes})
        assert vol == math.prod(shape) if shape else vol == 1, \
            "replica-0 chunks must partition the leaf exactly"
    # Balanced save: no device writes more than 2x the mean payload.
    sizes = list(per_device_bytes.values())
    assert max(sizes) <= 2 * (sum(sizes) / len(sizes)), sizes
    # All indices together stay MB-scale (restore fetches them eagerly).
    assert len(json.dumps(index_entries).encode()) < 8 << 20
    assert n_chunks < 65536, n_chunks


@pytest.mark.slow
def test_llama8b_width_train_step_compiles(devices):
    """The full train step at 8B widths (2-layer override, LoRA + remat)
    AOT-compiles over the fsdp=4,tp=2 mesh, and XLA's compiled memory
    accounting stays within a v4 chip's HBM for this slice."""
    from serverless_learn_tpu.data.datasets import SyntheticSource

    cfg = ExperimentConfig(
        model="llama_8b",
        model_overrides=dict(n_layers=2, lora_rank=16, remat=True),
        mesh=MeshConfig(fsdp=4, tp=2),
        optimizer=OptimizerConfig(name="adamw", learning_rate=2e-4),
        train=TrainConfig(batch_size=4, num_steps=1),
        data=DataConfig(seq_len=8),
    )
    mesh = make_mesh(cfg.mesh, devices=devices)
    tr = build_trainer(cfg, mesh=mesh)
    src = iter(SyntheticSource(tr.bundle.make_batch, cfg.data, 4, seed=0))
    batch = tr.shard_batch(next(src))
    compiled = tr.step_fn.lower(
        jax.eval_shape(lambda: tr.init_fn(0)), batch).compile()
    ma = compiled.memory_analysis()
    if ma is not None:
        total = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                 + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
        # CPU-backend accounting is looser than TPU's (less fusion), so
        # this is an upper bound smoke check, not the HBM budget.
        assert total < 32 * GIB, total / GIB
