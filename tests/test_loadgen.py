"""`slt loadgen` + the round-12 acceptance: measured, fault-injected
serving curves."""

import json
import random
import threading
import time

from serverless_learn_tpu.config import FleetConfig, HealthConfig
from serverless_learn_tpu.fleet import loadgen
from serverless_learn_tpu.fleet.router import FleetRouter
from serverless_learn_tpu.fleet.testing import stub_server
from serverless_learn_tpu.telemetry.registry import MetricsRegistry


# -- arrival processes -------------------------------------------------------


def test_arrivals_deterministic_and_shaped():
    rng = lambda: random.Random("loadgen-7")  # noqa: E731
    a = loadgen.poisson_arrivals(50.0, 10.0, rng())
    b = loadgen.poisson_arrivals(50.0, 10.0, rng())
    assert a == b, "same seed must give the identical schedule"
    assert 300 < len(a) < 700  # ~500 expected
    assert all(0 <= t < 10.0 for t in a)
    assert a == sorted(a)

    d = loadgen.diurnal_arrivals(50.0, 10.0, rng())
    assert d == loadgen.diurnal_arrivals(50.0, 10.0, rng())
    # First half-period runs above base rate, second half below.
    first, second = [t for t in d if t < 5], [t for t in d if t >= 5]
    assert len(first) > len(second)

    f = loadgen.flash_crowd_arrivals(20.0, 10.0, rng(), spike_mult=5.0,
                                     spike_at_frac=0.4, spike_dur_frac=0.2)
    assert f == sorted(f)
    in_spike = [t for t in f if 4.0 <= t < 6.0]
    before = [t for t in f if 2.0 <= t < 4.0]
    assert len(in_spike) > 2 * len(before), (len(in_spike), len(before))


def test_closed_loop_against_stub():
    srv = stub_server()
    try:
        rep = loadgen.run_closed_loop(srv.addr, concurrency=4,
                                      n_requests=40, seed=1)
        assert rep["sent"] == 40
        assert rep["hard_failures"] == 0
        assert rep["ok"] + rep["shed"] + rep["errors"] == 40
        assert rep["p99_ms"] is not None
    finally:
        srv.stop()


def test_bench_rows_gate_holds_the_line(tmp_path):
    """Loadgen rows land in bench history keyed per offered rate, gate
    with better=min, and a later 50% p99 regression FAILS the gate."""
    from serverless_learn_tpu.telemetry import benchgate

    history = str(tmp_path / "bench_history.json")
    good = [{"offered_rps": 20.0, "p99_ms": 40.0, "p50_ms": 10.0,
             "p95_ms": 30.0, "achieved_rps": 19.5, "shed": 0,
             "hard_failures": 0}]
    rows = loadgen.bench_rows(good, label="fleet", device_kind="fleet-stub")
    assert rows[0]["metric"] == "fleet_loadgen_20rps_p99_ms"
    loadgen.record_rows(rows, history)
    rep = benchgate.run_gate(history, metric="fleet")
    assert rep["ok"], rep  # first entry passes vacuously

    bad = [dict(good[0], p99_ms=65.0)]
    loadgen.record_rows(loadgen.bench_rows(
        bad, label="fleet", device_kind="fleet-stub"), history)
    rep = benchgate.run_gate(history, metric="fleet")
    assert not rep["ok"], rep
    assert rep["regressions"][0]["metric"] == "fleet_loadgen_20rps_p99_ms"


def test_smoke_zero_failures_across_kill_and_restart(tmp_path):
    """The CI smoke: 2-replica fleet, one killed + restarted mid-run,
    zero failed requests; bench rows pass the dry-run gate."""
    from serverless_learn_tpu.telemetry import benchgate

    history = str(tmp_path / "bench_history.json")
    rep = loadgen.run_smoke(seed=11, rate_rps=40.0, duration_s=3.5,
                            history_path=history)
    assert rep["ok"], {k: rep[k] for k in ("client", "router")}
    assert rep["client"]["hard_failures"] == 0
    assert rep["client"]["ok"] == rep["client"]["sent"] > 0
    assert rep["restarted"]
    alerts = {(a.get("alert"), a.get("state")) for a in rep["alerts"]}
    assert ("fleet.replica_dead", "firing") in alerts
    gate = benchgate.run_gate(history, metric=None)
    assert gate["ok"], gate


# -- the acceptance test -----------------------------------------------------


def test_fleet_acceptance_chaos_load_autoscale_gate(tmp_path):
    """ISSUE 7 acceptance: open-loop load with one replica KILLED and one
    STALLED (TcpChaosProxy); zero client-visible hard failures (hedges +
    retries absorb the faults; shedding is typed and only above
    capacity); the autoscaler scales OUT on the queue-wait burn-rate
    alert and drains back IN after calm; the run emits a
    p99-vs-offered-load curve into bench_history.json that
    `slt bench --gate --dry-run` accepts."""
    from serverless_learn_tpu.chaos.shim import TcpChaosProxy
    from serverless_learn_tpu.fleet.autoscaler import (CallbackLauncher,
                                                       FleetAutoscaler)
    from serverless_learn_tpu.telemetry import benchgate
    from serverless_learn_tpu.telemetry.health import HealthEngine

    registry = MetricsRegistry()
    events = []
    # Three modest replicas (~80 ms/request): offered 50 rps needs ~4
    # concurrent slots, capacity is 3 -> genuine overload until the
    # autoscaler adds the fast replica.
    r_a = stub_server(latency_s=0.08)
    r_b = stub_server(latency_s=0.08)
    r_c = stub_server(latency_s=0.08)
    proxy_b = TcpChaosProxy(upstream=r_b.addr).start()
    cfg = FleetConfig(max_inflight=3, queue_timeout_s=0.5,
                      shed_start_frac=0.9, health_interval_s=0.2,
                      dead_after_probes=2, hedge_min_delay_s=0.05,
                      upstream_timeout_s=2.0, eject_consecutive_errors=2,
                      eject_s=0.3, max_retries=2)
    router = FleetRouter(config=cfg, host="127.0.0.1", port=0,
                         replicas=(r_a.addr, proxy_b.addr, r_c.addr),
                         registry=registry, emit=events.append).start()

    hcfg = HealthConfig(sample_interval_s=0.15, slo_short_window_s=1.0,
                        slo_long_window_s=3.0, clear_after_ticks=2,
                        slos=({"name": "router_queue_wait",
                               "kind": "latency",
                               "metric": "slt_router_queue_wait_seconds",
                               "threshold_s": 0.05, "objective": 0.99},))
    engine = HealthEngine(registry=registry, config=hcfg,
                          emit=events.append,
                          dump_on_critical=False).start()

    extra = []      # autoscaler-launched fast replicas

    def scale_out():
        srv = stub_server(latency_s=0.002)
        extra.append(srv)
        router.add_replica(srv.addr, static=True)

    def scale_in():
        if extra:
            srv = extra.pop()
            router.remove_replica(srv.addr, drain=True,
                                  reason="autoscaler scale-in")
            srv.stop()

    launcher = CallbackLauncher(lambda: len(router.replicas()),
                                scale_out, scale_in)
    scaler = FleetAutoscaler(
        launcher, lambda: engine.alerts(firing_only=True),
        min_replicas=3, max_replicas=5, alert_substr="queue_wait",
        scale_out_cooldown_s=2.0, scale_in_cooldown_s=0.5,
        scale_in_calm_s=0.6, interval_s=0.15,
        registry=registry).start()

    def chaos():
        time.sleep(1.0)
        r_c.stop()                    # one replica KILLED
        time.sleep(0.4)
        proxy_b.set_fault("stall")    # one replica STALLED
        time.sleep(1.2)
        proxy_b.set_fault(None)

    chaos_t = threading.Thread(target=chaos, daemon=True)
    chaos_t.start()
    try:
        # Phase 1: overload (50 rps > ~37 rps fleet capacity) + faults.
        p1 = loadgen.run_open_loop(router.addr, 50.0, 4.0, seed=21,
                                   timeout_s=10.0)
        # Phase 2: light load on the scaled-out fleet.
        p2 = loadgen.run_open_loop(router.addr, 10.0, 3.0, seed=22,
                                   timeout_s=10.0)
        # Let the calm window elapse so the scale-in lands.
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline:
            if any(e["direction"] == "in" for e in scaler.events):
                break
            time.sleep(0.1)
    finally:
        chaos_t.join(timeout=5)
        scaler.stop()
        engine.stop()
        router.stop()
        for srv in [r_a, r_b] + extra:
            try:
                srv.stop()
            except Exception:
                pass
        proxy_b.stop()

    # Zero hard failures through a kill + a stall; every rejection is
    # the TYPED overload error (shed), never an untyped upstream error.
    for phase, rep in (("overload", p1), ("calm", p2)):
        assert rep["hard_failures"] == 0, (phase, rep)
        assert rep["errors"] == 0, (phase, rep)
        assert rep["ok"] + rep["shed"] == rep["sent"], (phase, rep)
    assert p1["ok"] > 0
    # Shedding only above capacity: the calm phase sheds nothing.
    assert p2["shed"] == 0, p2
    # The burn-rate alert fired critical and drove a scale-out, then the
    # calm window drove a scale-in (drain) back down.
    fired = [e for e in events if e.get("event") == "alert"
             and e.get("alert") == "slo.router_queue_wait"
             and e.get("severity") == "critical"
             and e.get("state") == "firing"]
    assert fired, "queue-wait burn-rate alert never fired critical"
    directions = [e["direction"] for e in scaler.events]
    assert "out" in directions, scaler.events
    assert "in" in directions, scaler.events
    # The kill was detected and named.
    assert any(e.get("alert") == "fleet.replica_dead"
               and (e.get("labels") or {}).get("replica") == r_c.addr
               for e in events), "killed replica never declared dead"

    # The curve lands in bench history and passes the dry-run gate.
    history = str(tmp_path / "bench_history.json")
    rows = loadgen.record_rows(
        loadgen.bench_rows([p1, p2], label="fleet_accept",
                           device_kind="fleet-stub"), history)
    assert len(rows) == 2 and all(r["value"] > 0 for r in rows)
    gate = benchgate.run_gate(history, metric=None)
    assert gate["ok"], gate
    from serverless_learn_tpu.cli import main

    assert main(["bench", "--gate", "--dry-run", "--history", history,
                 "--all", "--compact"]) == 0
