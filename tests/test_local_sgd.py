"""Local SGD: gossip mixing (the reference's model-sync semantics on ICI)
and DiLoCo-style outer averaging."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.local_sgd import (
    LocalSGDTrainer, replica_divergence)


def _trainer(outer="gossip", inner_steps=2, batch=16, **kw):
    cfg = ExperimentConfig(
        model="mlp_mnist",
        model_overrides=dict(features=(32,), dtype=jnp.float32),
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05,
                                  momentum=0.0),
        train=TrainConfig(batch_size=batch, num_steps=8),
        data=DataConfig())
    return LocalSGDTrainer(cfg, inner_steps=inner_steps, outer=outer, **kw)


def test_replicas_diverge_then_gossip_reconverges(devices):
    """Inner steps on different shards diverge replicas; log2(R) hypercube
    gossip rounds at rate 0.5 restore exact agreement (the global mean)."""
    tr = _trainer(outer="gossip", mix_rate=0.5)
    state = tr.init()
    assert float(replica_divergence(state.params)) < 1e-6

    src = iter(SyntheticSource(tr.bundle.make_batch, tr.config.data, 16,
                               seed=3))
    state, _ = tr.inner_step(state, tr.shard_batch(next(src)))
    div_after_inner = float(replica_divergence(state.params))
    assert div_after_inner > 1e-4  # different data => different replicas

    mean_before = jax.tree_util.tree_map(
        lambda p: np.asarray(p).mean(0), state.params)
    for _ in range(3):  # log2(8) rounds
        state = tr.outer_sync(state)
    assert float(replica_divergence(state.params)) < 1e-6
    # hypercube gossip at 0.5 computes exactly the pre-mix global mean
    for a, b in zip(jax.tree_util.tree_leaves(mean_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_allclose(a, np.asarray(b)[0], rtol=1e-5,
                                   atol=1e-6)


def test_gossip_single_round_is_pairwise_mix(devices):
    """One round mixes each replica halfway toward exactly one partner —
    the reference's delta-apply rule p += 0.5*(peer - p)."""
    tr = _trainer(outer="gossip", mix_rate=0.5)
    state = tr.init()
    src = iter(SyntheticSource(tr.bundle.make_batch, tr.config.data, 16,
                               seed=5))
    state, _ = tr.inner_step(state, tr.shard_batch(next(src)))
    before = np.asarray(
        jax.device_get(state.params["dense_0"]["kernel"]))  # [8, 784, 32]
    state = tr.outer_sync(state)  # round 0: partner = i XOR 1
    after = np.asarray(jax.device_get(state.params["dense_0"]["kernel"]))
    for i in range(8):
        np.testing.assert_allclose(
            after[i], 0.5 * (before[i] + before[i ^ 1]), rtol=1e-5,
            atol=1e-6)


def test_local_sgd_gossip_trains(devices):
    import itertools

    tr = _trainer(outer="gossip")
    batch = tr.bundle.make_batch(np.random.default_rng(0), tr.config.data, 16)
    state, losses = tr.run(itertools.repeat(batch), num_steps=8)
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # fixed batch is memorizable


def test_diloco_average_resyncs_replicas(devices):
    tr = _trainer(outer="average", inner_steps=3)
    src = iter(SyntheticSource(tr.bundle.make_batch, tr.config.data, 16,
                               seed=1))
    state = tr.init()
    for _ in range(3):
        state, _ = tr.inner_step(state, tr.shard_batch(next(src)))
    assert float(replica_divergence(state.params)) > 0.0
    state = tr.outer_sync(state)
    assert float(replica_divergence(state.params)) < 1e-6
    # anchor moved from init toward the replica mean (outer step applied)
    assert float(jax.device_get(state.step)) == 3


def test_inner_step_has_no_collectives(devices):
    """The compiled inner step must be purely replica-local — zero ICI
    traffic between syncs (the analogue of the reference's nodes training
    independently between gossip timers)."""
    tr = _trainer(outer="gossip")
    state = tr.init()
    src = iter(SyntheticSource(tr.bundle.make_batch, tr.config.data, 16,
                               seed=9))
    batch = tr.shard_batch(next(src))
    hlo = tr.inner_step.lower(state, batch).compile().as_text()
    for op in ("all-reduce", "all-gather", "collective-permute",
               "all-to-all", "reduce-scatter"):
        assert op not in hlo, f"inner step contains {op}"


def test_gossip_requires_power_of_two(devices):
    from serverless_learn_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(dp=6), devices=jax.devices()[:6])
    cfg = ExperimentConfig(
        model="mlp_mnist", mesh=MeshConfig(dp=6),
        train=TrainConfig(batch_size=12))
    with pytest.raises(ValueError, match="power-of-two"):
        LocalSGDTrainer(cfg, mesh=mesh, outer="gossip")
    # DiLoCo averaging has no such constraint
    tr = LocalSGDTrainer(cfg, mesh=mesh, outer="average")
    assert tr.R == 6


def test_unknown_outer_mode_rejected(devices):
    cfg = ExperimentConfig(
        model="mlp_mnist", mesh=MeshConfig(dp=8),
        train=TrainConfig(batch_size=16))
    with pytest.raises(ValueError, match="outer"):
        LocalSGDTrainer(cfg, outer="avg")


def _r18_trainer(outer="gossip", inner_steps=2, batch=16, norm="batch",
                 **kw):
    """ResNet-18 (BatchNorm: a `batch_stats` collection) — the stateful
    case round 3 refused outright (verdict #4)."""
    cfg = ExperimentConfig(
        model="resnet18_cifar",
        model_overrides=dict(num_classes=4, dtype=jnp.float32,
                             image_shape=(8, 8, 3), num_filters=32,
                             norm=norm),
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05,
                                  momentum=0.0),
        train=TrainConfig(batch_size=batch, num_steps=8, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig())
    return LocalSGDTrainer(cfg, inner_steps=inner_steps, outer=outer, **kw)


def test_stateful_resnet_gossip_trains_and_stats_gossip(devices):
    """BatchNorm models train under Local SGD: per-replica batch_stats are
    stacked [R, ...], diverge during inner steps (different shards), and
    gossip back toward agreement with the params."""
    import itertools

    tr = _r18_trainer(outer="gossip", mix_rate=0.5)
    state = tr.init()
    stats = state.model_state["batch_stats"]
    assert all(l.shape[0] == tr.R
               for l in jax.tree_util.tree_leaves(stats))

    batch = tr.bundle.make_batch(np.random.default_rng(0), tr.config.data, 16)
    state, losses0 = tr.inner_step(state, tr.shard_batch(batch))
    div = float(replica_divergence(state.model_state["batch_stats"]))
    assert div > 1e-6, "replica stats should diverge on different shards"
    mean_before = jax.tree_util.tree_map(
        lambda p: np.asarray(jax.device_get(p)).mean(0),
        state.model_state["batch_stats"])
    for _ in range(3):  # log2(8) hypercube rounds at rate 0.5 => exact mean
        state = tr.outer_sync(state)
    assert float(replica_divergence(state.model_state["batch_stats"])) < 1e-6
    for a, b in zip(jax.tree_util.tree_leaves(mean_before),
                    jax.tree_util.tree_leaves(state.model_state["batch_stats"])):
        np.testing.assert_allclose(a, np.asarray(jax.device_get(b))[0],
                                   rtol=1e-5, atol=1e-6)

    state, losses = tr.run(itertools.repeat(batch), num_steps=6)
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def _parity_losses(norm, batch, steps=4):
    """(local DiLoCo-degenerate losses, sync losses) on a fixed batch."""
    from serverless_learn_tpu.training.train_step import build_trainer

    tr = _r18_trainer(outer="average", inner_steps=1, outer_lr=1.0,
                      outer_momentum=0.0, batch=batch, norm=norm)
    sync = build_trainer(tr.config)
    b = tr.bundle.make_batch(np.random.default_rng(1), tr.config.data,
                             batch)
    l_state, s_state = tr.init(), sync.init()
    l_losses, s_losses = [], []
    for _ in range(steps):
        l_state, ll = tr.inner_step(l_state, tr.shard_batch(b))
        l_state = tr.outer_sync(l_state)
        l_losses.append(float(jax.device_get(ll.mean())))
        s_state, m = sync.step(s_state, sync.shard_batch(b))
        s_losses.append(float(jax.device_get(m["loss"])))
    return l_losses, s_losses


def test_stateful_diloco_exact_parity_groupnorm(devices):
    """DiLoCo degenerate case (inner_steps=1, outer lr=1, no momentum) is
    param-averaging every step — for plain SGD that EQUALS the synchronous
    trainer's step when normalization statistics are per-sample
    (GroupNorm): the only nonlinearity Local SGD changes is batch-stat
    scope, so with GroupNorm the loss trajectories must agree to float
    tolerance. This isolates the DiLoCo machinery from the BatchNorm
    semantics tested below."""
    l_losses, s_losses = _parity_losses("group", batch=16)
    np.testing.assert_allclose(l_losses, s_losses, rtol=2e-3)


def test_stateful_diloco_batchnorm_tolerance_documented(devices):
    """With BatchNorm the divergence is SEMANTIC, not a bug: each replica
    normalizes its own sub-batch where sync training psums statistics
    globally, so gradients genuinely differ. Measured on this fixture
    (8 replicas x 16 samples each, 4 steps, fixed batch): local losses
    track sync within ~35% per step and both decrease monotonically —
    THAT is the documented tolerance users opt into when running
    BatchNorm models under Local SGD (per-replica batch must be a sane
    BN batch; at 2 samples/replica the stats are noise and the gap is
    ~4x). Reference analogue: each gossiping worker trained on its own
    stream with no shared statistics at all (src/worker.cc:221-231)."""
    l_losses, s_losses = _parity_losses("batch", batch=128)
    assert l_losses[-1] < l_losses[0] and s_losses[-1] < s_losses[0]
    for l, s in zip(l_losses, s_losses):
        assert abs(l - s) <= 0.35 * max(abs(s), 1e-3) + 0.05, (
            l_losses, s_losses)


def test_run_local_sgd_integrated_with_checkpoint(tmp_path, devices):
    """Round-1 verdict: Local SGD was 'not reachable from the CLI ... a
    demonstration, not an integrated capability'. run_local_sgd is the
    integration: config-selected, data-plane-sourced, checkpointed, and
    resumable mid-run with the gossip round schedule restored."""
    import jax

    from serverless_learn_tpu.config import LocalSGDConfig
    from serverless_learn_tpu.training.checkpoint import (
        Checkpointer, LocalStore)
    from serverless_learn_tpu.training.local_sgd import run_local_sgd

    def cfg_for(steps):
        return ExperimentConfig(
            model="mlp_mnist",
            mesh=MeshConfig(dp=8),
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
            train=TrainConfig(batch_size=64, num_steps=steps,
                              checkpoint_every=4, dtype="float32",
                              param_dtype="float32"),
            data=DataConfig(learnable=True),
            local_sgd=LocalSGDConfig(outer="gossip", inner_steps=4),
        )

    store = LocalStore(str(tmp_path))
    ckpt = Checkpointer(store, async_save=False)
    state, meter = run_local_sgd(cfg_for(8), checkpointer=ckpt)
    assert int(jax.device_get(state.step)) == 8
    assert ckpt.latest_step() == 8

    # resume continues from the checkpoint, not from scratch
    ckpt2 = Checkpointer(store, async_save=False)
    state2, _ = run_local_sgd(cfg_for(12), checkpointer=ckpt2)
    assert int(jax.device_get(state2.step)) == 12
    # the resumed run must have restored the trained replicas (a fresh init
    # at the same seed would make the final params equal a 12-step cold run
    # only if restore worked; cheap sanity: loss is finite, params differ
    # from a fresh init)
    fresh = run_local_sgd(cfg_for(0), checkpointer=None)[0]
    diff = max(float(jnp.abs(a - b).max()) for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state2.params)),
        jax.tree_util.tree_leaves(jax.device_get(fresh.params))))
    assert diff > 1e-4


def test_local_sgd_config_selected_from_dict():
    from serverless_learn_tpu.config import ExperimentConfig as EC

    cfg = EC.from_dict({"local_sgd": {"outer": "average", "inner_steps": 16,
                                      "outer_lr": 0.5}})
    assert cfg.local_sgd.outer == "average"
    assert cfg.local_sgd.inner_steps == 16
    assert cfg.local_sgd.outer_lr == 0.5
    assert EC.from_dict({}).local_sgd.outer == ""


# -- round 3: sharded replicas (fsdp/tp within each dp replica) --------------


def _sharded_run(mesh_cfg, n_devices, outer, steps=6):
    """Train llama-free mlp local SGD on the given mesh; return losses and
    the final (host) params."""
    cfg = ExperimentConfig(
        model="mlp_mnist",
        model_overrides=dict(features=(32,), dtype=jnp.float32),
        mesh=mesh_cfg,
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05,
                                  momentum=0.0),
        train=TrainConfig(batch_size=16, num_steps=steps),
        data=DataConfig())
    from serverless_learn_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(mesh_cfg, devices=jax.devices()[:n_devices])
    tr = LocalSGDTrainer(cfg, mesh=mesh, inner_steps=2, outer=outer,
                         mix_rate=0.5)
    state = tr.init()
    src = iter(SyntheticSource(tr.bundle.make_batch, cfg.data, 16, seed=21))
    losses = []
    for t in range(steps):
        state, step_losses = tr.inner_step(state, tr.shard_batch(next(src)))
        losses.append(float(jax.device_get(step_losses.mean())))
        if (t + 1) % 2 == 0:
            state = tr.outer_sync(state)
    return losses, jax.device_get(state.params)


@pytest.mark.parametrize("outer", ["gossip", "average"])
@pytest.mark.parametrize("axis", ["fsdp", "tp"])
def test_sharded_replicas_match_single_chip(devices, outer, axis):
    """R=2 replicas each sharded over fsdp=2 (or tp=2) compute the SAME
    function as R=2 single-chip replicas — the sharding changes the
    collectives (scoped within each dp slice), not the math. r2 capped
    replicas at one chip; this is the lift.

    Un-xfailed in round 17: the numerics parity harness bisected the
    "fsdp drift" to step 0 — the losses differed before any training
    because the jitted random INIT with fsdp-sharded out_shardings drew
    different threefry bits per shard (jax_threefry_partitionable=False
    lowers the counters shard-locally under SPMD). With the two-stage
    sharding-invariant init in LocalSGDTrainer the runs agree to ~1e-7
    rel, far inside the 2e-5 tolerance — the training math never
    drifted at all."""
    base_losses, base_params = _sharded_run(MeshConfig(dp=2), 2, outer)
    mesh_kw = {"dp": 2, axis: 2}
    sh_losses, sh_params = _sharded_run(MeshConfig(**mesh_kw), 4, outer)
    np.testing.assert_allclose(base_losses, sh_losses, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(base_params),
                    jax.tree_util.tree_leaves(sh_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_sharded_replica_state_shardings(devices):
    """Stacked leaves carry the rule-table shardings on their inner dims:
    replica axis dp, kernels fsdp/tp-sharded within each replica."""
    from jax.sharding import PartitionSpec as P

    cfg = ExperimentConfig(
        model="mlp_mnist",
        model_overrides=dict(features=(32,), dtype=jnp.float32),
        mesh=MeshConfig(dp=2, fsdp=2, tp=2),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
        train=TrainConfig(batch_size=16),
        data=DataConfig())
    tr = LocalSGDTrainer(cfg, outer="average")
    flat = jax.tree_util.tree_flatten_with_path(
        tr.state_shardings.params)[0]
    kernel_specs = {jax.tree_util.keystr(p): s.spec for p, s in flat
                    if "kernel" in jax.tree_util.keystr(p)}
    assert kernel_specs, "no kernels found"
    for path, spec in kernel_specs.items():
        assert spec == P("dp", "fsdp", "tp"), (path, spec)
