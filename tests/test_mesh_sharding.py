"""Mesh construction and sharding-rule resolution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from serverless_learn_tpu.config import MeshConfig
from serverless_learn_tpu.parallel.mesh import batch_sharding, local_batch_size, make_mesh
from serverless_learn_tpu.parallel.sharding import (
    DEFAULT_RULES, ShardingRules, shardings_for_tree, specs_for_tree)


def test_mesh_shapes(devices):
    mesh = make_mesh(MeshConfig(dp=8))
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "fsdp": 1, "ep": 1, "tp": 2, "sp": 2,
                          "pp": 1}


def test_mesh_size_mismatch(devices):
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(dp=3))


def test_batch_sharding_splits_batch(devices):
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    x = np.zeros((16, 8), np.float32)
    arr = jax.device_put(x, batch_sharding(mesh))
    # each addressable shard holds 16/4 = 4 rows
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(4, 8)}
    assert local_batch_size(16, mesh) == 4


def test_rule_pruning_drops_absent_axes(devices):
    mesh = make_mesh(MeshConfig(dp=8))  # tp axis size 1
    tree = {"attn": {"q_proj": {"kernel": jnp.zeros((16, 4, 8))}}}
    specs = specs_for_tree(tree, mesh)
    # fsdp and tp are both size-1 => everything replicated
    assert specs["attn"]["q_proj"]["kernel"] == P()


def test_tp_rules_shard_heads(devices):
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    tree = {
        "q_proj": {"kernel": jnp.zeros((16, 8, 4))},
        "o_proj": {"kernel": jnp.zeros((8, 4, 16))},
        "gate_proj": {"kernel": jnp.zeros((16, 64))},
        "down_proj": {"kernel": jnp.zeros((64, 16))},
        "norm": {"scale": jnp.zeros((16,))},
    }
    specs = specs_for_tree(tree, mesh)
    assert specs["q_proj"]["kernel"] == P(None, "tp")
    assert specs["o_proj"]["kernel"] == P("tp")
    assert specs["gate_proj"]["kernel"] == P(None, "tp")
    assert specs["down_proj"]["kernel"] == P("tp")
    assert specs["norm"]["scale"] == P()


def test_fsdp_rules_shard_dim0(devices):
    mesh = make_mesh(MeshConfig(fsdp=8))
    tree = {"mlp": {"wi": {"kernel": jnp.zeros((32, 64))}}}
    shardings = shardings_for_tree(tree, mesh)
    s = shardings["mlp"]["wi"]["kernel"]
    assert isinstance(s, NamedSharding) and s.spec == P("fsdp")


def test_sharded_placement_distributes_bytes(devices):
    mesh = make_mesh(MeshConfig(fsdp=8))
    w = np.ones((64, 16), np.float32)
    tree = {"wi": {"kernel": w}}
    shardings = shardings_for_tree(tree, mesh)
    arr = jax.device_put(w, shardings["wi"]["kernel"])
    assert {s.data.shape for s in arr.addressable_shards} == {(8, 16)}


# -- ZeRO dp-axis composition (round 18) --------------------------------------


def test_compose_axis_into_empty_and_composed_specs(devices):
    from serverless_learn_tpu.parallel.sharding import compose_axis

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    # empty spec: dp lands on dim 0
    assert compose_axis(P(), (16, 8), mesh, "dp") == P("dp")
    # composed MAJOR to an existing fsdp entry when dim0 divides dp*fsdp
    assert compose_axis(P("fsdp", "tp"), (16, 8), mesh, "dp") == \
        P(("dp", "fsdp"), "tp")
    # dim0 full (16 % (2*2) == 0 but pretend it's 6): falls to dim 1
    assert compose_axis(P("fsdp", None), (6, 8), mesh, "dp") == \
        P("fsdp", "dp")
    # nothing divides: base spec unchanged (replicated is always correct)
    assert compose_axis(P(), (5, 3), mesh, "dp") == P()
    # scalar: unchanged
    assert compose_axis(P(), (), mesh, "dp") == P()
    # already carries the axis: unchanged
    assert compose_axis(P("dp"), (16,), mesh, "dp") == P("dp")
    # inert on a dp=1 mesh
    mesh1 = make_mesh(MeshConfig(fsdp=8))
    assert compose_axis(P("fsdp"), (16, 8), mesh1, "dp") == P("fsdp")


def test_zero_specs_shard_opt_leaves_but_not_indivisible(devices):
    """Opt-state-like trees: param-shaped leaves gain dp; factored /
    placeholder / indivisible leaves keep their (divisible-only) base."""
    from serverless_learn_tpu.training.zero import zero_specs_for_tree

    mesh = make_mesh(MeshConfig(dp=8))
    tree = {
        "dense_0": {"kernel": jnp.zeros((784, 64)), "bias": jnp.zeros((64,))},
        "head": {"kernel": jnp.zeros((64, 10)), "bias": jnp.zeros((10,))},
        "count": jnp.zeros((), jnp.int32),
        "v_placeholder": jnp.zeros((1,)),
    }
    specs = zero_specs_for_tree(tree, mesh)
    assert specs["dense_0"]["kernel"] == P("dp")
    assert specs["dense_0"]["bias"] == P("dp")
    # (64, 10): dim0 divides 8 even though dim1 (10) does not
    assert specs["head"]["kernel"] == P("dp")
    # nothing divides: replicated
    assert specs["head"]["bias"] == P()
    assert specs["count"] == P()
    assert specs["v_placeholder"] == P()
