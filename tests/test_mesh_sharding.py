"""Mesh construction and sharding-rule resolution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from serverless_learn_tpu.config import MeshConfig
from serverless_learn_tpu.parallel.mesh import batch_sharding, local_batch_size, make_mesh
from serverless_learn_tpu.parallel.sharding import (
    DEFAULT_RULES, ShardingRules, shardings_for_tree, specs_for_tree)


def test_mesh_shapes(devices):
    mesh = make_mesh(MeshConfig(dp=8))
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "fsdp": 1, "ep": 1, "tp": 2, "sp": 2,
                          "pp": 1}


def test_mesh_size_mismatch(devices):
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(dp=3))


def test_batch_sharding_splits_batch(devices):
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    x = np.zeros((16, 8), np.float32)
    arr = jax.device_put(x, batch_sharding(mesh))
    # each addressable shard holds 16/4 = 4 rows
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(4, 8)}
    assert local_batch_size(16, mesh) == 4


def test_rule_pruning_drops_absent_axes(devices):
    mesh = make_mesh(MeshConfig(dp=8))  # tp axis size 1
    tree = {"attn": {"q_proj": {"kernel": jnp.zeros((16, 4, 8))}}}
    specs = specs_for_tree(tree, mesh)
    # fsdp and tp are both size-1 => everything replicated
    assert specs["attn"]["q_proj"]["kernel"] == P()


def test_tp_rules_shard_heads(devices):
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    tree = {
        "q_proj": {"kernel": jnp.zeros((16, 8, 4))},
        "o_proj": {"kernel": jnp.zeros((8, 4, 16))},
        "gate_proj": {"kernel": jnp.zeros((16, 64))},
        "down_proj": {"kernel": jnp.zeros((64, 16))},
        "norm": {"scale": jnp.zeros((16,))},
    }
    specs = specs_for_tree(tree, mesh)
    assert specs["q_proj"]["kernel"] == P(None, "tp")
    assert specs["o_proj"]["kernel"] == P("tp")
    assert specs["gate_proj"]["kernel"] == P(None, "tp")
    assert specs["down_proj"]["kernel"] == P("tp")
    assert specs["norm"]["scale"] == P()


def test_fsdp_rules_shard_dim0(devices):
    mesh = make_mesh(MeshConfig(fsdp=8))
    tree = {"mlp": {"wi": {"kernel": jnp.zeros((32, 64))}}}
    shardings = shardings_for_tree(tree, mesh)
    s = shardings["mlp"]["wi"]["kernel"]
    assert isinstance(s, NamedSharding) and s.spec == P("fsdp")


def test_sharded_placement_distributes_bytes(devices):
    mesh = make_mesh(MeshConfig(fsdp=8))
    w = np.ones((64, 16), np.float32)
    tree = {"wi": {"kernel": w}}
    shardings = shardings_for_tree(tree, mesh)
    arr = jax.device_put(w, shardings["wi"]["kernel"])
    assert {s.data.shape for s in arr.addressable_shards} == {(8, 16)}
