"""Mixture-of-experts: routing correctness, dense equivalence, and
expert-parallel (ep axis) training equivalence vs pure DP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.models.transformer import TransformerConfig
from serverless_learn_tpu.ops.moe import MoELayer, top_k_routing
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.parallel.sharding import specs_for_tree
from jax.sharding import PartitionSpec as P


def test_top_k_routing_shapes_and_mass():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (2, 16, 4))  # 2 groups of 16 tokens
    dispatch, combine, aux = top_k_routing(logits, n_experts=4, top_k=2,
                                           capacity=16)
    assert dispatch.shape == (2, 16, 4, 16) and combine.shape == (2, 16, 4, 16)
    # ample capacity => every token lands exactly top_k slots
    np.testing.assert_allclose(np.asarray(dispatch.sum((2, 3))), 2.0)
    # combine weights renormalized over the chosen experts => sum to 1
    np.testing.assert_allclose(np.asarray(combine.sum((2, 3))), 1.0,
                               rtol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # uniform routing minimizes at 1


def test_capacity_drops_overflow_tokens():
    # All tokens prefer expert 0; capacity 2 keeps only the first 2 PER GROUP.
    logits = jnp.tile(jnp.array([[[10.0, 0.0, 0.0, 0.0]]]), (2, 8, 1))
    dispatch, _, _ = top_k_routing(logits, n_experts=4, top_k=1, capacity=2)
    per_expert = np.asarray(dispatch.sum((0, 1, 3)))
    assert per_expert[0] == 4.0  # 2 groups x capacity 2; rest dropped


def test_routing_is_group_local():
    """A hot group cannot steal capacity from another group's experts."""
    g0 = jnp.tile(jnp.array([[10.0, 0.0]]), (6, 1))  # all want expert 0
    g1 = jnp.stack([jnp.array([10.0, 0.0]),
                    *([jnp.array([0.0, 10.0])] * 5)])  # one wants expert 0
    logits = jnp.stack([g0, g1])  # [2, 6, 2]
    dispatch, _, _ = top_k_routing(logits, n_experts=2, top_k=1, capacity=3)
    kept_e0 = np.asarray(dispatch.sum((1, 3)))[:, 0]
    assert kept_e0[0] == 3.0  # group 0 saturates its own capacity
    assert kept_e0[1] == 1.0  # group 1's lone expert-0 token unaffected


def test_moe_layer_matches_manual_dense_top1():
    """top-1 routing with ample capacity == applying each token's argmax
    expert FFN directly."""
    cfg = TransformerConfig(d_model=16, d_ff=32, n_experts=4, moe_top_k=1,
                            moe_capacity_factor=8.0, dtype=jnp.float32,
                            param_dtype=jnp.float32)
    layer = MoELayer(cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    params = layer.init(jax.random.PRNGKey(2), x)["params"]
    y, _ = layer.apply({"params": params}, x, mutable=["losses"])

    xf = np.asarray(x).reshape(-1, 16)
    router = np.asarray(params["router"])
    choice = (xf @ router).argmax(-1)
    wg, wu, wd = (np.asarray(params["expert_gate"]),
                  np.asarray(params["expert_up"]),
                  np.asarray(params["expert_down"]))
    silu = lambda a: a / (1.0 + np.exp(-a))
    expect = np.stack([
        (silu(t @ wg[e]) * (t @ wu[e])) @ wd[e]
        for t, e in zip(xf, choice)])
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16), expect,
                               rtol=2e-4, atol=2e-4)


def test_expert_sharding_rules(devices):
    mesh = make_mesh(MeshConfig(dp=2, ep=2, tp=2))
    tree = {"layer_0": {"moe": {
        "expert_gate": jnp.zeros((4, 16, 32)),
        "expert_down": jnp.zeros((4, 32, 16)),
        "router": jnp.zeros((16, 4)),
    }}}
    specs = specs_for_tree(tree, mesh)["layer_0"]["moe"]
    assert specs["expert_gate"] == P("ep", None, "tp")
    assert specs["expert_down"] == P("ep", "tp")
    assert specs["router"] == P()


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(dp=2, ep=4),
    MeshConfig(dp=2, ep=2, tp=2),
])
def test_moe_trains_ep_matches_dp(devices, mesh_cfg):
    """Expert-parallel training produces the same losses as pure DP — the
    sharding changes the collectives, not the math."""
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    def run(mcfg):
        cfg = ExperimentConfig(
            model="moe_tiny",
            model_overrides=dict(dtype=jnp.float32),
            mesh=mcfg,
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
            train=TrainConfig(batch_size=8),
            data=DataConfig(seq_len=32))
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=11)
        losses = []
        for batch, _ in zip(iter(src), range(3)):
            state, m = trainer.step(state, trainer.shard_batch(batch))
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        return losses

    np.testing.assert_allclose(run(MeshConfig(dp=8)), run(mesh_cfg),
                               rtol=2e-4)


def test_pipeline_plus_moe_initializes(devices):
    """pipeline stages thread the sown aux loss (round 2); init must work,
    with the router loss sown into its own collection — never mixed into the
    param tree (the TrainState builder strips "losses"; see
    test_moe_init_state_has_no_losses_collection). Full dp-parity is covered
    by tests/test_pipeline.py::test_moe_pipeline_matches_dp."""
    from serverless_learn_tpu.models.registry import get_model

    bundle = get_model("moe_tiny", pipeline=True)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = bundle.module.init(jax.random.PRNGKey(0), tokens)
    assert set(variables) == {"params", "losses"}
    param_paths = [str(p) for p, _ in
                   jax.tree_util.tree_leaves_with_path(variables["params"])]
    assert not any("moe_aux" in p for p in param_paths)


def test_moe_group_size_bounds_capacity_without_changing_math():
    """With ample capacity, subgroup routing (moe_group_size < T) gives the
    same layer OUTPUT as whole-row routing — groups only bound slot
    competition for the forward compute. (The aux load-balance loss is a
    mean of per-group terms and so DOES depend on the grouping; that is
    documented at TransformerConfig.moe_group_size.)"""
    mk = lambda gs: TransformerConfig(
        d_model=16, d_ff=32, n_experts=4, moe_top_k=2,
        moe_capacity_factor=8.0, moe_group_size=gs,
        dtype=jnp.float32, param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16))
    params = MoELayer(mk(0)).init(jax.random.PRNGKey(4), x)["params"]
    y_row, _ = MoELayer(mk(0)).apply({"params": params}, x,
                                     mutable=["losses"])
    y_grp, _ = MoELayer(mk(4)).apply({"params": params}, x,
                                     mutable=["losses"])
    np.testing.assert_allclose(np.asarray(y_row), np.asarray(y_grp),
                               rtol=1e-5, atol=1e-5)


def test_n_experts_override_keeps_aux_loss(devices):
    """Enabling MoE on a dense family via model_overrides must not silently
    drop the router load-balance loss (all bundles use apply_with_losses)."""
    from serverless_learn_tpu.models.registry import get_model

    bundle = get_model("llama_tiny", n_experts=4, dtype=jnp.float32)
    batch = bundle.make_batch(np.random.default_rng(0),
                              DataConfig(seq_len=16), 4)
    params = bundle.module.init(jax.random.PRNGKey(0), batch["tokens"])["params"]
    loss, _ = bundle.loss_fn(params, batch)
    from serverless_learn_tpu.ops.losses import causal_lm_loss
    from serverless_learn_tpu.ops.moe import apply_with_losses

    logits, aux = apply_with_losses(bundle.module, params, batch["tokens"])
    lm_only, _ = causal_lm_loss(logits, batch["tokens"])
    assert float(aux) > 0.0
    np.testing.assert_allclose(float(loss), float(lm_only) + float(aux),
                               rtol=1e-6)


def test_moe_init_state_has_no_losses_collection(devices):
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig(
        model="moe_tiny", mesh=MeshConfig(dp=8),
        train=TrainConfig(batch_size=8), data=DataConfig(seq_len=16))
    trainer = build_trainer(cfg)
    state = trainer.init()
    assert "losses" not in state.model_state


def test_moe_aux_loss_reported(devices):
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig(
        model="moe_tiny", mesh=MeshConfig(dp=8),
        train=TrainConfig(batch_size=8), data=DataConfig(seq_len=16))
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=0)
    _, m = trainer.step(state, trainer.shard_batch(next(iter(src))))
    aux = float(jax.device_get(m["moe_aux_loss"]))
    assert np.isfinite(aux) and aux > 0.0
