"""MoE generation end-to-end (round-5 verdict #3): a framework whose
flagship family includes Mixtral-scale MoE must demonstrably SERVE one.

Decode-time routing semantics (the decision the verdict asked for):
inference routes PER TOKEN (``Block`` forces ``moe_group_size=1`` under
``decode``/``prefill``, ``models/transformer.py``). Grouped capacity is a
training-efficiency construct; at inference it would make a token's
routing depend on the other tokens in its group — under prefill that
includes FUTURE positions, so the cached incremental decode could never
match a full forward. Per-token groups give every token its full top-k
experts (capacity clamps to >= 1 slot, choices are distinct experts —
no drops by construction), which is also how Mixtral-class MoEs are
served in practice.

Goldens therefore compare against a full forward of a ``moe_group_size=1``
twin (same params — group size shapes no parameters).
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.generate import generate, init_cache
from serverless_learn_tpu.models.registry import get_model

MOE_KW = dict(n_experts=4, moe_top_k=2, moe_capacity_factor=1.0,
              dtype=jnp.float32, param_dtype=jnp.float32, max_seq_len=64)


@pytest.fixture(scope="module")
def moe(devices):
    bundle = get_model("llama_tiny", **MOE_KW)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def _per_token_twin(module):
    """Same params, routing groups of 1 — the full-forward golden that
    matches inference routing semantics."""
    return type(module)(dataclasses.replace(module.cfg, moe_group_size=1))


def test_moe_decode_matches_full_forward(moe):
    """Incremental cached decode == full forward, position for position —
    the golden equivalence, through expert routing."""
    module, params = moe
    twin = _per_token_twin(module)
    B, T = 2, 10
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, 512)
    full = twin.apply({"params": params}, tokens)  # [B, T, V]

    cache = init_cache(module, B)
    step_logits = []
    for t in range(T):
        logits, updated = module.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            decode=True, mutable=["cache"])
        cache = updated["cache"]
        step_logits.append(logits[:, 0])
    inc = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_moe_training_groups_would_diverge(moe):
    """Documents WHY inference forces per-token groups: the same params
    under training-grouped routing (tight capacity, whole-row groups)
    produce different logits than the per-token twin — tokens drop."""
    module, params = moe
    twin = _per_token_twin(module)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 512)
    grouped = module.apply({"params": params}, tokens)
    per_token = twin.apply({"params": params}, tokens)
    assert not np.allclose(np.asarray(grouped), np.asarray(per_token),
                           rtol=2e-4, atol=2e-4), \
        "tight-capacity grouped routing unexpectedly matched per-token " \
        "routing; the inference override would be untestable"


def test_moe_greedy_generation_matches_full_forward_argmax(moe):
    module, params = moe
    twin = _per_token_twin(module)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, 512)
    out = generate(module, params, prompt, max_new_tokens=6)
    assert out.shape == (1, 11)
    seq = prompt
    for _ in range(6):
        logits = twin.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_moe_batched_padded_prompts_match_solo(moe):
    """The serving primitive: right-padded unequal prompts, per-sequence
    cache indices, through expert routing."""
    module, params = moe

    def solo(prompt, n):
        toks = generate(module, params, jnp.asarray([prompt], jnp.int32), n)
        return [int(t) for t in jax.device_get(toks)[0][len(prompt):]]

    prompts = [[5, 9, 11], [7, 3, 2, 8, 1, 30, 12], [4]]
    P = max(len(p) for p in prompts)
    padded = np.zeros((3, P), np.int32)
    lens = np.zeros(3, np.int32)
    for i, p in enumerate(prompts):
        padded[i, :len(p)] = p
        lens[i] = len(p)
    toks = generate(module, params, jnp.asarray(padded), 6,
                    prompt_lengths=jnp.asarray(lens))
    new = np.asarray(jax.device_get(toks))[:, P:]
    for i, p in enumerate(prompts):
        assert new[i].tolist() == solo(p, 6), f"row {i}"


def test_moe_sampled_generation_runs(moe):
    module, params = moe
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, 512)
    out = generate(module, params, prompt, max_new_tokens=5,
                   temperature=0.8, top_k=16, rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 9)
    V = module.cfg.vocab_size
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < V).all()


def test_moe_through_continuous_engine(moe):
    """Mixtral-tiny through the round-5 slot scheduler: concurrent
    greedy requests, byte-identical to solo."""
    from serverless_learn_tpu.inference.continuous import (
        ContinuousBatchingEngine)

    module, params = moe
    eng = ContinuousBatchingEngine(module, params, max_slots=4,
                                   chunk_size=4)
    try:
        prompts = [[5, 9, 11], [7, 3, 2, 8], [4, 4]]
        results = [None] * 3

        def client(i):
            results[i] = eng.submit(prompts[i], 5, temperature=0.0,
                                    top_k=0, eos_id=None, seed=0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert "error" not in results[i], results[i]
            want = generate(module, params, jnp.asarray([p], jnp.int32), 5)
            assert results[i]["new_tokens"] == [
                int(t) for t in jax.device_get(want)[0][len(p):]]
    finally:
        eng.stop()


def test_moe_serves_over_the_wire(moe):
    """End to end: a MoE model behind the TCP server."""
    from serverless_learn_tpu.inference.server import (
        GenerationServer, request)

    module, params = moe
    srv = GenerationServer(module, params, engine="continuous",
                           chunk_size=4).start()
    try:
        rep = request(srv.addr, {"prompt": [5, 9, 11],
                                 "max_new_tokens": 4})
        want = generate(module, params,
                        jnp.asarray([[5, 9, 11]], jnp.int32), 4)
        assert rep.get("new_tokens") == [
            int(t) for t in jax.device_get(want)[0][3:]]
    finally:
        srv.stop()
