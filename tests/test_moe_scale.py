"""Mixtral-8x7B-scale validation on abstract shapes (companion of
tests/test_llama8b_scale.py for the MoE flagship).

The ep axis is where MoE differs from the dense 8B: expert tensors carry a
leading [E, ...] dim the rule table maps to ``ep``, so the per-device state
and checkpoint chunks divide by the EXPERT count as well. Nothing here
materializes a tensor.
"""

import math

import jax
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.training.train_step import build_trainer

GIB = 1 << 30


@pytest.fixture(scope="module")
def trainer_mixtral(devices):
    """Full 32-layer Mixtral-8x7B-shaped trainer on an ep=4,tp=2 mesh —
    abstract construction only."""
    cfg = ExperimentConfig(
        model="moe_mixtral_8x7b",
        model_overrides=dict(remat=True),
        mesh=MeshConfig(ep=4, tp=2),
        optimizer=OptimizerConfig(name="adafactor", learning_rate=1e-4),
        train=TrainConfig(batch_size=8),
        data=DataConfig(seq_len=4096),
    )
    mesh = make_mesh(cfg.mesh, devices=devices)
    return build_trainer(cfg, mesh=mesh)


def test_mixtral_param_census(trainer_mixtral):
    abstract = trainer_mixtral.abstract_state()
    n_params = sum(math.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(abstract.params))
    # Mixtral-8x7B: ~46.7B total (32 layers x 8 experts x 3 x 4096 x 14336
    # expert matrices dominate).
    assert 4.4e10 < n_params < 4.9e10, n_params


def test_mixtral_expert_tensors_sharded_over_ep_and_tp(trainer_mixtral):
    abstract = trainer_mixtral.abstract_state()
    sh = trainer_mixtral.state_shardings
    seen_expert = 0
    for (path, leaf), s in zip(
            jax.tree_util.tree_flatten_with_path(abstract.params)[0],
            jax.tree_util.tree_leaves(
                sh.params, is_leaf=lambda x: hasattr(x, "spec"))):
        key = jax.tree_util.keystr(path)
        if "expert_" in key:
            seen_expert += 1
            spec = tuple(s.spec)
            assert "ep" in spec, (key, spec)
            assert "tp" in spec, (key, spec)
    assert seen_expert == 3 * 32  # gate/up/down x layers


def test_mixtral_per_device_state_fits_hbm(trainer_mixtral):
    """f32 params sharded over ep=4 x tp=2: ~46.7B x 4B / 8 ~= 23 GiB of
    raw params per device — which does NOT fit a 16 GiB v5e, and the test
    documents the honest envelope: adafactor (factored second moment, no
    first moment) keeps optimizer state sub-linear, and the config needs
    bf16 params or ep=8 for v5e-class chips; a 32 GiB v4 holds it in f32.
    The assertion is the v4 budget."""
    abstract = trainer_mixtral.abstract_state()
    per_device = 0
    for leaf, s in zip(
            jax.tree_util.tree_leaves(abstract),
            jax.tree_util.tree_leaves(
                trainer_mixtral.state_shardings,
                is_leaf=lambda x: hasattr(x, "spec"))):
        n = 1
        for entry in s.spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                n *= s.mesh.shape[ax]
        per_device += math.prod(leaf.shape) * leaf.dtype.itemsize // n
    assert per_device < 30 * GIB, per_device / GIB


def test_mixtral_pipelined_mesh_shards_experts(devices):
    """Round-3 verdict #3: Mixtral-shaped sharding on a mesh the pipeline
    can USE — pp=2 x ep=2 x tp=2. Abstract construction of the full
    32-layer pipelined model: the stacked expert leaves must shard over
    pp (layers), ep (experts) AND tp (d_ff), and the per-device parameter
    bytes must divide by all three axes."""
    cfg = ExperimentConfig(
        model="moe_mixtral_8x7b",
        model_overrides=dict(remat=True, pipeline=True,
                             pipeline_microbatches=4),
        mesh=MeshConfig(pp=2, ep=2, tp=2),
        optimizer=OptimizerConfig(name="adafactor", learning_rate=1e-4),
        train=TrainConfig(batch_size=8),
        data=DataConfig(seq_len=4096),
    )
    mesh = make_mesh(cfg.mesh, devices=devices)
    trainer = build_trainer(cfg, mesh=mesh)
    abstract = trainer.abstract_state()
    n_params = sum(math.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(abstract.params))
    assert 4.4e10 < n_params < 4.9e10, n_params
    seen = 0
    for (path, leaf), s in zip(
            jax.tree_util.tree_flatten_with_path(abstract.params)[0],
            jax.tree_util.tree_leaves(
                trainer.state_shardings.params,
                is_leaf=lambda x: hasattr(x, "spec"))):
        key = jax.tree_util.keystr(path)
        if "expert_" in key:
            seen += 1
            spec = tuple(s.spec)
            flat = [a for e in spec if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))]
            assert {"pp", "ep", "tp"} <= set(flat), (key, spec)
            # 8-way sharded: a 4.6 GiB stacked expert leaf holds 1/8 per
            # device.
    assert seen == 3  # stacked gate/up/down (leading [L, E, ...] dims)


def test_mixtral_checkpoint_chunks_balanced(trainer_mixtral):
    """Every expert tensor must contribute ep x tp chunks whose volumes
    partition the leaf — the sharded-checkpoint math at 46B scale."""
    from serverless_learn_tpu.training.checkpoint import _norm_index

    abstract = trainer_mixtral.abstract_state()
    per_device: dict = {}
    for leaf, s in zip(
            jax.tree_util.tree_leaves(abstract),
            jax.tree_util.tree_leaves(
                trainer_mixtral.state_shardings,
                is_leaf=lambda x: hasattr(x, "spec"))):
        shape = tuple(leaf.shape)
        seen = set()
        vol = 0
        for dev, index in s.devices_indices_map(shape).items():
            box = _norm_index(index, shape)
            if box in seen:
                continue
            seen.add(box)
            v = math.prod(b - a for a, b in box) if box else 1
            vol += v
            per_device[dev.id] = per_device.get(dev.id, 0) \
                + v * leaf.dtype.itemsize
        assert vol == (math.prod(shape) if shape else 1)
    sizes = list(per_device.values())
    assert max(sizes) <= 2 * (sum(sizes) / len(sizes)), sizes
