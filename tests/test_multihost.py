"""Multi-host bootstrap: elastic membership → JAX process group.

The reference's birth registration (src/worker.cc:117-129) only populated a
list; here the same contract assigns SPMD ranks and forms the
jax.distributed world (serverless_learn_tpu/parallel/multihost.py).
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from serverless_learn_tpu.control.daemons import start_coordinator
from serverless_learn_tpu.parallel.multihost import (
    bootstrap_via_coordinator, free_port)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def coordinator_addr():
    port = free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=5000, sweep_ms=100)
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


def test_rank_assignment_three_hosts(coordinator_addr):
    """Three concurrent bootstraps agree on distinct ranks 0..2 and on
    rank 0's endpoint as the JAX coordinator (fake initialize)."""
    results = {}
    errors = []
    lock = threading.Lock()

    def host(i):
        calls = []

        def fake_init(addr, n, rank):
            calls.append((addr, n, rank))

        try:
            w = bootstrap_via_coordinator(
                coordinator_addr, world_size=3, name=f"h{i}",
                timeout_s=30, _initialize=fake_init)
            with lock:
                results[i] = (w, calls)
        except Exception as e:  # pragma: no cover
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=host, args=(i,)) for i in range(3)]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    assert not errors
    assert len(results) == 3
    worlds = [w for w, _ in results.values()]
    try:
        ranks = sorted(w.rank for w in worlds)
        assert ranks == [0, 1, 2]
        assert len({w.jax_coordinator for w in worlds}) == 1, \
            "all hosts must agree on the JAX coordination endpoint"
        rank0 = next(w for w in worlds if w.rank == 0)
        assert rank0.jax_coordinator == rank0.agent.advertise_addr
        for _, calls in results.values():
            assert calls and calls[0][1] == 3
    finally:
        for w in worlds:
            w.shutdown()


def test_world_formation_timeout(coordinator_addr):
    with pytest.raises(TimeoutError):
        bootstrap_via_coordinator(coordinator_addr, world_size=2,
                                  timeout_s=1.0, _initialize=lambda *a: None)


_WORKER_SCRIPT = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 device per process
import jax
jax.config.update("jax_platforms", "cpu")
from serverless_learn_tpu.parallel.multihost import bootstrap_via_coordinator
world = bootstrap_via_coordinator(sys.argv[1], world_size=2,
                                  name=f"proc{os.getpid()}", timeout_s=60)
assert jax.device_count() == 2, jax.device_count()
assert jax.process_count() == 2

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.training.loop import run_training
cfg = ExperimentConfig(
    model="mlp_mnist",
    mesh=MeshConfig(dp=2),
    optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
    train=TrainConfig(batch_size=16, num_steps=3),
    data=DataConfig(),
)
state, meter = run_training(cfg)
print(json.dumps({"rank": world.rank,
                  "step": int(jax.device_get(state.step)),
                  "loss_param_sum": float(
                      sum(abs(x).sum() for x in
                          jax.tree_util.tree_leaves(
                              jax.device_get(state.params))))}))
world.shutdown()
"""


def test_two_process_training(coordinator_addr, tmp_path):
    """Two real processes, one CPU device each, bootstrap ranks through the
    native coordinator, form a dp=2 global mesh, and take identical
    synchronized training steps."""
    script = tmp_path / "mh_worker.py"
    script.write_text(_WORKER_SCRIPT)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coordinator_addr],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=REPO, text=True) for _ in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert sorted(o["rank"] for o in outs) == [0, 1]
    assert all(o["step"] == 3 for o in outs)
    # Synchronous DP: after psum'd gradients both replicas hold identical
    # parameters.
    assert abs(outs[0]["loss_param_sum"] - outs[1]["loss_param_sum"]) < 1e-4
