"""Round 17: training-quality observability (telemetry/numerics.py,
training/audit.py, `slt numerics`).

Covers the ISSUE-12 acceptance surface: stat math exactness on
fabricated tensors, injected-NaN provenance naming the seeded layer and
faulting step, fingerprint bisection finding a seeded step-k subtree
divergence between two recorded runs, the loss-health detectors firing
through the HealthEngine into a flight dump, donation safety of the
cadence-gated fetch, and (slow tier) a tiny real train run proving the
host-sync cadence and the < 2% ledger overhead bound.
"""

import json
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, HealthConfig, MeshConfig, NumericsConfig,
    OptimizerConfig, TrainConfig)
from serverless_learn_tpu.telemetry import numerics


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"dense_0": {"kernel": rng.normal(size=(8, 4)).astype(np.float32),
                        "bias": rng.normal(size=(4,)).astype(np.float32)},
            "head": {"kernel": rng.normal(size=(4, 2)).astype(np.float32)}}


# -- stat math exactness ------------------------------------------------------


def test_tree_stats_exact_vs_numpy():
    tree = _tree()
    stats = jax.device_get(numerics.tree_stats(tree))
    for name in ("dense_0", "head"):
        leaves = [np.asarray(l) for l in
                  jax.tree_util.tree_leaves(tree[name])]
        flat = np.concatenate([l.ravel() for l in leaves])
        np.testing.assert_allclose(float(stats[name]["l2"]),
                                   np.sqrt((flat ** 2).sum()), rtol=1e-6)
        np.testing.assert_allclose(float(stats[name]["rms"]),
                                   np.sqrt((flat ** 2).sum()) /
                                   np.sqrt(flat.size), rtol=1e-6)
        np.testing.assert_allclose(float(stats[name]["absmax"]),
                                   np.abs(flat).max(), rtol=1e-6)
        assert int(stats[name]["nonfinite"]) == 0


def test_tree_stats_nonfinite_counted_not_poisoning():
    """NaN/Inf are COUNTED but excluded from the norms — the detectors
    baseline on the norms, and one NaN must not turn every later z-score
    into NaN-vs-NaN."""
    tree = _tree()
    tree["head"]["kernel"] = tree["head"]["kernel"].copy()
    tree["head"]["kernel"][0, 0] = np.nan
    tree["head"]["kernel"][1, 0] = np.inf
    stats = jax.device_get(numerics.tree_stats(tree))
    assert int(stats["head"]["nonfinite"]) == 2
    assert math.isfinite(float(stats["head"]["l2"]))
    assert math.isfinite(float(stats["head"]["absmax"]))


def test_global_norm_matches_numpy():
    tree = _tree(3)
    got = float(jax.device_get(numerics.global_norm(tree)))
    want = float(np.sqrt(sum((np.asarray(l) ** 2).sum()
                             for l in jax.tree_util.tree_leaves(tree))))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_step_summary_update_ratio_exact():
    params = _tree(1)
    grads = jax.tree_util.tree_map(lambda x: 0.1 * x, params)
    updates = jax.tree_util.tree_map(lambda x: -0.01 * x, params)
    out = jax.device_get(numerics.step_summary(params, grads, updates,
                                               loss=jnp.float32(1.0)))
    p_l2 = float(out["param/head/l2"])
    u_l2 = float(out["update/head/l2"])
    np.testing.assert_allclose(float(out["ratio/head"]), u_l2 / p_l2,
                               rtol=1e-6)
    # global rollups present; updates = -0.01 * params => exact ratio
    np.testing.assert_allclose(float(out["update_ratio"]), 0.01, rtol=1e-5)
    assert int(out["nonfinite_total"]) == 0
    assert "fp/dense_0/l2" in out and "fp/head/c0" in out


def test_fingerprint_chunks_localize_perturbation():
    tree = _tree(2)
    fa = jax.device_get(numerics.fingerprint(tree))
    tree2 = jax.tree_util.tree_map(np.array, tree)
    tree2["dense_0"]["kernel"] = tree2["dense_0"]["kernel"].copy()
    tree2["dense_0"]["kernel"][0, 0] += 1.0
    fb = jax.device_get(numerics.fingerprint(tree2))
    # untouched subtree agrees exactly
    for k, v in fa["head"].items():
        assert float(v) == float(fb["head"][k])
    worst = numerics.diff_fingerprints(
        {k: {f: float(x) for f, x in d.items()} for k, d in fa.items()},
        {k: {f: float(x) for f, x in d.items()} for k, d in fb.items()})
    assert worst is not None and worst["subtree"] == "dense_0"


# -- NaN/Inf provenance -------------------------------------------------------


def _mlp_bundle():
    from serverless_learn_tpu.models.registry import get_model

    return get_model("mlp_mnist", features=(16, 16), dtype=jnp.float32)


def test_provenance_names_seeded_nan_param(devices):
    bundle = _mlp_bundle()
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    params = jax.device_get(bundle.module.init(rng, x))["params"]
    params["dense_1"]["kernel"] = np.asarray(
        params["dense_1"]["kernel"]).copy()
    params["dense_1"]["kernel"][0, 0] = np.nan
    rep = numerics.nonfinite_provenance(bundle.module, params,
                                        {"image": np.zeros((4, 28, 28, 1),
                                                           np.float32)})
    assert rep["first"] == "params:dense_1"
    assert rep["kind"] == "nan"
    assert rep["param"]["subtree"] == "dense_1"


def test_provenance_names_overflowing_activation(devices):
    """Params finite but huge: the forward overflows to inf INSIDE the
    model — the intermediates sweep (not the param scan) must name the
    first overflowing layer."""
    bundle = _mlp_bundle()
    rng = jax.random.PRNGKey(0)
    x = jnp.ones((2, 28, 28, 1), jnp.float32)
    params = jax.device_get(bundle.module.init(rng, x))["params"]
    params["dense_1"]["kernel"] = np.full_like(
        np.asarray(params["dense_1"]["kernel"]), 3.0e38)
    rep = numerics.nonfinite_provenance(bundle.module, params,
                                        {"image": np.ones((2, 28, 28, 1),
                                                          np.float32)})
    assert rep["param"] is None  # 3e38 is a finite float32
    assert rep["first"] is not None
    assert rep["first"].startswith("intermediates:dense_1")
    assert rep["kind"] == "inf"


# -- fingerprint bisection between two recorded runs --------------------------


def _numerics_cfg(**over):
    return ExperimentConfig(
        model="mlp_mnist",
        model_overrides=dict(features=(16, 16), dtype=jnp.float32),
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05,
                                  momentum=0.0),
        train=TrainConfig(batch_size=8, num_steps=8, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig(),
        numerics=NumericsConfig(enabled=True, cadence=1, **over))


def _run_recording_fps(perturb_at=None, steps=8):
    """Run the real jitted trainer, recording per-step fingerprint
    records from the step's in-graph numerics output; optionally perturb
    one subtree's params mid-run (the seeded divergence)."""
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = _numerics_cfg()
    tr = build_trainer(cfg)
    state = tr.init()
    batch = tr.bundle.make_batch(np.random.default_rng(0), cfg.data, 8)
    sharded = tr.shard_batch(batch)
    records = []
    for t in range(steps):
        if perturb_at is not None and t + 1 == perturb_at:
            bumped = jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x)),
                state.params)
            bumped["head"]["kernel"] = bumped["head"]["kernel"] + 1e-3
            state = state.replace(params=jax.tree_util.tree_map(
                lambda h, p: jax.device_put(h.astype(p.dtype), p.sharding),
                bumped, state.params))
        state, metrics = tr.step(state, sharded)
        host = {k: float(v) for k, v in
                jax.device_get(metrics["numerics"]).items()}
        fp = {}
        for key, val in host.items():
            parts = key.split("/")
            if parts[0] == "fp":
                fp.setdefault(parts[1], {})[parts[2]] = val
        records.append({"event": "numerics_fingerprint", "step": t + 1,
                        "fp": fp})
    return records


def test_fingerprint_bisection_finds_seeded_divergence(devices):
    ref = _run_recording_fps()
    div = _run_recording_fps(perturb_at=5)
    rep = numerics.diff_fingerprint_logs(ref, div)
    assert rep["diverged"], rep
    assert rep["first_divergent_step"] == 5, rep
    assert rep["subtree"] == "head", rep
    assert rep["last_agreeing_step"] == 4
    # identical runs agree everywhere
    rep2 = numerics.diff_fingerprint_logs(ref, _run_recording_fps())
    assert not rep2["diverged"], rep2
    assert rep2["steps_compared"] == 8


# -- parity harness -----------------------------------------------------------


def test_parity_harness_identical_and_perturbed(devices):
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = _numerics_cfg()
    tr = build_trainer(cfg)
    batch = tr.shard_batch(
        tr.bundle.make_batch(np.random.default_rng(1), cfg.data, 8))
    h = numerics.ParityHarness(tr.step, tr.step, tr.init(), tr.init())
    for _ in range(3):
        h.step(batch)
    rep = h.report()
    assert rep["within_tolerance"], rep
    assert all(c["max_ulp"] == 0 for c in rep["subtrees"].values()), rep

    # candidate starts perturbed -> first step already exceeds
    bad = tr.init()
    bumped = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), bad.params)
    bumped["dense_0"]["kernel"] = bumped["dense_0"]["kernel"] + 1e-2
    bad = bad.replace(params=jax.tree_util.tree_map(
        lambda hh, p: jax.device_put(hh.astype(p.dtype), p.sharding),
        bumped, bad.params))
    h2 = numerics.ParityHarness(tr.step, tr.step, tr.init(), bad)
    h2.step(batch)
    rep2 = h2.report(rtol=1e-5, atol=1e-6)
    assert not rep2["within_tolerance"]
    assert rep2["first_exceeded"]["subtree"] == "dense_0"


# -- loss-health detectors through the HealthEngine ---------------------------


def _engine(tmp_path=None, **hc):
    from serverless_learn_tpu.telemetry.health import HealthEngine
    from serverless_learn_tpu.telemetry.registry import MetricsRegistry

    sink = []
    eng = HealthEngine(
        registry=MetricsRegistry(),
        config=HealthConfig(numerics_spike_z=4.0, **hc),
        emit=sink.append, clock=time.time,
        flight_dir=str(tmp_path) if tmp_path else None)
    return eng, sink


def test_loss_spike_fires_health_engine_and_flight_dump(tmp_path, devices):
    numerics.clear_steps()
    eng, sink = _engine(tmp_path)
    t = 1_000_000.0
    for i in range(16):
        numerics.note_step({"step": i + 1, "loss": 2.0 - 0.02 * i,
                            "grad_norm": 1.0, "nonfinite": 0})
        eng.sample_once(now=t)
        t += 1.0
    assert not eng.alerts(firing_only=True)
    # a massive spike (> 2x the z threshold) escalates to critical ->
    # the engine writes a flight dump with the firing alert attached
    numerics.note_step({"step": 17, "loss": 500.0, "grad_norm": 1.0,
                        "nonfinite": 0})
    eng.sample_once(now=t)
    firing = eng.alerts(firing_only=True)
    spikes = [a for a in firing if a["alert"] == "numerics.loss_spike"]
    assert spikes and spikes[0]["severity"] == "critical", firing
    assert any(r.get("alert") == "numerics.loss_spike" for r in sink)
    dumps = list(tmp_path.glob("flight-*.json"))
    assert dumps, "critical numerics alert must write a flight dump"
    payload = json.loads(dumps[0].read_text())
    assert payload["reason"] == "alert:numerics.loss_spike"
    numerics.clear_steps()


def test_nonfinite_record_fires_critical_alert(devices):
    numerics.clear_steps()
    eng, sink = _engine()
    t = 1_000_000.0
    numerics.note_step({"step": 3, "loss": float("nan"), "nonfinite": 42,
                        "first": "params:dense_1"})
    eng.sample_once(now=t)
    firing = eng.alerts(firing_only=True)
    nf = [a for a in firing if a["alert"] == "numerics.nonfinite"]
    assert nf and nf[0]["severity"] == "critical"
    assert "dense_1" in nf[0]["message"]
    numerics.clear_steps()


def test_plateau_and_explosion_detectors():
    lh = numerics.LossHealth(plateau_window=10, plateau_min_rel=1e-3,
                             explode_z=6.0, min_samples=4)
    fired = []
    for i in range(30):
        loss = 2.0 - 0.05 * min(i, 10)  # improves then flatlines
        v = lh.update(i + 1, loss, grad_norm=1.0)
        if v["loss_plateau"]:
            fired.append(i + 1)
    assert fired and fired[0] >= 21, fired  # window after the last best
    v = lh.update(31, 1.5, grad_norm=1e6)
    assert v["grad_explosion"] is not None
    assert v["grad_explosion"]["severity"] == "critical"


# -- end-to-end: seeded NaN injection through the real loop -------------------


def _train_cfg(**num_over):
    return ExperimentConfig(
        model="mlp_mnist",
        model_overrides=dict(features=(16, 16), dtype=jnp.float32),
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05,
                                  momentum=0.0),
        train=TrainConfig(batch_size=8, num_steps=12, dtype="float32",
                          param_dtype="float32", log_every=100),
        data=DataConfig(),
        numerics=NumericsConfig(enabled=True, cadence=4, **num_over))


def test_injected_nan_is_named_with_step_and_layer(devices):
    """The acceptance path: a seeded mid-run NaN in one subtree's
    gradient is detected AT the faulting step (forced off-cadence fetch
    from the already-fetched metrics), provenance names the seeded
    layer, and the record trail carries both — with donate_state=True,
    proving the sweep reads pre-donation values."""
    from serverless_learn_tpu.telemetry.registry import MetricsRegistry
    from serverless_learn_tpu.training.audit import NumericsAuditor
    from serverless_learn_tpu.training.loop import run_training
    from serverless_learn_tpu.training.train_step import build_trainer

    numerics.clear_steps()
    cfg = _train_cfg(inject_nan_step=6, inject_nan_subtree="dense_1")
    assert cfg.train.donate_state  # the hazard under test
    reg = MetricsRegistry()
    events = []
    trainer = build_trainer(cfg)
    auditor = NumericsAuditor(cfg, registry=reg, bundle=trainer.bundle,
                              emit=events.append)
    run_training(cfg, trainer=trainer, auditor=auditor)
    bad = [r for r in events if r["event"] == "numerics_nonfinite"]
    assert bad, events
    assert bad[0]["step"] == 6
    assert bad[0]["provenance"]["first"] == "params:dense_1"
    assert "grad:dense_1" in bad[0]["bad_subtrees"]
    assert auditor.nonfinite_steps[0] == 6
    assert reg.counter("slt_numerics_nonfinite_total").value >= 1
    # the /numerics payload is host floats only (json-serializable: no
    # retained device references anywhere a scrape could reach)
    json.dumps(numerics.endpoint_payload())
    numerics.clear_steps()


def test_provenance_prefers_host_shadow(devices):
    """With a shadow_fn wired (the checkpointer's note_state shadow),
    provenance reads it instead of the live state — the donated-buffer-
    safe path."""
    from serverless_learn_tpu.telemetry.registry import MetricsRegistry
    from serverless_learn_tpu.training.audit import NumericsAuditor
    from serverless_learn_tpu.training.train_step import build_trainer

    numerics.clear_steps()
    cfg = _train_cfg()
    tr = build_trainer(cfg)
    state = tr.init()
    shadow = jax.device_get(state)
    events = []
    auditor = NumericsAuditor(cfg, registry=MetricsRegistry(),
                              bundle=tr.bundle,
                              shadow_fn=lambda: (shadow, 0),
                              emit=events.append)
    auditor._on_nonfinite(5, {"nonfinite_total": 1.0,
                              "grad/dense_1/nonfinite": 1.0},
                          state=None, batch={"image": np.zeros(
                              (2, 28, 28, 1), np.float32)})
    assert auditor.last_provenance["source"] == "host_shadow"
    assert events and events[0]["event"] == "numerics_nonfinite"
    numerics.clear_steps()


# -- cadence + overhead acceptance (slow tier) --------------------------------


def test_numerics_cadence_and_overhead_acceptance(devices):
    """Tiny real train run with numerics enabled: stats present, host
    syncs exactly at the cadence (not per step), and the `numerics`
    ledger phase under 2% of the run's wall-clock."""
    from serverless_learn_tpu.telemetry import goodput
    from serverless_learn_tpu.telemetry.registry import MetricsRegistry
    from serverless_learn_tpu.training.audit import NumericsAuditor
    from serverless_learn_tpu.training.loop import run_training
    from serverless_learn_tpu.training.train_step import build_trainer

    numerics.clear_steps()
    cfg = _train_cfg().override(
        train=TrainConfig(batch_size=8, num_steps=40, dtype="float32",
                          param_dtype="float32", log_every=100))
    reg = MetricsRegistry()
    events = []
    ledger = goodput.PhaseLedger(emit=False)
    prev = goodput.set_ledger(ledger)
    try:
        trainer = build_trainer(cfg)
        auditor = NumericsAuditor(cfg, registry=reg,
                                  bundle=trainer.bundle,
                                  emit=events.append)
        run_training(cfg, trainer=trainer, auditor=auditor)
    finally:
        goodput.set_ledger(prev)
    stats = [r for r in events if r["event"] == "numerics_stats"]
    assert stats, "no numerics_stats records emitted"
    # cadence 4 over 40 steps = 10 fetches, none forced (run is clean)
    assert auditor.fetches == 10
    assert reg.counter("slt_numerics_fetches_total").value == 10
    assert all(r["step"] % 4 == 0 for r in stats)
    assert all(r["nonfinite"] == 0 for r in stats)
    rep = ledger.report()
    num_phase = rep["phases"].get("numerics", {"seconds": 0.0})
    assert num_phase["seconds"] < 0.02 * rep["total_s"], rep
    numerics.clear_steps()


# -- CLI ----------------------------------------------------------------------


def test_cli_numerics_diff_and_selfcheck(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    tree = _tree(7)
    recs_a = [{"event": "numerics_fingerprint", "step": s,
               "fp": {k: {f: float(v) for f, v in d.items()}
                      for k, d in jax.device_get(
                          numerics.fingerprint(tree)).items()}}
              for s in range(4)]
    recs_b = [json.loads(json.dumps(r)) for r in recs_a]
    recs_b[2]["fp"]["head"]["sum"] += 0.5
    recs_b[3]["fp"]["head"]["sum"] += 0.5
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    a.write_text("".join(json.dumps(r) + "\n" for r in recs_a))
    b.write_text("".join(json.dumps(r) + "\n" for r in recs_b))
    rc = main(["numerics", "diff", str(a), str(b), "--compact"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["diverged"]
    assert out["first_divergent_step"] == 2 and out["subtree"] == "head"

    rc = main(["numerics", "diff", str(a), str(a), "--compact"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and not out["diverged"]

    rc = main(["numerics", "--self-check", "--compact"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"], out


def test_cli_numerics_summary_flags_incidents(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    log = tmp_path / "ev.jsonl"
    recs = [{"event": "numerics_stats", "step": 4, "grad_norm": 1.5,
             "update_ratio": 0.001, "nonfinite": 0, "subtrees": {}},
            {"event": "numerics_nonfinite", "step": 6,
             "first": "params:dense_1",
             "bad_subtrees": ["grad:dense_1"], "nonfinite": 3}]
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    rc = main(["numerics", "summary", str(log), "--compact"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # incidents present -> nonzero, scriptable
    assert out["nonfinite_incidents"][0]["first"] == "params:dense_1"
    assert out["grad_norm"]["last"] == 1.5


def test_numerics_config_from_dict():
    cfg = ExperimentConfig.from_dict(
        {"numerics": {"enabled": True, "cadence": 7,
                      "inject_nan_step": 3}})
    assert cfg.numerics.enabled and cfg.numerics.cadence == 7
    assert cfg.numerics.inject_nan_step == 3
    assert not ExperimentConfig.from_dict({}).numerics.enabled
