"""Every registered optimizer family trains the MLP a step and reduces loss
on a fixed batch within a few iterations."""

import jax
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.optimizer import make_optimizer
from serverless_learn_tpu.training.train_step import build_trainer

NAMES = ["adamw", "adam", "sgd", "adafactor", "lion", "rmsprop"]


@pytest.mark.parametrize("name", NAMES)
def test_optimizer_reduces_loss_on_fixed_batch(devices, name):
    lr = 1e-4 if name == "lion" else 1e-3
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name=name, learning_rate=lr,
                                  warmup_steps=0),
        train=TrainConfig(batch_size=32, num_steps=8),
        data=DataConfig(),
    )
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 32, seed=11)
    batch = trainer.shard_batch(next(iter(src)))
    losses = []
    for _ in range(8):
        state, metrics = trainer.step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], (name, losses)


def test_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(OptimizerConfig(name="nope"))


def test_schedule_warmup_and_decay(devices):
    from serverless_learn_tpu.training.optimizer import make_schedule

    sched = make_schedule(OptimizerConfig(
        learning_rate=1e-2, warmup_steps=10, decay_steps=100))
    assert float(sched(0)) == 0.0
    assert float(sched(10)) == pytest.approx(1e-2, rel=1e-3)
    assert float(sched(100)) < 1e-3
