"""Every registered optimizer family trains the MLP a step and reduces loss
on a fixed batch within a few iterations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.optimizer import make_optimizer
from serverless_learn_tpu.training.train_step import build_trainer

NAMES = ["adamw", "adam", "sgd", "adafactor", "lion", "rmsprop"]


@pytest.mark.parametrize("name", NAMES)
def test_optimizer_reduces_loss_on_fixed_batch(devices, name):
    lr = 1e-4 if name == "lion" else 1e-3
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name=name, learning_rate=lr,
                                  warmup_steps=0),
        train=TrainConfig(batch_size=32, num_steps=8),
        data=DataConfig(),
    )
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 32, seed=11)
    batch = trainer.shard_batch(next(iter(src)))
    losses = []
    for _ in range(8):
        state, metrics = trainer.step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], (name, losses)


def test_weight_decay_skips_1d_params(devices):
    """adamw's decay must not touch norm scales/biases by default."""
    import optax

    params = {"dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))},
              "norm": {"scale": jnp.ones((4,))}}
    tx = make_optimizer(OptimizerConfig(name="adamw", learning_rate=0.0,
                                        weight_decay=0.5))
    state = tx.init(params)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(zeros, state, params)
    # lr=0 => schedule contributes nothing; with zero grads only the decay
    # term could move params — and it must only hit the 2-D kernel.
    assert float(jnp.abs(updates["dense"]["bias"]).max()) == 0.0
    assert float(jnp.abs(updates["norm"]["scale"]).max()) == 0.0


def test_lr_reported_in_metrics(devices):
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource

    cfg = ExperimentConfig(
        model="mlp_mnist", mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-2,
                                  warmup_steps=10),
        train=TrainConfig(batch_size=16, num_steps=2), data=DataConfig())
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=0)
    batch = trainer.shard_batch(next(iter(src)))
    state, m0 = trainer.step(state, batch)
    lr0 = float(jax.device_get(m0["lr"]))
    state, m1 = trainer.step(state, batch)
    lr1 = float(jax.device_get(m1["lr"]))
    assert 0.0 <= lr0 < lr1 <= 1e-2, (lr0, lr1)  # warming up


def test_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(OptimizerConfig(name="nope"))


@pytest.mark.parametrize("name", ["adam", "sgd", "adafactor", "rmsprop"])
def test_ignored_weight_decay_rejected(name):
    """Optimizers without decoupled decay must fail loudly, not silently
    train with no decay (ADVICE.md round 1)."""
    with pytest.raises(ValueError, match="weight_decay"):
        make_optimizer(OptimizerConfig(name=name, weight_decay=0.1))


def test_schedule_warmup_and_decay(devices):
    from serverless_learn_tpu.training.optimizer import make_schedule

    sched = make_schedule(OptimizerConfig(
        learning_rate=1e-2, warmup_steps=10, decay_steps=100))
    assert float(sched(0)) == 0.0
    assert float(sched(10)) == pytest.approx(1e-2, rel=1e-3)
    assert float(sched(100)) < 1e-3


def test_adafactor_trains_on_tp_sharded_mesh(devices):
    """Regression (r3): adafactor's factored second-moment leaves share the
    params' tree PATHS but not their shapes — a (1,) placeholder matched
    the embedding rule and got an invalid tp sharding, crashing jit for
    any adafactor + tp/fsdp config. Non-dividing rule axes now drop to
    replicated for optimizer state."""
    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig(
        model="llama_tiny",
        model_overrides=dict(dtype=jnp.float32),
        mesh=MeshConfig(dp=2, fsdp=2, tp=2),
        optimizer=OptimizerConfig(name="adafactor", learning_rate=1e-3),
        train=TrainConfig(batch_size=8),
        data=DataConfig(seq_len=16))
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data, 8,
                               seed=0))
    state, m = trainer.step(state, trainer.shard_batch(next(src)))
    assert np.isfinite(float(jax.device_get(m["loss"])))


# -- ZeRO update sharding across optimizer families (round 18) ----------------


@pytest.mark.parametrize("name", ["adamw", "adafactor"])
def test_zero1_update_matches_replicated(devices, name):
    """Sharding the optimizer state/update over dp must not change any
    family's math — adamw (the dense-state case) and adafactor (whose
    factored stats exercise the indivisible-leaf fallback)."""
    from serverless_learn_tpu.config import DataConfig as DC
    from serverless_learn_tpu.telemetry.numerics import compare_trees
    from serverless_learn_tpu.training.train_step import build_trainer

    def cfg(stage):
        return ExperimentConfig(
            model="mlp_mnist", mesh=MeshConfig(dp=8),
            optimizer=OptimizerConfig(name=name, learning_rate=1e-3),
            train=TrainConfig(batch_size=32, zero_stage=stage),
            data=DC(), model_overrides={"dtype": jnp.float32})

    t0, t1 = build_trainer(cfg(0)), build_trainer(cfg(1))
    s0, s1 = t0.init(), t1.init()
    src = SyntheticSource(t0.bundle.make_batch, DC(), 32, seed=17)
    for b, _ in zip(iter(src), range(2)):
        s0, _ = t0.step(s0, t0.shard_batch(b))
        s1, _ = t1.step(s1, t1.shard_batch(b))
    cmp = compare_trees(jax.device_get(s0.params), jax.device_get(s1.params))
    if name == "adamw":
        # Element-wise state: reduce-scatter + all-gather re-associates
        # the same summands — ulp-tight.
        assert max(c["max_ulp"] for c in cmp.values()) <= 8, cmp
    else:
        # Adafactor's factored stats REDUCE over the sharded dim, so the
        # cross-device accumulation order genuinely re-associates; the
        # parity bound is a float tolerance, not ulp identity.
        assert max(c["max_abs_err"] for c in cmp.values()) <= 1e-6, cmp
