"""Parallel multi-source ingest + device-side augmentation (round-3
verdict #1).

The data plane's per-host bar is per-chip demand x chips-per-host (4 on a
v4 host). Two capabilities close the gap: ``ParallelIngestSource`` (N
fetch+transform processes striping one host's shard share) and the
device-augment geometry (host ships stored-size uint8 records; the train
step crops/flips on device from its PRNG). These tests pin both: exact
per-epoch record coverage across workers, error propagation, crop/flip
parity device-vs-host, and the resnet50 ``device_augment=True`` bundle
training end to end from 256x256 records.
"""

import socket

import numpy as np
import pytest

from serverless_learn_tpu.control.daemons import start_shard_server
from serverless_learn_tpu.data.parallel_ingest import ParallelIngestSource
from serverless_learn_tpu.data.shard_client import publish_dataset


@pytest.fixture
def shard_server(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    try:
        yield f"127.0.0.1:{port}"
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_workers_cover_every_record_exactly_once(shard_server):
    n = 1024
    publish_dataset(shard_server, "cover",
                    {"idx": np.arange(n, dtype=np.int64)},
                    records_per_shard=128)  # 8 shards over 2 workers
    src = ParallelIngestSource(shard_server, "cover", batch_size=64,
                               workers=2, loop=False)
    seen = []
    for batch in src:
        seen.extend(batch["idx"].tolist())
    src.close()
    # Full batches of 64 from 128-record shards: no partial-batch drops,
    # so the union across workers is exactly one epoch.
    assert sorted(seen) == list(range(n))


def test_worker_striping_matches_plain_source_share(shard_server):
    """Workers subdivide THIS host's dp share: whatever the worker count,
    the union must be EXACTLY the shard set a plain single-source rank
    owns ({i : i % dp_size == dp_rank}) — otherwise a parallel-ingest
    host mixed with plain-source hosts double-trains some shards and
    never sees others (round-4 code-review finding)."""
    n = 1024
    publish_dataset(shard_server, "stripe",
                    {"idx": np.arange(n, dtype=np.int64)},
                    records_per_shard=128)
    want = set()
    for shard in range(8):
        if shard % 2 == 0:  # plain ShardStreamSource(dp_rank=0, dp_size=2)
            want.update(range(shard * 128, (shard + 1) * 128))
    for workers in (1, 2, 3):
        src = ParallelIngestSource(shard_server, "stripe", batch_size=64,
                                   workers=workers, dp_rank=0, dp_size=2,
                                   loop=False)
        seen = set()
        for batch in src:
            seen.update(batch["idx"].tolist())
        src.close()
        assert seen == want, f"workers={workers}"

    # More workers than the rank's shards (4 shards in the stripe, 5
    # workers): surplus workers must FAIL LOUDLY, not wrap onto siblings'
    # shards and silently train records twice per epoch.
    src = ParallelIngestSource(shard_server, "stripe", batch_size=64,
                               workers=5, dp_rank=0, dp_size=2, loop=False)
    with pytest.raises(Exception, match="ingest workers"):
        for batch in src:
            pass
    src.close()


def _double_and_tag_factory(worker_idx):
    # Module-level: spawn-based workers pickle the factory by reference.
    def fn(batch):
        out = dict(batch)
        out["idx"] = batch["idx"] * 2
        out["worker"] = np.full(len(batch["idx"]), worker_idx, np.int32)
        return out
    return fn


def test_transform_factory_runs_in_child(shard_server):
    n = 256
    publish_dataset(shard_server, "xform",
                    {"idx": np.arange(n, dtype=np.int64)},
                    records_per_shard=64)

    src = ParallelIngestSource(shard_server, "xform", batch_size=32,
                               workers=2, loop=False,
                               transform_factory=_double_and_tag_factory)
    seen, workers = [], set()
    for batch in src:
        seen.extend(batch["idx"].tolist())
        workers.update(batch["worker"].tolist())
    src.close()
    assert sorted(seen) == [2 * i for i in range(n)]
    assert workers == {0, 1}


def test_worker_error_propagates(shard_server):
    src = ParallelIngestSource(shard_server, "does_not_exist", batch_size=8,
                               workers=2, loop=False)
    with pytest.raises(Exception):
        next(iter(src))
    src.close()


def test_device_crop_flip_matches_host():
    import jax.numpy as jnp

    from serverless_learn_tpu.data.transforms import _crop_flip
    from serverless_learn_tpu.models.resnet import device_crop_flip

    rng = np.random.default_rng(11)
    img = rng.integers(0, 256, (8, 40, 40, 3), dtype=np.uint8)
    ys = rng.integers(0, 9, 8)
    xs = rng.integers(0, 9, 8)
    fl = rng.random(8) < 0.5
    host = _crop_flip(img, 32, 32, ys, xs, fl)
    dev = device_crop_flip(jnp.asarray(img), jnp.asarray(ys, jnp.int32),
                           jnp.asarray(xs, jnp.int32), jnp.asarray(fl),
                           32, 32)
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_resnet50_device_augment_trains(devices):
    """device_augment=True: batches carry STORED-size (here 48x48) uint8
    records; the jitted step crops to image_shape on device, per-step
    random (different steps -> different crops -> different losses on
    frozen params), and eval center-crops deterministically."""
    import jax
    import jax.numpy as jnp

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.training.train_step import build_trainer

    cfg = ExperimentConfig(
        model="resnet50_imagenet",
        model_overrides=dict(num_classes=4, device_augment=True,
                             stored_hw=(48, 48),
                             image_shape=(32, 32, 3), dtype="float32"),
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.01,
                                  momentum=0.9),
        train=TrainConfig(batch_size=16, dtype="float32"),
        data=DataConfig())
    trainer = build_trainer(cfg)
    spec = trainer.bundle.input_spec(cfg.data, 16)
    assert tuple(spec["image"].shape) == (16, 48, 48, 3)  # stored size

    rng = np.random.default_rng(0)
    batch = trainer.bundle.make_batch(rng, cfg.data, 16)
    state = trainer.init()
    losses = []
    for _ in range(2):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(jax.device_get(m["loss"])))
    assert all(np.isfinite(l) for l in losses)

    # Frozen params, same batch, two different step counters -> the crop
    # randomness must come from the step PRNG (losses differ).
    l0, _ = trainer.bundle.loss_fn(
        state.params, batch, rngs=jax.random.PRNGKey(0),
        model_state=state.model_state)
    l1, _ = trainer.bundle.loss_fn(
        state.params, batch, rngs=jax.random.PRNGKey(1),
        model_state=state.model_state)
    assert float(l0) != float(l1)

    # Eval: deterministic center crop (no rng), matches a manual slice.
    from serverless_learn_tpu.models.registry import get_model
    bundle = get_model("resnet50_imagenet", num_classes=4,
                       device_augment=True, stored_hw=(48, 48),
                       image_shape=(32, 32, 3), dtype=jnp.float32)
    cropped = {"image": batch["image"][:, 8:40, 8:40],
               "label": batch["label"]}
    plain = get_model("resnet50_imagenet", num_classes=4,
                      image_shape=(32, 32, 3), dtype=jnp.float32)
    le, _ = bundle.eval_loss_fn(state.params, batch,
                                model_state=state.model_state)
    lp, _ = plain.eval_loss_fn(state.params, cropped,
                               model_state=state.model_state)
    np.testing.assert_allclose(float(le), float(lp), rtol=1e-6)
