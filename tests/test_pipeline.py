"""Pipeline parallelism (GPipe over the ``pp`` mesh axis).

The reference has no pipeline concept (single-process model vector,
``src/master.cc:58``; SURVEY.md §2.9 PP row: absent). These tests hold the
pipelined schedule to the sequential golden model, on the 8-virtual-device
CPU mesh from conftest.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.parallel.pipeline import gpipe_apply, sequential_apply
from serverless_learn_tpu.training.train_step import build_trainer


def _toy_block(p, h, pos, mask=None):
    out = jnp.tanh(h @ p) + h
    if mask is not None:
        out = out * mask[..., None]
    return out


@pytest.fixture(scope="module")
def pp_mesh(devices):
    return make_mesh(MeshConfig(dp=2, pp=4))


def _toy_inputs(pp_mesh, L=8, D=16, B=8, T=4):
    W = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)).astype(jnp.int32)
    W_s = jax.device_put(W, NamedSharding(pp_mesh, P("pp")))
    x_s = jax.device_put(x, NamedSharding(pp_mesh, P(("dp", "fsdp"))))
    pos_s = jax.device_put(pos, NamedSharding(pp_mesh, P(("dp", "fsdp"))))
    return W, x, pos, W_s, x_s, pos_s


def test_gpipe_matches_sequential_forward(pp_mesh):
    W, x, pos, W_s, x_s, pos_s = _toy_inputs(pp_mesh)
    ref = jax.jit(lambda w, h, p: sequential_apply(_toy_block, w, h, p))(
        W, x, pos)
    out = jax.jit(lambda w, h, p: gpipe_apply(
        _toy_block, w, h, p, mesh=pp_mesh, n_microbatches=4))(W_s, x_s, pos_s)
    assert jnp.allclose(ref, jax.device_get(out), atol=1e-5)


def test_gpipe_matches_sequential_grads(pp_mesh):
    W, x, pos, W_s, x_s, pos_s = _toy_inputs(pp_mesh)
    gref = jax.grad(
        lambda w: sequential_apply(_toy_block, w, x, pos).sum())(W)
    gout = jax.jit(jax.grad(lambda w: gpipe_apply(
        _toy_block, w, x_s, pos_s, mesh=pp_mesh,
        n_microbatches=4).sum()))(W_s)
    assert jnp.allclose(gref, jax.device_get(gout), atol=1e-4)


def test_gpipe_microbatch_count_independence(pp_mesh):
    W, x, pos, W_s, x_s, pos_s = _toy_inputs(pp_mesh)
    outs = [
        jax.device_get(jax.jit(lambda w, h, p, m=m: gpipe_apply(
            _toy_block, w, h, p, mesh=pp_mesh, n_microbatches=m))(
                W_s, x_s, pos_s))
        for m in (1, 2, 4)
    ]
    assert jnp.allclose(outs[0], outs[1], atol=1e-5)
    assert jnp.allclose(outs[1], outs[2], atol=1e-5)


def _train_cfg(mesh_cfg):
    return ExperimentConfig(
        model="llama_tiny",
        model_overrides=dict(pipeline=True, pipeline_microbatches=4,
                             n_layers=4),
        mesh=mesh_cfg,
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16),
        data=DataConfig(seq_len=32),
    )


def test_pipelined_train_step_matches_dp(devices):
    """Same seed, same batches: a dp=2,pp=4 pipelined run must track dp=8."""
    losses = {}
    for name, mesh_cfg in (("dp", MeshConfig(dp=8)),
                           ("pp", MeshConfig(dp=2, pp=4))):
        cfg = _train_cfg(mesh_cfg)
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data,
                                   cfg.train.batch_size, seed=0))
        batch = trainer.shard_batch(next(src))
        for _ in range(3):
            state, metrics = trainer.step(state, batch)
        losses[name] = float(jax.device_get(metrics["loss"]))
    assert abs(losses["dp"] - losses["pp"]) < 5e-3, losses


def test_gpipe_threads_mask(pp_mesh):
    """An attention-style mask rides the microbatch schedule with x."""
    W, x, pos, W_s, x_s, pos_s = _toy_inputs(pp_mesh)
    mask = (jax.random.uniform(jax.random.PRNGKey(2), x.shape[:2]) > 0.3
            ).astype(x.dtype)
    mask_s = jax.device_put(
        mask, NamedSharding(pp_mesh, P(("dp", "fsdp"))))
    ref = jax.jit(lambda w, h, p, m: sequential_apply(
        _toy_block, w, h, p, m))(W, x, pos, mask)
    out = jax.jit(lambda w, h, p, m: gpipe_apply(
        _toy_block, w, h, p, m, mesh=pp_mesh, n_microbatches=4))(
            W_s, x_s, pos_s, mask_s)
    assert jnp.allclose(ref, jax.device_get(out), atol=1e-5)


def test_gpipe_rejects_indivisible_layers(pp_mesh):
    W = jax.random.normal(jax.random.PRNGKey(0), (6, 16, 16))  # 6 % 4 != 0
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 16))
    pos = jnp.zeros((8, 4), jnp.int32)
    with pytest.raises(ValueError, match="n_layers"):
        gpipe_apply(_toy_block, W, x, pos, mesh=pp_mesh, n_microbatches=4)


def test_pipeline_rejects_sp(devices):
    mesh = make_mesh(MeshConfig(dp=1, sp=2, pp=4))
    W, x, pos, *_ = _toy_inputs(make_mesh(MeshConfig(dp=2, pp=4)))
    with pytest.raises(NotImplementedError):
        gpipe_apply(_toy_block, W, x, pos, mesh=mesh, n_microbatches=4)


def _train_losses(mesh_cfg, extra=None, steps=3):
    ov = dict(pipeline=True, pipeline_microbatches=4, n_layers=4)
    ov.update(extra or {})
    cfg = ExperimentConfig(
        model="llama_tiny", model_overrides=ov, mesh=mesh_cfg,
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16), data=DataConfig(seq_len=32))
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data, 16,
                               seed=0))
    batch = trainer.shard_batch(next(src))
    for _ in range(steps):
        state, metrics = trainer.step(state, batch)
    return float(jax.device_get(metrics["loss"]))


def test_pp_tp_train_step_matches_dp(devices):
    """VERDICT round 1 item 8: a pp=2 x tp=2 llama step must track the dp
    golden model — Megatron-style manual tp inside pipeline stages."""
    l_dp = _train_losses(MeshConfig(dp=8))
    l_tp = _train_losses(MeshConfig(dp=2, pp=2, tp=2))
    assert abs(l_dp - l_tp) < 5e-3, (l_dp, l_tp)


def test_interleaved_schedule_matches_dp(devices):
    """The interleaved (V=2) circular schedule trains the same model as the
    sequential golden (which replays the pinned layer order)."""
    extra = dict(pipeline_interleave=2, pipeline_stages=2)
    l_dp = _train_losses(MeshConfig(dp=8), extra)
    l_iv = _train_losses(MeshConfig(dp=4, pp=2), extra)
    l_iv_tp = _train_losses(MeshConfig(dp=2, pp=2, tp=2), extra)
    assert abs(l_dp - l_iv) < 5e-3, (l_dp, l_iv)
    assert abs(l_dp - l_iv_tp) < 5e-3, (l_dp, l_iv_tp)


def test_interleave_needs_pinned_stages(devices):
    with pytest.raises(ValueError, match="pipeline_stages"):
        _train_losses(MeshConfig(dp=4, pp=2),
                      dict(pipeline_interleave=2), steps=1)


def test_interleave_needs_enough_microbatches(pp_mesh):
    W, x, pos, W_s, x_s, pos_s = _toy_inputs(pp_mesh)
    with pytest.raises(ValueError, match="n_microbatches >= pp"):
        gpipe_apply(_toy_block, W_s, x_s, pos_s, mesh=pp_mesh,
                    n_microbatches=2, n_virtual=2)


def test_interleaved_toy_matches_permuted_sequential(pp_mesh):
    """V=2 over the toy block: pipeline output equals sequential application
    in the schedule's layer order."""
    from serverless_learn_tpu.parallel.pipeline import layer_execution_order

    W, x, pos, W_s, x_s, pos_s = _toy_inputs(pp_mesh)
    order = layer_execution_order(8, 4, 2)
    ref = jax.jit(lambda w, h, p: sequential_apply(
        _toy_block, w, h, p, layer_order=order))(W, x, pos)
    out = jax.jit(lambda w, h, p: gpipe_apply(
        _toy_block, w, h, p, mesh=pp_mesh, n_microbatches=4,
        n_virtual=2))(W_s, x_s, pos_s)
    assert jnp.allclose(ref, jax.device_get(out), atol=1e-5)


def _moe_losses(mesh_cfg, extra=None, steps=3):
    ov = dict(pipeline=True, pipeline_microbatches=4, n_layers=4,
              moe_group_size=32)
    ov.update(extra or {})
    cfg = ExperimentConfig(
        model="moe_tiny", model_overrides=ov, mesh=mesh_cfg,
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16), data=DataConfig(seq_len=32))
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data, 16,
                               seed=0))
    batch = trainer.shard_batch(next(src))
    for _ in range(steps):
        state, metrics = trainer.step(state, batch)
    m = jax.device_get(metrics)
    return float(m["loss"]), float(m.get("moe_aux_loss", 0.0))


def test_pp_sp_train_step_matches_dp(devices):
    """Round-4: the LAST composition refusal removed — pp=2 x sp=2 runs
    manual ring attention inside pipeline stages (seq dim sharded across
    the sp ring, K/V hopping via ppermute from within each stage) and
    tracks the dp golden model."""
    l_dp = _train_losses(MeshConfig(dp=8))
    l_sp = _train_losses(MeshConfig(dp=2, pp=2, sp=2))
    assert abs(l_dp - l_sp) < 5e-3, (l_dp, l_sp)


def test_pp_sp_suffix_lengths_match_dp(devices):
    """The pp x sp padding escape hatch (causal + suffix kv_lengths): the
    stage derives lengths from its LOCAL mask shard and psums them over sp
    to recover the GLOBAL suffix length — logits must match the dp golden
    at every valid position (code-review finding: local sums passed as
    global lengths silently mis-masked)."""
    import numpy as np

    from serverless_learn_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.parallel.ring_attention import set_active_mesh

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=32, causal=True, use_rope=True,
        suffix_padding_mask=True, pipeline=True, pipeline_microbatches=2,
        dtype=jnp.float32, param_dtype=jnp.float32)
    module = Transformer(cfg)
    rng = np.random.default_rng(0)
    B, T = 8, 32
    tokens = jnp.asarray(rng.integers(0, 64, (B, T)), jnp.int32)
    lens = np.full(B, T)
    lens[1], lens[3], lens[5] = 20, 8, 26
    mask = jnp.asarray((np.arange(T)[None, :] < lens[:, None])
                       )[:, None, None, :]

    set_active_mesh(make_mesh(MeshConfig(dp=8)))
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    golden = jax.device_get(jax.jit(
        lambda p: module.apply({"params": p}, tokens, mask=mask))(params))

    set_active_mesh(make_mesh(MeshConfig(dp=2, pp=2, sp=2)))
    got = jax.device_get(jax.jit(
        lambda p: module.apply({"params": p}, tokens, mask=mask))(params))
    valid = (np.arange(T)[None, :] < lens[:, None])[:, :, None]
    err = np.abs((got - golden) * valid).max()
    assert err < 2e-3, err


def test_pp_sp_rejects_noncausal(devices):
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.parallel.ring_attention import set_active_mesh

    mesh = make_mesh(MeshConfig(dp=2, pp=2, sp=2))
    set_active_mesh(mesh)
    from serverless_learn_tpu.models.transformer import (
        Transformer, TransformerConfig)

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=4,
                            n_heads=2, d_ff=64, max_seq_len=64,
                            causal=False, use_rope=True, pipeline=True,
                            pipeline_microbatches=2)
    with pytest.raises(NotImplementedError, match="causal"):
        jax.eval_shape(
            lambda: Transformer(cfg).init(
                jax.random.PRNGKey(0), jnp.zeros((8, 32), jnp.int32)))


def test_pp_ep_train_step_matches_dp(devices):
    """Round-3 verdict #3: a Mixtral-shaped model must PIPELINE — pp=2 x
    ep=2 (manual GShard all-to-alls inside pipeline stages) tracks the dp
    golden model, aux loss included. moe_group_size=seq makes routing
    groups per-row, so capacity drops are identical under any batch split
    and parity is exact up to float association."""
    l_dp, a_dp = _moe_losses(MeshConfig(dp=8))
    l_ep, a_ep = _moe_losses(MeshConfig(dp=2, pp=2, ep=2))
    assert abs(l_dp - l_ep) < 5e-3, (l_dp, l_ep)
    assert a_ep > 0.0, "aux loss must reach the metrics on the pp x ep mesh"
    assert abs(a_dp - a_ep) < 1e-4, (a_dp, a_ep)


def test_pp_tp_moe_train_step_matches_dp(devices):
    """Round-3 verdict #3 second refusal: pp x tp x MoE — expert d_ff
    tp-sliced like the dense MLP, with MoELayer psumming its row-parallel
    down projection."""
    l_dp, a_dp = _moe_losses(MeshConfig(dp=8))
    l_tp, a_tp = _moe_losses(MeshConfig(dp=2, pp=2, tp=2))
    assert abs(l_dp - l_tp) < 5e-3, (l_dp, l_tp)
    assert abs(a_dp - a_tp) < 1e-4, (a_dp, a_tp)


def test_moe_pipeline_matches_dp(devices):
    """Round-1 NotImplementedError removed: a pipelined MoE model threads
    the router aux loss out of the stages (blocks return their sown losses
    explicitly; the schedule sums over layers, averages over microbatches,
    and re-sows). moe_group_size = seq_len makes routing groups per-row,
    so grouping — and therefore capacity drops and the aux term — is
    identical under any batch split, enabling exact parity with dp."""
    losses = {}
    for name, mesh_cfg in (("dp", MeshConfig(dp=8)),
                           ("pp", MeshConfig(dp=4, pp=2))):
        cfg = ExperimentConfig(
            model="moe_tiny",
            model_overrides=dict(pipeline=True, pipeline_microbatches=4,
                                 n_layers=4, moe_group_size=32),
            mesh=mesh_cfg,
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
            train=TrainConfig(batch_size=16),
            data=DataConfig(seq_len=32),
        )
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data,
                                   cfg.train.batch_size, seed=0))
        batch = trainer.shard_batch(next(src))
        for _ in range(3):
            state, metrics = trainer.step(state, batch)
        m = jax.device_get(metrics)
        losses[name] = (float(m["loss"]), float(m.get("moe_aux_loss", 0.0)))
    assert abs(losses["dp"][0] - losses["pp"][0]) < 5e-3, losses
    assert losses["pp"][1] > 0.0, "aux loss must reach the metrics"
    assert abs(losses["dp"][1] - losses["pp"][1]) < 1e-4, losses
