"""Partitioned frozen-base training (training/partition.py) and QLoRA.

Round-5 verdict #1 machinery, pinned at small scale:

* The trainer with a ``trainable_mask`` differentiates ONLY the trainable
  subtree: frozen base params are bit-identical after a step, optimizer
  state covers adapters only, and the LoRA gradients match a hand-rolled
  ``jax.grad`` over the same leaves.
* grad_accum composes with partitioning (microbatched == whole-batch).
* An int8-quantized FROZEN base trains its LoRA adapters: the step runs
  (int leaves are never differentiated — impossible, not just masked) and
  the LoRA grads through the int8 base track the bf16-base grads within
  the quantization error bound — the "gradient quality" evidence behind
  the 8B QLoRA ladder row (``benchmarks/ladder.py --rows llama8b_real``).

The reference trains nothing (``/root/reference/src/worker.cc:221-231``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.training.partition import overlay, prune
from serverless_learn_tpu.training.train_step import build_trainer as _build


def build_trainer(cfg):
    return _build(cfg, mesh=make_mesh(cfg.mesh, devices=jax.devices()[:1]))


def _cfg(**model_overrides):
    return ExperimentConfig(
        model="llama_tiny",
        model_overrides=dict(lora_rank=4, **model_overrides),
        mesh=MeshConfig(dp=1),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-2),
        # donate_state=False: these tests read pre-step params after the
        # step; donation would delete their buffers.
        train=TrainConfig(batch_size=4, seed=0, donate_state=False),
        data=DataConfig(seq_len=32),
    )


def _batch(trainer, cfg):
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data,
                               cfg.train.batch_size, seed=0))
    return trainer.shard_batch(next(src))


def test_prune_overlay_roundtrip():
    tree = {"a": {"x": 1, "y": 2}, "b": {"z": 3}}
    mask = {"a": {"x": True, "y": False}, "b": {"z": False}}
    sub = prune(tree, mask)
    assert sub == {"a": {"x": 1}}
    merged = overlay(tree, {"a": {"x": 10}})
    assert merged == {"a": {"x": 10, "y": 2}, "b": {"z": 3}}
    with pytest.raises(ValueError):
        prune(tree, jax.tree_util.tree_map(lambda _: False, tree))


def test_frozen_base_is_bit_identical_and_opt_state_is_adapter_sized():
    cfg = _cfg()
    tr = build_trainer(cfg)
    state = tr.init()
    mask = tr.bundle.trainable_mask(state.params)
    base_before = jax.device_get(
        prune(state.params, jax.tree_util.tree_map(lambda m: not m, mask)))
    batch = _batch(tr, cfg)
    state2, metrics = tr.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    base_after = jax.device_get(
        prune(state2.params, jax.tree_util.tree_map(lambda m: not m, mask)))
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(base_before)[0],
            jax.tree_util.tree_flatten_with_path(base_after)[0]):
        assert pa == pb
        np.testing.assert_array_equal(a, b, err_msg=str(pa))
    # Adapters actually moved.
    lora_before = prune(state.params, mask)
    lora_after = prune(state2.params, mask)
    moved = any(
        not np.array_equal(x, y) for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(lora_before)),
            jax.tree_util.tree_leaves(jax.device_get(lora_after))))
    assert moved
    # Optimizer state elements ~ O(trainable), not O(model).
    import math

    n_opt = sum(math.prod(np.shape(l))
                for l in jax.tree_util.tree_leaves(state.opt_state))
    n_train = sum(math.prod(np.shape(l))
                  for l in jax.tree_util.tree_leaves(lora_before))
    n_model = sum(math.prod(np.shape(l))
                  for l in jax.tree_util.tree_leaves(state.params))
    assert n_opt <= 3 * n_train + 64
    assert n_opt < n_model / 10


def test_partitioned_grads_match_manual_grad():
    cfg = _cfg()
    tr = build_trainer(cfg)
    state = tr.init()
    batch = _batch(tr, cfg)
    mask = tr.bundle.trainable_mask(state.params)
    sub = prune(state.params, mask)

    def loss_of(sub_params):
        params = overlay(state.params, sub_params)
        rng = jax.random.fold_in(jax.random.PRNGKey(cfg.train.seed),
                                 state.step)
        loss, _ = tr.bundle.loss_fn(params, batch, rngs=rng, model_state={})
        return loss

    manual = jax.grad(loss_of)(sub)
    # Reproduce the trainer's gradient through one sgd step of lr=1:
    # delta = -grad for plain sgd. Use a dedicated sgd trainer to read the
    # gradient straight off the parameter delta.
    sgd_cfg = dataclasses.replace(
        cfg, optimizer=OptimizerConfig(name="sgd", learning_rate=1.0))
    tr2 = build_trainer(sgd_cfg)
    state2 = tr2.init()
    state2 = state2.replace(params=state.params)
    after, _ = tr2.step(state2, batch)
    got = jax.tree_util.tree_map(
        lambda a, b: np.asarray(b - a),
        jax.device_get(prune(after.params, mask)),
        jax.device_get(sub))
    for (pa, g), (pb, d) in zip(
            jax.tree_util.tree_flatten_with_path(jax.device_get(manual))[0],
            jax.tree_util.tree_flatten_with_path(got)[0]):
        assert pa == pb
        # bf16 compute: two differently-fused XLA graphs of the same math
        # agree to ~1e-3 absolute on grads of this scale, not bitwise.
        np.testing.assert_allclose(np.asarray(g), d, rtol=5e-2, atol=1e-3,
                                   err_msg=str(pa))


def test_grad_accum_composes_with_partitioning():
    # sgd, not adam: adam's first step is ~sign(grad) * lr, so a
    # near-zero gradient whose bf16 sign flips between the fused
    # whole-batch graph and the microbatch scan flips a whole +-lr —
    # testing the optimizer's noise amplification, not the accumulation.
    cfg1 = dataclasses.replace(
        _cfg(), optimizer=OptimizerConfig(name="sgd", learning_rate=1.0))
    cfg2 = dataclasses.replace(
        cfg1, train=TrainConfig(batch_size=4, seed=0, grad_accum=2,
                                donate_state=False))
    tr1, tr2 = build_trainer(cfg1), build_trainer(cfg2)
    s1, s2 = tr1.init(), tr2.init()
    batch = _batch(tr1, cfg1)
    a1, m1 = tr1.step(s1, batch)
    a2, m2 = tr2.step(s2, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    mask = tr1.bundle.trainable_mask(s1.params)
    for x, y in zip(
            jax.tree_util.tree_leaves(
                jax.device_get(prune(a1.params, mask))),
            jax.tree_util.tree_leaves(
                jax.device_get(prune(a2.params, mask)))):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=5e-2, atol=2e-3)


def test_int8_frozen_base_trains_lora():
    """The QLoRA configuration end-to-end at tiny scale: int8 base params
    (integer leaves in the pytree!), bf16 compute, LoRA-only training."""
    cfg = _cfg(quant="int8")
    tr = build_trainer(cfg)
    state = tr.init()
    # Give the zero-init int8 base real values: quantize a bf16-base init.
    from serverless_learn_tpu.inference.quantize import quantize_params_int8

    base_tr = build_trainer(_cfg())
    bf16_params = base_tr.init().params
    state = state.replace(params=quantize_params_int8(bf16_params))
    batch = _batch(tr, cfg)
    has_int8 = [l for l in jax.tree_util.tree_leaves(state.params)
                if l.dtype == jnp.int8]
    assert has_int8, "int8 config must store int8 kernels"
    s2, metrics = tr.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    s3, metrics = tr.step(s2, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_qlora_lora_grads_track_bf16_base_grads():
    """Gradient quality: LoRA grads through the int8 base stay within a
    few percent (relative, per-leaf norm) of the same grads through the
    bf16 base — per-channel symmetric weight-only int8's standard
    behavior, asserted rather than assumed (8B ladder row's evidence)."""
    cfg_fp = _cfg()
    tr_fp = build_trainer(cfg_fp)
    state = tr_fp.init()
    batch = _batch(tr_fp, cfg_fp)
    mask = tr_fp.bundle.trainable_mask(state.params)
    sub = prune(state.params, mask)

    def grads_with(params_full, bundle):
        def loss_of(sub_params):
            p = overlay(params_full, sub_params)
            loss, _ = bundle.loss_fn(p, batch, rngs=jax.random.PRNGKey(0),
                                     model_state={})
            return loss
        return jax.device_get(jax.grad(loss_of)(sub))

    g_fp = grads_with(state.params, tr_fp.bundle)

    from serverless_learn_tpu.inference.quantize import quantize_params_int8

    cfg_q = _cfg(quant="int8")
    tr_q = build_trainer(cfg_q)
    g_q = grads_with(quantize_params_int8(state.params), tr_q.bundle)

    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_fp)[0],
            jax.tree_util.tree_flatten_with_path(g_q)[0]):
        assert pa == pb
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = np.linalg.norm(a)
        if denom < 1e-12:
            continue
        rel = np.linalg.norm(a - b) / denom
        assert rel < 0.10, (str(pa), rel)
