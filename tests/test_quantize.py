"""Weight-only int8 inference (round 4): quantized projections, logit
error bounds, and end-to-end decode through the quant module."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.quantize import (
    QUANT_DIRS, quantize_params_int8)
from serverless_learn_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def fp_model(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def _quant_module(module):
    return type(module)(dataclasses.replace(module.cfg, quant="int8"))


def test_quantized_tree_structure(fp_model):
    module, params = fp_model
    qp = quantize_params_int8(params)
    flat = {jax.tree_util.keystr(p): l for p, l in
            jax.tree_util.tree_flatten_with_path(qp)[0]}
    n_q = sum(1 for k in flat if k.endswith("['kernel_q']"))
    # llama_tiny: 2 layers x (q,k,v,o,gate,up,down) + lm_head
    assert n_q == 2 * 7 + 1, sorted(flat)[:10]
    for k, l in flat.items():
        if k.endswith("['kernel_q']"):
            assert l.dtype == jnp.int8, k
            assert int(jnp.abs(l).max()) <= 127
        if k.endswith("['scale']"):
            assert l.dtype == jnp.float32, k
    # Norms/embeddings untouched.
    assert flat["['embedder']['embedding']"].dtype == jnp.float32
    # And the quant module's own init matches the transformed tree's
    # structure exactly (same paths, same shapes).
    qm = _quant_module(module)
    abstract = jax.eval_shape(lambda: qm.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))["params"]
    want = {jax.tree_util.keystr(p): (tuple(l.shape), l.dtype) for p, l in
            jax.tree_util.tree_flatten_with_path(abstract)[0]}
    got = {jax.tree_util.keystr(p): (tuple(l.shape), l.dtype) for p, l in
           jax.tree_util.tree_flatten_with_path(qp)[0]}
    assert got == want


def test_dequantized_kernel_error_bounded(fp_model):
    """Per-output-channel symmetric int8: |w - q*s| <= s/2 elementwise —
    the textbook bound, including the 2-contract o_proj layout."""
    _, params = fp_model
    qp = quantize_params_int8(params)
    layer = params["layer_0"]["attn"]
    qlayer = qp["layer_0"]["attn"]
    for name, nc in (("q_proj", 1), ("o_proj", 2)):
        w = np.asarray(layer[name]["kernel"], np.float32)
        q = np.asarray(qlayer[name]["kernel_q"], np.float32)
        s = np.asarray(qlayer[name]["scale"], np.float32)
        deq = q * s  # broadcast over leading contraction dims
        assert np.abs(w - deq).max() <= s.max() / 2 + 1e-7, name


def test_quant_logits_close_and_decode_runs(fp_model):
    """End to end: the quant module's logits track fp32 within the quant
    error budget, and KV-cache generation runs through the int8 path."""
    from serverless_learn_tpu.inference.generate import generate

    module, params = fp_model
    qm = _quant_module(module)
    qp = quantize_params_int8(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                module.cfg.vocab_size)
    ref = jax.device_get(module.apply({"params": params}, tokens))
    got = jax.device_get(qm.apply({"params": qp}, tokens))
    scale = np.abs(ref).max()
    rel = np.abs(got - ref).max() / scale
    assert rel < 0.05, f"relative logit error {rel}"

    out = generate(qm, qp, jnp.asarray([[5, 9, 11]], jnp.int32), 8)
    out = jax.device_get(out)
    assert out.shape == (1, 11)
    assert (out >= 0).all() and (out < module.cfg.vocab_size).all()


def test_quant_moe_experts(devices):
    """Round 5 (round 4 refused this): MoE expert tensors — the BULK of
    an MoE model's params — quantize to int8 + per-(expert, out-channel)
    scales, the quant module's logits track fp32 within the error
    budget, decode runs, and resident bytes actually halve."""
    from serverless_learn_tpu.inference.generate import generate

    bundle = get_model("moe_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    qp = quantize_params_int8(params)
    moe_q = qp["layer_0"]["moe"]
    assert moe_q["expert_gate_q"].dtype == jnp.int8
    assert moe_q["expert_down_q"].dtype == jnp.int8
    assert "router" in moe_q  # tiny, stays float
    # Dequant error bound per (expert, channel).
    w = np.asarray(params["layer_0"]["moe"]["expert_gate"], np.float32)
    q = np.asarray(moe_q["expert_gate_q"], np.float32)
    s = np.asarray(moe_q["expert_gate_scale"], np.float32)
    deq = q * s[:, None, :]
    assert np.abs(w - deq).max() <= s.max() / 2 + 1e-7

    qm = _quant_module(module)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                module.cfg.vocab_size)
    ref = jax.device_get(module.apply({"params": params}, tokens))
    got = jax.device_get(qm.apply({"params": qp}, tokens))
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.05, f"relative logit error {rel}"

    out = jax.device_get(generate(qm, qp,
                                  jnp.asarray([[5, 9, 11]], jnp.int32), 6))
    assert out.shape == (1, 9)

    def nbytes(tree):
        return sum(l.size * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(tree))

    # f32 baseline -> int8 should cut well below 40% of the original
    # (experts + projections are ~all the params at this shape).
    assert nbytes(qp) < 0.4 * nbytes(params), \
        (nbytes(qp), nbytes(params))


def test_quant_leaves_carry_sharding_rules(fp_model):
    """A quantized tree must shard like its float twin on a serving mesh
    (the capacity story depends on it): kernel_q leaves pick up the same
    fsdp/tp specs as kernel; scales replicate."""
    import jax.numpy as _  # noqa: F401

    from serverless_learn_tpu.config import MeshConfig
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.parallel.sharding import specs_for_tree

    module, params = fp_model
    qp = quantize_params_int8(params)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    fspecs = {jax.tree_util.keystr(p): s for p, s in
              jax.tree_util.tree_flatten_with_path(
                  specs_for_tree(params, mesh))[0]}
    qspecs = {jax.tree_util.keystr(p): s for p, s in
              jax.tree_util.tree_flatten_with_path(
                  specs_for_tree(qp, mesh))[0]}
    checked = 0
    for k, spec in qspecs.items():
        if k.endswith("['kernel_q']"):
            twin = k.replace("['kernel_q']", "['kernel']")
            assert qspecs[k] == fspecs[twin], (k, spec, fspecs[twin])
            assert tuple(spec), f"{k} fell to replicated default"
            checked += 1
        if k.endswith("['scale']"):
            assert tuple(spec) == (), (k, spec)
    assert checked >= 15


def test_quant_dirs_cover_proj_sites(fp_model):
    """Every float projection kernel in the tree is covered by QUANT_DIRS
    (a new projection name must be added deliberately, not silently left
    unquantized)."""
    _, params = fp_model
    flat = {jax.tree_util.keystr(p) for p, _ in
            jax.tree_util.tree_flatten_with_path(params)[0]}
    for k in flat:
        if "['kernel']" not in k:
            continue
        mod_dir = k.split("[")[-2].strip("]'")
        assert mod_dir in QUANT_DIRS | {"lora_a", "lora_b"}, k
