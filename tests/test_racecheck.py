"""Tier-1 tests for the runtime happens-before race detector
(serverless_learn_tpu/analysis/racecheck.py) and the `slt race` replay.

The unit tests drive the vector-clock monitor synthetically (explicit
thread handles, like the offline replay does) so they are deterministic
by construction; one integration test exercises the real
install()-patched primitives end to end and is skipped when the session
itself runs under SLT_RACECHECK=1 (the global monitor then belongs to
the session, not to this test).
"""

import json
import threading
import time

import pytest

from serverless_learn_tpu.analysis import racecheck
from serverless_learn_tpu.analysis.racecheck import RaceMonitor


def _w(mon, tid, var="Obj.v", obj="o1"):
    st = mon.thread_state(tid)
    cls, _, attr = var.rpartition(".")
    mon.record_access((obj, attr), cls, attr, st, is_write=True,
                      stack=[f"{tid}.py:1 in w"], thread_name=tid)


def _r(mon, tid, var="Obj.v", obj="o1"):
    st = mon.thread_state(tid)
    cls, _, attr = var.rpartition(".")
    mon.record_access((obj, attr), cls, attr, st, is_write=False,
                      stack=[f"{tid}.py:2 in r"], thread_name=tid)


# -- vector-clock core -------------------------------------------------------

def test_unordered_write_write_is_a_race():
    mon = RaceMonitor("unit")
    _w(mon, "t1")
    _w(mon, "t2")
    races = mon.races()
    assert len(races) == 1
    r = races[0]
    assert r["kind"] == "write/write"
    assert r["class"] == "Obj" and r["attr"] == "v"
    assert r["first"]["stack"] and r["second"]["stack"]


def test_lock_edge_orders_the_writes():
    mon = RaceMonitor("unit")
    st1, st2 = mon.thread_state("t1"), mon.thread_state("t2")
    mon.acquire_from("lock:L", st1)
    _w(mon, "t1")
    mon.publish("lock:L", st1)
    mon.acquire_from("lock:L", st2)   # joins t1's release clock
    _w(mon, "t2")
    mon.publish("lock:L", st2)
    assert mon.races() == []


def test_unordered_read_write_is_a_race():
    mon = RaceMonitor("unit")
    _w(mon, "t1")
    # Order the second thread AFTER the write via a channel, then read —
    # clean; a third thread's unordered read against a later write races.
    st1 = mon.thread_state("t1")
    mon.publish("q", st1)
    st2 = mon.thread_state("t2")
    mon.acquire_from("q", st2)
    _r(mon, "t2")
    assert mon.races() == []
    _w(mon, "t3")                      # unordered vs t2's read
    races = mon.races()
    assert len(races) >= 1
    assert any(r["kind"] in ("read/write", "write/write") for r in races)


def test_distinct_objects_do_not_conflate():
    mon = RaceMonitor("unit")
    _w(mon, "t1", obj="o1")
    _w(mon, "t2", obj="o2")            # different creation identity
    assert mon.races() == []


def test_allowlist_suppresses_with_justification():
    mon = RaceMonitor("unit")
    _w(mon, "t1", var="PrefixTrie.hits")
    _w(mon, "t2", var="PrefixTrie.hits")
    assert mon.races() == []           # allowlisted by default
    allowed = mon.races(include_allowlisted=True)
    assert len(allowed) == 1 and allowed[0]["allowlisted"]
    assert ("PrefixTrie", "hits") in racecheck.ALLOWLIST


# -- event log + offline replay (slt race) -----------------------------------

def test_replay_log_reproduces_the_race(tmp_path):
    log = tmp_path / "access.jsonl"
    mon = RaceMonitor("rec", log_path=str(log))
    _w(mon, "t1")
    _w(mon, "t2")
    mon.close_log()
    assert len(mon.races()) == 1

    replayed = racecheck.replay_log(str(log))
    races = replayed.races()
    assert len(races) == 1
    assert races[0]["class"] == "Obj" and races[0]["attr"] == "v"


def test_replay_log_clean_run_is_clean(tmp_path):
    log = tmp_path / "access.jsonl"
    recs = [
        {"op": "acquire", "ch": "lock:L", "t": "t1"},
        {"op": "write", "var": "Obj.v", "obj": "o1", "t": "t1",
         "stack": ["a.py:1 in w"]},
        {"op": "publish", "ch": "lock:L", "t": "t1"},
        {"op": "acquire", "ch": "lock:L", "t": "t2"},
        {"op": "write", "var": "Obj.v", "obj": "o1", "t": "t2",
         "stack": ["a.py:2 in w"]},
        {"op": "publish", "ch": "lock:L", "t": "t2"},
        {"malformed": True},           # unknown shapes are skipped
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert racecheck.replay_log(str(log)).races() == []


def test_cli_race_replay(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(json.dumps(r) + "\n" for r in [
        {"op": "write", "var": "Foo.x", "obj": "o1", "t": "t1",
         "stack": ["a.py:1 in w1"]},
        {"op": "write", "var": "Foo.x", "obj": "o1", "t": "t2",
         "stack": ["a.py:9 in w2"]},
    ]))
    rc = main(["race", str(bad), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 2 and out["ok"] is False and len(out["races"]) == 1

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(
        {"op": "write", "var": "Foo.x", "obj": "o1", "t": "t1",
         "stack": []}) + "\n")
    assert main(["race", str(good)]) == 0


# -- live instrumentation ----------------------------------------------------

@pytest.mark.skipif(racecheck.enabled_by_env() or racecheck.installed(),
                    reason="session-global monitor belongs to the session")
def test_install_catches_seeded_unguarded_write_and_respects_locks():
    """End to end: install() patches Thread/queue/Event + lockcheck
    listeners; a class with two threads writing the same attribute
    lock-free races, the same writes under an instrumented lock do not."""
    mon = racecheck.install()
    mon.reset()
    try:
        from serverless_learn_tpu.analysis import lockcheck

        class Shared:
            pass

        racecheck.instrument_class(Shared, mon)

        # seeded race: two threads, no synchronization
        obj = Shared()
        obj.v = 0

        def bump():
            for _ in range(3):
                obj.v += 1
                time.sleep(0.001)

        ts = [threading.Thread(target=bump) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        races = mon.races()
        assert any(r["class"].endswith("Shared") and r["attr"] == "v"
                   for r in races), races

        # clean under a (lockcheck-instrumented) lock
        mon.reset()
        lk = lockcheck.monitor().wrap(site="test_racecheck.py:guard")
        obj2 = Shared()
        with lk:
            obj2.v = 0

        def bump_locked():
            for _ in range(3):
                with lk:
                    obj2.v += 1

        ts = [threading.Thread(target=bump_locked) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert [r for r in mon.races()
                if r["class"].endswith("Shared")] == [], mon.report()
    finally:
        mon.reset()
        racecheck.uninstall()


@pytest.mark.skipif(racecheck.enabled_by_env() or racecheck.installed(),
                    reason="session-global monitor belongs to the session")
def test_install_queue_handoff_is_an_edge():
    """Producer writes, consumer reads after q.get(): the put/get pair
    publishes the producer's clock, so the pair is ordered — no race."""
    import queue

    mon = racecheck.install()
    mon.reset()
    try:
        class Box:
            pass

        racecheck.instrument_class(Box, mon)
        q = queue.Queue()

        def produce():
            b = Box()
            b.payload = 42
            q.put(b)

        got = []

        def consume():
            b = q.get()
            got.append(b.payload)
            b.payload = 43         # ordered after the producer's write

        t1 = threading.Thread(target=produce)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=consume)
        t2.start()
        t2.join()
        assert got == [42]
        assert [r for r in mon.races()
                if r["class"].endswith("Box")] == [], mon.report()
    finally:
        mon.reset()
        racecheck.uninstall()
