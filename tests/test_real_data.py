"""Real dataset ingestion (VERDICT round 1 item 3): raw-format parsers
(MNIST IDX, CIFAR-10 binary, token corpora), host-pipeline transforms
(decode/augment/dynamic-MLM), and the end-to-end path: raw bytes on disk ->
published shards on the data plane -> streamed, transformed batches ->
rising eval accuracy.

No egress from this machine, so tests synthesize format-exact files; the
parsers implement the published IDX / CIFAR binary layouts byte for byte.
"""

import gzip
import os
import socket
import struct
import tempfile

import numpy as np
import pytest

from serverless_learn_tpu.data import raw
from serverless_learn_tpu.data.transforms import (
    image_transform, mlm_transform, lm_transform)


def _write_idx(path, arr, gz=False):
    hdr = bytes([0, 0, 0x08, arr.ndim]) + b"".join(
        struct.pack(">I", s) for s in arr.shape)
    data = hdr + arr.tobytes()
    if gz:
        with gzip.open(path + ".gz", "wb") as f:
            f.write(data)
    else:
        with open(path, "wb") as f:
            f.write(data)


def _write_cifar(dirpath, images, labels, files=1):
    os.makedirs(dirpath, exist_ok=True)
    recs = np.concatenate(
        [labels[:, None].astype(np.uint8),
         images.transpose(0, 3, 1, 2).reshape(len(images), -1)],
        axis=1).astype(np.uint8)
    per = len(recs) // files
    for i in range(files):
        with open(os.path.join(dirpath, f"data_batch_{i + 1}.bin"),
                  "wb") as f:
            f.write(recs[i * per:(i + 1) * per].tobytes())


# -- parsers -----------------------------------------------------------------


def test_idx_roundtrip_including_gzip(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (40, 28, 28), dtype=np.uint8)
    labs = rng.integers(0, 10, 40, dtype=np.uint8)
    _write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs, gz=True)
    _write_idx(str(tmp_path / "train-labels-idx1-ubyte"), labs)
    m = raw.load_mnist(str(tmp_path), "train")
    assert m["image"].shape == (40, 28, 28, 1)
    assert m["image"].dtype == np.uint8 and m["label"].dtype == np.int32
    np.testing.assert_array_equal(m["image"][..., 0], imgs)
    np.testing.assert_array_equal(m["label"], labs)


def test_idx_rejects_corrupt_headers(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\x01\x00\x08\x01" + b"\x00" * 8)
    with pytest.raises(ValueError, match="magic"):
        raw.load_idx(p)
    with open(p, "wb") as f:  # dims promise more payload than present
        f.write(bytes([0, 0, 0x08, 1]) + struct.pack(">I", 100) + b"\x00" * 10)
    with pytest.raises(ValueError, match="payload"):
        raw.load_idx(p)


def test_cifar10_binary_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (30, 32, 32, 3), dtype=np.uint8)
    labs = rng.integers(0, 10, 30).astype(np.uint8)
    _write_cifar(str(tmp_path / "cifar-10-batches-bin"), imgs, labs, files=2)
    c = raw.load_cifar10(str(tmp_path), "train")
    np.testing.assert_array_equal(c["image"], imgs)
    np.testing.assert_array_equal(c["label"], labs.astype(np.int32))


def test_token_corpus_text_and_bin(tmp_path):
    text = b"hello world, a tiny corpus." * 50
    p = str(tmp_path / "corpus.txt")
    with open(p, "wb") as f:
        f.write(text)
    t = raw.load_token_corpus(p, seq_len=32)
    assert t["input_ids"].shape[1] == 32
    assert (t["input_ids"][:, 0] == raw.BOS_ID).all()
    assert raw.detokenize_bytes(t["input_ids"][0]).startswith(b"hello world")

    ids = np.arange(1000, dtype=np.uint16) % 500
    pb = str(tmp_path / "corpus.bin")
    with open(pb, "wb") as f:
        f.write(ids.tobytes())
    tb = raw.load_token_corpus(pb, seq_len=101)
    assert tb["input_ids"].shape == (10, 101)
    np.testing.assert_array_equal(tb["input_ids"][0, 1:],
                                  ids[:100].astype(np.int32))

    # a gzipped token dump must NOT fall into the byte-level text branch
    pz = str(tmp_path / "corpus.bin.gz")
    with gzip.open(pz, "wb") as f:
        f.write(ids.tobytes())
    tz = raw.load_token_corpus(pz, seq_len=101)
    np.testing.assert_array_equal(tz["input_ids"], tb["input_ids"])


# -- transforms --------------------------------------------------------------


def test_image_transform_eval_is_pure_decode():
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (16, 32, 32, 3), dtype=np.uint8)
    out = image_transform(train=False)({"image": imgs, "label": imgs[:, 0, 0, 0]})
    assert out["image"].dtype == np.float32
    np.testing.assert_allclose(out["image"], imgs / np.float32(255))


def test_image_transform_train_augments():
    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 256, (64, 32, 32, 3), dtype=np.uint8)
    base = image_transform(train=False)({"image": imgs})["image"]
    aug = image_transform(train=True, seed=7)({"image": imgs})["image"]
    assert aug.shape == base.shape
    assert not np.allclose(aug, base), "crop/flip must move pixels"
    # Each augmented image is a crop of the padded original: every pixel
    # value must already exist in the source image or be pad-zero.
    assert aug.max() <= 1.0 and aug.min() >= 0.0


def test_mlm_transform_dynamic_masking():
    rng = np.random.default_rng(4)
    ids = rng.integers(raw.BYTE_OFFSET, 260, (16, 48)).astype(np.int32)
    ids[:, -6:] = 0  # padding
    fn = mlm_transform(vocab_size=260, mask_rate=0.15, seed=5)
    b = fn({"input_ids": ids})
    assert set(b) == {"tokens", "labels", "mlm_mask", "attn_mask"}
    np.testing.assert_array_equal(b["labels"], ids)
    assert (b["mlm_mask"][:, -6:] == 0).all(), "pads never selected"
    assert (b["attn_mask"] == (ids != 0)).all()
    frac = b["mlm_mask"][:, :-6].mean()
    assert 0.05 < frac < 0.30
    changed = b["tokens"] != b["labels"]
    assert changed.any() and (changed <= (b["mlm_mask"] == 1)).all()
    # dynamic: a second pass masks differently
    b2 = fn({"input_ids": ids})
    assert (b2["mlm_mask"] != b["mlm_mask"]).any()


def test_lm_transform_renames():
    ids = np.arange(12, dtype=np.int32).reshape(2, 6)
    out = lm_transform()({"input_ids": ids})
    assert list(out) == ["tokens"]
    np.testing.assert_array_equal(out["tokens"], ids)


# -- end to end through the data plane ---------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_cifar_bytes_to_rising_accuracy(tmp_path, devices):
    """Raw CIFAR binary on disk -> publish -> augmented stream -> training
    with rising eval accuracy (the VERDICT item's 'done' bar)."""
    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.control.daemons import start_shard_server
    from serverless_learn_tpu.data.shard_client import publish_dataset
    from serverless_learn_tpu.training.loop import run_eval, run_training
    from serverless_learn_tpu.training.train_step import build_trainer

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    addr = f"127.0.0.1:{port}"
    try:
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (2048, 32, 32, 3), dtype=np.uint8)
        proj = np.random.default_rng(7).standard_normal(
            (3072, 10)).astype(np.float32)
        labs = np.argmax((imgs.reshape(2048, -1) / 255.0) @ proj,
                         axis=1).astype(np.uint8)
        _write_cifar(str(tmp_path / "cifar-10-batches-bin"), imgs, labs)
        arrays = raw.load_cifar10(str(tmp_path), "train")
        publish_dataset(addr, "cifar", arrays, records_per_shard=512)

        cfg = ExperimentConfig(
            model="mlp_mnist",
            model_overrides={"image_shape": [32, 32, 3], "features": [256],
                             "num_classes": 10},
            mesh=MeshConfig(dp=8),
            optimizer=OptimizerConfig(name="adamw", learning_rate=3e-3),
            train=TrainConfig(batch_size=256, num_steps=25, dtype="float32",
                              param_dtype="float32"),
            data=DataConfig(dataset="cifar", shard_server_addr=addr,
                            augment=True))
        trainer = build_trainer(cfg)
        state0 = trainer.init()
        ev0 = run_eval(cfg, trainer, state0, num_batches=4)
        state, _ = run_training(cfg, trainer=trainer, state=state0)
        ev = run_eval(cfg, trainer, state, num_batches=4)
        assert ev["eval_accuracy"] > max(0.3, 2 * ev0["eval_accuracy"]), \
            (ev0, ev)
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_corpus_to_bert_mlm_training(tmp_path, devices):
    """Raw text -> byte-level token shards -> dynamic-MLM batches feeding a
    BERT trainer; loss decreases on the highly regular corpus."""
    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.control.daemons import start_shard_server
    from serverless_learn_tpu.data.shard_client import publish_dataset
    from serverless_learn_tpu.training.loop import make_source
    from serverless_learn_tpu.training.train_step import build_trainer

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    addr = f"127.0.0.1:{port}"
    try:
        p = str(tmp_path / "corpus.txt")
        with open(p, "wb") as f:
            f.write(b"the quick brown fox jumps over the lazy dog. " * 2000)
        toks = raw.load_token_corpus(p, seq_len=64)
        publish_dataset(addr, "corpus", toks, records_per_shard=256)

        cfg = ExperimentConfig(
            model="bert_tiny",
            model_overrides={"vocab_size": 260, "max_seq_len": 64},
            mesh=MeshConfig(dp=8),
            optimizer=OptimizerConfig(name="adamw", learning_rate=2e-3),
            train=TrainConfig(batch_size=32, num_steps=12, dtype="float32",
                              param_dtype="float32"),
            data=DataConfig(dataset="corpus", shard_server_addr=addr,
                            seq_len=64))
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = iter(make_source(cfg, trainer))
        losses = []
        for _ in range(12):
            state, m = trainer.step(state, trainer.shard_batch(next(src)))
            losses.append(float(jax.device_get(m["loss"])))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses
    finally:
        proc.terminate()
        proc.wait(timeout=5)
