"""Crash-safe training state (round 15): verified checkpoints with
quarantine + fallback-to-last-good, emergency save on the death path,
peer state replication, and the `slt chaos recover` RPO/RTO harness.

The corrupt-restore matrix (truncated blob, bit-flipped payload, missing
LATEST, stale LATEST at a deleted step) asserts the typed-error +
fallback contract in every case; the RecoveryRun acceptance drives the
REAL checkpoint stack through kills mid-run and mid-save and proves the
bound, with `slt doctor` naming every incident from telemetry alone.
"""

import json
import os
import threading

import numpy as np
import pytest

from serverless_learn_tpu.chaos.plan import FaultPlan
from serverless_learn_tpu.chaos.recover import RecoveryRun, default_plan
from serverless_learn_tpu.telemetry import flight, get_registry
from serverless_learn_tpu.training.checkpoint import (
    Checkpointer, CheckpointCorrupt, LocalStore, ShardServerStore)
from serverless_learn_tpu.training.replicate import (ReplicatedStore,
                                                     maybe_replicated)


def _state(step: int, n: int = 16) -> dict:
    return {"step": np.asarray(step, np.int64),
            "w": np.arange(n, dtype=np.float32) + np.float32(step)}


def _template(n: int = 16) -> dict:
    return {"step": np.asarray(0, np.int64),
            "w": np.zeros(n, np.float32)}


def _blob_path(root, name, step):
    return os.path.join(str(root), name, f"step-{step:010d}")


def _flip_byte(path, offset=None):
    size = os.path.getsize(path)
    off = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


def _ckpt(root, **kw):
    kw.setdefault("async_save", False)
    kw.setdefault("name", "t")
    return Checkpointer(LocalStore(str(root)), **kw)


# -- corrupt-restore matrix: typed error + fallback-to-last-good -------------


def test_truncated_blob_falls_back_and_quarantines(tmp_path):
    ck = _ckpt(tmp_path)
    ck.save(_state(1), step=1)
    ck.save(_state(2), step=2)
    path = _blob_path(tmp_path, "t", 2)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    fb0 = ck._m_fallbacks.value
    restored = ck.restore_host(_template())
    assert int(restored["step"]) == 1, "must fall back to last good step"
    np.testing.assert_array_equal(restored["w"], _state(1)["w"])
    assert ck._m_fallbacks.value == fb0 + 1
    # step 2 is quarantined: marked, out of the candidate list, and the
    # payload kept in place for forensics.
    assert os.path.isfile(path + ".CORRUPT")
    assert ck.candidate_steps() == [1]
    assert os.path.isfile(path)


def test_bitflipped_blob_falls_back(tmp_path):
    ck = _ckpt(tmp_path)
    ck.save(_state(3), step=3)
    ck.save(_state(4), step=4)
    _flip_byte(_blob_path(tmp_path, "t", 4))
    restored = ck.restore_host(_template())
    assert int(restored["step"]) == 3
    assert ck.candidate_steps() == [3]


def test_missing_latest_listing_wins(tmp_path):
    ck = _ckpt(tmp_path)
    ck.save(_state(1), step=1)
    ck.save(_state(2), step=2)
    LocalStore(str(tmp_path)).delete("t/LATEST")
    assert ck.latest_step() == 2
    assert int(ck.restore_host(_template())["step"]) == 2


def test_stale_latest_pointing_at_deleted_step(tmp_path):
    ck = _ckpt(tmp_path)
    ck.save(_state(5), step=5)
    store = LocalStore(str(tmp_path))
    store.put("t/LATEST", json.dumps({"step": 99}).encode())
    assert ck.latest_step() == 5, "stale pointer must not hide real steps"
    assert int(ck.restore_host(_template())["step"]) == 5
    # ... and an unreadable pointer degrades the same way
    store.put("t/LATEST", b"\x00not json")
    assert ck.latest_step() == 5


def test_explicit_restore_of_corrupt_step_raises(tmp_path):
    ck = _ckpt(tmp_path)
    ck.save(_state(1), step=1)
    ck.save(_state(2), step=2)
    _flip_byte(_blob_path(tmp_path, "t", 2))
    with pytest.raises(CheckpointCorrupt) as ei:
        ck.restore_host(_template(), step=2)
    assert ei.value.step == 2
    # no silent substitution: step 1 was NOT quarantine-scanned or loaded
    assert not os.path.isfile(_blob_path(tmp_path, "t", 2) + ".CORRUPT")


def test_every_copy_corrupt_raises_never_loads_garbage(tmp_path):
    ck = _ckpt(tmp_path)
    for s in (1, 2):
        ck.save(_state(s), step=s)
        _flip_byte(_blob_path(tmp_path, "t", s))
    with pytest.raises(CheckpointCorrupt):
        ck.restore_host(_template())


def test_gc_never_collects_last_verified_step(tmp_path):
    ck = _ckpt(tmp_path, keep=1)
    ck.save(_state(1), step=1)
    assert int(ck.restore_host(_template())["step"]) == 1  # verified
    ck.save(_state(2), step=2)  # keep=1 would normally GC step 1
    assert 1 in ck.candidate_steps(), "last verified step must survive GC"
    _flip_byte(_blob_path(tmp_path, "t", 2))
    assert int(ck.restore_host(_template())["step"]) == 1


def test_sharded_chunk_corruption_detected(tmp_path):
    ck = _ckpt(tmp_path, sharded=True)
    ck.save_sharded(_state(1), step=1, barrier=lambda tag: None)
    ck.save_sharded(_state(2), step=2, barrier=lambda tag: None)
    dat = os.path.join(str(tmp_path), "t", "step-0000000002",
                       "proc-00000.dat")
    _flip_byte(dat, offset=os.path.getsize(dat) - 4)  # inside "w"'s chunk
    with pytest.raises(CheckpointCorrupt):
        ck.restore_host(_template(), step=2)
    assert int(ck.restore_host(_template())["step"]) == 1
    # truncation of the .dat is caught by the size-stamped index too
    with open(dat, "r+b") as f:
        f.truncate(os.path.getsize(dat) // 2)
    with pytest.raises(CheckpointCorrupt):
        ck.restore_host(_template(), step=2)


# -- satellites: tmp sweep, atexit drain, exists semantics -------------------


def test_localstore_sweeps_orphan_tmp_from_dead_writers(tmp_path):
    os.makedirs(str(tmp_path / "t"))
    dead = str(tmp_path / "t" / "step-0000000001.tmp.99999999")
    live = str(tmp_path / "t" / f"step-0000000002.tmp.{os.getpid()}")
    for p in (dead, live):
        with open(p, "wb") as f:
            f.write(b"partial")
    LocalStore(str(tmp_path))
    assert not os.path.exists(dead), "dead writer's tmp debris must go"
    assert os.path.exists(live), "a live writer's in-flight tmp must stay"
    os.remove(live)


def test_close_drains_pending_async_commit(tmp_path):
    gate = threading.Event()

    class GatedStore(LocalStore):
        def put(self, key, data):
            gate.wait(timeout=10.0)
            super().put(key, data)

    store = GatedStore(str(tmp_path))
    ck = Checkpointer(store, name="t", async_save=True)
    ck.save(_state(1), step=1)
    assert not store.exists("t/LATEST"), "upload still gated"
    assert ck._atexit_armed, "async save must arm the atexit drain"
    gate.set()
    ck.close()  # the same drain the atexit hook runs
    assert store.exists("t/LATEST")
    assert ck.latest_step() == 1
    assert not ck._atexit_armed


def test_shard_store_exists_distinguishes_unreachable(tmp_path):
    from serverless_learn_tpu.control.client import KeyNotFound

    store = ShardServerStore.__new__(ShardServerStore)

    class _Absent:
        def size_of(self, key):
            raise KeyNotFound(f"unknown key {key!r}")

    class _Partitioned:
        def size_of(self, key):
            raise ConnectionError("store unreachable")

    store.client = _Absent()
    assert store.exists("t/step-0000000001") is False
    store.client = _Partitioned()
    with pytest.raises(ConnectionError):
        store.exists("t/step-0000000001")


# -- emergency save on the flight recorder's death path ----------------------


def test_emergency_save_on_death_path(tmp_path):
    from serverless_learn_tpu.training.train_state import TrainState

    ck = _ckpt(tmp_path / "store", name="emg")
    state = TrainState(step=np.asarray(7, np.int64),
                       params={"w": np.arange(4, dtype=np.float32)},
                       opt_state={}, model_state={})
    ck.arm_emergency(lambda: state, min_interval_s=60.0)
    os.makedirs(str(tmp_path / "flight"))
    try:
        e0 = ck._m_emergency.value
        path = flight.dump("test-sigterm", dir=str(tmp_path / "flight"))
        assert path is not None
        assert ck.latest_step() == 7
        assert ck._m_emergency.value == e0 + 1
        man = json.loads(LocalStore(str(tmp_path / "store")).get(
            "emg/step-0000000007.manifest"))
        assert man["emergency"] == "emergency:test-sigterm"
        with open(path) as f:
            payload = json.load(f)
        assert payload["death_hooks"]["ckpt:emg"]["step"] == 7
        # rate limit: a crash loop must not write-amplify the store
        path2 = flight.dump("test-sigterm-again",
                            dir=str(tmp_path / "flight"))
        with open(path2) as f:
            payload2 = json.load(f)
        assert payload2["death_hooks"]["ckpt:emg"] == {
            "skipped": "rate-limited"}
        assert ck._m_emergency.value == e0 + 1
        # the emergency commit is a verified, restorable checkpoint
        restored = ck.restore_host(TrainState(
            step=np.asarray(0, np.int64),
            params={"w": np.zeros(4, np.float32)},
            opt_state={}, model_state={}))
        np.testing.assert_array_equal(restored.params["w"],
                                      np.arange(4, dtype=np.float32))
    finally:
        ck.close()  # disarms the hook
    path3 = flight.dump("after-disarm", dir=str(tmp_path / "flight"))
    with open(path3) as f:
        assert "ckpt:emg" not in json.load(f).get("death_hooks", {})


def test_emergency_shadow_survives_donated_state(tmp_path):
    """The training step DONATES the previous state's buffers, so by
    death time a live state reference dereferences freed memory (found
    by a real SIGTERM drill). note_state's host shadow is what the death
    hook commits; an explicit state_fn whose state died falls back to
    the same shadow."""
    from serverless_learn_tpu.training.train_state import TrainState

    def _ts(step):
        return TrainState(step=np.asarray(step, np.int64),
                          params={"w": np.arange(4, dtype=np.float32)
                                  + np.float32(step)},
                          opt_state={}, model_state={})

    os.makedirs(str(tmp_path / "flight"))
    ck = _ckpt(tmp_path / "store", name="shadow")
    ck.note_state(_ts(3))
    assert ck._emg_shadow is None, "unarmed note_state must be free"
    ck.arm_emergency(min_interval_s=0.0)
    try:
        ck.note_state(_ts(5))  # the training thread's boundary shadow
        path = flight.dump("sigterm", dir=str(tmp_path / "flight"))
        with open(path) as f:
            assert json.load(f)["death_hooks"]["ckpt:shadow"]["step"] == 5
        assert ck.latest_step() == 5
    finally:
        ck.close()
    # state_fn raising like a donated jax.Array → shadow fallback
    ck2 = _ckpt(tmp_path / "store2", name="shadow")

    def donated():
        raise RuntimeError("Array has been deleted with shape=int32[].")

    ck2.arm_emergency(donated, min_interval_s=0.0)
    try:
        ck2._emg_shadow, ck2._emg_shadow_step = _ts(7), 7
        path = flight.dump("sigterm-donated", dir=str(tmp_path / "flight"))
        with open(path) as f:
            assert json.load(f)["death_hooks"]["ckpt:shadow"]["step"] == 7
        assert ck2.latest_step() == 7
    finally:
        ck2.close()


# -- peer state replication --------------------------------------------------


class _CountingStore:
    """Delegating store that records get/get_range keys."""

    def __init__(self, inner):
        self.inner = inner
        self.reads = []

    def put(self, key, data):
        self.inner.put(key, data)

    def get(self, key):
        self.reads.append(key)
        return self.inner.get(key)

    def get_range(self, key, offset, length):
        self.reads.append(key)
        return self.inner.get_range(key, offset, length)

    def exists(self, key):
        return self.inner.exists(key)

    def list(self, prefix):
        return self.inner.list(prefix)

    def delete(self, key):
        self.inner.delete(key)


class _DownStore:
    """Every op fails like a partitioned shard server."""

    def _down(self, *a, **k):
        raise ConnectionError("primary partitioned (injected)")

    put = get = get_range = exists = list = delete = _down


def test_cache_serves_restore_without_primary_reads(tmp_path):
    primary = _CountingStore(LocalStore(str(tmp_path / "store")))
    rs = ReplicatedStore(primary, cache=LocalStore(str(tmp_path / "cache")))
    ck = Checkpointer(rs, name="t", async_save=False)
    ck.save(_state(1), step=1)
    peer0 = ck._m_peer_restores.value
    primary.reads.clear()
    restored = ck.restore_host(_template())
    assert int(restored["step"]) == 1
    # the remesh pattern — "re-read the state I just committed" — must be
    # a local read: no blob/manifest bytes moved from the central store
    assert primary.reads == []
    assert ck._m_peer_restores.value == peer0 + 1
    rs.close()


def test_intact_primary_heals_corrupt_cache_copy(tmp_path):
    rs = ReplicatedStore(_CountingStore(LocalStore(str(tmp_path / "store"))),
                         cache=LocalStore(str(tmp_path / "cache")))
    ck = Checkpointer(rs, name="t", async_save=False)
    ck.save(_state(1), step=1)
    _flip_byte(_blob_path(tmp_path / "cache", "t", 1))
    c0, fb0 = ck._m_corrupt.value, ck._m_fallbacks.value
    restored = ck.restore_host(_template())
    np.testing.assert_array_equal(restored["w"], _state(1)["w"])
    assert ck._m_corrupt.value == c0 + 1, "cache corruption detected"
    assert ck._m_fallbacks.value == fb0, "healed in-step, no fallback"
    assert not os.path.isfile(
        _blob_path(tmp_path / "store", "t", 1) + ".CORRUPT"), \
        "a step healed by a replica must not be quarantined"
    rs.close()


def test_peer_replica_survives_partitioned_primary(tmp_path):
    # Commit through a healthy tier with one peer...
    peer = LocalStore(str(tmp_path / "peer"))
    rs = ReplicatedStore(LocalStore(str(tmp_path / "store")),
                         peers=[peer], fanout=1)
    ck = Checkpointer(rs, name="t", async_save=False)
    ck.save(_state(1), step=1)
    ck.save(_state(2), step=2)
    assert rs.flush(), "peer pushes must drain"
    rs.close()
    # ... then rejoin with the central store down: the peer carries it.
    rs2 = ReplicatedStore(_DownStore(), peers=[peer], fanout=1)
    ck2 = Checkpointer(rs2, name="t", async_save=False)
    restored = ck2.restore_host(_template())
    assert int(restored["step"]) == 2
    rs2.close()


def test_latest_vote_when_primary_partitioned(tmp_path):
    stale = LocalStore(str(tmp_path / "a"))
    stale.put("t/LATEST", json.dumps({"step": 1}).encode())
    fresh = LocalStore(str(tmp_path / "b"))
    fresh.put("t/LATEST", json.dumps({"step": 3}).encode())
    rs = ReplicatedStore(_DownStore(), peers=[stale, fresh])
    assert json.loads(rs.get("t/LATEST"))["step"] == 3, \
        "a lagging peer must not roll the run back"
    rs.close()


def test_maybe_replicated_identity_without_config(tmp_path):
    from serverless_learn_tpu.config import CheckpointConfig

    store = LocalStore(str(tmp_path))
    assert maybe_replicated(store, None) is store
    assert maybe_replicated(store, CheckpointConfig()) is store
    wrapped = maybe_replicated(
        store, CheckpointConfig(cache_dir=str(tmp_path / "cache")))
    assert isinstance(wrapped, ReplicatedStore)
    wrapped.close()


# -- `slt chaos recover`: the RPO/RTO acceptance -----------------------------


def test_recover_default_plan_acceptance(tmp_path):
    from serverless_learn_tpu.telemetry.doctor import diagnose

    log = str(tmp_path / "events.jsonl")
    reg = get_registry()
    inc0 = reg.counter("slt_recovery_incidents_total").value
    rep = RecoveryRun(seed=0, events_log=log).run()
    assert rep["ok"], rep["violations"]
    causes = {i["cause"] for i in rep["incidents"]}
    assert "kill" in causes and "kill-midsave" in causes
    for i in rep["incidents"]:
        assert i["rpo_steps"] <= i["rpo_bound_steps"]
        assert i["rto_s"] > 0
    assert rep["orphan_tmp_swept"] >= 1, \
        "the mid-save death must strand (and the reboot sweep) a .tmp"
    assert reg.counter("slt_recovery_incidents_total").value \
        == inc0 + len(rep["incidents"])
    # doctor names every incident — cause, RPO vs bound, corruption —
    # from the events log alone
    verdict = diagnose(paths=[log])["summary"]["verdict"]
    assert f"{len(rep['incidents'])} training recovery incident(s)" in verdict
    assert "kill-midsave" in verdict
    assert "within the checkpoint-interval bound" in verdict
    assert "checkpoint corruption detected" in verdict


def test_recover_corrupt_everywhere_quarantines_and_falls_back(tmp_path):
    plan = FaultPlan.from_obj({"faults": [
        {"at": 2.55, "op": "corrupt", "scope": "everywhere"},
        {"at": 2.6, "op": "kill", "node": "worker"},
        {"at": 3.0, "op": "restart", "node": "worker"},
    ]})
    rep = RecoveryRun(seed=1, steps=120, checkpoint_every=10,
                      plan=plan).run()
    assert rep["ok"], rep["violations"]
    (incident,) = rep["incidents"]
    assert incident["corruption_detected"]
    assert incident["quarantined_steps"] == [50]
    assert incident["restored_step"] == 40, \
        "every copy corrupt: fall back one interval, never load garbage"
    assert incident["rpo_steps"] <= 2 * 10  # widened by the quarantine


def test_recover_replays_deterministically(tmp_path):
    plan = default_plan()
    r1 = RecoveryRun(seed=7, plan=plan).run()
    r2 = RecoveryRun(seed=7, plan=default_plan()).run()
    for k in ("steps", "checkpoints_committed", "rpo_worst_steps"):
        assert r1[k] == r2[k]
    assert [i["restored_step"] for i in r1["incidents"]] \
        == [i["restored_step"] for i in r2["incidents"]]


def test_peer_cache_measurably_shrinks_restore_time(tmp_path):
    # Injected per-read latency on the CENTRAL store only (the recover
    # harness's `store_latency_s`), so the comparison measures where the
    # restore BYTES come from — not wall-clock noise: the store-only leg
    # pays >= 2 lagged reads (manifest + blob), the replica leg zero.
    plan = FaultPlan.from_obj({"faults": [
        {"at": 2.5, "op": "kill", "node": "worker"},
        {"at": 2.9, "op": "restart", "node": "worker"},
    ]})
    kw = dict(seed=2, steps=100, checkpoint_every=10,
              store_latency_s=0.03)
    r_peer = RecoveryRun(plan=plan, peer_cache=True, **kw).run()
    r_store = RecoveryRun(plan=FaultPlan.from_obj({"faults": [
        {"at": 2.5, "op": "kill", "node": "worker"},
        {"at": 2.9, "op": "restart", "node": "worker"},
    ]}), peer_cache=False, **kw).run()
    assert r_peer["ok"] and r_store["ok"]
    assert r_peer["incidents"][0]["replica_reads"] > 0, \
        "the rejoin must be served by the cache/peer tier"
    assert r_store["rto_worst_s"] > r_peer["rto_worst_s"] + 0.02, \
        (f"store-only restore ({r_store['rto_worst_s']}s) must pay the "
         f"central-store latency the replica path ({r_peer['rto_worst_s']}s) "
         f"avoids")


def test_recover_plan_validation():
    with pytest.raises(ValueError, match="scope"):
        FaultPlan.from_obj({"faults": [
            {"at": 1.0, "op": "corrupt", "scope": "bogus"}]})
    with pytest.raises(ValueError, match="scope"):
        FaultPlan.from_obj({"faults": [
            {"at": 1.0, "op": "kill", "node": "worker",
             "scope": "local"}]})
    drop_plan = FaultPlan.from_obj({"faults": [
        {"at": 1.0, "op": "drop", "rate": 0.5}]})
    with pytest.raises(ValueError, match="supports"):
        RecoveryRun(plan=drop_plan)


def test_recover_cli_smoke(capsys):
    from serverless_learn_tpu.cli import main

    rc = main(["chaos", "recover", "--smoke", "--seed", "5", "--compact"])
    out = capsys.readouterr().out
    rep = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert rep["ok"]
    assert "recovery incident" in rep["doctor_verdict"]
    assert "corruption detected" in rep["doctor_verdict"]
