"""Cross-run differential attribution (`telemetry/regress.py`,
`slt regress`, `slt bench --gate --attribute`, round 24).

Fast tier only: the decomposition engine's sum invariants on synthetic
and committed-fixture runs (hand-computed deltas — the goodput total
grows exactly 2.0s, the xray step wall exactly 18ms with 81% of it new
exposed all-reduce on dp), byte-identical reports as a drift guard
against ``tests/fixtures/regress/expected_report.json``, RunBundle
write/load round-trips, the gate's `--attribute` path naming the
planted dominant cause (and degrading to row-level / unattributable —
never crashing — over pre-bundle and pre-column history), and doctor
folding the verdicts into its diagnosis. No accelerator, no network.
"""

import json
import os
import time

import pytest

from serverless_learn_tpu.telemetry import regress
from serverless_learn_tpu.telemetry.regress import (RunBundle,
                                                    attribute_rows,
                                                    compare, config_drift,
                                                    mfu_hw_disagreements,
                                                    write_bundle)

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "regress")
FIXTURE_HISTORY = os.path.join(FIXTURE_DIR, "bench_history_regress.json")


def _fixture_bundles():
    return (RunBundle.load(os.path.join(FIXTURE_DIR, "run_a")),
            RunBundle.load(os.path.join(FIXTURE_DIR, "run_b")))


# -- decomposition invariants (synthetic) ------------------------------------


def test_goodput_decomposition_sums_exactly():
    """Run-total delta = sum of phase deltas, by construction: +2.0s =
    step +1.8 + data_wait +0.2 on the synthetic pair."""
    a, b = regress._synthetic_bundles()
    rep = compare(a, b)
    gd = next(d for d in rep["decompositions"]
              if d["headline"] == "run_total_s[n0]")
    assert gd["sums_to_delta"] is True
    assert gd["delta"] == pytest.approx(2.0)
    assert gd["terms"]["step"] == pytest.approx(1.8)
    assert gd["terms"]["data_wait"] == pytest.approx(0.2)
    assert gd["terms"]["compile"] == pytest.approx(0.0)


def test_xray_decomposition_partitions_step_wall():
    """busy+idle == wall and busy == compute+exposed+other, so the four
    terms partition the step-wall delta exactly; the verdict quotes the
    exposed share (81%) and names the grown collective's mesh axis."""
    a, b = regress._synthetic_bundles()
    rep = compare(a, b)
    xd = next(d for d in rep["decompositions"]
              if d["headline"] == "step_wall_s")
    assert xd["sums_to_delta"] is True
    assert xd["delta"] == pytest.approx(0.018)
    assert xd["terms"]["exposed_collective_s"] == pytest.approx(0.01458)
    assert xd["terms"]["compute_s"] == pytest.approx(0.0018)
    assert xd["terms"]["idle_s"] == pytest.approx(0.00162)
    dom = rep["dominant_cause"]
    assert "81% is new exposed all-reduce" in dom and "dp" in dom
    assert "zero_stage changed 1 -> 0" in dom


def test_goodput_pairs_lone_nodes_with_different_names():
    """Real runs carry pid-suffixed node names (`vm-<pid>`), so two runs
    of the same single-node job never share a name — the lone nodes pair
    anyway, with both names visible in the headline."""
    a = {"vm-100": {"total_s": 10.0, "phases": {
        "step": {"seconds": 10.0, "count": 5}}}}
    b = {"vm-200": {"total_s": 12.0, "phases": {
        "step": {"seconds": 12.0, "count": 5}}}}
    decs = regress.goodput_decomposition(a, b, 0.05)
    assert len(decs) == 1
    assert decs[0]["headline"] == "run_total_s[vm-100->vm-200]"
    assert decs[0]["sums_to_delta"] is True
    assert decs[0]["terms"]["step"] == pytest.approx(2.0)
    # Multi-node runs still join strictly by name.
    a["vm-300"] = a["vm-100"]
    assert regress.goodput_decomposition(a, b, 0.05) == []


def test_inconsistent_terms_fail_the_sum_invariant():
    """The machine check is real: terms that do NOT sum to the headline
    delta flag the decomposition and fail the report's invariant."""
    bad = regress._decomp("test", "t", 1.0, {"x": 0.2}, 0.05)
    assert bad["sums_to_delta"] is False
    assert bad["residual"] == pytest.approx(0.8)
    ok = regress._decomp("test", "t", 1.0, {"x": 0.98}, 0.05)
    assert ok["sums_to_delta"] is True


def test_report_is_deterministic_and_portable():
    """Byte-identical on identical inputs; no wall-clock stamps and no
    absolute paths in the compare output (reports must diff clean
    across checkouts and reruns)."""
    rep1 = compare(*regress._synthetic_bundles())
    rep2 = compare(*regress._synthetic_bundles())
    s1 = json.dumps(rep1, sort_keys=True)
    assert s1 == json.dumps(rep2, sort_keys=True)
    assert "created_unix_s" not in s1
    assert os.sep + "tmp" not in s1 and "/root/" not in s1


# -- the committed fixture (hand-computed) -----------------------------------


def test_fixture_report_matches_committed_expected():
    """Drift guard: the committed two-run fixture reproduces its
    expected_report.json byte-for-byte. Regenerate deliberately (and
    re-review the hand-computed numbers) if the engine changes."""
    a, b = _fixture_bundles()
    got = json.dumps(compare(a, b), indent=2, sort_keys=True) + "\n"
    with open(os.path.join(FIXTURE_DIR, "expected_report.json")) as f:
        assert got == f.read()


def test_fixture_decompositions_each_sum_to_headline():
    """Acceptance: every per-ledger decomposition over the fixture pair
    sums to its headline delta within tolerance — goodput (+2.0s run),
    xray (+18ms step), waterfall TTFT (+50ms = compile 80% + prefill
    20%), stall causes (+40ms preempt), DCN (+740kB diloco)."""
    a, b = _fixture_bundles()
    rep = compare(a, b)
    assert rep["invariants"]["ok"] is True
    assert rep["invariants"]["checked"] >= 5
    by = {d["headline"]: d for d in rep["decompositions"]}
    assert by["run_total_s[n0]"]["delta"] == pytest.approx(2.0)
    assert by["ttft_p99_s"]["terms"]["compile"] == pytest.approx(0.04)
    assert by["ttft_p99_s"]["terms"]["prefill"] == pytest.approx(0.01)
    assert by["decode_stall_total_s"]["terms"]["preempt"] == \
        pytest.approx(0.04)
    assert by["wire_bytes_total"]["terms"]["diloco"] == \
        pytest.approx(740000.0)
    # The ledger facts the verdicts quote: per-axis collective growth,
    # the roofline flip, the codec-disengaged compression collapse, and
    # the numerics bisection naming the first divergent step.
    xf = rep["facts"]["xray"]
    assert xf["per_collective_delta_s"]["all-reduce@dp"] == \
        pytest.approx(0.07)
    assert xf["roofline_verdict_flips"] == [
        {"op": "fusion.123", "a": "compute-bound", "b": "hbm-bound"}]
    dcn = rep["facts"]["dcn"]["diloco"]
    assert dcn["compression_ratio_a"] == pytest.approx(3.846154)
    assert dcn["compression_ratio_b"] == pytest.approx(1.0)
    assert rep["numerics"]["diverged"] is True
    assert rep["numerics"]["first_divergent_step"] == 2


def test_self_check_passes():
    rep = regress.self_check()
    assert rep["ok"] is True, [c for c in rep["checks"] if not c["ok"]]


# -- RunBundle write/load ----------------------------------------------------


def test_bundle_roundtrip(tmp_path):
    events = tmp_path / "events.jsonl"
    events.write_text(json.dumps(
        {"event": "phase", "phase": "step", "node": "n0",
         "t0_unix_s": 1.0, "duration_s": 2.0, "self_s": 2.0}) + "\n")
    path = write_bundle(
        str(tmp_path / "bundle"), run_id="rt-1", role="train",
        bench_rows=[{"metric": "m", "value": 1.0}],
        events=[str(events)],
        xray_summary={"busy_frac": 0.5, "steps": {"mean_wall_s": 0.1}},
        config={"zero_stage": 1}, config_fp="cfg-x",
        git_sha_value="abc123", weight_version="wv-1",
        extra={"goodput": {"goodput": 0.9}})
    b = RunBundle.load(path)
    assert b.run_id == "rt-1"
    assert b.identity()["git_sha"] == "abc123"
    assert b.identity()["weight_version"] == "wv-1"
    assert b.config() == {"zero_stage": 1}
    assert b.bench_rows() == [{"metric": "m", "value": 1.0}]
    assert [r["phase"] for r in b.events() if r.get("event") == "phase"] \
        == ["step"]
    assert b.xray_summary()["busy_frac"] == 0.5
    assert b.goodput()["n0"]["total_s"] == pytest.approx(2.0)
    # Loading the directory (not the run.json) works too.
    assert RunBundle.load(str(tmp_path / "bundle")).run_id == "rt-1"


def test_bundle_tolerates_missing_artifacts(tmp_path):
    """A bundle whose event log was rotated away still loads and joins
    on its stamps — loaders consume only what exists."""
    path = write_bundle(str(tmp_path / "b"), run_id="gone", role="bench",
                        events=[str(tmp_path / "never-written.jsonl")])
    b = RunBundle.load(path)
    assert b.events() == []
    assert b.xray_summary() is None
    assert b.goodput() == {}
    assert b.waterfall_summary() is None
    rep = compare(b, b)
    assert rep["invariants"]["ok"] is True  # nothing to check, nothing broke


# -- row-level fallback + schema tolerance -----------------------------------


def _row(value, **extra):
    return {"metric": "resnet18_cifar_train_samples_per_sec_per_chip",
            "value": value, "unit": "samples/sec/chip",
            "device_kind": "TPU v5 lite", "batch_per_chip": 4096, **extra}


def test_attribute_rows_names_planted_column():
    rep = attribute_rows(_row(100.0, exposed_comms_frac=0.05,
                              zero_stage=1),
                         _row(80.0, exposed_comms_frac=0.22,
                              zero_stage=0))
    assert rep["mode"] == "rows"
    assert "exposed_comms_frac" in rep["dominant"]
    assert any("zero_stage changed 1 -> 0" in v for v in rep["verdicts"])


def test_attribute_rows_predating_columns_is_note_not_error():
    """Satellite: rows that predate every attribution column are
    joinable but unattributable — a note, never an exception."""
    rep = attribute_rows(_row(100.0), _row(80.0))
    assert rep["dominant"] is None
    assert "unattributable" in rep["note"]


def test_config_drift_skips_missing_stamps():
    """Missing git_sha/config_fingerprint stamps never register as
    drift (schema tolerance for pre-round-24 rows)."""
    drift = config_drift(None, None, _row(100.0),
                         _row(80.0, git_sha="bbb"))
    assert drift == []
    drift = config_drift(None, None, _row(100.0, git_sha="aaa"),
                         _row(80.0, git_sha="bbb"))
    assert [d["field"] for d in drift] == ["git_sha"]


def test_mfu_hw_disagreements_surfaces_latest_row():
    hist = [_row(100.0),
            _row(99.0, mfu_vs_hw_warning="analytic mfu 0.62 exceeds "
                                         "hardware busy fraction 0.48")]
    rows = mfu_hw_disagreements(hist)
    assert len(rows) == 1 and "0.62" in rows[0]["warning"]
    # The warning's appearance across two compared runs rides the report.
    a = RunBundle({"run_id": "wa", "bench_rows": [_row(100.0)]})
    b = RunBundle({"run_id": "wb", "bench_rows": [
        _row(99.0, mfu_vs_hw_warning="cost-model overcount?")]})
    rep = compare(a, b)
    assert any("mfu_vs_hw_warning appeared" in w for w in rep["warnings"])
    assert any("mfu_vs_hw_warning" in v for v in rep["verdicts"])


# -- CLI: slt regress --------------------------------------------------------


def test_cli_regress_fixture_pair(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["regress", os.path.join(FIXTURE_DIR, "run_a"),
                 os.path.join(FIXTURE_DIR, "run_b"), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["invariants"]["ok"] is True
    assert "exposed all-reduce" in rep["dominant_cause"]


def test_cli_regress_human_render(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["regress", os.path.join(FIXTURE_DIR, "run_a"),
                 os.path.join(FIXTURE_DIR, "run_b")]) == 0
    out = capsys.readouterr().out
    assert "verdict" in out and "drift: zero_stage 1 -> 0" in out


def test_cli_regress_self_check(capsys):
    from serverless_learn_tpu.cli import main

    assert main(["regress", "--self-check", "--compact"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] is True
    names = {c["check"] for c in rep["checks"]}
    assert "fixture_report_byte_identical" in names


def test_cli_regress_usage_and_load_errors(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    assert main(["regress"]) == 2
    assert main(["regress", str(tmp_path / "nope"),
                 str(tmp_path / "nada")]) == 2


# -- CLI: slt bench --gate --attribute ---------------------------------------


def test_bench_gate_attribute_names_planted_cause(capsys):
    """Acceptance: over the committed fixture history the gate fails
    AND the exit message names the planted dominant cause — the
    exposed-collective growth on dp."""
    from serverless_learn_tpu.cli import main

    assert main(["bench", "--gate", "--attribute", "--dry-run",
                 "--history", FIXTURE_HISTORY]) == 1
    out = capsys.readouterr()
    assert "gate FAILED" in out.err
    assert "exposed all-reduce" in out.err and "dp" in out.err
    rep = json.loads(out.out)
    assert rep["attribution"][0]["mode"] == "bundles"
    assert rep["attribution"][0]["invariants"]["ok"] is True


def test_bench_gate_attribute_row_fallback(tmp_path, capsys):
    """History rows with attribution columns but no bundle pointers
    degrade to row-level attribution naming the worst column."""
    from serverless_learn_tpu.cli import main

    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps(
        [_row(100.0, exposed_comms_frac=0.05),
         _row(80.0, exposed_comms_frac=0.30)]))
    assert main(["bench", "--gate", "--attribute", "--dry-run",
                 "--history", str(hist)]) == 1
    out = capsys.readouterr()
    assert "exposed_comms_frac" in out.err
    rep = json.loads(out.out)
    assert rep["attribution"][0]["mode"] == "rows"


def test_bench_gate_attribute_pre_column_history_no_crash(tmp_path,
                                                          capsys):
    """Satellite regression test: a history where EVERY row predates
    the attribution columns (pre-round-16 shape) must neither gate on
    those columns nor crash --attribute — the regression is reported
    as joinable-but-unattributable."""
    from serverless_learn_tpu.cli import main

    hist = tmp_path / "old.json"
    # Pre-round-16 rows: value + keys only (no goodput, no attribution
    # columns, no stamps, no bundle pointers).
    hist.write_text(json.dumps([_row(100.0), _row(80.0)]))
    assert main(["bench", "--gate", "--attribute", "--dry-run",
                 "--history", str(hist)]) == 1
    out = capsys.readouterr()
    assert "unattributable" in out.err
    rep = json.loads(out.out)
    assert rep["attribution"][0].get("note")
    capsys.readouterr()
    # And a NON-regressing pre-column history passes clean (the columns
    # must not gate when no row ever carried them).
    flat = tmp_path / "flat.json"
    flat.write_text(json.dumps([_row(100.0), _row(101.0)]))
    assert main(["bench", "--gate", "--attribute", "--dry-run",
                 "--history", str(flat)]) == 0


# -- loadgen bundle stamping -------------------------------------------------


def test_loadgen_stamp_bundle_points_rows(tmp_path):
    from serverless_learn_tpu.fleet.loadgen import stamp_bundle

    hist = tmp_path / "hist.json"
    rows = [{"metric": "serve_ttft_p99_ms", "value": 12.0,
             "device_kind": "serve-cpu"}]
    ptr = stamp_bundle(rows, str(hist), role="loadgen-test")
    assert ptr and rows[0]["bundle"] == ptr
    b = RunBundle.load(os.path.join(str(tmp_path), ptr))
    assert b.manifest["role"] == "loadgen-test"
    assert b.bench_rows()[0]["metric"] == "serve_ttft_p99_ms"
    assert b.bench_rows()[0]["bundle"] == ptr  # rows stamped pre-write


# -- bench.py bundle stamping ------------------------------------------------


def test_bench_write_run_bundle(tmp_path):
    import bench as bench_mod

    rec = _row(100.0, zero_stage=1, git_sha="abc",
               config_fingerprint="cfg-1")
    hist = tmp_path / "bench_history.json"
    ptr = bench_mod.write_run_bundle(rec, str(hist))
    assert ptr and rec["bundle"] == ptr
    b = RunBundle.load(os.path.join(str(tmp_path), ptr))
    assert b.manifest["role"] == "bench"
    assert b.identity()["git_sha"] == "abc"
    assert b.bench_rows()[0]["value"] == 100.0


# -- doctor integration ------------------------------------------------------


def test_doctor_folds_cross_run_verdicts():
    from serverless_learn_tpu.telemetry import doctor

    rep = doctor.diagnose(bench_history=FIXTURE_HISTORY)
    verdict = rep["summary"]["verdict"]
    assert "bench regression attributed" in verdict
    assert "exposed all-reduce" in verdict
    attrib = rep["bench"]["attribution"]
    assert attrib and attrib[0]["mode"] == "bundles"


def test_doctor_surfaces_mfu_vs_hw_warning(tmp_path):
    from serverless_learn_tpu.telemetry import doctor

    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps(
        [_row(100.0),
         _row(99.0, mfu_vs_hw_warning="analytic mfu 0.62 exceeds "
                                      "hardware busy fraction 0.48")]))
    rep = doctor.diagnose(bench_history=str(hist))
    assert "analytic MFU disagrees" in rep["summary"]["verdict"]
    assert rep["bench"]["mfu_vs_hw_warnings"][0]["warning"].startswith(
        "analytic mfu 0.62")


# -- gate integration (library level) ----------------------------------------


def test_attribute_gate_failures_never_raises_on_garbage():
    """A malformed gate report/history degrades per-check, keeps gating."""
    out = regress.attribute_gate_failures(
        {"regressions": [{"metric": "m", "device_kind": None,
                          "batch_per_chip": None}]},
        [{"metric": "m", "value": "not-a-number"}], history_dir=None)
    assert out and out[0].get("mode") in ("rows", "error")


def test_attribute_bench_history_clean_history_is_empty(tmp_path):
    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps([_row(100.0), _row(101.0)]))
    assert regress.attribute_bench_history(str(hist)) == []
    assert regress.attribute_bench_history(
        str(tmp_path / "missing.json")) == []
