"""ResNet normalization options (docs/MFU_ANALYSIS.md).

``norm="batch"`` is the canonical recipe; ``"group"`` removes cross-replica
stat syncs and running-stats state; ``"none"`` (scale+bias, zero-init
residual scales) removes every normalization reduction — the full measured
BN cost. These tests pin the option surface and a small-scale training
parity: every variant must actually optimize, and the BN-free variant must
not lag catastrophically on a memorization task (large-scale accuracy parity
is a recipe question, documented honestly in the analysis doc, not claimed
by this test).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.models.registry import get_model
from serverless_learn_tpu.training.train_step import build_trainer


def _train_losses(norm, steps=30):
    cfg = ExperimentConfig(
        model="resnet18_cifar",
        model_overrides=dict(norm=norm, num_classes=4,
                             dtype=jnp.float32, param_dtype=jnp.float32),
        mesh=MeshConfig(dp=8),
        # adamw: the unnormalized variant diverges under the BN recipe's
        # SGD momentum at lr 0.05 (measured — the classic NF lr
        # sensitivity); an adaptive optimizer lets one recipe compare all
        # three variants.
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3),
        train=TrainConfig(batch_size=64),
        data=DataConfig(),
    )
    trainer = build_trainer(cfg)
    state = trainer.init()
    rng = np.random.default_rng(7)
    batch = trainer.shard_batch({
        "image": rng.standard_normal((64, 32, 32, 3), dtype=np.float32),
        "label": rng.integers(0, 4, 64).astype(np.int32),
    })
    losses = []
    for _ in range(steps):
        state, m = trainer.step(state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    return losses


@pytest.mark.slow
@pytest.mark.parametrize("norm", ["batch", "group", "none"])
def test_all_norms_train(devices, norm):
    """Each variant memorizes a fixed batch: loss drops well below init
    (measured at 30 steps: batch 0.001x, group 0.08x, none 0.63x)."""
    losses = _train_losses(norm)
    assert np.isfinite(losses).all(), losses[-5:]
    assert losses[-1] < 0.7 * losses[0], (norm, losses[0], losses[-1])


def test_none_norm_has_no_stats_state(devices):
    bundle = get_model("resnet18_cifar", norm="none")
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = bundle.module.init(jax.random.PRNGKey(0), x, train=True)
    assert "batch_stats" not in variables
    # blocks start as identity: residual-branch output scales are zero
    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    zero_scales = [p for p, leaf in flat
                   if "scale" in jax.tree_util.keystr(p)
                   and float(jnp.abs(leaf).max()) == 0.0]
    assert zero_scales, "zero-init residual scales missing"


def test_group_norm_has_no_stats_state(devices):
    bundle = get_model("resnet18_cifar", norm="group")
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = bundle.module.init(jax.random.PRNGKey(0), x, train=True)
    assert "batch_stats" not in variables


def test_unknown_norm_rejected(devices):
    bundle = get_model("resnet18_cifar", norm="layer")
    with pytest.raises(ValueError, match="unknown norm"):
        bundle.module.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3), jnp.float32))
