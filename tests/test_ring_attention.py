"""Ring attention (sequence parallelism) correctness vs the dense reference
implementation, and end-to-end training with an sp axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.ops.attention import xla_attention
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.parallel.ring_attention import (
    ring_attention, set_active_mesh)


@pytest.fixture()
def sp_mesh(devices):
    mesh = make_mesh(MeshConfig(sp=8))
    set_active_mesh(mesh)
    yield mesh
    set_active_mesh(None)


def _qkv(rng, B, T, H, D, K=None):
    K = K or H
    q = jax.random.normal(rng, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, T, K, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, T, K, D), jnp.float32)
    return q, k, v


def test_ring_matches_dense_full(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 4, 16)
    ref = xla_attention(q, k, v, causal=False)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=False, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_causal(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 64, 4, 16)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_gqa(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 64, 8, 16, K=2)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_grad_matches_dense(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 2, 8)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, causal=True, mesh=sp_mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_llama_trains_with_sp_axis(devices):
    """End-to-end: llama_tiny with dp=2, sp=4 and ring attention produces the
    same losses as pure-DP dense attention (fp32)."""
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    def run(mesh_cfg, overrides):
        cfg = ExperimentConfig(
            model="llama_tiny", mesh=mesh_cfg,
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
            train=TrainConfig(batch_size=8),
            data=DataConfig(seq_len=32),
            model_overrides=overrides)
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=7)
        losses = []
        for batch, _ in zip(iter(src), range(3)):
            state, m = trainer.step(state, trainer.shard_batch(batch))
            losses.append(float(m["loss"]))
        return losses

    base = {"dtype": jnp.float32}
    l_dense = run(MeshConfig(dp=8), dict(base))
    l_ring = run(MeshConfig(dp=2, sp=4),
                 dict(base, attention_impl="ring"))
    np.testing.assert_allclose(l_dense, l_ring, rtol=2e-4)


@pytest.fixture()
def sp2_mesh(devices):
    """sp=2 with T=256 gives T_loc=128 — large enough for the blocked
    (flash) hop path instead of the dense fallback."""
    mesh = make_mesh(MeshConfig(dp=4, sp=2))
    set_active_mesh(mesh)
    yield mesh
    set_active_mesh(None)


def test_ring_flash_hops_selected_and_match(sp2_mesh):
    """VERDICT round 1 item 9: hops must run the blocked Pallas kernel
    (O(T_loc x block) memory), proven on the jaxpr, with dense parity."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 4, 256, 4, 64)
    fn = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh))
    jaxpr = str(jax.make_jaxpr(fn)(q, k, v))
    assert "pallas_call" in jaxpr, "ring hops must use the flash kernel"
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_hops_gqa_unexpanded(sp2_mesh):
    """GQA K/V ride the ring unexpanded; the kernel's index map reads the
    shared head. Parity + gradient against the dense reference."""
    q, k, v = _qkv(jax.random.PRNGKey(4), 4, 256, 8, 64, K=2)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    gf = jax.jit(jax.grad(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh).sum(), (0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: xla_attention(
        q, k, v, causal=True).astype(jnp.float32).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=f"d{name}")


def test_ring_flash_hops_noncausal_grad(sp2_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(5), 4, 256, 4, 64)
    gf = jax.jit(jax.grad(lambda q, k, v: ring_attention(
        q, k, v, causal=False, mesh=sp2_mesh).sum(), (0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: xla_attention(
        q, k, v, causal=False).astype(jnp.float32).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=f"d{name}")


def _len_mask(kv_lengths, B, T):
    return (np.arange(T)[None, :] < np.asarray(kv_lengths)[:, None]
            ).reshape(B, 1, 1, T)


# -- round 3: suffix padding through the ring + zigzag schedule --------------


def test_ring_kv_lengths_matches_dense(sp_mesh):
    """Global suffix lengths slice to per-hop local lengths; parity against
    dense attention with the equivalent mask — including rows whose valid
    prefix ends mid-shard and rows with fully-padded shards."""
    B, T = 4, 64
    q, k, v = _qkv(jax.random.PRNGKey(6), B, T, 4, 16)
    lens = jnp.array([64, 37, 8, 50], jnp.int32)  # shard size is 8
    for causal in (False, True):
        ref = xla_attention(q, k, v, causal=causal,
                            mask=jnp.asarray(_len_mask(lens, B, T)))
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=causal, kv_lengths=lens, mesh=sp_mesh))(q, k, v)
        # padded q rows attend nothing real; compare valid rows only
        # (same contract as the flash kernel's kv_lengths path)
        for b in range(B):
            n_valid = int(lens[b])
            np.testing.assert_allclose(
                np.asarray(out)[b, :n_valid], np.asarray(ref)[b, :n_valid],
                rtol=2e-5, atol=2e-5, err_msg=f"row {b} causal={causal}")


def test_ring_kv_lengths_grad_finite(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(7), 2, 32, 2, 8)
    lens = jnp.array([32, 11], jnp.int32)
    g = jax.jit(jax.grad(lambda q, k, v: (ring_attention(
        q, k, v, causal=True, kv_lengths=lens, mesh=sp_mesh) ** 2).sum(),
        (0, 1, 2)))(q, k, v)
    for a in g:
        assert np.isfinite(np.asarray(a)).all()


def test_zigzag_flash_matches_dense(sp2_mesh):
    """Long-context shape (T_loc=256 -> half-blocks 128): the zigzag
    schedule must engage the blocked kernel and match dense causal
    attention, fwd + grad, incl. GQA."""
    from serverless_learn_tpu.parallel.ring_attention import _auto_zigzag

    q, k, v = _qkv(jax.random.PRNGKey(8), 4, 512, 4, 32, K=2)
    assert _auto_zigzag(causal=True, n=2, t_loc=256)
    fn = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh))
    jaxpr = str(jax.make_jaxpr(fn)(q, k, v))
    assert "pallas_call" in jaxpr
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    gf = jax.jit(jax.grad(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh).sum(), (0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: xla_attention(
        q, k, v, causal=True).astype(jnp.float32).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=f"d{name}")


def test_zigzag_with_kv_lengths(sp2_mesh):
    B, T = 4, 512
    q, k, v = _qkv(jax.random.PRNGKey(9), B, T, 4, 32)
    lens = jnp.array([512, 300, 128, 511], jnp.int32)
    ref = xla_attention(q, k, v, causal=True,
                        mask=jnp.asarray(_len_mask(lens, B, T)))
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, kv_lengths=lens, layout="zigzag",
        mesh=sp2_mesh))(q, k, v)
    for b in range(B):
        n_valid = int(lens[b])
        np.testing.assert_allclose(
            np.asarray(out)[b, :n_valid], np.asarray(ref)[b, :n_valid],
            rtol=2e-5, atol=2e-5, err_msg=f"row {b}")


def test_forced_layouts_agree(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(10), 2, 64, 4, 16)
    a = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, layout="contiguous", mesh=sp_mesh))(q, k, v)
    b = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, layout="zigzag", mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="zigzag"):
        ring_attention(q, k, v, causal=False, layout="zigzag", mesh=sp_mesh)


def test_auto_dispatch_padded_sp_uses_ring(sp_mesh, monkeypatch):
    """sp>1 with SUFFIX padding must take the ring path (r2 it silently
    fell back to GSPMD-partitioned dense attention)."""
    from serverless_learn_tpu.ops import attention as attn_mod
    from serverless_learn_tpu.parallel import ring_attention as ring_mod

    calls = []
    real = ring_mod.ring_attention

    def spy(*a, **kw):
        calls.append(kw.get("kv_lengths") is not None)
        return real(*a, **kw)

    monkeypatch.setattr(ring_mod, "ring_attention", spy)
    B, T = 2, 64
    q, k, v = _qkv(jax.random.PRNGKey(11), B, T, 4, 16)
    lens = jnp.array([64, 40], jnp.int32)
    attn_mod.dot_product_attention(
        q, k, v, causal=True, mask=jnp.asarray(_len_mask(lens, B, T)),
        kv_lengths=lens, axis_name="sp")
    assert calls == [True], "padded sp batch must ride the ring with lengths"


def test_zigzag_halves_causal_compute(sp2_mesh):
    """The measurable balance win on a virtual mesh: XLA's compiled FLOP
    count per shard. Contiguous causal ring computes hidden hops only to
    discard them; zigzag computes exactly the visible half-pairs
    (measured 2.5x fewer FLOPs at sp=2, T=1024)."""
    B, T, H, D = 4, 1024, 4, 64
    q = jnp.zeros((B, T, H, D), jnp.float32)
    k = jnp.zeros((B, T, H, D), jnp.float32)
    v = jnp.zeros((B, T, H, D), jnp.float32)
    flops = {}
    for layout in ("contiguous", "zigzag"):
        fn = jax.jit(lambda q, k, v, lay=layout: ring_attention(
            q, k, v, causal=True, layout=lay, mesh=sp2_mesh))
        analysis = fn.lower(q, k, v).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):  # newer jax: list of dicts
            analysis = analysis[0]
        flops[layout] = analysis["flops"]
    assert flops["zigzag"] < 0.6 * flops["contiguous"], flops


def test_ring_kv_lengths_multi_q_block(sp2_mesh):
    """Regression (r3 review): hop kernels must use keys-only length
    masking ("klen"). The self-attention "len" mode skips q BLOCKS whose
    index exceeds the kv shard's local length — with multiple q blocks per
    hop (T_loc=1024 -> two 512-blocks) that silently dropped the hop's
    valid keys for valid q rows."""
    B, T = 4, 2048
    q, k, v = _qkv(jax.random.PRNGKey(12), B, T, 2, 16)
    lens = jnp.array([2048, 1200, 512, 2048], jnp.int32)
    for causal in (False, True):
        ref = xla_attention(q, k, v, causal=causal,
                            mask=jnp.asarray(_len_mask(lens, B, T)))
        out = jax.jit(lambda q, k, v, c=causal: ring_attention(
            q, k, v, causal=c, kv_lengths=lens, mesh=sp2_mesh))(q, k, v)
        for b in range(B):
            n_valid = int(lens[b])
            np.testing.assert_allclose(
                np.asarray(out)[b, :n_valid], np.asarray(ref)[b, :n_valid],
                rtol=2e-5, atol=2e-5, err_msg=f"row {b} causal={causal}")
