"""Ring attention (sequence parallelism) correctness vs the dense reference
implementation, and end-to-end training with an sp axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.ops.attention import xla_attention
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.parallel.ring_attention import (
    ring_attention, set_active_mesh)


@pytest.fixture()
def sp_mesh(devices):
    mesh = make_mesh(MeshConfig(sp=8))
    set_active_mesh(mesh)
    yield mesh
    set_active_mesh(None)


def _qkv(rng, B, T, H, D, K=None):
    K = K or H
    q = jax.random.normal(rng, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, T, K, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, T, K, D), jnp.float32)
    return q, k, v


def test_ring_matches_dense_full(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 4, 16)
    ref = xla_attention(q, k, v, causal=False)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=False, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_causal(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 64, 4, 16)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_gqa(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 64, 8, 16, K=2)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_grad_matches_dense(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 2, 8)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, causal=True, mesh=sp_mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_llama_trains_with_sp_axis(devices):
    """End-to-end: llama_tiny with dp=2, sp=4 and ring attention produces the
    same losses as pure-DP dense attention (fp32)."""
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.training.train_step import build_trainer

    def run(mesh_cfg, overrides):
        cfg = ExperimentConfig(
            model="llama_tiny", mesh=mesh_cfg,
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
            train=TrainConfig(batch_size=8),
            data=DataConfig(seq_len=32),
            model_overrides=overrides)
        trainer = build_trainer(cfg)
        state = trainer.init()
        src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=7)
        losses = []
        for batch, _ in zip(iter(src), range(3)):
            state, m = trainer.step(state, trainer.shard_batch(batch))
            losses.append(float(m["loss"]))
        return losses

    base = {"dtype": jnp.float32}
    l_dense = run(MeshConfig(dp=8), dict(base))
    l_ring = run(MeshConfig(dp=2, sp=4),
                 dict(base, attention_impl="ring"))
    np.testing.assert_allclose(l_dense, l_ring, rtol=2e-4)


@pytest.fixture()
def sp2_mesh(devices):
    """sp=2 with T=256 gives T_loc=128 — large enough for the blocked
    (flash) hop path instead of the dense fallback."""
    mesh = make_mesh(MeshConfig(dp=4, sp=2))
    set_active_mesh(mesh)
    yield mesh
    set_active_mesh(None)


def test_ring_flash_hops_selected_and_match(sp2_mesh):
    """VERDICT round 1 item 9: hops must run the blocked Pallas kernel
    (O(T_loc x block) memory), proven on the jaxpr, with dense parity."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 4, 256, 4, 64)
    fn = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh))
    jaxpr = str(jax.make_jaxpr(fn)(q, k, v))
    assert "pallas_call" in jaxpr, "ring hops must use the flash kernel"
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_hops_gqa_unexpanded(sp2_mesh):
    """GQA K/V ride the ring unexpanded; the kernel's index map reads the
    shared head. Parity + gradient against the dense reference."""
    q, k, v = _qkv(jax.random.PRNGKey(4), 4, 256, 8, 64, K=2)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    gf = jax.jit(jax.grad(lambda q, k, v: ring_attention(
        q, k, v, causal=True, mesh=sp2_mesh).sum(), (0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: xla_attention(
        q, k, v, causal=True).astype(jnp.float32).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=f"d{name}")


def test_ring_flash_hops_noncausal_grad(sp2_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(5), 4, 256, 4, 64)
    gf = jax.jit(jax.grad(lambda q, k, v: ring_attention(
        q, k, v, causal=False, mesh=sp2_mesh).sum(), (0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: xla_attention(
        q, k, v, causal=False).astype(jnp.float32).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=f"d{name}")
