"""Generation server: greedy determinism over the wire, error replies that
keep the daemon alive, and concurrent clients."""

import json
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.generate import generate
from serverless_learn_tpu.inference.server import GenerationServer, request
from serverless_learn_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def server(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    srv = GenerationServer(bundle.module, params).start()
    yield srv, bundle.module, params
    srv.stop()


def test_serve_matches_direct_generate(server):
    srv, module, params = server
    rep = request(srv.addr, {"prompt": [5, 9, 11], "max_new_tokens": 6})
    direct = generate(module, params, jnp.asarray([[5, 9, 11]], jnp.int32), 6)
    assert rep["tokens"] == [int(t) for t in jax.device_get(direct)[0]]
    assert rep["new_tokens"] == rep["tokens"][3:]
    assert rep["latency_ms"] > 0


def test_serve_error_replies_keep_server_alive(server):
    srv, _, _ = server
    assert "error" in request(srv.addr, {"prompt": []})
    assert "error" in request(srv.addr, {"prompt": [1, 2], "max_new_tokens": 999})
    assert "error" in request(srv.addr, {"prompt": [999999]})
    # Garbage line → error reply, connection stays usable for valid requests.
    host, _, port = srv.addr.rpartition(":")
    with socket.create_connection((host, int(port))) as s:
        f = s.makefile("rwb")
        f.write(b"this is not json\n")
        f.flush()
        assert "error" in json.loads(f.readline())
        f.write(json.dumps({"prompt": [1, 2], "max_new_tokens": 2}).encode()
                + b"\n")
        f.flush()
        assert "tokens" in json.loads(f.readline())


def test_serve_survives_malformed_json_values(server):
    """Valid JSON that isn't a valid request must get an error reply, not
    kill the server: non-object payloads and uncoercible fields."""
    srv, _, _ = server
    host, _, port = srv.addr.rpartition(":")
    with socket.create_connection((host, int(port))) as s:
        f = s.makefile("rwb")
        for bad in (b"[1,2,3]", b"\"str\"",
                    json.dumps({"prompt": [1], "max_new_tokens": "lots"}).encode()):
            f.write(bad + b"\n")
            f.flush()
            assert "error" in json.loads(f.readline()), bad
    # Server still serves fresh connections.
    assert "tokens" in request(srv.addr, {"prompt": [1], "max_new_tokens": 1})


def test_serve_oversized_line_rejected(server):
    """A newline-free flood must get one error reply + hangup, not
    unbounded buffering (ADVICE.md round 1)."""
    from serverless_learn_tpu.inference import server as srv_mod

    srv, _, _ = server
    host, _, port = srv.addr.rpartition(":")
    with socket.create_connection((host, int(port))) as s:
        f = s.makefile("rwb")
        f.write(b"x" * (srv_mod.MAX_LINE + 2) + b"\n")
        f.flush()
        assert "error" in json.loads(f.readline())
        assert f.readline() == b""  # server hung up
    # Fresh connections still served.
    assert "tokens" in request(srv.addr, {"prompt": [1], "max_new_tokens": 1})


def test_serve_idle_client_does_not_starve_others(server):
    """An open idle connection must not block other clients (per-connection
    threads; ADVICE.md round 1)."""
    srv, _, _ = server
    host, _, port = srv.addr.rpartition(":")
    with socket.create_connection((host, int(port))):
        # Idle keepalive held open; a second client must still get served.
        rep = request(srv.addr, {"prompt": [3], "max_new_tokens": 2},
                      timeout=30.0)
        assert "tokens" in rep


def test_serve_sequential_clients_and_sampling(server):
    srv, _, _ = server
    a = request(srv.addr, {"prompt": [7, 8], "max_new_tokens": 4,
                           "temperature": 0.9, "top_k": 8, "seed": 1})
    b = request(srv.addr, {"prompt": [7, 8], "max_new_tokens": 4,
                           "temperature": 0.9, "top_k": 8, "seed": 1})
    assert a["tokens"] == b["tokens"], "same seed must reproduce"
    assert srv.requests_served >= 2
