"""Batched serving (round-3 verdict #2): the admission queue must coalesce
concurrent requests into batched prefill+decode — and batching must not
change greedy results.

Exactness hinges on per-sequence cache indices (``cache_index`` is a [B]
vector in ``models/transformer.py``): unequal prompts right-pad to one
shape, each sequence decodes from its own true length. The throughput bar
(4 concurrent clients >= 2.5x the serialized aggregate) is asserted on
real silicon by ``benchmarks/gen_bench.py --concurrent``; here on 1-core
CPU we assert the *mechanism*: requests actually share batches, and the
outputs are byte-identical to solo calls.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.batching import BatchingEngine
from serverless_learn_tpu.inference.generate import generate
from serverless_learn_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def model(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def _solo(module, params, prompt, n):
    toks = generate(module, params, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in jax.device_get(toks)[0][len(prompt):]]


def test_padded_batch_generate_matches_solo(model):
    """The primitive: one batched call over right-padded unequal prompts
    reproduces each solo greedy continuation exactly."""
    module, params = model
    prompts = [[5, 9, 11], [7, 3, 2, 8, 1, 30, 12], [4]]
    P = max(len(p) for p in prompts)
    padded = np.zeros((3, P), np.int32)
    lens = np.zeros(3, np.int32)
    for i, p in enumerate(prompts):
        padded[i, :len(p)] = p
        lens[i] = len(p)
    toks = generate(module, params, jnp.asarray(padded), 6,
                    prompt_lengths=jnp.asarray(lens))
    new = np.asarray(jax.device_get(toks))[:, P:]
    for i, p in enumerate(prompts):
        assert new[i].tolist() == _solo(module, params, p, 6), f"row {i}"


def test_engine_coalesces_and_is_exact(model):
    """4 threads submit simultaneously -> fewer batches than requests, and
    every reply equals the solo greedy continuation."""
    module, params = model
    eng = BatchingEngine(module, params, max_batch=8, batch_wait_ms=200.0)
    try:
        prompts = [[5, 9, 11], [7, 3, 2, 8], [4, 4, 4, 4, 4], [1, 2]]
        results = [None] * 4

        def client(i):
            results[i] = eng.submit(prompts[i], 5, temperature=0.0,
                                    top_k=0, eos_id=None, seed=0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert eng.requests_batched == 4
        assert eng.batches_run < 4, \
            f"4 requests ran {eng.batches_run} batches: no coalescing"
        for i, p in enumerate(prompts):
            assert "error" not in results[i], results[i]
            assert results[i]["new_tokens"] == _solo(module, params, p, 5), \
                f"request {i} diverged under batching"
    finally:
        eng.stop()


def test_engine_groups_by_sampling_params(model):
    """Different temperatures must NOT share a batch (their sampling math
    differs); both still complete."""
    module, params = model
    eng = BatchingEngine(module, params, max_batch=8, batch_wait_ms=100.0)
    try:
        results = {}

        def client(name, temp):
            results[name] = eng.submit([5, 9], 4, temperature=temp,
                                       top_k=0, eos_id=None, seed=1)

        ts = [threading.Thread(target=client, args=("greedy", 0.0)),
              threading.Thread(target=client, args=("sampled", 0.9))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        assert eng.batches_run == 2
        assert all("new_tokens" in r for r in results.values())
    finally:
        eng.stop()


def test_engine_mixed_max_new_truncates_exactly(model):
    module, params = model
    eng = BatchingEngine(module, params, max_batch=8, batch_wait_ms=100.0)
    try:
        results = [None, None]

        def client(i, n):
            results[i] = eng.submit([5, 9, 11], n, temperature=0.0,
                                    top_k=0, eos_id=None, seed=0)

        ts = [threading.Thread(target=client, args=(0, 3)),
              threading.Thread(target=client, args=(1, 4))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        want = _solo(module, params, [5, 9, 11], 4)
        assert results[0]["new_tokens"] == want[:3]
        assert results[1]["new_tokens"] == want
    finally:
        eng.stop()


def test_long_prompt_near_window_still_serves(model):
    """Code-review regression: power-of-two padding must never push a
    valid request past max_seq_len. llama_tiny's window is 64; a
    40-token prompt + 8 new would bucket to 64 + 8 = 72 > 64 and error —
    the shape key must shrink the pad instead."""
    module, params = model
    eng = BatchingEngine(module, params, max_batch=4, batch_wait_ms=5.0)
    try:
        prompt = [(i % 37) + 1 for i in range(40)]
        r = eng.submit(prompt, 8, temperature=0.0, top_k=0, eos_id=None,
                       seed=0)
        assert "error" not in r, r
        assert r["new_tokens"] == _solo(module, params, prompt, 8)
        # And the extreme: prompt + max_new exactly at the window.
        prompt = [(i % 37) + 1 for i in range(61)]
        r = eng.submit(prompt, 3, temperature=0.0, top_k=0, eos_id=None,
                       seed=0)
        assert "error" not in r, r
        assert len(r["new_tokens"]) == 3
    finally:
        eng.stop()


def test_server_concurrent_clients_share_batches(model):
    """End to end over the wire: concurrent clients get exact greedy
    results and the server's engine reports coalescing."""
    from serverless_learn_tpu.inference.server import (
        GenerationServer, request)

    module, params = model
    srv = GenerationServer(module, params, batch_wait_ms=200.0,
                           engine="static").start()
    try:
        prompts = [[5, 9, 11], [7, 3, 2, 8], [4, 4], [1, 2, 3, 4, 5]]
        reps = [None] * 4

        def client(i):
            reps[i] = request(srv.addr, {"prompt": prompts[i],
                                         "max_new_tokens": 4})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, p in enumerate(prompts):
            assert reps[i].get("new_tokens") == _solo(module, params, p, 4)
        assert srv.engine.batches_run < srv.engine.requests_batched
    finally:
        srv.stop()
