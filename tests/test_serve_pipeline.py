"""Serving pipeline-trained checkpoints (VERDICT r2 item 7).

A pp-trained model's params live as one layer-stacked ``pipe_blocks``
subtree; the KV-cached decode path needs the sequential per-layer layout.
``unstack_pipeline_params`` converts at load time (undoing the interleaved
execution order when present), ``Checkpointer.restore_params_host``
restores the params subtree template-free from both checkpoint layouts,
and the generate/serve CLIs wire it together.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.cli import main
from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.inference.generate import generate
from serverless_learn_tpu.models.registry import get_model
from serverless_learn_tpu.models.transformer import unstack_pipeline_params
from serverless_learn_tpu.parallel.mesh import make_mesh
from serverless_learn_tpu.training.checkpoint import Checkpointer, LocalStore
from serverless_learn_tpu.training.train_step import build_trainer


def _train_pp(tmp_path, devices, sharded, overrides=None, steps=2):
    """Train llama_tiny on a dp2.pp2 mesh briefly; checkpoint; return cfg."""
    cfg = ExperimentConfig(
        model="llama_tiny",
        model_overrides=dict(pipeline=True, pipeline_microbatches=2,
                             n_layers=4, **(overrides or {})),
        mesh=MeshConfig(dp=2, pp=2),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
        train=TrainConfig(batch_size=8, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig(seq_len=32),
    )
    mesh = make_mesh(cfg.mesh, devices=devices[:4])
    trainer = build_trainer(cfg, mesh=mesh)
    state = trainer.init()
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=3))
    for _ in range(steps):
        state, _ = trainer.step(state, trainer.shard_batch(next(src)))
    ckpt = Checkpointer(LocalStore(str(tmp_path)), async_save=False,
                        sharded=sharded)
    ckpt.save(state)
    return cfg, trainer, state


@pytest.mark.parametrize("sharded", [False, True])
def test_generate_from_pp_checkpoint(tmp_path, devices, sharded):
    """The verdict's done-criterion: generate produces tokens from a
    pp=2-trained llama checkpoint — via template-free params restore +
    layout conversion, greedy output deterministic."""
    cfg, _, _ = _train_pp(tmp_path, devices, sharded=sharded)

    ckpt = Checkpointer(LocalStore(str(tmp_path)), async_save=False)
    host_params = ckpt.restore_params_host()
    assert "pipe_blocks" in host_params["pipeline"]

    serve_overrides = {k: v for k, v in cfg.model_overrides.items()
                       if not k.startswith("pipeline")}
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, **serve_overrides)
    params = unstack_pipeline_params(host_params, bundle.module.cfg)
    assert "pipe_blocks" not in params and "layer_0" in params

    prompt = jnp.asarray([[5, 9, 11]], jnp.int32)
    out = generate(bundle.module, params, prompt, max_new_tokens=6)
    assert out.shape == (1, 9)
    out2 = generate(bundle.module, params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_converted_params_match_pipeline_forward(tmp_path, devices):
    """Logit parity: the sequential module with converted params computes
    the same function the pipeline-trained model computed."""
    cfg, trainer, state = _train_pp(tmp_path, devices, sharded=True)
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, 512, (4, 32)), jnp.int32)
    # the trained function, on the training (pp=2) mesh
    logits_pp = trainer.bundle.module.apply(
        {"params": jax.device_get(state.params)}, tokens)

    ckpt = Checkpointer(LocalStore(str(tmp_path)), async_save=False)
    host_params = ckpt.restore_params_host()
    serve_overrides = {k: v for k, v in cfg.model_overrides.items()
                       if not k.startswith("pipeline")}
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, **serve_overrides)
    params = unstack_pipeline_params(host_params, bundle.module.cfg)
    logits_seq = bundle.module.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(logits_seq),
                               np.asarray(logits_pp), rtol=2e-5, atol=2e-5)


def test_interleaved_checkpoint_layer_order(tmp_path, devices):
    """A V-chunk (interleaved) checkpoint's stack is indexed by layer
    identity while execution follows layer_execution_order; conversion
    must map sequential layer_i to stack[order[i]] or the served model
    runs its layers in the wrong order."""
    cfg, trainer, state = _train_pp(
        tmp_path, devices, sharded=False,
        overrides=dict(pipeline_interleave=2, pipeline_stages=2))
    tokens = jnp.asarray(np.random.default_rng(1).integers(
        0, 512, (4, 32)), jnp.int32)
    logits_pp = trainer.bundle.module.apply(
        {"params": jax.device_get(state.params)}, tokens)

    ckpt = Checkpointer(LocalStore(str(tmp_path)), async_save=False)
    host_params = ckpt.restore_params_host()
    serve_overrides = {k: v for k, v in cfg.model_overrides.items()
                       if k not in ("pipeline", "pipeline_microbatches")}
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, **serve_overrides)
    assert bundle.module.cfg.pipeline_interleave == 2
    params = unstack_pipeline_params(host_params, bundle.module.cfg)
    logits_seq = bundle.module.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(logits_seq),
                               np.asarray(logits_pp), rtol=2e-5, atol=2e-5)


def test_generate_cli_from_pp_checkpoint(tmp_path, devices, capsys):
    """End to end through the CLI: a pipeline-trained checkpoint serves
    tokens with no manual surgery."""
    cfg, _, _ = _train_pp(tmp_path, devices, sharded=True)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(cfg.to_json())
    rc = main(["generate", "--config", str(cfg_path),
               "--set", "mesh.dp=1", "--set", "mesh.pp=1",
               "--checkpoint-dir", str(tmp_path),
               "--prompt", "5,9,11", "--max-new-tokens", "4"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out["tokens"][0]) == 7
    assert out["checkpoint_step"] is not None
