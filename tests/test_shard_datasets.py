"""Shard-server dataset pipeline: publish → stream → train.

Closes the loop the reference never did: its workers received the pushed file
and discarded it (``src/worker.cc:54-56``). Here the shard server's bytes are
decoded into typed batches that actually feed the jitted train step.
"""

import socket

import numpy as np
import pytest

from serverless_learn_tpu.control.daemons import start_shard_server
from serverless_learn_tpu.data.shard_client import (
    DatasetMeta, FieldSpec, ShardStreamSource, decode_shard, encode_shard,
    load_meta, publish_dataset, publish_from_bundle)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def shard_server(tmp_path):
    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path))
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


def _toy_arrays(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": rng.standard_normal((n, 4, 4, 1)).astype(np.float32),
        "label": rng.integers(0, 10, (n,)).astype(np.int32),
    }


def test_encode_decode_roundtrip():
    arrays = _toy_arrays(10)
    meta = DatasetMeta(
        fields=(FieldSpec("image", "float32", (4, 4, 1)),
                FieldSpec("label", "int32", ())),
        num_records=10, records_per_shard=10)
    out = decode_shard(meta, encode_shard(meta, arrays, 0, 10), 10)
    np.testing.assert_array_equal(out["image"], arrays["image"])
    np.testing.assert_array_equal(out["label"], arrays["label"])


def test_publish_and_meta(shard_server):
    arrays = _toy_arrays(100)
    meta = publish_dataset(shard_server, "toy", arrays, records_per_shard=32)
    assert meta.num_shards == 4  # 32+32+32+4
    fetched = load_meta(shard_server, "toy")
    assert fetched == meta


def test_single_pass_sees_every_record_once(shard_server):
    arrays = _toy_arrays(100)
    publish_dataset(shard_server, "toy", arrays, records_per_shard=32)
    src = ShardStreamSource(shard_server, "toy", batch_size=10, loop=False)
    seen = []
    for batch in src:
        assert batch["image"].shape == (10, 4, 4, 1)
        assert batch["label"].shape == (10,)
        # Identify records by their image contents (unique with overwhelming
        # probability for gaussian floats).
        seen.extend(batch["image"].reshape(10, -1).sum(axis=1).tolist())
    src.close()
    assert len(seen) == 100
    expect = sorted(arrays["image"].reshape(100, -1).sum(axis=1).tolist())
    assert np.allclose(sorted(seen), expect)


def test_stream_deterministic_given_seed(shard_server):
    publish_dataset(shard_server, "toy", _toy_arrays(64), records_per_shard=16)

    def take(n, seed):
        src = ShardStreamSource(shard_server, "toy", batch_size=8, seed=seed)
        it = iter(src)
        out = [next(it) for _ in range(n)]
        src.close()
        return out

    a, b = take(12, seed=3), take(12, seed=3)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["label"], y["label"])
    c = take(12, seed=4)
    assert any((x["label"] != y["label"]).any() for x, y in zip(a, c))


def test_epochs_reshuffle(shard_server):
    publish_dataset(shard_server, "toy", _toy_arrays(40), records_per_shard=40)
    src = ShardStreamSource(shard_server, "toy", batch_size=40, seed=0)
    it = iter(src)
    e0, e1 = next(it), next(it)  # one batch == one epoch here
    src.close()
    assert (e0["label"] != e1["label"]).any()
    assert sorted(e0["label"].tolist()) == sorted(e1["label"].tolist())


def test_dp_ranks_get_disjoint_shards(shard_server):
    arrays = _toy_arrays(96)
    publish_dataset(shard_server, "toy", arrays, records_per_shard=24)

    def records_of(rank):
        src = ShardStreamSource(shard_server, "toy", batch_size=12,
                                dp_rank=rank, dp_size=2, loop=False)
        got = []
        for b in src:
            got.extend(b["image"].reshape(len(b["image"]), -1).sum(1).tolist())
        src.close()
        return got

    r0, r1 = records_of(0), records_of(1)
    assert len(r0) == len(r1) == 48
    assert not set(np.round(r0, 6)) & set(np.round(r1, 6))
    both = sorted(r0 + r1)
    expect = sorted(arrays["image"].reshape(96, -1).sum(1).tolist())
    assert np.allclose(both, expect)


def test_publish_from_bundle_and_training(shard_server, devices):
    """End-to-end: publish an MNIST-shaped dataset, then run_training pulls
    it through the shard server (data.shard_server_addr set)."""
    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
    from serverless_learn_tpu.models.registry import get_model
    from serverless_learn_tpu.training.loop import make_source, run_training
    from serverless_learn_tpu.training.train_step import build_trainer

    bundle = get_model("mlp_mnist")
    data_cfg = DataConfig(dataset="mnist_synth",
                          shard_server_addr=shard_server)
    publish_from_bundle(shard_server, "mnist_synth", bundle.make_batch,
                        data_cfg, num_records=256, records_per_shard=64)
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=len(jax.devices())),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.05),
        train=TrainConfig(batch_size=32, num_steps=4, dtype="float32"),
        data=data_cfg,
    )
    trainer = build_trainer(cfg)
    src = make_source(cfg, trainer)
    assert isinstance(src, ShardStreamSource)
    state, meter = run_training(cfg, trainer=trainer, source=src)
    src.close()
    assert int(jax.device_get(state.step)) == 4
    assert np.isfinite(meter.history[-1].metrics["loss"])


def test_too_few_records_per_rank_fails_fast(shard_server):
    publish_dataset(shard_server, "toy", _toy_arrays(20), records_per_shard=10)
    with pytest.raises(ValueError, match="fewer than batch_size"):
        ShardStreamSource(shard_server, "toy", batch_size=16,
                          dp_rank=0, dp_size=2)


def test_mismatched_field_lengths_rejected(shard_server):
    arrays = _toy_arrays(10)
    arrays["label"] = arrays["label"][:5]
    with pytest.raises(ValueError):
        publish_dataset(shard_server, "bad", arrays)
