"""Speculative decoding: EXACT greedy equivalence is the whole contract.

The draft model only proposes; every emitted token is the argmax of the
TARGET's logits given the same prefix, so the output must be
byte-identical to plain ``generate`` greedy for ANY draft — an adversarial
draft can only make it slow. Pinned here with a same-model draft
(acceptance 100%, the fast path), a differently-initialized draft
(near-chance acceptance, the worst case), unequal padded prompts, and
the sticky-EOS contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.inference.generate import generate
from serverless_learn_tpu.inference.speculative import speculative_generate
from serverless_learn_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def models(devices):
    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    module = bundle.module
    tparams = module.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    dparams = module.init(jax.random.PRNGKey(7),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    return module, tparams, dparams


def _golden(module, params, prompt, n, eos_id=None):
    return np.asarray(jax.device_get(generate(
        module, params, jnp.asarray(prompt, jnp.int32), n, eos_id=eos_id)))


def test_self_draft_is_exact_and_fully_accepted(models):
    """draft == target: every draft accepted, K+1 tokens per round."""
    module, tparams, _ = models
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 512)
    want = _golden(module, tparams, prompt, 12)
    got, stats = speculative_generate(module, tparams, module, tparams,
                                      prompt, 12, K=4)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["acceptance"] > 0.99, stats
    # ceil(12 / (K+1)) rounds when everything accepts.
    assert stats["rounds"] <= 3, stats


def test_cross_draft_is_exact(models):
    """A draft with DIFFERENT weights (chance-level agreement) changes
    speed only — outputs still match plain target greedy exactly."""
    module, tparams, dparams = models
    prompt = jax.random.randint(jax.random.PRNGKey(2), (3, 6), 0, 512)
    want = _golden(module, tparams, prompt, 10)
    for k in (1, 3, 5):
        got, stats = speculative_generate(module, tparams, module, dparams,
                                          prompt, 10, K=k)
        np.testing.assert_array_equal(np.asarray(got), want,
                                      err_msg=f"K={k}")
        assert stats["rounds"] >= 2  # chance acceptance => many rounds


def test_unequal_prompts_exact(models):
    module, tparams, dparams = models
    prompts = [[5, 9, 11], [7, 3, 2, 8, 1, 30, 12], [4]]
    P = max(len(p) for p in prompts)
    padded = np.zeros((3, P), np.int32)
    lens = np.zeros(3, np.int32)
    for i, p in enumerate(prompts):
        padded[i, :len(p)] = p
        lens[i] = len(p)
    got, _ = speculative_generate(module, tparams, module, dparams,
                                  jnp.asarray(padded), 8, K=3,
                                  prompt_lengths=jnp.asarray(lens))
    new = np.asarray(got)[:, P:]
    for i, p in enumerate(prompts):
        want = _golden(module, tparams, [p], 8)[0][len(p):]
        np.testing.assert_array_equal(new[i], want, err_msg=f"row {i}")


def test_eos_sticky_matches_generate(models):
    module, tparams, dparams = models
    prompt = [[5, 9, 11]]
    first = _golden(module, tparams, prompt, 1)[0][-1]
    want = _golden(module, tparams, prompt, 8, eos_id=int(first))
    got, _ = speculative_generate(module, tparams, module, dparams,
                                  jnp.asarray(prompt, jnp.int32), 8, K=3,
                                  eos_id=int(first))
    np.testing.assert_array_equal(np.asarray(got), want)


def test_validation(models):
    module, tparams, dparams = models
    prompt = jnp.ones((1, 50), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        speculative_generate(module, tparams, module, dparams, prompt,
                             12, K=4)
    with pytest.raises(ValueError, match="K must be"):
        speculative_generate(module, tparams, module, dparams,
                             jnp.ones((1, 4), jnp.int32), 4, K=0)
