"""Unified cluster telemetry (`serverless_learn_tpu/telemetry/`).

Fast tier: registry types (histogram bucketing, thread-safety under
concurrent increments), Prometheus text round trip over a live HTTP
endpoint, span/event-log/bench-row plumbing, `slt top` parse+render.

Slow tier (compile-heavy): the serving integration — a GenerationServer
scraped over its live /metrics endpoint (nonzero requests_total, TTFT and
queue-wait histograms), the continuous engine's cancellation path, warm's
deterministic admit buckets, and a `top --once` snapshot covering one
trainer and one inference server.
"""

import json
import os
import threading
import time

import pytest

from serverless_learn_tpu.telemetry import (JsonlEventLog, MetricsExporter,
                                            MetricsRegistry, Span,
                                            fetch_text, publish_rpc_stats)
from serverless_learn_tpu.telemetry.registry import percentile_from_buckets
from serverless_learn_tpu.telemetry.top import parse_prometheus_text, render


# -- registry types (fast) ---------------------------------------------------

def test_counter_and_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("slt_x_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)  # counters are monotonic
    g = reg.gauge("slt_y")
    g.set(2.5)
    g.inc()
    g.dec(0.5)
    assert g.value == 3.0
    # Same (name, labels) returns the same instrument; same name with a
    # different type is a registration bug, loudly.
    assert reg.counter("slt_x_total") is c
    with pytest.raises(ValueError):
        reg.gauge("slt_x_total")


def test_histogram_bucketing_and_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("slt_lat_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 0.5, 2.0):  # edge 0.01 lands in le=0.01
        h.observe(v)
    snap = h.snapshot()
    assert snap["cumulative"] == [2, 3, 4, 5]  # le=.01, .1, 1, +Inf
    assert snap["count"] == 5
    assert abs(snap["sum"] - 2.565) < 1e-9
    p50 = h.percentile(0.5)
    assert 0.01 < p50 <= 0.1, p50  # interpolated inside the (.01, .1] bucket
    assert h.percentile(1.0) == 1.0  # +Inf bucket clamps to top edge
    assert MetricsRegistry().histogram("e").percentile(0.5) is None
    with pytest.raises(ValueError):
        reg.histogram("slt_lat_seconds", buckets=(1, 2))  # bucket mismatch
    with pytest.raises(ValueError):
        MetricsRegistry().histogram("bad", buckets=(3, 1, 2))  # unsorted


def test_concurrent_increments_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("slt_n_total", engine="continuous")
    h = reg.histogram("slt_t_seconds")

    def work():
        for _ in range(5000):
            c.inc()
            h.observe(0.003)

    threads = [threading.Thread(target=work) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert c.value == 40000
    assert h.count == 40000
    assert abs(h.sum - 120.0) < 1e-6


def test_prometheus_text_round_trips_through_top_parser():
    reg = MetricsRegistry()
    reg.counter("slt_requests_total", engine="continuous").inc(7)
    reg.counter("slt_requests_total", engine="static").inc(2)
    reg.gauge("slt_train_loss").set(1.25)
    h = reg.histogram("slt_request_ttft_seconds", engine="continuous")
    h.observe(0.004)
    h.observe(0.02)
    text = reg.render_prometheus()
    parsed = parse_prometheus_text(text)
    # Labelled series sum per name (top shows per-endpoint rollups).
    assert parsed["values"]["slt_requests_total"] == 9
    assert parsed["values"]["slt_train_loss"] == 1.25
    ph = parsed["hists"]["slt_request_ttft_seconds"]
    assert ph["count"] == 2
    assert abs(ph["sum"] - 0.024) < 1e-9
    assert ph["cumulative"][-1] == 2
    # Percentile machinery agrees between live histogram and parsed text.
    assert abs(percentile_from_buckets(ph["buckets"], ph["cumulative"], 0.5)
               - h.percentile(0.5)) < 1e-9


def test_metrics_endpoint_http_round_trip():
    reg = MetricsRegistry()
    reg.counter("slt_requests_total").inc(3)
    reg.histogram("slt_request_queue_wait_seconds").observe(0.007)
    exp = MetricsExporter(reg).start()
    try:
        text = fetch_text(exp.addr)
        assert text == reg.render_prometheus()
        parsed = parse_prometheus_text(text)
        assert parsed["values"]["slt_requests_total"] == 3
        assert parsed["hists"]["slt_request_queue_wait_seconds"]["count"] == 1
        snap = json.loads(fetch_text(exp.addr, "/metrics.json"))
        assert snap["slt_requests_total"]["series"][0]["value"] == 3
        assert json.loads(fetch_text(exp.addr, "/healthz"))["ok"] is True
        with pytest.raises(Exception):
            fetch_text(exp.addr, "/nope")
    finally:
        exp.stop()


def test_span_marks_and_event_log(tmp_path):
    s = Span("request")
    s.mark("admit")
    time.sleep(0.002)
    s.mark("done")
    s.mark("admit")  # duplicate mark: first wins
    assert s.between(None, "admit") <= s.between(None, "done")
    assert s.between("admit", "done") >= 0.002
    assert s.between(None, "missing") is None
    log = JsonlEventLog(str(tmp_path / "events.jsonl"))
    log.emit(s.to_event())
    log.emit({"event": "other"})
    lines = [json.loads(l) for l in
             open(tmp_path / "events.jsonl").read().splitlines()]
    assert lines[0]["event"] == "span"
    assert "admit" in lines[0]["marks_s"] and "ts" in lines[0]
    assert lines[1]["event"] == "other"


def test_bench_rows_attach_percentiles():
    reg = MetricsRegistry()
    reg.counter("slt_requests_total", engine="continuous").inc(4)
    h = reg.histogram("slt_request_latency_seconds")
    for v in (0.01, 0.02, 0.04, 0.4):
        h.observe(v)
    rows = reg.bench_rows()
    by_metric = {r["metric"]: r for r in rows}
    lat = by_metric["slt_request_latency_seconds"]
    # bench.py-compatible shape: metric/value/unit, percentile fields ride
    # along so BENCH_*.json rows can adopt them without schema churn.
    assert set(lat) >= {"metric", "value", "unit", "count", "p50", "p95"}
    assert lat["count"] == 4 and lat["p50"] <= lat["p95"]
    assert by_metric["slt_requests_total_continuous"]["value"] == 4


def test_publish_rpc_stats_lands_in_registry():
    reg = MetricsRegistry()
    publish_rpc_stats(
        {"rpc/fetch": {"count": 5, "total_s": 0.5, "max_s": 0.2},
         "rpc/put": {"count": 1, "total_s": 0.1, "max_s": 0.1}},
        reg, daemon="shard-server")
    text = reg.render_prometheus()
    assert 'slt_rpc_calls{daemon="shard-server",rpc="fetch"} 5' in text
    # Re-scrape overwrites (gauge semantics): a daemon restart must not
    # double-count.
    publish_rpc_stats({"rpc/fetch": {"count": 2, "total_s": 0.1,
                                     "max_s": 0.1}}, reg,
                      daemon="shard-server")
    assert 'slt_rpc_calls{daemon="shard-server",rpc="fetch"} 2' in \
        reg.render_prometheus()


def test_rpc_stats_bounds_unknown_and_overflow_tags():
    """Regression (PR 2 satellite): a StatsReply carrying MsgType tags the
    scraper doesn't know — gaps inside the table (9..19), the daemons'
    kMaxMsgType overflow slot (32), or tags past it from a daemon built
    with a larger table — must keep their count AND max latency instead of
    being dropped or colliding."""
    from serverless_learn_tpu.utils.tracing import (K_MAX_MSG_TYPE,
                                                    MSG_TYPE_NAMES,
                                                    rpc_stats)

    class _Stat:
        def __init__(self, t, c, tot, mx):
            self.msg_type, self.count = t, c
            self.total_us, self.max_us = tot, mx

    class _Reply:
        rpc = [_Stat(3, 5, 1000, 800),           # known: heartbeat
               _Stat(13, 2, 300, 200),           # sibling-range gap
               _Stat(K_MAX_MSG_TYPE, 4, 900, 700),  # daemon overflow slot
               _Stat(40, 1, 50, 50)]             # future daemon's tag

    out = rpc_stats(_Reply())
    assert set(out) == {"rpc/heartbeat", "rpc/msg_13", "rpc/other",
                        "rpc/msg_40"}
    assert out["rpc/other"]["max_s"] == pytest.approx(700e-6)
    assert out["rpc/msg_40"]["max_s"] == pytest.approx(50e-6)
    assert MSG_TYPE_NAMES[K_MAX_MSG_TYPE] == "other"

    # publish_rpc_stats lands every series (max included) in the registry.
    reg = MetricsRegistry()
    publish_rpc_stats(out, reg, daemon="coordinator")
    text = reg.render_prometheus()
    for rpc in ("heartbeat", "msg_13", "other", "msg_40"):
        assert f'slt_rpc_calls{{daemon="coordinator",rpc="{rpc}"}}' in text
    assert 'slt_rpc_max_seconds{daemon="coordinator",rpc="other"}' in text


def test_publish_rpc_stats_clamps_malformed_entries():
    """Bounds handling: non-dict rows are skipped; NaN/inf/negative values
    clamp to 0 rather than poisoning the gauges."""
    reg = MetricsRegistry()
    publish_rpc_stats(
        {"rpc/fetch": {"count": -3, "total_s": float("nan"),
                       "max_s": float("inf")},
         "rpc/garbage": "not-a-dict",
         "rpc/put": {"count": 2, "total_s": 0.5, "max_s": 0.4}},
        reg, daemon="shard-server")
    text = reg.render_prometheus()
    assert 'slt_rpc_calls{daemon="shard-server",rpc="fetch"} 0' in text
    assert 'slt_rpc_time_seconds{daemon="shard-server",rpc="fetch"} 0' in text
    assert 'slt_rpc_max_seconds{daemon="shard-server",rpc="fetch"} 0' in text
    assert "garbage" not in text
    assert 'slt_rpc_max_seconds{daemon="shard-server",rpc="put"} 0.4' in text


def test_debug_profile_endpoint(tmp_path):
    """Satellite: /debug/profile captures an on-demand jax.profiler trace
    from a live metrics server; disabled (404) without --profile-dir;
    bad/oversized seconds are 400."""
    import urllib.error

    disabled = MetricsExporter(MetricsRegistry()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            fetch_text(disabled.addr, "/debug/profile?seconds=1")
        assert ei.value.code == 404
    finally:
        disabled.stop()

    exp = MetricsExporter(MetricsRegistry(),
                          profile_dir=str(tmp_path / "prof")).start()
    try:
        for q, code in (("seconds=abc", 400), ("seconds=9999", 400)):
            with pytest.raises(urllib.error.HTTPError) as ei:
                fetch_text(exp.addr, f"/debug/profile?{q}")
            assert ei.value.code == code
        rep = json.loads(fetch_text(exp.addr, "/debug/profile?seconds=0.2",
                                    timeout=60))
        assert rep["ok"] is True
        assert os.path.isdir(rep["dir"])
        # The capture produced profiler artifacts, not an empty dir.
        found = []
        for root, _, files in os.walk(rep["dir"]):
            found += files
        assert found, "profile capture wrote no files"
    finally:
        exp.stop()


def test_top_renders_trainer_and_inference_sections():
    """Pure-python `slt top` smoke: two endpoints, one publishing trainer
    metrics, one inference metrics, rendered into one screen."""
    infer = MetricsRegistry()
    infer.counter("slt_requests_total", engine="continuous").inc(12)
    infer.histogram("slt_request_ttft_seconds",
                    engine="continuous").observe(0.004)
    infer.gauge("slt_slots_in_use", engine="continuous").set(3)
    train = MetricsRegistry()
    train.counter("slt_train_steps_total").inc(20)
    train.gauge("slt_train_samples_per_sec").set(1234.5)
    train.gauge("slt_train_loss").set(2.31)
    e1, e2 = MetricsExporter(infer).start(), MetricsExporter(train).start()
    try:
        from serverless_learn_tpu.telemetry.top import EndpointState

        states = [EndpointState(e1.addr), EndpointState(e2.addr)]
        for st in states:
            st.poll()
        screen = render(states)
        assert "INFERENCE" in screen and "TRAINING" in screen
        assert e1.addr in screen and e2.addr in screen
        assert "12" in screen and "2.3100" in screen
        # A dead endpoint renders as DOWN, not a crash.
        dead = EndpointState("127.0.0.1:1")
        dead.poll()
        assert "DOWN" in render([dead])
    finally:
        e1.stop()
        e2.stop()


def test_diloco_nonleader_liveness_escape(tmp_path):
    """ADVICE round 5: a leader whose heartbeat thread outlives a wedged
    training thread keeps its lease forever; non-leaders must not poll
    unboundedly. After liveness_factor * round_timeout_s with no new
    anchor and LATEST unmoved, a non-leader challenges — leads the round
    itself — and the escape is counted."""
    import numpy as np

    from serverless_learn_tpu.training import diloco_dcn as dd
    from serverless_learn_tpu.training.checkpoint import LocalStore

    isl = dd.DilocoIsland.__new__(dd.DilocoIsland)
    isl.store = LocalStore(str(tmp_path))
    isl.run = "t"
    isl.poll_s = 0.01
    isl.round_timeout_s = 0.05
    isl.liveness_factor = 2.0
    isl.outer_lr, isl.outer_momentum = 0.1, 0.9
    isl.report = dd.IslandReport()
    isl.abort = None
    reg = MetricsRegistry()
    isl._m_rounds = reg.counter("slt_diloco_rounds_total")
    isl._m_led = reg.counter("slt_diloco_led_rounds_total")
    isl._m_escapes = reg.counter("slt_diloco_liveness_escapes_total")
    isl._m_round = reg.gauge("slt_diloco_round")
    isl._m_lag = reg.gauge("slt_diloco_anchor_lag_rounds")

    class FakeAgent:
        worker_id = 7

    isl.agent = FakeAgent()
    # id 3 is the hung leader: live in membership, never publishes.
    isl._live_ids = lambda: [3, 7]
    template = {"w": np.zeros((2,), np.float32)}
    anchor = {"w": np.ones((2,), np.float32)}
    trace = {"w": np.zeros((2,), np.float32)}
    isl._publish(0, anchor, trace, 0)
    t0 = time.time()
    isl._await_next_anchor(0, anchor, trace, template)
    assert time.time() - t0 < 10, "non-leader waited unboundedly"
    assert isl.store.exists(isl._k("round-1", "anchor")), \
        "challenger did not publish the next anchor"
    assert isl._m_escapes.value == 1
    assert isl.report.led_rounds == 1


# -- serving integration (compile-heavy; slow tier) --------------------------

@pytest.fixture(scope="module")
def model(devices):
    import jax
    import jax.numpy as jnp

    from serverless_learn_tpu.models.registry import get_model

    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return bundle.module, params


def test_server_metrics_endpoint_scrape(model):
    """Acceptance: a live /metrics endpoint on the serving process from
    which a scrape reads nonzero requests_total plus TTFT and queue-wait
    histograms recorded per request."""
    from serverless_learn_tpu.inference.server import (GenerationServer,
                                                       request)

    module, params = model
    reg = MetricsRegistry()
    srv = GenerationServer(module, params, engine="continuous",
                           registry=reg, metrics_port=0).start()
    try:
        assert srv.metrics_addr
        prompts = [[5, 9, 11], [7, 3, 2, 8], [4, 4], [1, 2, 3]]
        reps = [None] * len(prompts)

        def client(i):
            reps[i] = request(srv.addr, {"prompt": prompts[i],
                                         "max_new_tokens": 4})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        [t.start() for t in threads]
        [t.join(timeout=300) for t in threads]
        assert all(r and "new_tokens" in r for r in reps), reps
        parsed = parse_prometheus_text(fetch_text(srv.metrics_addr))
        assert parsed["values"]["slt_requests_total"] >= 4
        assert parsed["values"]["slt_server_requests_total"] >= 4
        ttft = parsed["hists"]["slt_request_ttft_seconds"]
        qwait = parsed["hists"]["slt_request_queue_wait_seconds"]
        assert ttft["count"] >= 4 and ttft["sum"] > 0
        assert qwait["count"] >= 4
        assert parsed["values"]["slt_decode_tokens_total"] >= 16
        # Span-derived ordering: queueing is part of TTFT, so per-request
        # TTFT can never be cheaper than its queue wait in aggregate.
        assert ttft["sum"] >= qwait["sum"]
    finally:
        srv.stop()


def test_continuous_cancellation_retires_slot(model):
    """ADVICE round 5: a submit() that times out must not decode to full
    budget — the request retires at the next boundary and the counter
    records it."""
    from serverless_learn_tpu.inference.continuous import (
        ContinuousBatchingEngine)

    module, params = model
    reg = MetricsRegistry()
    eng = ContinuousBatchingEngine(module, params, max_slots=2,
                                   chunk_size=2, registry=reg)
    try:
        # timeout 0: guaranteed to abandon (queued or just-admitted).
        r = eng.submit([5, 6], 40, 0.0, 0, None, 0, timeout_s=0.0)
        assert "error" in r and "timed out" in r["error"], r
        deadline = time.time() + 60
        while eng.requests_cancelled < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert eng.requests_cancelled == 1
        deadline = time.time() + 60
        while (any(s is not None for s in eng._slots)
               and time.time() < deadline):
            time.sleep(0.02)
        assert all(s is None for s in eng._slots), \
            "cancelled request kept its slot"
        c = reg.counter("slt_requests_cancelled_total", engine="continuous")
        assert c.value == 1
        # Engine still serves live traffic after the retirement.
        import jax
        import jax.numpy as jnp

        from serverless_learn_tpu.inference.generate import generate

        ok = eng.submit([5, 9, 11], 4, 0.0, 0, None, 0)
        solo = [int(t) for t in jax.device_get(generate(
            module, params, jnp.asarray([[5, 9, 11]], jnp.int32), 4))[0][3:]]
        assert ok["new_tokens"] == solo
    finally:
        eng.stop()


def test_warm_compiles_admit_buckets_deterministically(model):
    """ADVICE round 5 (gen_bench warmup hazard): warm(batch_sizes=[1,2,4])
    must compile the admit bucket for EVERY size — admission may not split
    on thread-arrival timing."""
    from serverless_learn_tpu.inference.continuous import (
        ContinuousBatchingEngine)

    module, params = model
    eng = ContinuousBatchingEngine(module, params, max_slots=4,
                                   chunk_size=4, registry=MetricsRegistry())
    try:
        eng.warm(8, 4, batch_sizes=[1, 2, 4])
        compiled_nb = {k[0] for k in eng._admit_jits}
        assert {1, 2, 4} <= compiled_nb, compiled_nb
    finally:
        eng.stop()


def test_top_once_covers_trainer_and_inference(model, capsys):
    """Acceptance: `slt top --once` renders a one-shot cluster snapshot
    spanning one trainer and one inference server."""
    from serverless_learn_tpu.cli import main
    from serverless_learn_tpu.config import (DataConfig, ExperimentConfig,
                                             MeshConfig, OptimizerConfig,
                                             TrainConfig)
    from serverless_learn_tpu.inference.server import (GenerationServer,
                                                       request)
    from serverless_learn_tpu.telemetry import get_registry
    from serverless_learn_tpu.training.loop import run_training

    # Trainer arm: a short real run publishing into the process-default
    # registry, exported like `train --metrics-port 0` would.
    cfg = ExperimentConfig(
        model="mlp_mnist", mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16, num_steps=3, dtype="float32",
                          param_dtype="float32"),
        data=DataConfig())
    run_training(cfg)
    train_exp = MetricsExporter(get_registry()).start()

    # Inference arm: its own registry + endpoint, like a second process.
    module, params = model
    srv = GenerationServer(module, params, engine="continuous",
                           registry=MetricsRegistry(), metrics_port=0)
    srv.start()
    try:
        assert "new_tokens" in request(
            srv.addr, {"prompt": [5, 9, 11], "max_new_tokens": 4})
        rc = main(["top", f"{train_exp.addr},{srv.metrics_addr}", "--once"])
        assert rc == 0
        screen = capsys.readouterr().out
        assert "TRAINING" in screen and "INFERENCE" in screen
        assert train_exp.addr in screen and srv.metrics_addr in screen
    finally:
        srv.stop()
        train_exp.stop()
