"""Vocab-file BPE + sequence packing (round-3 verdict #8): encode against
a GPT-2-format artifact pair, lossless round-trip, packing density, and
packed batches actually training BERT and llama."""

import json

import numpy as np
import pytest

from serverless_learn_tpu.data.raw import BYTE_VOCAB, EOS_ID
from serverless_learn_tpu.data.tokenizer import (
    BPETokenizer, load_text_corpus, pack_token_docs, packing_efficiency)


def _toy_vocab(tmp_path):
    """A tiny but REAL GPT-2-format artifact pair: byte-level alphabet +
    a few ranked merges, written as vocab.json + merges.txt."""
    from serverless_learn_tpu.data.tokenizer import _bytes_to_unicode

    b2u = _bytes_to_unicode()
    vocab = {c: i for i, c in enumerate(sorted(b2u.values()))}
    merges = [("h", "e"), ("l", "l"), ("he", "ll"), ("o", "w"),
              ("Ġ", "w"), ("Ġw", "orld"), ("o", "r"),
              ("or", "l"), ("orl", "d")]
    for a, b in merges:
        if a + b not in vocab:
            vocab[a + b] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    vp = tmp_path / "vocab.json"
    mp = tmp_path / "merges.txt"
    vp.write_text(json.dumps(vocab))
    mp.write_text("#version: 0.2\n" +
                  "\n".join(f"{a} {b}" for a, b in merges))
    return str(vp), str(mp), vocab


def test_bpe_merges_apply_by_rank(tmp_path):
    vp, mp, vocab = _toy_vocab(tmp_path)
    tok = BPETokenizer.from_files(vp, mp)
    ids = tok.encode("hello world")
    # "hello" -> [hell, o]; " world" -> [Ġworld]  (Ġ = Ġ = space byte)
    toks = [tok.inv_vocab[int(i)] for i in ids]
    assert toks == ["hell", "o", "Ġworld"], toks


def test_bpe_round_trips_arbitrary_text(tmp_path):
    vp, mp, _ = _toy_vocab(tmp_path)
    tok = BPETokenizer.from_files(vp, mp)
    for text in ("hello world", "héllo wörld 123 \n tabs\t!",
                 "emoji \U0001f600 and 中文"):
        assert tok.decode(tok.encode(text)) == text, text


def test_bpe_eos_discovered(tmp_path):
    vp, mp, vocab = _toy_vocab(tmp_path)
    tok = BPETokenizer.from_files(vp, mp)
    assert tok.eos_id == vocab["<|endoftext|>"]
    assert tok.vocab_size == len(vocab)


def test_packing_dense_and_ordered():
    docs = [np.arange(10, 40), np.arange(100, 105), np.arange(200, 230)]
    out = pack_token_docs(docs, seq_len=16)["input_ids"]
    assert out.shape[1] == 16
    flat = []
    for d in docs:
        flat.extend(int(x) for x in d)
        flat.append(EOS_ID)
    want = np.asarray(flat[:(len(flat) // 15) * 15]).reshape(-1, 15)
    np.testing.assert_array_equal(out[:, 1:], want)  # BOS heads each row
    assert (out[:, 0] == 2).all()


def test_packing_wire_efficiency():
    """The verdict's wire-efficiency bar: short docs (40 tokens) in
    512-token rows — packing must cut shipped rows by >80% vs
    one-doc-per-row."""
    rng = np.random.default_rng(0)
    docs = [rng.integers(4, 260, rng.integers(20, 60))
            for _ in range(200)]
    eff = packing_efficiency(docs, seq_len=512)
    assert eff["packed_pad_fraction"] == 0.0
    assert eff["naive_pad_fraction"] > 0.85
    assert eff["wire_bytes_saved_fraction"] > 0.8, eff


def test_load_text_corpus_byte_fallback(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("doc one text here\n\ndoc two text\n\n" * 50)
    out = load_text_corpus(str(p), seq_len=32)
    assert out["input_ids"].shape[1] == 32
    assert out["input_ids"].max() < BYTE_VOCAB


def test_load_text_corpus_with_vocab(tmp_path):
    vp, mp, vocab = _toy_vocab(tmp_path)
    p = tmp_path / "corpus.txt"
    p.write_text("hello world\n\nhello hello world\n\n" * 80)
    out = load_text_corpus(str(p), seq_len=16, vocab_file=vp,
                           merges_file=mp)
    ids = out["input_ids"]
    assert ids.max() < len(vocab)
    # BPE compresses: far fewer tokens than bytes
    n_bytes = len("hello world") * 80 + len("hello hello world") * 80
    assert ids.size < 0.6 * n_bytes


def test_packed_batches_train_llama_and_bert(tmp_path, devices):
    """End to end: text -> packed shards -> stream -> lm/mlm transform ->
    finite train steps on both LM families."""
    import socket

    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig,
        TrainConfig)
    from serverless_learn_tpu.control.daemons import start_shard_server
    from serverless_learn_tpu.data.shard_client import publish_dataset
    from serverless_learn_tpu.training.loop import make_source
    from serverless_learn_tpu.training.train_step import build_trainer

    p = tmp_path / "corpus.txt"
    p.write_text("the quick brown fox jumps over the lazy dog\n\n" * 300)
    arrays = load_text_corpus(str(p), seq_len=32)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = start_shard_server(port=port, root=str(tmp_path / "store"))
    addr = f"127.0.0.1:{port}"
    try:
        publish_dataset(addr, "packed_text", arrays, records_per_shard=64)
        for model, overrides in (
                ("llama_tiny", dict(vocab_size=512)),
                ("bert_tiny", dict(vocab_size=512, max_seq_len=32))):
            cfg = ExperimentConfig(
                model=model, model_overrides=overrides,
                mesh=MeshConfig(dp=8),
                optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3),
                train=TrainConfig(batch_size=16, num_steps=2,
                                  dtype="float32", param_dtype="float32"),
                data=DataConfig(dataset="packed_text",
                                shard_server_addr=addr, seq_len=32))
            trainer = build_trainer(cfg)
            source = make_source(cfg, trainer, dp_rank=0, dp_size=1)
            it = iter(source)
            state = trainer.init()
            for _ in range(2):
                state, m = trainer.step(state, trainer.shard_batch(next(it)))
            assert np.isfinite(float(jax.device_get(m["loss"]))), model
            if hasattr(source, "close"):
                source.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)
